// fast_ingest — native host-side ingestion for pagerank_tpu.
//
// The reference inherits its ingestion machinery from Hadoop/Spark (JVM,
// Sparky.java:61); this library is the build's native-runtime equivalent
// for the host side of L1/L2: memory-mapped multithreaded edge-list
// parsing and a 64-bit LSD radix sort-dedup that produces the dst-major
// edge order the device kernels require (SURVEY.md §7: host ingestion of
// 1.47B edges must not dwarf the device budget; text parsing in Python
// would).
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libfast_ingest.so \
//            fast_ingest.cpp -lpthread

#include <atomic>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// Edge-list text parsing: whitespace-separated integer pairs, '#' comments.
// ---------------------------------------------------------------------------

struct ParseResult {
  int64_t* src;
  int64_t* dst;
  int64_t count;
  int64_t error;  // 0 ok; 1 open/map failure; 2 odd token count; 3 bad token
};

static void parse_span(const char* p, const char* end, std::vector<int64_t>* out,
                       std::atomic<int>* bad) {
  // Parses full lines in [p, end); caller aligns spans to line boundaries.
  while (p < end) {
    // skip whitespace/newlines
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
    if (p >= end) break;
    if (*p == '#') {  // comment to end of line
      while (p < end && *p != '\n') p++;
      continue;
    }
    bool neg = false;
    if (*p == '-') { neg = true; p++; }
    int64_t v = 0;
    const char* digits_start = p;
    while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
    if (p == digits_start) {
      // Token with no digits (e.g. a stray word): flag and skip it —
      // never stall. The caller surfaces error=3 as a ValueError.
      bad->store(1, std::memory_order_relaxed);
      while (p < end && *p != ' ' && *p != '\t' && *p != '\r' && *p != '\n') p++;
      continue;
    }
    out->push_back(neg ? -v : v);
  }
}

ParseResult parse_edgelist(const char* path, int32_t num_threads) {
  ParseResult r{nullptr, nullptr, 0, 0};
  int fd = open(path, O_RDONLY);
  if (fd < 0) { r.error = 1; return r; }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    if (st.st_size == 0) { r.count = 0; return r; }
    r.error = 1; return r;
  }
  size_t size = static_cast<size_t>(st.st_size);
  char* data = static_cast<char*>(mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (data == MAP_FAILED) { r.error = 1; return r; }

  int nt = num_threads > 0 ? num_threads : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  std::vector<std::vector<int64_t>> parts(nt);
  std::vector<std::thread> threads;
  size_t chunk = size / nt + 1;
  std::vector<const char*> bounds(nt + 1);
  bounds[0] = data;
  for (int t = 1; t < nt; t++) {
    const char* b = data + std::min(size, t * chunk);
    // advance to next newline so each span holds whole lines
    while (b < data + size && *b != '\n') b++;
    if (b < data + size) b++;
    bounds[t] = b;
  }
  bounds[nt] = data + size;
  std::atomic<int> bad{0};
  for (int t = 0; t < nt; t++) {
    threads.emplace_back(parse_span, bounds[t], bounds[t + 1], &parts[t], &bad);
  }
  for (auto& th : threads) th.join();
  munmap(data, size);

  if (bad.load()) { r.error = 3; return r; }
  int64_t total = 0;
  for (auto& p : parts) total += (int64_t)p.size();
  if (total % 2 != 0) { r.error = 2; return r; }
  int64_t e = total / 2;
  r.src = static_cast<int64_t*>(malloc(sizeof(int64_t) * (e ? e : 1)));
  r.dst = static_cast<int64_t*>(malloc(sizeof(int64_t) * (e ? e : 1)));
  int64_t k = 0;
  // Token stream is strictly ordered across spans (spans are disjoint,
  // line-aligned, in file order), alternating src dst src dst...
  int64_t parity = 0;
  for (auto& p : parts) {
    for (int64_t v : p) {
      if (parity == 0) r.src[k] = v; else r.dst[k++] = v;
      parity ^= 1;
    }
  }
  r.count = e;
  return r;
}

void free_edges(int64_t* src, int64_t* dst) {
  free(src);
  free(dst);
}

// ---------------------------------------------------------------------------
// Radix sort-dedup: key = dst * n + src (dst-major order), 8-bit LSD.
// Outputs int32 src/dst plus out/in degrees. Returns deduped edge count.
// ---------------------------------------------------------------------------

static void lsd_radix_sort_parallel(uint64_t*& a, uint64_t*& b, int64_t e,
                                    uint64_t maxkey, int nt) {
  // 16-bit digits => at most 4 passes for 64-bit keys; stable LSD with
  // per-thread histograms so the scatter runs fully parallel.
  constexpr int RADIX = 1 << 16;
  constexpr uint64_t MASK = RADIX - 1;
  int passes = 1;
  while (passes < 4 && (maxkey >> (16 * passes)) != 0) passes++;
  int64_t chunk = (e + nt - 1) / nt;
  std::vector<std::vector<int64_t>> hist(nt, std::vector<int64_t>(RADIX));
  for (int p = 0; p < passes; p++) {
    int shift = 16 * p;
    {
      std::vector<std::thread> ths;
      for (int t = 0; t < nt; t++) {
        ths.emplace_back([&, t] {
          auto& h = hist[t];
          std::fill(h.begin(), h.end(), 0);
          int64_t lo = t * chunk, hi = std::min(e, lo + chunk);
          for (int64_t i = lo; i < hi; i++) h[(a[i] >> shift) & MASK]++;
        });
      }
      for (auto& th : ths) th.join();
    }
    // exclusive prefix over (digit-major, thread-minor) keeps stability
    int64_t pos = 0;
    for (int d = 0; d < RADIX; d++) {
      for (int t = 0; t < nt; t++) {
        int64_t c = hist[t][d];
        hist[t][d] = pos;
        pos += c;
      }
    }
    {
      std::vector<std::thread> ths;
      for (int t = 0; t < nt; t++) {
        ths.emplace_back([&, t] {
          auto& h = hist[t];
          int64_t lo = t * chunk, hi = std::min(e, lo + chunk);
          for (int64_t i = lo; i < hi; i++) b[h[(a[i] >> shift) & MASK]++] = a[i];
        });
      }
      for (auto& th : ths) th.join();
    }
    std::swap(a, b);
  }
}

int64_t sort_dedup_degrees(const int64_t* src, const int64_t* dst, int64_t e,
                           int64_t n, int32_t* out_src, int32_t* out_dst,
                           int32_t* out_degree, int32_t* in_degree) {
  if (e == 0) {
    memset(out_degree, 0, sizeof(int32_t) * n);
    memset(in_degree, 0, sizeof(int32_t) * n);
    return 0;
  }
  int nt = (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (nt > 32) nt = 32;
  std::vector<uint64_t> keys(e), tmp(e);
  {
    int64_t chunk = (e + nt - 1) / nt;
    std::vector<std::thread> ths;
    for (int t = 0; t < nt; t++) {
      ths.emplace_back([&, t] {
        int64_t lo = t * chunk, hi = std::min(e, lo + chunk);
        for (int64_t i = lo; i < hi; i++) {
          keys[i] = (uint64_t)dst[i] * (uint64_t)n + (uint64_t)src[i];
        }
      });
    }
    for (auto& th : ths) th.join();
  }
  uint64_t maxkey = (uint64_t)(n - 1) * (uint64_t)n + (uint64_t)(n - 1);
  uint64_t* a = keys.data();
  uint64_t* b = tmp.data();
  lsd_radix_sort_parallel(a, b, e, maxkey, nt);
  // dedup + decode + degrees
  memset(out_degree, 0, sizeof(int32_t) * n);
  memset(in_degree, 0, sizeof(int32_t) * n);
  int64_t k = 0;
  uint64_t prev = ~a[0];  // != a[0]
  for (int64_t i = 0; i < e; i++) {
    if (a[i] == prev) continue;
    prev = a[i];
    int32_t d = (int32_t)(a[i] / (uint64_t)n);
    int32_t s = (int32_t)(a[i] % (uint64_t)n);
    out_src[k] = s;
    out_dst[k] = d;
    out_degree[s]++;
    in_degree[d]++;
    k++;
  }
  return k;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Rank-line formatter — the native L4 (utils/snapshot.TextDumper).
//
// The reference dumps the full rank vector as text every iteration
// (Sparky.java:237); a per-line Python formatter manages ~4e5 lines/s
// and dominated the end-to-end job (VERDICT r4 weak #1). This produces
// the SAME bytes — "(key,repr(value))\n" with CPython's float repr —
// in bulk: std::to_chars gives the shortest round-trip digit string
// (the identical unique shortest representation CPython's dtoa picks),
// and the presentation policy below is CPython's float_repr_style:
// fixed notation iff -4 < decimal_point <= 16, else scientific with a
// signed >=2-digit exponent; integral fixed values get a trailing
// ".0"; 0.0/-0.0/inf/nan spelled as Python spells them. Byte-equality
// against the Python formatter is differentially fuzzed in
// tests/test_snapshot.py.
// ---------------------------------------------------------------------------

#if defined(__cpp_lib_to_chars)
static char* fmt_double_pyrepr(double v, char* out) {
  if (std::isnan(v)) { memcpy(out, "nan", 3); return out + 3; }
  if (std::signbit(v)) { *out++ = '-'; v = -v; }
  if (std::isinf(v)) { memcpy(out, "inf", 3); return out + 3; }
  if (v == 0.0) { memcpy(out, "0.0", 3); return out + 3; }
  char buf[48];
  auto res =
      std::to_chars(buf, buf + sizeof buf, v, std::chars_format::scientific);
  // Parse "d[.ddd]e[+-]dd+" into the digit string and decimal exponent.
  char digits[24];
  int nd = 0;
  const char* p = buf;
  while (*p != 'e') {
    if (*p != '.') digits[nd++] = *p;
    p++;
  }
  p++;  // 'e'
  bool eneg = (*p == '-');
  p++;  // sign (to_chars always emits one in scientific form)
  int exp10 = 0;
  while (p < res.ptr) exp10 = exp10 * 10 + (*p++ - '0');
  if (eneg) exp10 = -exp10;
  int dp = exp10 + 1;  // digits before the decimal point
  if (-4 < dp && dp <= 16) {
    if (dp <= 0) {
      *out++ = '0';
      *out++ = '.';
      for (int i = 0; i < -dp; i++) *out++ = '0';
      memcpy(out, digits, nd);
      out += nd;
    } else if (dp >= nd) {
      memcpy(out, digits, nd);
      out += nd;
      for (int i = 0; i < dp - nd; i++) *out++ = '0';
      *out++ = '.';
      *out++ = '0';
    } else {
      memcpy(out, digits, dp);
      out += dp;
      *out++ = '.';
      memcpy(out, digits + dp, nd - dp);
      out += nd - dp;
    }
    return out;
  }
  *out++ = digits[0];
  if (nd > 1) {
    *out++ = '.';
    memcpy(out, digits + 1, nd - 1);
    out += nd - 1;
  }
  *out++ = 'e';
  int e10 = dp - 1;
  *out++ = e10 < 0 ? '-' : '+';
  if (e10 < 0) e10 = -e10;
  char ebuf[8];
  int ne = 0;
  while (e10) { ebuf[ne++] = (char)('0' + e10 % 10); e10 /= 10; }
  while (ne < 2) ebuf[ne++] = '0';
  while (ne) *out++ = ebuf[--ne];
  return out;
}
#endif  // __cpp_lib_to_chars

extern "C" {

// Formats n "(key,value)\n" lines into out (capacity cap bytes).
// Keys: when names_blob/name_offsets are non-null, key i is the byte
// range [name_offsets[i], name_offsets[i+1]) of names_blob; otherwise
// the decimal integer key_base + i (key_base lets callers format in
// bounded row chunks without the keys restarting — the symbol carries
// a "2" so a stale prebuilt .so without the parameter makes the
// Python binding fall back instead of silently misnumbering keys).
// Returns bytes written, -1 if cap would be exceeded (caller sizes
// cap from the documented per-line bound), or -2 when the toolchain
// that built this library lacks floating-point charconv (pre-GCC-11)
// — callers fall back to the Python formatter without losing the rest
// of the library.
int64_t format_rank_lines2(const double* ranks, int64_t n,
                           int64_t key_base, const char* names_blob,
                           const int64_t* name_offsets, char* out,
                           int64_t cap) {
#if !defined(__cpp_lib_to_chars)
  (void)ranks; (void)n; (void)key_base; (void)names_blob;
  (void)name_offsets; (void)out; (void)cap;
  return -2;
#else
  // repr of a double is at most 24 chars ("-1.7976931348623157e+308");
  // "(" + key + "," + value + ")\n" adds 4.
  char* q = out;
  char* end = out + cap;
  for (int64_t i = 0; i < n; i++) {
    int64_t keylen =
        names_blob ? name_offsets[i + 1] - name_offsets[i] : 20;
    if (end - q < keylen + 24 + 4) return -1;
    *q++ = '(';
    if (names_blob) {
      memcpy(q, names_blob + name_offsets[i], keylen);
      q += keylen;
    } else {
      char kbuf[24];
      int nk = 0;
      int64_t k = key_base + i;
      if (k == 0) kbuf[nk++] = '0';
      while (k) { kbuf[nk++] = (char)('0' + k % 10); k /= 10; }
      while (nk) *q++ = kbuf[--nk];
    }
    *q++ = ',';
    q = fmt_double_pyrepr(ranks[i], q);
    *q++ = ')';
    *q++ = '\n';
  }
  return q - out;
#endif
}

}  // extern "C"
