// crawl_ingest — native L1 for pagerank_tpu: SequenceFile + crawl-JSON.
//
// The reference's L1 parses 301 Common Crawl SequenceFiles across the
// cluster (Sparky.java:44-61) and extracts anchor links with Gson
// (Sparky.java:78-124). The Python path (ingest/seqfile.py +
// ingest/crawljson.py) is the behavioral spec but is CPU-bound at
// ~14k records/s/core (docs/PERF_NOTES.md "Host ingest"); this library
// is the same pipeline in C++ — container decode (uncompressed,
// record-deflate, block-deflate), Python-json-compatible parsing with
// the Gson rendering quirks, and string->int32 id interning — behind a
// C ABI for ctypes (ingest/native.py). The Python reader remains the
// oracle: tests/test_native_crawl.py differentially checks byte-exact
// graph/name equality on adversarial inputs.
//
// Build: compiled together with fast_ingest.cpp into libfast_ingest.so
// (ingest/native.py adds -lz -std=c++17).

#include <algorithm>
#include <atomic>
#include <charconv>
#include <cmath>
#include <deque>
#include <thread>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

#include <zlib.h>

namespace {

// ---------------------------------------------------------------------------
// Error categories — the ctypes wrapper maps these back to the exception
// types the Python path raises, so strict-mode semantics are identical.
// ---------------------------------------------------------------------------
enum ErrCat : int64_t {
  OK = 0,
  FORMAT = 1,     // malformed container structure -> ValueError
  JSON = 2,       // malformed JSON -> json.JSONDecodeError
  KEY = 3,        // link entry missing href/type -> KeyError
  TYPE = 4,       // link entry / JSONL root of wrong type -> TypeError
  VALUE = 5,      // other value errors -> ValueError
  INTERNAL = 6,   // depth/overflow -> RuntimeError (RecursionError class)
  EOF_ = 7,       // truncated container -> EOFError (Python reader parity)
  ZLIB = 8,       // corrupt deflate stream -> zlib.error
  UNSUPPORTED = 9,  // valid for Python, unrepresentable natively (e.g.
                    // non-string JSONL url) -> wrapper falls back to Python
};

struct Fail {
  ErrCat cat;
  std::string msg;
};

// ---------------------------------------------------------------------------
// UTF-8 validate-and-replace (CPython errors="replace" semantics: one
// U+FFFD per maximal invalid subpart, WHATWG algorithm). Both the
// SequenceFile Text payloads and TSV files are decoded this way in the
// Python path before any parsing, so the native path must see the same
// replaced text.
// ---------------------------------------------------------------------------
void utf8_replace(const uint8_t* p, size_t len, std::string& out) {
  static const char REP[] = "\xef\xbf\xbd";  // U+FFFD
  out.clear();
  out.reserve(len);
  size_t i = 0;
  while (i < len) {
    uint8_t b = p[i];
    if (b < 0x80) {
      out.push_back((char)b);
      i++;
      continue;
    }
    int need;
    uint8_t lo = 0x80, hi = 0xBF;
    if (b >= 0xC2 && b <= 0xDF) {
      need = 1;
    } else if (b == 0xE0) {
      need = 2; lo = 0xA0;
    } else if (b >= 0xE1 && b <= 0xEC) {
      need = 2;
    } else if (b == 0xED) {
      need = 2; hi = 0x9F;  // no surrogates
    } else if (b >= 0xEE && b <= 0xEF) {
      need = 2;
    } else if (b == 0xF0) {
      need = 3; lo = 0x90;
    } else if (b >= 0xF1 && b <= 0xF3) {
      need = 3;
    } else if (b == 0xF4) {
      need = 3; hi = 0x8F;
    } else {
      out.append(REP, 3);  // invalid lead (C0/C1/F5-FF/continuation)
      i++;
      continue;
    }
    // Consume continuations while they are in range; a maximal subpart
    // ends at the first out-of-range byte.
    size_t start = i;
    i++;
    int got = 0;
    while (got < need && i < len) {
      uint8_t c = p[i];
      uint8_t clo = (got == 0) ? lo : 0x80;
      uint8_t chi = (got == 0) ? hi : 0xBF;
      if (c < clo || c > chi) break;
      i++;
      got++;
    }
    if (got == need) {
      out.append((const char*)p + start, (size_t)(need + 1));
    } else {
      out.append(REP, 3);
    }
  }
}

// ---------------------------------------------------------------------------
// Byte-span reader with the Python reader's error wording category.
// ---------------------------------------------------------------------------
struct Span {
  const uint8_t* p;
  const uint8_t* end;
  size_t left() const { return (size_t)(end - p); }
  bool take(size_t n, const uint8_t** out) {
    if (left() < n) return false;
    *out = p;
    p += n;
    return true;
  }
};

// Hadoop WritableUtils VInt (ingest/seqfile.py:_read_vint).
bool read_vint(Span& s, int64_t* out) {
  const uint8_t* b;
  if (!s.take(1, &b)) return false;
  int8_t first = (int8_t)b[0];
  if (first >= -112) {
    *out = first;
    return true;
  }
  bool negative;
  int size;
  if (first >= -120) {
    size = -(first + 112);
    negative = false;
  } else {
    size = -(first + 120);
    negative = true;
  }
  const uint8_t* d;
  if (!s.take((size_t)size, &d)) return false;
  int64_t value = 0;
  for (int i = 0; i < size; i++) value = (value << 8) | d[i];
  *out = negative ? ~value : value;
  return true;
}

bool read_i32(Span& s, int32_t* out) {
  const uint8_t* b;
  if (!s.take(4, &b)) return false;
  *out = (int32_t)(((uint32_t)b[0] << 24) | ((uint32_t)b[1] << 16) |
                   ((uint32_t)b[2] << 8) | (uint32_t)b[3]);
  return true;
}

// VInt-length-prefixed byte string (Hadoop Text / writeString payload).
// Distinguishes truncation (Python: EOFError) from a negative length
// (Python: ValueError) for exception-class parity.
enum TextRead { TEXT_OK, TEXT_EOF, TEXT_NEG };
TextRead read_text_raw(Span& s, const uint8_t** out, int64_t* n) {
  if (!read_vint(s, n)) return TEXT_EOF;
  if (*n < 0) return TEXT_NEG;
  return s.take((size_t)*n, out) ? TEXT_OK : TEXT_EOF;
}

// zlib stream (zlib.decompress default = wbits 15).
bool inflate_all(const uint8_t* p, size_t len, std::string& out) {
  out.clear();
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit(&zs) != Z_OK) return false;
  zs.next_in = const_cast<Bytef*>(p);
  zs.avail_in = (uInt)len;
  char buf[1 << 16];
  int rc;
  do {
    zs.next_out = (Bytef*)buf;
    zs.avail_out = sizeof(buf);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
    out.append(buf, sizeof(buf) - zs.avail_out);
  } while (rc != Z_STREAM_END);
  inflateEnd(&zs);
  return true;
}

// ---------------------------------------------------------------------------
// Python-json-compatible parser (json.loads defaults): NaN/Infinity
// accepted, control chars in strings rejected, duplicate keys keep the
// LAST value, lone \uXXXX surrogates kept (encoded WTF-8 so they round-
// trip through Python's surrogatepass). Depth-capped (CPython hits
// RecursionError there; both map to the INTERNAL category).
// ---------------------------------------------------------------------------
struct JValue {
  enum Kind { Null, True, False, Int, Dbl, Str, Arr, Obj } kind = Null;
  std::string s;  // Str: decoded text; Int: raw token
  double d = 0;   // Dbl
  std::vector<JValue> arr;
  std::vector<std::pair<std::string, JValue>> obj;
};

constexpr int MAX_DEPTH = 400;

struct JsonParser {
  const char* p;
  const char* end;
  Fail* fail;

  bool err(ErrCat cat, const char* msg) {
    if (fail->cat == OK) *fail = {cat, msg};
    return false;
  }
  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool lit(const char* w, size_t n) {
    if ((size_t)(end - p) < n || std::memcmp(p, w, n) != 0) return false;
    p += n;
    return true;
  }

  static int hex(char c) {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
  }

  static void put_cp(uint32_t cp, std::string& out) {
    // Encodes any scalar incl. lone surrogates (WTF-8 3-byte form).
    if (cp < 0x80) {
      out.push_back((char)cp);
    } else if (cp < 0x800) {
      out.push_back((char)(0xC0 | (cp >> 6)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back((char)(0xE0 | (cp >> 12)));
      out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    } else {
      out.push_back((char)(0xF0 | (cp >> 18)));
      out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back((char)(0x80 | (cp & 0x3F)));
    }
  }

  bool read_u4(uint32_t* out) {
    if (end - p < 4) return err(JSON, "Invalid \\uXXXX escape");
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      int h = hex(p[i]);
      if (h < 0) return err(JSON, "Invalid \\uXXXX escape");
      v = (v << 4) | (uint32_t)h;
    }
    p += 4;
    *out = v;
    return true;
  }

  bool parse_string(std::string& out) {
    // Caller consumed the opening quote.
    out.clear();
    while (true) {
      if (p >= end) return err(JSON, "Unterminated string");
      unsigned char c = (unsigned char)*p;
      if (c == '"') {
        p++;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return err(JSON, "Unterminated string");
        char e = *p++;
        switch (e) {
          case '"': out.push_back('"'); break;
          case '\\': out.push_back('\\'); break;
          case '/': out.push_back('/'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'n': out.push_back('\n'); break;
          case 'r': out.push_back('\r'); break;
          case 't': out.push_back('\t'); break;
          case 'u': {
            uint32_t cp;
            if (!read_u4(&cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 2 && p[0] == '\\' &&
                p[1] == 'u') {
              const char* save = p;
              p += 2;
              uint32_t lo;
              if (!read_u4(&lo)) return false;
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              } else {
                p = save;  // lone high surrogate; low-part stays literal
              }
            }
            put_cp(cp, out);
            break;
          }
          default:
            return err(JSON, "Invalid \\escape");
        }
        continue;
      }
      if (c < 0x20) return err(JSON, "Invalid control character in string");
      out.push_back((char)c);
      p++;
    }
  }

  bool parse_number(JValue& v) {
    const char* start = p;
    if (p < end && *p == '-') p++;
    if (p >= end) return err(JSON, "Expecting value");
    if (*p == '0') {
      p++;
    } else if (*p >= '1' && *p <= '9') {
      while (p < end && *p >= '0' && *p <= '9') p++;
    } else {
      return err(JSON, "Expecting value");
    }
    bool is_float = false;
    if (p < end && *p == '.') {
      is_float = true;
      p++;
      if (p >= end || *p < '0' || *p > '9')
        return err(JSON, "Expecting digits after decimal point");
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      is_float = true;
      p++;
      if (p < end && (*p == '+' || *p == '-')) p++;
      if (p >= end || *p < '0' || *p > '9')
        return err(JSON, "Expecting digits in exponent");
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (is_float) {
      v.kind = JValue::Dbl;
      auto res = std::from_chars(start, p, v.d);
      if (res.ec == std::errc::result_out_of_range) {
        // Both overflow and underflow land here; strtod resolves them
        // the way Python float() does (inf vs 0/denormal).
        std::string tok(start, (size_t)(p - start));
        v.d = std::strtod(tok.c_str(), nullptr);
      } else if (res.ec != std::errc()) {
        return err(JSON, "Invalid number");
      }
    } else {
      v.kind = JValue::Int;
      v.s.assign(start, (size_t)(p - start));
      if (v.s == "-0") v.s = "0";  // repr(int("-0")) == "0"
    }
    return true;
  }

  bool parse_value(JValue& v, int depth) {
    if (depth > MAX_DEPTH)
      return err(INTERNAL, "maximum JSON nesting depth exceeded");
    ws();
    if (p >= end) return err(JSON, "Expecting value");
    char c = *p;
    if (c == '"') {
      p++;
      v.kind = JValue::Str;
      return parse_string(v.s);
    }
    if (c == '{') {
      p++;
      v.kind = JValue::Obj;
      ws();
      if (p < end && *p == '}') {
        p++;
        return true;
      }
      while (true) {
        ws();
        if (p >= end || *p != '"')
          return err(JSON, "Expecting property name in double quotes");
        p++;
        std::string key;
        if (!parse_string(key)) return false;
        ws();
        if (p >= end || *p != ':') return err(JSON, "Expecting ':'");
        p++;
        JValue child;
        if (!parse_value(child, depth + 1)) return false;
        v.obj.emplace_back(std::move(key), std::move(child));
        ws();
        if (p < end && *p == ',') {
          p++;
          continue;
        }
        if (p < end && *p == '}') {
          p++;
          return true;
        }
        return err(JSON, "Expecting ',' or '}'");
      }
    }
    if (c == '[') {
      p++;
      v.kind = JValue::Arr;
      ws();
      if (p < end && *p == ']') {
        p++;
        return true;
      }
      while (true) {
        JValue child;
        if (!parse_value(child, depth + 1)) return false;
        v.arr.push_back(std::move(child));
        ws();
        if (p < end && *p == ',') {
          p++;
          continue;
        }
        if (p < end && *p == ']') {
          p++;
          return true;
        }
        return err(JSON, "Expecting ',' or ']'");
      }
    }
    if (lit("true", 4)) {
      v.kind = JValue::True;
      return true;
    }
    if (lit("false", 5)) {
      v.kind = JValue::False;
      return true;
    }
    if (lit("null", 4)) {
      v.kind = JValue::Null;
      return true;
    }
    if (lit("NaN", 3)) {
      v.kind = JValue::Dbl;
      v.d = NAN;
      return true;
    }
    if (lit("Infinity", 8)) {
      v.kind = JValue::Dbl;
      v.d = HUGE_VAL;
      return true;
    }
    if (lit("-Infinity", 9)) {
      v.kind = JValue::Dbl;
      v.d = -HUGE_VAL;
      return true;
    }
    if (c == '-' || (c >= '0' && c <= '9')) return parse_number(v);
    return err(JSON, "Expecting value");
  }

  bool parse_document(JValue& v) {
    if (!parse_value(v, 0)) return false;
    ws();
    if (p != end) return err(JSON, "Extra data");
    return true;
  }

  // -- allocation-free validating skip (records value spans) --------------
  // The hot path: one skip pass validates the whole document exactly as
  // parse_value would, then the caller re-walks only the content/links
  // subtrees it needs (walk_object/walk_array below) and materializes
  // only matched href values.

  bool skip_string() {
    while (true) {
      if (p >= end) return err(JSON, "Unterminated string");
      unsigned char c = (unsigned char)*p;
      if (c == '"') {
        p++;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return err(JSON, "Unterminated string");
        char e = *p++;
        switch (e) {
          case '"': case '\\': case '/': case 'b': case 'f':
          case 'n': case 'r': case 't':
            break;
          case 'u': {
            if (end - p < 4) return err(JSON, "Invalid \\uXXXX escape");
            for (int i = 0; i < 4; i++)
              if (hex(p[i]) < 0) return err(JSON, "Invalid \\uXXXX escape");
            p += 4;
            break;
          }
          default:
            return err(JSON, "Invalid \\escape");
        }
        continue;
      }
      if (c < 0x20) return err(JSON, "Invalid control character in string");
      p++;
    }
  }

  bool skip_number() {
    if (p < end && *p == '-') p++;
    if (p >= end) return err(JSON, "Expecting value");
    if (*p == '0') {
      p++;
    } else if (*p >= '1' && *p <= '9') {
      while (p < end && *p >= '0' && *p <= '9') p++;
    } else {
      return err(JSON, "Expecting value");
    }
    if (p < end && *p == '.') {
      p++;
      if (p >= end || *p < '0' || *p > '9')
        return err(JSON, "Expecting digits after decimal point");
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      p++;
      if (p < end && (*p == '+' || *p == '-')) p++;
      if (p >= end || *p < '0' || *p > '9')
        return err(JSON, "Expecting digits in exponent");
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    return true;
  }

  // Skips one value; *s0/*s1 get the span (first non-ws char .. end).
  bool skip_value(int depth, const char** s0, const char** s1) {
    if (depth > MAX_DEPTH)
      return err(INTERNAL, "maximum JSON nesting depth exceeded");
    ws();
    if (p >= end) return err(JSON, "Expecting value");
    *s0 = p;
    char c = *p;
    bool ok;
    if (c == '"') {
      p++;
      ok = skip_string();
    } else if (c == '{') {
      p++;
      ws();
      if (p < end && *p == '}') {
        p++;
        ok = true;
      } else {
        ok = false;
        while (true) {
          ws();
          if (p >= end || *p != '"') {
            err(JSON, "Expecting property name in double quotes");
            break;
          }
          p++;
          if (!skip_string()) break;
          ws();
          if (p >= end || *p != ':') {
            err(JSON, "Expecting ':'");
            break;
          }
          p++;
          const char *c0, *c1;
          if (!skip_value(depth + 1, &c0, &c1)) break;
          ws();
          if (p < end && *p == ',') {
            p++;
            continue;
          }
          if (p < end && *p == '}') {
            p++;
            ok = true;
          } else {
            err(JSON, "Expecting ',' or '}'");
          }
          break;
        }
      }
    } else if (c == '[') {
      p++;
      ws();
      if (p < end && *p == ']') {
        p++;
        ok = true;
      } else {
        ok = false;
        while (true) {
          const char *c0, *c1;
          if (!skip_value(depth + 1, &c0, &c1)) break;
          ws();
          if (p < end && *p == ',') {
            p++;
            continue;
          }
          if (p < end && *p == ']') {
            p++;
            ok = true;
          } else {
            err(JSON, "Expecting ',' or ']'");
          }
          break;
        }
      }
    } else if (lit("true", 4) || lit("false", 5) || lit("null", 4) ||
               lit("NaN", 3) || lit("Infinity", 8) || lit("-Infinity", 9)) {
      ok = true;
    } else if (c == '-' || (c >= '0' && c <= '9')) {
      ok = skip_number();
    } else {
      ok = err(JSON, "Expecting value");
    }
    *s1 = p;
    return ok;
  }

  bool skip_document(const char** s0, const char** s1) {
    if (!skip_value(0, s0, s1)) return false;
    ws();
    if (p != end) return err(JSON, "Extra data");
    return true;
  }
};

// Re-walk helpers over ALREADY-VALIDATED spans (skip_document passed):
// no parse error is possible, so Fail sinks are dummies.

// Last-occurrence member span of `key` in an object span (duplicate
// keys: last wins, like json.loads -> dict). Returns false if absent.
bool span_obj_get(const char* s0, const char* s1, const char* key,
                  std::string& scratch, const char** v0, const char** v1) {
  Fail dummy{OK, ""};
  JsonParser jp{s0, s1, &dummy};
  bool found = false;
  jp.p++;  // consume '{' (caller checked *s0 == '{')
  jp.ws();
  if (jp.p < jp.end && *jp.p == '}') return false;
  while (true) {
    jp.ws();
    jp.p++;  // consume '"'
    jp.parse_string(scratch);
    jp.ws();
    jp.p++;  // consume ':'
    const char *c0, *c1;
    jp.skip_value(0, &c0, &c1);
    if (scratch == key) {
      *v0 = c0;
      *v1 = c1;
      found = true;
    }
    jp.ws();
    if (jp.p < jp.end && *jp.p == ',') {
      jp.p++;
      continue;
    }
    return found;  // '}'
  }
}

// ---------------------------------------------------------------------------
// json.dumps(..., ensure_ascii=False) rendering — Gson toString() per the
// Python spec (crawljson.py:_render): default separators, float repr.
// ---------------------------------------------------------------------------
void py_float_repr(double d, std::string& out) {
  if (std::isnan(d)) {
    out += "NaN";
    return;
  }
  if (std::isinf(d)) {
    out += d > 0 ? "Infinity" : "-Infinity";
    return;
  }
  char buf[64];
  auto res = std::to_chars(buf, buf + sizeof(buf) - 1, d,
                           std::chars_format::scientific);
  *res.ptr = '\0';  // strtol on the exponent must stop at the end
  // "d[.ddd]e±k" with shortest digits; rebuild Python repr rules from
  // (sign, digits, exp10): fixed form iff -4 <= exp10 < 16.
  char* q = buf;
  bool neg = false;
  if (*q == '-') {
    neg = true;
    q++;
  }
  std::string digits;
  int exp10 = 0;
  for (; q < res.ptr && *q != 'e'; q++) {
    if (*q != '.') digits.push_back(*q);
  }
  if (q < res.ptr) {  // *q == 'e'
    exp10 = (int)std::strtol(q + 1, nullptr, 10);
  }
  int nd = (int)digits.size();
  if (neg) out.push_back('-');
  if (exp10 >= -4 && exp10 < 16) {
    if (exp10 >= nd - 1) {
      out += digits;
      out.append((size_t)(exp10 - (nd - 1)), '0');
      out += ".0";
    } else if (exp10 >= 0) {
      out.append(digits, 0, (size_t)(exp10 + 1));
      out.push_back('.');
      out.append(digits, (size_t)(exp10 + 1), std::string::npos);
    } else {
      out += "0.";
      out.append((size_t)(-exp10 - 1), '0');
      out += digits;
    }
  } else {
    out.push_back(digits[0]);
    if (nd > 1) {
      out.push_back('.');
      out.append(digits, 1, std::string::npos);
    }
    out.push_back('e');
    out.push_back(exp10 < 0 ? '-' : '+');
    int ae = exp10 < 0 ? -exp10 : exp10;
    char eb[16];
    int en = std::snprintf(eb, sizeof(eb), "%02d", ae);
    out.append(eb, (size_t)en);
  }
}

void render_string(const std::string& s, std::string& out) {
  out.push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      default:
        if (c < 0x20) {
          char eb[8];
          std::snprintf(eb, sizeof(eb), "\\u%04x", (int)c);
          out += eb;
        } else {
          out.push_back((char)c);
        }
    }
  }
  out.push_back('"');
}

void render(const JValue& v, std::string& out) {
  switch (v.kind) {
    case JValue::Null: out += "null"; break;
    case JValue::True: out += "true"; break;
    case JValue::False: out += "false"; break;
    case JValue::Int: out += v.s; break;
    case JValue::Dbl: py_float_repr(v.d, out); break;
    case JValue::Str: render_string(v.s, out); break;
    case JValue::Arr: {
      out.push_back('[');
      bool first = true;
      for (const auto& e : v.arr) {
        if (!first) out += ", ";
        first = false;
        render(e, out);
      }
      out.push_back(']');
      break;
    }
    case JValue::Obj: {
      out.push_back('{');
      bool first = true;
      for (const auto& kv : v.obj) {
        if (!first) out += ", ";
        first = false;
        render_string(kv.first, out);
        out += ": ";
        render(kv.second, out);
      }
      out.push_back('}');
      break;
    }
  }
}

// ---------------------------------------------------------------------------
// String interner: open-addressing map whose keys live in the names blob
// (insertion-ordered ids, exactly IdMap.get_or_add).
// ---------------------------------------------------------------------------
struct Interner {
  std::string blob;
  std::vector<int64_t> offsets{0};
  std::vector<uint64_t> hashes;
  std::vector<int32_t> table;  // id+1; 0 = empty
  uint64_t mask = 0;

  Interner() { table.assign(1 << 16, 0), mask = (1 << 16) - 1; }

  static uint64_t hash(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;  // FNV-1a 64
    for (size_t i = 0; i < n; i++) {
      h ^= (uint8_t)s[i];
      h *= 1099511628211ull;
    }
    return h ? h : 1;
  }

  size_t size() const { return hashes.size(); }

  const char* name(int32_t id, int64_t* n) const {
    *n = offsets[(size_t)id + 1] - offsets[(size_t)id];
    return blob.data() + offsets[(size_t)id];
  }

  void grow() {
    std::vector<int32_t> nt((mask + 1) * 2, 0);
    uint64_t nm = nt.size() - 1;
    for (uint64_t i = 0; i <= mask; i++) {
      int32_t v = table[i];
      if (!v) continue;
      uint64_t j = hashes[(size_t)(v - 1)] & nm;
      while (nt[j]) j = (j + 1) & nm;
      nt[j] = v;
    }
    table.swap(nt);
    mask = nm;
  }

  int32_t get_or_add(const char* s, size_t n) {
    uint64_t h = hash(s, n);
    uint64_t j = h & mask;
    while (table[j]) {
      int32_t id = table[j] - 1;
      if (hashes[(size_t)id] == h) {
        int64_t len;
        const char* nm = name(id, &len);
        if ((size_t)len == n && std::memcmp(nm, s, n) == 0) return id;
      }
      j = (j + 1) & mask;
    }
    int32_t id = (int32_t)hashes.size();
    hashes.push_back(h);
    blob.append(s, n);
    offsets.push_back((int64_t)blob.size());
    table[j] = id + 1;
    if (hashes.size() * 10 > (mask + 1) * 7) grow();
    return id;
  }
};

// Object-member loop shared by the single-pass extractor: the callback
// consumes (and validates) each member's value after ``keybuf`` holds
// the decoded member name.
template <class F>
bool walk_object_members(JsonParser& jp, std::string& keybuf,
                         F consume_value) {
  jp.p++;  // '{' (caller dispatched on it)
  jp.ws();
  if (jp.p < jp.end && *jp.p == '}') {
    jp.p++;
    return true;
  }
  while (true) {
    jp.ws();
    if (jp.p >= jp.end || *jp.p != '"')
      return jp.err(JSON, "Expecting property name in double quotes");
    jp.p++;
    if (!jp.parse_string(keybuf)) return false;
    jp.ws();
    if (jp.p >= jp.end || *jp.p != ':') return jp.err(JSON, "Expecting ':'");
    jp.p++;
    if (!consume_value(keybuf)) return false;
    jp.ws();
    if (jp.p < jp.end && *jp.p == ',') {
      jp.p++;
      continue;
    }
    if (jp.p < jp.end && *jp.p == '}') {
      jp.p++;
      return true;
    }
    return jp.err(JSON, "Expecting ',' or '}'");
  }
}

// ---------------------------------------------------------------------------
// The accumulating ingest state (one handle per segment load).
//
// Two sinks: the serial path interns records directly; the threaded
// path (`crawl_ingest_files` with threads > 1) parses files in worker
// threads into per-file FileCaptures — url/target TEXT in a private
// arena, no shared state — and the main thread replays captures in
// strict file order into the interner, so ids are byte-identical to
// the serial path (the analogue of the Python process pool's
// order-identity contract, ingest/seqfile.py:iter_segment_records).
// ---------------------------------------------------------------------------
struct FileCapture {
  std::string arena;  // url + target bytes, concatenated
  struct Rec {
    int64_t url_off, url_len, n_targets;
  };
  std::vector<Rec> recs;
  std::vector<std::pair<int64_t, int64_t>> tspans;  // flattened (off, len)
  Fail fail{OK, ""};
  bool failed = false;
};

struct CrawlState {
  Interner ids;
  std::vector<int32_t> src, dst;
  std::vector<uint8_t> crawled_by_id;  // grows with ids
  int64_t num_records = 0;
  Fail fail{OK, ""};
  int64_t failed_file = -1;  // index within the last multi-file call
  FileCapture* capture = nullptr;  // non-null: capture instead of intern
  // scratch (reused across records to avoid churn)
  std::string url_text, val_text, rendered, scratch_key;
  std::string key_root, key_content, key_entry;
  // single-pass extractor state (per record). Targets are spans —
  // either into the record text (escape-free string hrefs, the common
  // case) or into owned_pool (rendered values) — valid until commit.
  struct Target {
    const char* p;
    size_t n;
  };
  std::vector<Target> targets;
  // deque: growth must not move existing strings — Target spans point
  // into them (SSO buffers live inside the string object itself)
  std::deque<std::string> owned_pool;
  size_t n_owned = 0, n_targets = 0;
  int content_count = 0, links_count = 0;
  bool dup_fallback = false, strict_cur = false;
  Fail pending{OK, ""};  // first strict entry error, deferred to commit

  enum Ctx { CTX_ROOT, CTX_CONTENT, CTX_LINKS };

  void mark_crawled(int32_t id) {
    if ((size_t)id >= crawled_by_id.size()) crawled_by_id.resize(ids.size(), 0);
    crawled_by_id[(size_t)id] = 1;
  }

  // Commit the current record (url + collected targets) to the active
  // sink. Id-assignment order — url first, then targets in order — is
  // what makes serial, threaded, and Python paths byte-identical.
  void commit_current(const std::string& url) {
    if (capture) {
      capture->recs.push_back({(int64_t)capture->arena.size(),
                               (int64_t)url.size(), (int64_t)n_targets});
      capture->arena.append(url);
      for (size_t i = 0; i < n_targets; i++) {
        capture->tspans.emplace_back((int64_t)capture->arena.size(),
                                     (int64_t)targets[i].n);
        capture->arena.append(targets[i].p, targets[i].n);
      }
      return;
    }
    num_records++;
    int32_t u = ids.get_or_add(url.data(), url.size());
    mark_crawled(u);
    for (size_t i = 0; i < n_targets; i++) {
      int32_t tid = ids.get_or_add(targets[i].p, targets[i].n);
      src.push_back(u);
      dst.push_back(tid);
    }
  }

  bool ingest_record(const std::string& url, const char* json, size_t jlen,
                     bool strict) {
    Fail jfail{OK, ""};
    JsonParser jp{json, json + jlen, &jfail};
    n_targets = n_owned = 0;
    content_count = links_count = 0;
    dup_fallback = false;
    strict_cur = strict;
    pending = {OK, ""};
    // Single validating pass that extracts along the way; the walk is
    // exactly json.loads-then-dict-walk EXCEPT when content/links keys
    // repeat (dict would keep the last), where it falls back to the
    // span re-walk.
    jp.ws();
    const char* d0 = jp.p;
    bool ok = xvalue(jp, 0, CTX_ROOT);
    const char* d1 = jp.p;
    if (ok) {
      jp.ws();
      if (jp.p != jp.end) ok = jp.err(JSON, "Extra data");
    }
    if (!ok) {
      // JSON errors beat deferred entry errors (Python parses first).
      if (jfail.cat == INTERNAL || strict) {
        fail = jfail;
        return false;
      }
      n_targets = 0;
      commit_current(url);  // non-strict: record kept, no targets
      return true;
    }
    if (dup_fallback) {
      n_targets = n_owned = 0;
      if (!extract_span(d0, d1, strict)) return false;
    } else if (pending.cat != OK) {  // set only under strict
      fail = pending;
      return false;
    }
    commit_current(url);
    return true;
  }

  bool xvalue(JsonParser& jp, int depth, int ctx) {
    if (depth > MAX_DEPTH)
      return jp.err(INTERNAL, "maximum JSON nesting depth exceeded");
    jp.ws();
    if (jp.p >= jp.end) return jp.err(JSON, "Expecting value");
    char c = *jp.p;
    if (ctx == CTX_ROOT && c == '{') {
      return walk_object_members(jp, key_root, [&](const std::string& k) {
        if (k == "content") {
          if (++content_count > 1) dup_fallback = true;
          n_targets = n_owned = 0;
          links_count = 0;
          return xvalue(jp, depth + 1, CTX_CONTENT);
        }
        const char *a, *b;
        return jp.skip_value(depth + 1, &a, &b);
      });
    }
    if (ctx == CTX_CONTENT && c == '{') {
      return walk_object_members(jp, key_content, [&](const std::string& k) {
        if (k == "links") {
          if (++links_count > 1) dup_fallback = true;
          n_targets = n_owned = 0;
          return xvalue(jp, depth + 1, CTX_LINKS);
        }
        const char *a, *b;
        return jp.skip_value(depth + 1, &a, &b);
      });
    }
    if (ctx == CTX_LINKS && c == '[') {
      jp.p++;
      jp.ws();
      if (jp.p < jp.end && *jp.p == ']') {
        jp.p++;
        return true;
      }
      while (true) {
        if (!xentry(jp, depth + 1)) return false;
        jp.ws();
        if (jp.p < jp.end && *jp.p == ',') {
          jp.p++;
          continue;
        }
        if (jp.p < jp.end && *jp.p == ']') {
          jp.p++;
          return true;
        }
        return jp.err(JSON, "Expecting ',' or ']'");
      }
    }
    // Shape didn't match the crawl path at this level: plain skip.
    const char *a, *b;
    return jp.skip_value(depth, &a, &b);
  }

  bool xentry(JsonParser& jp, int depth) {
    jp.ws();
    if (jp.p >= jp.end) return jp.err(JSON, "Expecting value");
    if (*jp.p != '{') {  // entry["href"] on a non-dict -> TypeError
      const char *a, *b;
      if (!jp.skip_value(depth, &a, &b)) return false;
      if (strict_cur && pending.cat == OK)
        pending = {TYPE, "link entry is not an object"};
      return true;
    }
    const char *h0 = nullptr, *h1 = nullptr, *t0 = nullptr, *t1 = nullptr;
    bool ok = walk_object_members(jp, key_entry, [&](const std::string& k) {
      const char *a, *b;
      if (!jp.skip_value(depth + 1, &a, &b)) return false;
      if (k == "href") {  // duplicate member: last wins (dict semantics)
        h0 = a;
        h1 = b;
      } else if (k == "type") {
        t0 = a;
        t1 = b;
      }
      return true;
    });
    if (!ok) return false;
    if (!h0 || !t0) {
      if (strict_cur && pending.cat == OK)
        pending = {KEY, !h0 ? "href" : "type"};
      return true;
    }
    // _render(type) == '"a"'  <=>  type is the JSON string "a".
    if (*t0 != '"') return true;
    if (t1 - t0 == 3) {  // unescaped token: exactly "a"
      if (t0[1] != 'a') return true;
    } else {
      Fail dummy{OK, ""};
      JsonParser tp{t0 + 1, t1, &dummy};
      tp.parse_string(scratch_key);
      if (scratch_key != "a") return true;
    }
    push_target_value(h0, h1);
    return true;
  }

  // Push a matched href value span onto the per-record target list —
  // shared tail of the single-pass and span-walk extractors.
  void push_target_value(const char* h0, const char* h1) {
    if (n_targets == targets.size()) targets.emplace_back();
    // Fast path: an escape-free string href re-renders to its own raw
    // bytes (dumps adds nothing, and it can contain no quote — one
    // would have ended the token), so the span itself is the target.
    if (*h0 == '"' &&
        std::memchr(h0 + 1, '\\', (size_t)(h1 - h0 - 2)) == nullptr) {
      targets[n_targets++] = {h0 + 1, (size_t)(h1 - h0 - 2)};
      return;
    }
    // Slow path: materialize + render.
    Fail dummy{OK, ""};
    JValue href;
    JsonParser hp{h0, h1, &dummy};
    hp.parse_value(href, 0);
    if (n_owned == owned_pool.size()) owned_pool.emplace_back();
    std::string& out = owned_pool[n_owned++];
    out.clear();
    render(href, out);
    // Sparky.java:105 strips every double quote from the rendering.
    out.erase(std::remove(out.begin(), out.end(), '"'), out.end());
    targets[n_targets++] = {out.data(), out.size()};
  }

  // Link extraction over a validated value span — the crawljson.py walk:
  // root["content"]["links"][i]{"type" == "a"} -> render(href). Fills
  // `targets`; the caller commits.
  bool extract_span(const char* s0, const char* s1, bool strict) {
    if (s0 >= s1 || *s0 != '{') return true;  // root not an object
    const char *c0, *c1;
    if (!span_obj_get(s0, s1, "content", scratch_key, &c0, &c1)) return true;
    if (*c0 != '{') return true;
    const char *l0, *l1;
    if (!span_obj_get(c0, c1, "links", scratch_key, &l0, &l1)) return true;
    if (*l0 != '[') return true;
    // Walk the links array (validated; no parse errors possible).
    Fail dummy{OK, ""};
    JsonParser jp{l0, l1, &dummy};
    jp.p++;  // '['
    jp.ws();
    if (jp.p < jp.end && *jp.p == ']') return true;
    while (true) {
      const char *e0, *e1;
      jp.skip_value(0, &e0, &e1);
      if (!handle_entry(e0, e1, strict)) return false;
      jp.ws();
      if (jp.p < jp.end && *jp.p == ',') {
        jp.p++;
        continue;
      }
      return true;  // ']'
    }
  }

  bool handle_entry(const char* e0, const char* e1, bool strict) {
    if (*e0 != '{') {  // entry["href"] on a non-dict -> TypeError
      if (strict) {
        fail = {TYPE, "link entry is not an object"};
        return false;
      }
      return true;
    }
    const char *h0, *h1, *t0, *t1;
    bool has_href = span_obj_get(e0, e1, "href", scratch_key, &h0, &h1);
    bool has_type = span_obj_get(e0, e1, "type", scratch_key, &t0, &t1);
    if (!has_href || !has_type) {
      if (strict) {
        fail = {KEY, !has_href ? "href" : "type"};
        return false;
      }
      return true;
    }
    // _render(type) == '"a"'  <=>  type is the JSON string "a".
    if (*t0 != '"') return true;
    Fail dummy{OK, ""};
    JsonParser tp{t0 + 1, t1, &dummy};
    tp.parse_string(scratch_key);
    if (scratch_key != "a") return true;
    push_target_value(h0, h1);
    return true;
  }
};

// ---------------------------------------------------------------------------
// SequenceFile container walk — mirrors ingest/seqfile.py exactly.
// ---------------------------------------------------------------------------
const char TEXT_CLASS[] = "org.apache.hadoop.io.Text";

bool is_deflate_codec(const uint8_t* s, int64_t n) {
  static const char* CODECS[] = {
      "org.apache.hadoop.io.compress.DefaultCodec",
      "org.apache.hadoop.io.compress.DeflateCodec",
  };
  for (const char* c : CODECS)
    if ((size_t)n == std::strlen(c) && std::memcmp(s, c, (size_t)n) == 0)
      return true;
  return false;
}

bool seq_fail(CrawlState& st, ErrCat cat, const char* msg) {
  st.fail = {cat, msg};
  return false;
}

bool text_fail(CrawlState& st, TextRead rc, const char* what) {
  return seq_fail(st, rc == TEXT_NEG ? FORMAT : EOF_, what);
}

// One decoded (key, value) record -> crawl record.
bool seq_record(CrawlState& st, const uint8_t* kraw, int64_t kn,
                const uint8_t* vraw, int64_t vn, bool strict) {
  // Python ignores trailing bytes after each Text payload.
  Span ks{kraw, kraw + kn};
  const uint8_t* kp;
  int64_t klen;
  TextRead rc = read_text_raw(ks, &kp, &klen);
  if (rc != TEXT_OK) return text_fail(st, rc, "truncated record (key Text)");
  Span vs{vraw, vraw + vn};
  const uint8_t* vp;
  int64_t vlen;
  rc = read_text_raw(vs, &vp, &vlen);
  if (rc != TEXT_OK) return text_fail(st, rc, "truncated record (value Text)");
  utf8_replace(kp, (size_t)klen, st.url_text);
  utf8_replace(vp, (size_t)vlen, st.val_text);
  return st.ingest_record(st.url_text, st.val_text.data(), st.val_text.size(),
                          strict);
}

bool ingest_seqfile(CrawlState& st, const uint8_t* data, int64_t len,
                    bool strict) {
  Span s{data, data + len};
  const uint8_t* magic;
  if (!s.take(4, &magic) || std::memcmp(magic, "SEQ", 3) != 0)
    return seq_fail(st, FORMAT, "not a SequenceFile (bad magic)");
  if (magic[3] != 6)
    return seq_fail(st, FORMAT, "unsupported SequenceFile version");
  // Read BOTH class names before validating either — the Python
  // reader does (corrupt headers must fail at the same stage with the
  // same exception class; the fuzz in tests/test_native_crawl.py
  // caught the early-validation order).
  const uint8_t* cls[2];
  int64_t cn[2];
  for (int i = 0; i < 2; i++) {
    TextRead rc = read_text_raw(s, &cls[i], &cn[i]);
    if (rc != TEXT_OK) return text_fail(st, rc, "truncated header (class name)");
  }
  for (int i = 0; i < 2; i++) {
    if ((size_t)cn[i] != std::strlen(TEXT_CLASS) ||
        std::memcmp(cls[i], TEXT_CLASS, (size_t)cn[i]) != 0)
      return seq_fail(st, FORMAT, "expected Text/Text classes");
  }
  const uint8_t* flags;
  if (!s.take(2, &flags))
    return seq_fail(st, EOF_, "truncated header (flags)");
  bool compressed = flags[0] != 0;
  bool block_compressed = flags[1] != 0;
  if (compressed) {
    const uint8_t* codec;
    int64_t codn;
    TextRead rc = read_text_raw(s, &codec, &codn);
    if (rc != TEXT_OK) return text_fail(st, rc, "truncated header (codec)");
    if (!is_deflate_codec(codec, codn))
      return seq_fail(st, FORMAT, "unsupported codec");
  }
  int32_t n_meta;
  if (!read_i32(s, &n_meta))
    return seq_fail(st, EOF_, "truncated metadata count");
  // 64-bit loop bound: a corrupt count near INT32_MAX must walk (and
  // fail at EOF) like the Python reader, not overflow n_meta * 2.
  for (int64_t i = 0; i < (int64_t)n_meta * 2; i++) {
    const uint8_t* m;
    int64_t mn;
    TextRead rc = read_text_raw(s, &m, &mn);
    if (rc != TEXT_OK) return text_fail(st, rc, "truncated metadata");
  }
  const uint8_t* sync;
  if (!s.take(16, &sync))
    return seq_fail(st, EOF_, "truncated header (sync marker)");

  std::string kinf, vinf, klinf, vlinf, vrecinf;
  if (block_compressed) {
    // Checked HERE, not at the flags: the Python reader only rejects a
    // codec-less block file when it enters the block loop, after the
    // metadata/sync parse — corrupt headers must fail at the same
    // stage with the same class.
    if (!compressed)
      return seq_fail(st, FORMAT,
                      "block-compressed flag set without a codec");
    while (s.left() > 0) {
      if (s.left() < 4) return true;  // clean EOF between blocks
      int32_t head;
      read_i32(s, &head);
      if (head != -1)
        return seq_fail(st, FORMAT, "expected block sync escape");
      const uint8_t* marker;
      if (!s.take(16, &marker))
        return seq_fail(st, EOF_, "truncated block sync marker");
      if (std::memcmp(marker, sync, 16) != 0)
        return seq_fail(st, FORMAT, "sync marker mismatch (corrupt file)");
      int64_t n_rec;
      if (!read_vint(s, &n_rec))
        return seq_fail(st, EOF_, "truncated block record count");
      if (n_rec < 0) return seq_fail(st, FORMAT, "bad block record count");
      std::string* bufs[4] = {&klinf, &kinf, &vlinf, &vinf};
      for (auto* buf : bufs) {
        const uint8_t* comp;
        int64_t compn;
        TextRead rc = read_text_raw(s, &comp, &compn);
        if (rc == TEXT_NEG)
          return seq_fail(st, FORMAT, "bad block buffer length");
        if (rc != TEXT_OK)
          return seq_fail(st, EOF_, "truncated block buffer");
        if (!inflate_all(comp, (size_t)compn, *buf))
          return seq_fail(st, ZLIB, "bad deflate stream in block");
      }
      Span kls{(const uint8_t*)klinf.data(),
               (const uint8_t*)klinf.data() + klinf.size()};
      Span ks{(const uint8_t*)kinf.data(),
              (const uint8_t*)kinf.data() + kinf.size()};
      Span vls{(const uint8_t*)vlinf.data(),
               (const uint8_t*)vlinf.data() + vlinf.size()};
      Span vs{(const uint8_t*)vinf.data(),
              (const uint8_t*)vinf.data() + vinf.size()};
      for (int64_t i = 0; i < n_rec; i++) {
        int64_t klen, vlen;
        const uint8_t *kraw, *vraw;
        // Python: _read_vint EOF -> EOFError; short payload reads ->
        // "truncated block record" EOFError; negative -> Text length
        // ValueError happens inside seq_record's VInt (not here, the
        // buffer lengths are plain VInts with no sign check in the
        // Python reader -- a negative reads 0 bytes then fails the
        // length check as EOFError).
        if (!read_vint(kls, &klen))
          return seq_fail(st, EOF_, "truncated block record");
        if (!ks.take((size_t)(klen < 0 ? 0 : klen), &kraw) || klen < 0)
          return seq_fail(st, EOF_, "truncated block record");
        if (!read_vint(vls, &vlen))
          return seq_fail(st, EOF_, "truncated block record");
        if (!vs.take((size_t)(vlen < 0 ? 0 : vlen), &vraw) || vlen < 0)
          return seq_fail(st, EOF_, "truncated block record");
        if (!seq_record(st, kraw, klen, vraw, vlen, strict)) return false;
      }
    }
    return true;
  }

  while (true) {
    if (s.left() < 4) return true;  // clean EOF
    int32_t rec_len;
    read_i32(s, &rec_len);
    if (rec_len == -1) {
      const uint8_t* marker;
      if (!s.take(16, &marker))
        return seq_fail(st, EOF_, "truncated sync marker");
      if (std::memcmp(marker, sync, 16) != 0)
        return seq_fail(st, FORMAT, "sync marker mismatch (corrupt file)");
      continue;
    }
    if (rec_len < 0) return seq_fail(st, FORMAT, "bad record length");
    int32_t key_len;
    if (!read_i32(s, &key_len))
      return seq_fail(st, EOF_, "truncated key length");
    if (key_len < 0 || key_len > rec_len)
      return seq_fail(st, FORMAT, "bad key length");
    const uint8_t *kraw, *vraw;
    if (!s.take((size_t)key_len, &kraw) ||
        !s.take((size_t)(rec_len - key_len), &vraw))
      return seq_fail(st, EOF_, "truncated record");
    int64_t vn = rec_len - key_len;
    if (compressed) {
      if (!inflate_all(vraw, (size_t)vn, vrecinf))
        return seq_fail(st, ZLIB, "bad deflate stream in record");
      if (!seq_record(st, kraw, key_len, (const uint8_t*)vrecinf.data(),
                      (int64_t)vrecinf.size(), strict))
        return false;
    } else {
      if (!seq_record(st, kraw, key_len, vraw, vn, strict)) return false;
    }
  }
}

// ---------------------------------------------------------------------------
// TSV / JSONL crawl files (crawljson.py:iter_crawl_records): decoded with
// utf-8 replace + universal newlines; url<TAB>json lines, or JSONL
// objects with "url" + "metadata"/"json" members.
// ---------------------------------------------------------------------------
bool ingest_tsv(CrawlState& st, const uint8_t* data, int64_t len, bool strict) {
  std::string text;
  utf8_replace(data, (size_t)len, text);
  const char* p = text.data();
  const char* end = p + text.size();
  std::string line;
  while (p < end) {
    // Universal newlines: \n, \r\n, or \r all end a line.
    const char* q = p;
    while (q < end && *q != '\n' && *q != '\r') q++;
    line.assign(p, (size_t)(q - p));
    if (q < end) {
      if (*q == '\r' && q + 1 < end && q[1] == '\n') q++;
      q++;
    }
    p = q;
    if (line.empty()) continue;
    size_t tab = line.find('\t');
    if (tab != std::string::npos) {
      st.url_text.assign(line, 0, tab);
      if (!st.ingest_record(st.url_text, line.data() + tab + 1,
                            line.size() - tab - 1, strict))
        return false;
      continue;
    }
    // JSONL: json.loads(line) errors ALWAYS raise (outside the strict
    // try in the Python path), as do a non-object root / missing url.
    Fail jfail{OK, ""};
    JsonParser jp{line.data(), line.data() + line.size(), &jfail};
    const char *d0, *d1;
    if (!jp.skip_document(&d0, &d1)) {
      st.fail = jfail;
      return false;
    }
    if (*d0 != '{') {
      st.fail = {TYPE, "JSONL record is not an object"};
      return false;
    }
    const char *u0, *u1;
    if (!span_obj_get(d0, d1, "url", st.scratch_key, &u0, &u1)) {
      st.fail = {KEY, "url"};
      return false;
    }
    if (*u0 != '"') {
      // Python succeeds here (the parsed value becomes the dict key);
      // non-string names are unrepresentable in the native interner,
      // so the wrapper falls back to the Python path for this load.
      st.fail = {UNSUPPORTED, "JSONL url is not a string"};
      return false;
    }
    Fail dummy{OK, ""};
    JsonParser up{u0 + 1, u1, &dummy};
    up.parse_string(st.url_text);
    const char *m0 = nullptr, *m1 = nullptr;
    bool has_meta =
        span_obj_get(d0, d1, "metadata", st.scratch_key, &m0, &m1) ||
        span_obj_get(d0, d1, "json", st.scratch_key, &m0, &m1);
    st.n_targets = st.n_owned = 0;
    if (has_meta && !st.extract_span(m0, m1, strict)) return false;
    st.commit_current(st.url_text);
  }
  return true;
}

bool ingest_one(CrawlState& st, const uint8_t* data, int64_t len,
                int32_t kind, bool strict) {
  return kind == 0 ? ingest_seqfile(st, data, len, strict)
                   : ingest_tsv(st, data, len, strict);
}

// Parallel multi-file ingest: worker threads parse files into private
// FileCaptures (a bounded window of files in flight caps memory), the
// calling thread replays captures in file order into the interner —
// ids and edges byte-identical to the serial path. On a strict error
// the EARLIEST failing file in input order wins, like the serial walk
// (later files may have been parsed speculatively; their captures are
// discarded, which is side-effect-free).
bool ingest_files_threaded(CrawlState& st, int64_t n_files,
                           const uint8_t* const* datas, const int64_t* lens,
                           int32_t kind, bool strict, int32_t threads) {
  int64_t window = (int64_t)threads * 2;
  for (int64_t w0 = 0; w0 < n_files; w0 += window) {
    int64_t w1 = std::min(n_files, w0 + window);
    std::vector<FileCapture> caps((size_t)(w1 - w0));
    std::atomic<int64_t> next{w0};
    int nt = (int)std::min<int64_t>(threads, w1 - w0);
    std::vector<std::thread> ths;
    for (int t = 0; t < nt; t++) {
      ths.emplace_back([&] {
        CrawlState worker;  // scratch only; its interner stays empty
        while (true) {
          int64_t i = next.fetch_add(1);
          if (i >= w1) return;
          FileCapture& cap = caps[(size_t)(i - w0)];
          worker.capture = &cap;
          worker.fail = {OK, ""};
          if (!ingest_one(worker, datas[i], lens[i], kind, strict)) {
            cap.failed = true;
            cap.fail = worker.fail;
          }
        }
      });
    }
    for (auto& th : ths) th.join();
    for (int64_t i = w0; i < w1; i++) {
      FileCapture& cap = caps[(size_t)(i - w0)];
      if (cap.failed) {
        st.fail = cap.fail;
        st.failed_file = i;
        return false;
      }
      size_t toff = 0;
      for (const FileCapture::Rec& rec : cap.recs) {
        st.num_records++;
        int32_t u = st.ids.get_or_add(cap.arena.data() + rec.url_off,
                                      (size_t)rec.url_len);
        st.mark_crawled(u);
        for (int64_t j = 0; j < rec.n_targets; j++) {
          const auto& sp = cap.tspans[toff++];
          int32_t tid = st.ids.get_or_add(cap.arena.data() + sp.first,
                                          (size_t)sp.second);
          st.src.push_back(u);
          st.dst.push_back(tid);
        }
      }
    }
  }
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------
extern "C" {

void* crawl_new() { return new CrawlState(); }

void crawl_free(void* h) { delete static_cast<CrawlState*>(h); }

static int64_t finish_ingest(CrawlState* st, bool ok) {
  if (ok && (st->ids.size() > (size_t)INT32_MAX ||
             st->src.size() > (size_t)INT32_MAX)) {
    st->fail = {INTERNAL, "more than 2^31 vertices or edges"};
    ok = false;
  }
  return ok ? OK : st->fail.cat;
}

// kind: 0 = SequenceFile bytes, 1 = TSV/JSONL text bytes.
// Returns the error category (0 = ok); message via crawl_error.
int64_t crawl_ingest_file(void* h, const uint8_t* data, int64_t len,
                          int32_t kind, int32_t strict) {
  auto* st = static_cast<CrawlState*>(h);
  st->fail = {OK, ""};
  return finish_ingest(st, ingest_one(*st, data, len, kind, strict != 0));
}

// Batched multi-file form; threads > 1 parses files in parallel with
// file-ordered interning (see ingest_files_threaded).
int64_t crawl_ingest_files(void* h, int64_t n_files, const uint8_t** datas,
                           const int64_t* lens, int32_t kind, int32_t strict,
                           int32_t threads) {
  auto* st = static_cast<CrawlState*>(h);
  st->fail = {OK, ""};
  st->failed_file = -1;
  bool ok = true;
  if (threads <= 1 || n_files <= 1) {
    for (int64_t i = 0; ok && i < n_files; i++) {
      ok = ingest_one(*st, datas[i], lens[i], kind, strict != 0);
      if (!ok) st->failed_file = i;
    }
  } else {
    ok = ingest_files_threaded(*st, n_files, datas, lens, kind, strict != 0,
                               threads);
  }
  return finish_ingest(st, ok);
}

const char* crawl_error(void* h) {
  return static_cast<CrawlState*>(h)->fail.msg.c_str();
}

// Index of the failing file within the last crawl_ingest_files call
// (-1 when it succeeded) — error messages name the actual culprit.
int64_t crawl_failed_index(void* h) {
  return static_cast<CrawlState*>(h)->failed_file;
}

int64_t crawl_num_edges(void* h) {
  return (int64_t)static_cast<CrawlState*>(h)->src.size();
}

int64_t crawl_num_vertices(void* h) {
  return (int64_t)static_cast<CrawlState*>(h)->ids.size();
}

int64_t crawl_num_records(void* h) {
  return static_cast<CrawlState*>(h)->num_records;
}

void crawl_copy_edges(void* h, int32_t* src, int32_t* dst) {
  auto* st = static_cast<CrawlState*>(h);
  if (!st->src.empty()) {
    std::memcpy(src, st->src.data(), st->src.size() * sizeof(int32_t));
    std::memcpy(dst, st->dst.data(), st->dst.size() * sizeof(int32_t));
  }
}

// Copies the edges accumulated since the last drain and RELEASES them
// (the interner and crawled flags persist) — the out-of-core crawl
// build's per-batch spill hook (ingest/native.crawl_load_external):
// edge memory stays bounded by the batch while the vertex table keeps
// growing file-ordered. Returns the drained count.
int64_t crawl_drain_edges(void* h, int32_t* src, int32_t* dst) {
  auto* st = static_cast<CrawlState*>(h);
  int64_t e = (int64_t)st->src.size();
  if (e) {
    std::memcpy(src, st->src.data(), e * sizeof(int32_t));
    std::memcpy(dst, st->dst.data(), e * sizeof(int32_t));
  }
  std::vector<int32_t>().swap(st->src);
  std::vector<int32_t>().swap(st->dst);
  return e;
}

void crawl_copy_crawled(void* h, uint8_t* mask) {
  auto* st = static_cast<CrawlState*>(h);
  size_t n = st->ids.size();
  std::memset(mask, 0, n);
  std::memcpy(mask, st->crawled_by_id.data(),
              std::min(n, st->crawled_by_id.size()));
}

int64_t crawl_names_blob_size(void* h) {
  return (int64_t)static_cast<CrawlState*>(h)->ids.blob.size();
}

void crawl_copy_names(void* h, char* blob, int64_t* offsets) {
  auto* st = static_cast<CrawlState*>(h);
  std::memcpy(blob, st->ids.blob.data(), st->ids.blob.size());
  std::memcpy(offsets, st->ids.offsets.data(),
              st->ids.offsets.size() * sizeof(int64_t));
}

}  // extern "C"
