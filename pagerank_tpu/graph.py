"""Host-side graph construction (L2).

Replaces the reference's RDD graph build (`Sparky.java:78-184`):
  - edge dedup + adjacency build (`.distinct().groupByKey()`, Sparky.java:124)
  - vertex-universe completion: sources ∪ targets ∪ crawled-but-linkless
    pages (Sparky.java:137-161)
  - dangling set: `dangUrls` additions (Sparky.java:114-118,147-150) minus
    the repair pass (:172-184). Because `JavaPairRDD.lookup` returns the
    *list of values* for a key, a crawled linkless page's lookup yields a
    non-null Iterable([null]) and the repair pass REMOVES it; only
    uncrawled targets (stored value literally null, :149) survive. The
    post-repair dangling-mass set is therefore exactly the *uncrawled
    targets* — vertices that never appear as a crawl source. For pure
    edge-list inputs every source has out-degree > 0, so this coincides
    with out_degree == 0 (the default mask); crawl ingestion passes an
    explicit ~crawled mask instead.
  - the "missing-key retention" mask z = (in_degree == 0) needed by the
    reference's `subtractByKey` quirk (Sparky.java:224-225)

The device-facing representation is a deduplicated COO edge list sorted
by destination (CSC order) so the per-iteration scatter-add is a sorted
segment-sum, plus per-edge contribution weights w[e] = 1/out_degree[src[e]].
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from pagerank_tpu.obs import trace as obs_trace


@dataclass
class Graph:
    """A directed graph in destination-sorted COO form.

    Attributes:
      n: number of vertices (the reference's ``totalUrlCount``,
         Sparky.java:162).
      src, dst: int32 [num_edges] deduplicated edges, sorted by (dst, src).
      out_degree: int32 [n] — number of *unique* targets per source
         (dedup before out-degree, Sparky.java:124; self-loops kept).
      in_degree: int32 [n].
      dangling_mask: bool [n] — the reference's ``dangUrls`` after its
         repair pass (Sparky.java:172-184): uncrawled targets. Defaults
         to out_degree == 0 (exact for edge-list inputs); crawl
         ingestion overrides it with ~crawled.
      zero_in_mask: bool [n] — in_degree == 0 (vertices that receive no
         contributions; the reference re-feeds them their old rank via
         ``subtractByKey``, Sparky.java:224-225).
      edge_weight: float64 [num_edges] — 1 / out_degree[src[e]].
      vertex_names: optional id->name table when built from string keys.
    """

    n: int
    src: np.ndarray
    dst: np.ndarray
    out_degree: np.ndarray
    in_degree: np.ndarray
    dangling_mask: np.ndarray
    zero_in_mask: np.ndarray
    edge_weight: np.ndarray
    vertex_names: Optional[Sequence[str]] = field(default=None, repr=False)

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def fingerprint(self) -> str:
        """Stable hash of the graph structure, used to validate that a
        checkpoint being resumed matches the graph (utils/snapshot.py).

        The dangling mask is hashed ONLY when it differs from the
        edge-derivable default (out_degree == 0): for crawl inputs the
        mask is a semantic input in its own right (uncrawled targets,
        SURVEY §2a.3) and identical edge sets must not cross-validate —
        while edge-list graphs keep their pre-override fingerprints, so
        existing snapshots still resume."""
        import hashlib

        h = hashlib.sha256()
        h.update(np.int64(self.n).tobytes())
        h.update(self.src.tobytes())
        h.update(self.dst.tobytes())
        if not np.array_equal(self.dangling_mask, self.out_degree == 0):
            h.update(np.packbits(self.dangling_mask).tobytes())
        return h.hexdigest()[:16]


def build_graph(
    src: np.ndarray,
    dst: np.ndarray,
    n: Optional[int] = None,
    extra_vertices: Optional[np.ndarray] = None,
    dedup: bool = True,
    dangling_mask: Optional[np.ndarray] = None,
    vertex_names: Optional[Sequence[str]] = None,
    use_native_sort: Optional[bool] = None,
) -> Graph:
    """Build a :class:`Graph` from raw (src, dst) edge arrays.

    Mirrors the reference's graph-construction semantics:
      - duplicate (src, dst) edges collapse before out-degree is counted
        (``.distinct()``, Sparky.java:124);
      - the vertex universe is sources ∪ targets ∪ ``extra_vertices``
        (crawled pages with no anchor links — the reference's dangling
        sentinel rows, Sparky.java:114-118 — and linked-to-but-uncrawled
        targets, Sparky.java:137-161);
      - self-loops are *not* filtered (SURVEY.md §2a.5).

    Args:
      src, dst: integer edge arrays of equal length.
      n: vertex count; inferred as max id + 1 when omitted.
      extra_vertices: ids of vertices with no edges that must still exist.
      dedup: collapse duplicate edges (reference behavior). Disable only
        for pre-deduplicated inputs.
      dangling_mask: explicit dangling-mass membership (the post-repair
        ``dangUrls``). Default: out_degree == 0, which equals the
        reference semantics for edge-list inputs; crawl ingestion passes
        ~crawled because the repair pass un-dangles every crawled page
        (see module docstring).
      use_native_sort: route dedup+sort through the C++ radix sorter
        (native/fast_ingest.cpp). Default None = AUTO: engage when the
        native library is available and either the host has >1 core
        and >= 2^22 edges (the sorter is multithreaded), or the input
        is >= 2^27 edges even single-core — measured end to end on this
        1-core image (unloaded): ~parity at 16-67M edges, radix 1.40x
        at 537M (195s -> 139s; the numpy path's int64 key divmod and
        sort working set blow up past ~100M edges). docs/PERF_NOTES.md
        "Host ingest".
    """
    t_build0 = time.perf_counter()
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    if src.shape != dst.shape:
        raise ValueError(f"src/dst length mismatch: {src.shape} vs {dst.shape}")

    if n is None:
        n = 0
        for arr in (src, dst, extra_vertices):
            if arr is not None and len(arr) > 0:
                n = max(n, int(np.max(arr)) + 1)
    n = int(n)
    if n == 0:
        raise ValueError("empty graph: no vertices")

    if len(src) > 0 and (src.min() < 0 or src.max() >= n or dst.min() < 0 or dst.max() >= n):
        raise ValueError("edge endpoint out of range [0, n)")

    # Dedup + sort by (dst, src) in one pass via a packed 64-bit key.
    # dst-major ordering makes the per-iteration scatter a *sorted*
    # segment-sum (fast path on TPU). Large inputs take the native C++
    # radix-sort path (native/fast_ingest.cpp) when available.
    out_degree = in_degree = None
    if len(src) > 0:
        native_out = None
        if use_native_sort is None:
            import os

            use_native_sort = (
                ((os.cpu_count() or 1) > 1 and len(src) >= (1 << 22))
                or len(src) >= (1 << 27)
            )
        if dedup and use_native_sort:
            from pagerank_tpu.ingest import native as native_lib

            native_out = native_lib.sort_dedup_degrees_native(src, dst, n)
        if native_out is not None:
            src_s, dst_s, out_degree, in_degree = native_out
        else:
            key = dst * np.int64(n) + src
            if dedup:
                key = np.unique(key)  # unique() also sorts
            else:
                key = np.sort(key, kind="stable")
            dst_s = (key // n).astype(np.int32)
            src_s = (key % n).astype(np.int32)
    else:
        src_s = np.zeros(0, dtype=np.int32)
        dst_s = np.zeros(0, dtype=np.int32)

    if out_degree is None:
        out_degree = np.bincount(src_s, minlength=n).astype(np.int32)
        in_degree = np.bincount(dst_s, minlength=n).astype(np.int32)

    if dangling_mask is None:
        dangling_mask = out_degree == 0
    else:
        dangling_mask = np.ascontiguousarray(dangling_mask, dtype=bool)
        if dangling_mask.shape != (n,):
            raise ValueError(f"dangling_mask shape {dangling_mask.shape} != ({n},)")
        if np.any(dangling_mask & (out_degree > 0)):
            raise ValueError("dangling_mask marks a vertex that has out-edges")
    zero_in_mask = in_degree == 0

    edge_weight = inv_out_degree(out_degree)[src_s]

    # Recorded as a pre-measured span (no behavior change when tracing
    # is off): the host build is a single stage from the trace's point
    # of view — its internal sort/pack split lives in PERF_NOTES, the
    # device build's per-stage spans in ops/device_build.
    tracer = obs_trace.get_tracer()
    if tracer.enabled:
        tracer.add_span(
            "build/host_graph", t_build0,
            time.perf_counter() - t_build0, n=n, edges=int(len(src_s)),
        )
    return Graph(
        n=n,
        src=src_s,
        dst=dst_s,
        out_degree=out_degree,
        in_degree=in_degree,
        dangling_mask=dangling_mask,
        zero_in_mask=zero_in_mask,
        edge_weight=edge_weight,
        vertex_names=vertex_names,
    )


def inv_out_degree(out_degree, xp=np, dtype=None):
    """``1/out_degree`` with 0 where out_degree == 0 — the row
    normalization of Aᵀ (the reference's rank/out_degree scatter,
    Sparky.java:207). Works for numpy and jax.numpy; the single home for
    this formula (used by graph build, both engines, and the on-device
    builder)."""
    deg = out_degree
    if dtype is not None:
        deg = deg.astype(dtype)
    else:
        deg = deg.astype(xp.float64 if xp is np else xp.float32)
    if xp is np:
        with np.errstate(divide="ignore"):
            return np.where(out_degree > 0, 1.0 / deg, 0.0)
    return xp.where(out_degree > 0, 1.0 / deg, 0.0)


def to_csr_transpose(graph: Graph):
    """The row-normalized adjacency, transposed, as ``scipy.sparse.csr_matrix``.

    ``A_T[d, s] = 1/out_degree[s]`` for each edge s->d, so the reference's
    contribution scatter + reduceByKey (Sparky.java:192-229) is exactly
    ``A_T @ r``. Used by the CPU oracle engine.
    """
    from scipy import sparse

    return sparse.csr_matrix(
        (graph.edge_weight, (graph.dst, graph.src)),
        shape=(graph.n, graph.n),
    )
