"""Out-of-core host graph build (VERDICT r3 missing #2).

`graph.build_graph` materializes the raw edges, the packed sort keys,
and the sort's working set in one address space — measured 25.7 GB peak
at 537M edges (docs/PERF_NOTES.md "Host ingest"), ~70 GB-class at
Twitter-2010's 1.47B, Common-Crawl-scale impossible. The reference
never holds the edge set in one space: Spark streams partitions from S3
through the shuffle (Sparky.java:61,124). This module is the host-side
analogue: an external-sort dedup whose WORKING memory is bounded by a
configurable cap, independent of edge count.

Pipeline (classic external sort, numpy-vectorized):

  1. **Spill**: stream (src, dst) chunks sized from the cap; pack each
     into ``(dst << 32) | src`` uint64 keys (exactly the (dst, src)
     total order build_graph sorts by), `np.unique` the chunk, spill
     the sorted run to a temp file.
  2. **Merge**: windowed k-way merge of the sorted runs — load bounded
     blocks per run, cut at the smallest loaded block-max, sort+unique
     the window (duplicates across runs collapse here), stream the
     window out: accumulate out/in-degrees and append the final int32
     (src, dst) arrays.

Peak RSS = the final Graph arrays (16 B/edge src+dst int32 + 8 B/edge
weight + degrees) + O(cap) transients — vs ~48 B/edge transient in the
in-memory path. The output Graph is FIELD-IDENTICAL to
`build_graph(src, dst)` (pinned by tests/test_external_build.py).

For inputs too large even for the final arrays, the on-device build
(`ops/device_build`) or striped consumption would be next; this module
covers the reference-scale host path (SURVEY §7 "Ingesting 1.47B
edges").
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from pagerank_tpu.graph import Graph, inv_out_degree

# Working-memory budget split: a spill chunk at flush holds the pending
# key (8 B/edge), np.unique's internal sort copy (~8), its output (~8),
# and the live input chunk views — measured 60 B/edge peak at a
# 26.8M-edge chunk (2^27-edge demo, docs/PERF_NOTES.md "Host ingest"),
# so 64 keeps the observed working set within the caller's cap.
_SPILL_BYTES_PER_EDGE = 64
_MERGE_FRACTION = 0.25
_MIN_CHUNK_EDGES = 1 << 16  # spill-chunk floor (module-level so tests
# can force many tiny runs without gigabyte inputs)


def iter_text_chunks(path: str, chunk_edges: int,
                     comments: str = "#") -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream a SNAP-style text edge list in ~``chunk_edges`` chunks
    without loading the file (1 line = 1 edge; ``#`` comments)."""
    from pagerank_tpu.utils import fsio

    buf = b""
    # Text lines run ~8-20 bytes/edge; read enough for one chunk.
    block = max(1 << 20, chunk_edges * 16)
    with fsio.fopen(path, "rb") as f:
        while True:
            data = f.read(block)
            if not data:
                break
            data = buf + data
            cut = data.rfind(b"\n")
            if cut == -1:
                buf = data
                continue
            buf = data[cut + 1:]
            yield _parse_lines(data[:cut], path, comments)
    if buf.strip():
        yield _parse_lines(buf, path, comments)


def _parse_lines(data: bytes, path: str, comments: str):
    lines = [
        ln for ln in data.splitlines()
        if ln and not ln.lstrip().startswith(comments.encode())
    ]
    flat = np.array(b" ".join(lines).split(), dtype=np.int64)
    if flat.size % 2:
        raise ValueError(f"{path}: odd token count; not a src/dst list")
    pairs = flat.reshape(-1, 2)
    return pairs[:, 0].copy(), pairs[:, 1].copy()


def _iter_array_chunks(src, dst, chunk_edges):
    for lo in range(0, len(src), chunk_edges):
        yield src[lo : lo + chunk_edges], dst[lo : lo + chunk_edges]


def _npy_stream_header(f, path):
    """Parse the npy magic+header off a streaming member and return
    (count, dtype). Rejects shapes the edge-member contract excludes."""
    from numpy.lib import format as npy_format

    version = npy_format.read_magic(f)
    if version == (1, 0):
        shape, fortran, dtype = npy_format.read_array_header_1_0(f)
    else:
        shape, fortran, dtype = npy_format.read_array_header_2_0(f)
    if len(shape) != 1 or dtype.hasobject:
        raise ValueError(
            f"{path}: edge members must be 1-D numeric arrays "
            f"(got shape {shape}, dtype {dtype})"
        )
    return shape[0], dtype


def iter_npz_chunks(path: str, chunk_edges: int):
    """Stream the ``src``/``dst`` members of a local ``.npz`` in
    parallel ~``chunk_edges`` chunks with bounded RSS (VERDICT r4 #7).

    numpy's npz is a zip of ``.npy`` members; ``zipfile`` reads a
    member incrementally (stored copies bytes, deflated inflates with
    an O(window) state), so after parsing each member's npy header off
    the stream the element bytes can be consumed chunkwise — the input
    file never materializes in RAM, stored or compressed. Two members
    are streamed in lockstep via independent ``ZipFile.open`` handles
    (concurrent member reads are supported when the archive is opened
    by name). Returns (iterator, n_hint)."""
    import zipfile

    from numpy.lib import format as npy_format

    if chunk_edges <= 0:
        raise ValueError(f"chunk_edges must be positive, got {chunk_edges}")
    zf = zipfile.ZipFile(path, "r")
    fs = fd = None
    try:
        names = set(zf.namelist())

        def member(base):
            nm = base + ".npy"
            if nm in names:
                return nm
            if base in names:
                return base
            raise ValueError(f"{path}: .npz is missing member {base!r}")

        n = None
        if "n.npy" in names or "n" in names:
            with zf.open(member("n")) as f:
                n = int(npy_format.read_array(f))

        fs = zf.open(member("src"))
        fd = zf.open(member("dst"))
        ns, dt_s = _npy_stream_header(fs, path)
        nd, dt_d = _npy_stream_header(fd, path)
        if ns != nd:
            raise ValueError(
                f"{path}: src/dst length mismatch: {ns} vs {nd}"
            )
    except BaseException:
        for h in (fs, fd, zf):
            if h is not None:
                h.close()
        raise

    def gen():
        with zf, fs, fd:
            left = ns
            while left:
                k = min(chunk_edges, left)
                sb = fs.read(k * dt_s.itemsize)
                db = fd.read(k * dt_d.itemsize)
                if len(sb) != k * dt_s.itemsize or len(db) != k * dt_d.itemsize:
                    raise ValueError(f"{path}: truncated .npy member data")
                yield (
                    np.frombuffer(sb, dt_s),
                    np.frombuffer(db, dt_d),
                )
                left -= k

    return gen(), n


def open_edge_chunks(path: str, chunk_edges: int):
    """Chunk iterator for a path: .npz binary (members streamed through
    zipfile with bounded RSS — :func:`iter_npz_chunks`; remote URIs
    still load whole, a seekable local file is required to stream zip
    members) or text (truly streamed). Returns (iterator, n_hint)."""
    from pagerank_tpu.utils import fsio

    if os.path.splitext(path)[1] == ".npz":
        if fsio.scheme_of(path) is None:
            return iter_npz_chunks(path, chunk_edges)
        from pagerank_tpu.ingest.edgelist import load_binary_edges

        src, dst, n = load_binary_edges(path)
        return _iter_array_chunks(src, dst, chunk_edges), n
    return iter_text_chunks(path, chunk_edges), None


def build_graph_external(
    edges,
    n: Optional[int] = None,
    mem_cap_bytes: int = 2 << 30,
    tmp_dir: Optional[str] = None,
    dangling_mask: Optional[np.ndarray] = None,
) -> Graph:
    """`graph.build_graph` semantics under a bounded working-memory cap.

    Args:
      edges: a path (text / .npz — see :func:`open_edge_chunks`) or an
        iterable of (src, dst) int array chunks (any chunking; re-cut
        internally to the cap).
      n: vertex count; discovered as max id + 1 when omitted (ids must
        fit int32 either way, like build_graph's device contract). May
        be a CALLABLE resolved after the input is fully consumed — for
        producers whose vertex count is only known at end of stream
        (the crawl interner, ingest/native.crawl_load_external).
      mem_cap_bytes: working-memory budget for the build's transients
        (spill chunks, merge windows). The final Graph arrays are
        excluded — they are the caller's product, not working state.
      tmp_dir: where sorted runs spill (default: a fresh tempdir,
        removed on return).
      dangling_mask: explicit mass mask (crawl semantics), as in
        build_graph; may be a callable like ``n``.

    Returns a Graph FIELD-IDENTICAL to ``build_graph(src, dst, n=n)``
    on the concatenated input.
    """
    if mem_cap_bytes < (64 << 20):
        raise ValueError("mem_cap_bytes must be at least 64 MiB")
    chunk_edges = max(_MIN_CHUNK_EDGES, mem_cap_bytes // _SPILL_BYTES_PER_EDGE)
    if isinstance(edges, (str, os.PathLike)):
        chunks, n_hint = open_edge_chunks(str(edges), chunk_edges)
        if n is None:
            n = n_hint
    else:
        chunks = iter(edges)
    n_lazy = n if callable(n) else None

    own_tmp = tmp_dir is None
    tmp = tmp_dir or tempfile.mkdtemp(prefix="pagerank_extsort_")
    runs = []
    max_id = -1
    try:
        # -- spill phase ------------------------------------------------
        pend = []
        pend_n = 0

        def flush_run():
            nonlocal pend, pend_n, max_id
            if not pend_n:
                return
            key = np.concatenate(pend) if len(pend) > 1 else pend[0]
            pend, pend_n = [], 0
            key = np.unique(key)
            hi = int(key[-1] >> 32)
            lo_max = int((key & np.uint64(0xFFFFFFFF)).max())
            max_id = max(max_id, hi, lo_max)
            path = os.path.join(tmp, f"run{len(runs):05d}.npy")
            np.save(path, key)
            runs.append(path)
            del key

        for s, d in chunks:
            s = np.ascontiguousarray(s, dtype=np.int64)
            d = np.ascontiguousarray(d, dtype=np.int64)
            if s.shape != d.shape:
                raise ValueError(
                    f"src/dst length mismatch: {s.shape} vs {d.shape}"
                )
            if len(s) == 0:
                continue
            if s.min() < 0 or d.min() < 0:
                raise ValueError("edge endpoint out of range [0, n)")
            if max(int(s.max()), int(d.max())) >= (1 << 31):
                raise ValueError("vertex ids must fit int32")
            # Re-cut to the cap regardless of input chunking.
            for lo in range(0, len(s), chunk_edges):
                key = (
                    d[lo : lo + chunk_edges].astype(np.uint64) << np.uint64(32)
                ) | s[lo : lo + chunk_edges].astype(np.uint64)
                pend.append(key)
                pend_n += len(key)
                if pend_n >= chunk_edges:
                    flush_run()
        flush_run()

        if n_lazy is not None:
            n = n_lazy()  # producer's count, known at end of stream
        if n is None:
            n = max_id + 1 if max_id >= 0 else 0
        n = int(n)
        if n == 0:
            raise ValueError("empty graph: no vertices")
        if max_id >= n:
            raise ValueError("edge endpoint out of range [0, n)")

        out_degree = np.zeros(n, np.int32)
        in_degree = np.zeros(n, np.int32)
        if not runs:
            src_s = np.zeros(0, np.int32)
            dst_s = np.zeros(0, np.int32)
        else:
            # -- merge phase --------------------------------------------
            block = max(
                1 << 14,
                int(mem_cap_bytes * _MERGE_FRACTION) // (16 * len(runs)),
            )
            # Merged keys buffer to DISK, not to growing in-RAM parts:
            # a list-of-parts + final concatenate would peak at final
            # arrays + one full extra copy (measured +1.6 GB at 2^27
            # edges); the file costs one 8 B/edge write+read and keeps
            # the peak at final arrays + O(block).
            merged_path = os.path.join(tmp, "merged.bin")
            merged_f = open(merged_path, "wb")
            n_unique = 0
            mms = [np.load(p, mmap_mode="r") for p in runs]
            loaded = [m[:block].copy() for m in mms]
            pos = [b.size for b in loaded]  # next unread offset per run
            while True:
                live = [i for i in range(len(runs))
                        if loaded[i].size or pos[i] < mms[i].size]
                if not live:
                    break
                # Refill empties, then cut at the smallest loaded
                # block-max among runs that still have unloaded data
                # (everything <= that bound is globally complete).
                for i in live:
                    if not loaded[i].size:
                        p = pos[i]
                        loaded[i] = mms[i][p : p + block].copy()
                        pos[i] = p + loaded[i].size
                bound = None
                for i in live:
                    if pos[i] < mms[i].size or loaded[i].size:
                        m = int(loaded[i][-1]) if loaded[i].size else None
                        if m is not None and (
                            pos[i] < mms[i].size
                        ):
                            bound = m if bound is None else min(bound, m)
                take = []
                for i in live:
                    if bound is None:
                        cut = loaded[i].size
                    else:
                        cut = int(np.searchsorted(
                            loaded[i], np.uint64(bound), side="right"
                        ))
                    if cut:
                        take.append(loaded[i][:cut])
                        loaded[i] = loaded[i][cut:]
                if not take:
                    continue
                window = np.concatenate(take) if len(take) > 1 else take[0]
                window = np.unique(window)
                # Cross-WINDOW duplicates cannot exist (windows are
                # disjoint key ranges), so emit directly.
                np.add.at(
                    out_degree,
                    (window & np.uint64(0xFFFFFFFF)).astype(np.int32), 1,
                )
                np.add.at(
                    in_degree, (window >> np.uint64(32)).astype(np.int32), 1,
                )
                merged_f.write(window.tobytes())
                n_unique += window.size
            merged_f.close()
            del mms
            # Decode the merged key stream into exactly-sized arrays.
            src_s = np.empty(n_unique, np.int32)
            dst_s = np.empty(n_unique, np.int32)
            keys = np.memmap(merged_path, dtype=np.uint64, mode="r")
            dec_block = max(1 << 16, int(mem_cap_bytes * _MERGE_FRACTION) // 16)
            for lo in range(0, n_unique, dec_block):
                kb = np.array(keys[lo : lo + dec_block])
                src_s[lo : lo + kb.size] = (
                    kb & np.uint64(0xFFFFFFFF)
                ).astype(np.int32)
                dst_s[lo : lo + kb.size] = (kb >> np.uint64(32)).astype(np.int32)
            del keys
            os.remove(merged_path)
    finally:
        for p in runs:
            try:
                os.remove(p)
            except OSError:
                pass
        if own_tmp:
            try:
                os.rmdir(tmp)
            except OSError:
                pass

    if callable(dangling_mask):
        dangling_mask = dangling_mask()
    if dangling_mask is None:
        dangling_mask = out_degree == 0
    else:
        dangling_mask = np.ascontiguousarray(dangling_mask, dtype=bool)
        if dangling_mask.shape != (n,):
            raise ValueError(
                f"dangling_mask shape {dangling_mask.shape} != ({n},)"
            )
        if np.any(dangling_mask & (out_degree > 0)):
            raise ValueError("dangling_mask marks a vertex that has out-edges")

    return Graph(
        n=n,
        src=src_s,
        dst=dst_s,
        out_degree=out_degree,
        in_degree=in_degree,
        dangling_mask=dangling_mask,
        zero_in_mask=in_degree == 0,
        edge_weight=inv_out_degree(out_degree)[src_s],
        vertex_names=None,
    )
