"""Edge-list ingestion (C4 in SURVEY.md §2) — the loader family replacing
``ctx.sequenceFile`` (Sparky.java:61) for integer-id graph inputs.

Formats:
  - SNAP-style text: one ``src dst`` pair per line, ``#`` comments
    (web-Google / soc-LiveJournal1 / Twitter-2010 distribution format);
  - binary ``.npz`` with int arrays ``src``/``dst`` (+ optional ``n``) —
    the memory-mapped fast path for billion-edge inputs (SURVEY.md §7:
    text parsing must not dwarf the device budget).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.utils import fsio


def load_edgelist(path: str, comments: str = "#") -> Tuple[np.ndarray, np.ndarray]:
    """Parse a whitespace-separated text edge list into (src, dst).

    Uses the native mmap/multithreaded parser (native/fast_ingest.cpp)
    when available; falls back to numpy. ``path`` may use a registered
    URI scheme (utils/fsio); the native mmap parser applies to local
    paths only."""
    with obs_trace.span("ingest/edgelist", path=path) as sp:
        if comments == "#" and fsio.scheme_of(path) is None:
            from pagerank_tpu.ingest import native as native_lib

            try:
                out = native_lib.parse_edgelist_native(path)
            except FileNotFoundError:
                raise
            if out is not None:
                if sp is not None:
                    sp.attrs.update(edges=len(out[0]), parser="native")
                return out
        with fsio.fopen(path, "rb") as f:
            data = f.read()
        if comments:
            lines = [
                ln for ln in data.splitlines() if ln and not ln.lstrip().startswith(comments.encode())
            ]
            data = b"\n".join(lines)
        flat = np.array(data.split(), dtype=np.int64)
        if flat.size % 2 != 0:
            raise ValueError(f"{path}: odd token count {flat.size}; not a src/dst list")
        pairs = flat.reshape(-1, 2)
        if sp is not None:
            sp.attrs.update(edges=len(pairs), parser="numpy")
        return pairs[:, 0].copy(), pairs[:, 1].copy()


def save_binary_edges(
    path: str, src: np.ndarray, dst: np.ndarray, n: Optional[int] = None
) -> None:
    arrays = {"src": np.asarray(src, np.int64), "dst": np.asarray(dst, np.int64)}
    if n is not None:
        arrays["n"] = np.int64(n)
    if not path.endswith(".npz"):
        path += ".npz"  # np.savez's path behavior, kept for file objects
    with fsio.fopen(path, "wb") as f:
        np.savez(f, **arrays)


def load_binary_edges(path: str) -> Tuple[np.ndarray, np.ndarray, Optional[int]]:
    with obs_trace.span("ingest/npz", path=path):
        with fsio.fopen(path, "rb") as f, np.load(f) as z:
            n = int(z["n"]) if "n" in z.files else None
            return z["src"], z["dst"], n


def load_edges_any(path: str) -> Tuple[np.ndarray, np.ndarray, Optional[int]]:
    """Dispatch on extension: .npz binary, else text edge list."""
    if os.path.splitext(path)[1] == ".npz":
        return load_binary_edges(path)
    src, dst = load_edgelist(path)
    return src, dst, None
