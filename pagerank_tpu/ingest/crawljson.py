"""Common Crawl metadata-JSON link extraction (C6 in SURVEY.md §2) —
host-side equivalent of the reference's Gson flatMap
(`Sparky.java:78-124`), quirks preserved:

  - only links whose ``type`` is the *string* ``"a"`` count
    (Sparky.java:103 — the reference compares Gson ``toString()`` output
    against ``"\"a\""``, which is string-equality on "a");
  - every double-quote character is stripped from ``href``
    (Sparky.java:101,105 — ``replace("\"", "")`` runs on the *quoted*
    Gson rendering, so embedded quotes vanish too);
  - a record with zero anchor links yields a vertex with no out-edges
    (the (url, null) sentinel + dangUrls, Sparky.java:114-118);
  - ``content`` / ``links`` may be absent (null-checks at :91,:94) — the
    record is then dangling;
  - a malformed JSON record or a link entry missing ``href``/``type``
    crashes the reference job (Gson parse/NPE inside the flatMap);
    ``strict=True`` reproduces that, ``strict=False`` skips bad entries.

Input file format here: one record per line, ``url<TAB>json`` (the
(Text, Text) SequenceFile pairs of Sparky.java:61 flattened to TSV), or
JSONL with ``{"url": ..., "metadata": {...}}``.
"""

from __future__ import annotations

import json
from typing import Iterator, List, Tuple

from pagerank_tpu.utils import fsio


def _render(value) -> str:
    """Gson ``JsonElement.toString()`` for primitives: strings keep their
    quotes, numbers/bools/null render as JSON literals. Gson does not
    escape non-ASCII, so neither do we."""
    return json.dumps(value, ensure_ascii=False)


def parse_metadata_record(
    url: str, metadata_json: str, strict: bool = True
) -> Tuple[str, List[str]]:
    """One crawl record -> (url, anchor targets). Empty targets means the
    page is dangling (no anchor links)."""
    try:
        root = json.loads(metadata_json)
    except json.JSONDecodeError:
        if strict:
            raise
        return url, []
    targets: List[str] = []
    content = root.get("content") if isinstance(root, dict) else None
    if isinstance(content, dict):
        links = content.get("links")
        if isinstance(links, list):
            for entry in links:
                try:
                    href = entry["href"]  # KeyError == reference NPE
                    ltype = entry["type"]
                except (KeyError, TypeError):
                    if strict:
                        raise
                    continue
                # type.equals("\"a\"") on the quoted rendering == the
                # JSON string "a" (Sparky.java:103).
                if _render(ltype) == '"a"':
                    # strip ALL double quotes from the quoted rendering
                    # (Sparky.java:105).
                    targets.append(_render(href).replace('"', ""))
    return url, targets


def iter_crawl_records(
    path: str, strict: bool = True
) -> Iterator[Tuple[str, List[str]]]:
    """Yield (url, targets) from a TSV (url<TAB>json) or JSONL file."""
    with fsio.fopen(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            if "\t" in line:
                url, meta = line.split("\t", 1)
            else:
                obj = json.loads(line)
                url = obj["url"]
                meta = json.dumps(obj.get("metadata", obj.get("json", {})))
            yield parse_metadata_record(url, meta, strict=strict)


def load_crawl_file(path: str, strict: bool = True, native: str = "auto"):
    """Parse a crawl-metadata file (TSV or JSONL) into a Graph (+ IdMap).

    ``native="auto"`` uses the C++ L1 (ingest/native.py:crawl_load) when
    available; output parity with this Python path is pinned by
    tests/test_native_crawl.py."""
    return _load_crawl_file(path, strict, native, raw=False)


def load_crawl_file_arrays(path: str, strict: bool = True,
                           native: str = "auto"):
    """Like :func:`load_crawl_file` but stops before the host graph
    build: raw ``(src, dst, crawled_mask, IdMap)`` for the on-device
    build (`--device-build` on crawl inputs)."""
    return _load_crawl_file(path, strict, native, raw=True)


def _load_crawl_file(path, strict, native, raw):
    from pagerank_tpu.obs import trace as obs_trace

    with obs_trace.span("ingest/crawl", path=path) as sp:
        if native == "auto":
            from pagerank_tpu.ingest import native as native_mod

            result = native_mod.try_crawl_load([path], "tsv", strict=strict,
                                               raw=raw)
            if result is not None:
                if sp is not None:
                    sp.attrs["parser"] = "native"
                return result
        from pagerank_tpu.ingest.ids import (records_to_arrays,
                                             records_to_graph)

        if sp is not None:
            sp.attrs["parser"] = "python"
        records = iter_crawl_records(path, strict=strict)
        return (records_to_arrays(records) if raw
                else records_to_graph(records))
