"""ctypes bindings for the native ingestion library (native/fast_ingest.cpp).

Compiled on demand with g++ (no pybind11 in this environment; C ABI +
ctypes instead). Every entry point has a numpy fallback, so the package
works without a toolchain — the native path exists because host-side
ingestion of billion-edge graphs must not dwarf the device budget
(SURVEY.md §7).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "fast_ingest.cpp")
_SO = os.path.join(os.path.dirname(__file__), "..", "..", "native", "libfast_ingest.so")


class _ParseResult(ctypes.Structure):
    _fields_ = [
        ("src", ctypes.POINTER(ctypes.c_int64)),
        ("dst", ctypes.POINTER(ctypes.c_int64)),
        ("count", ctypes.c_int64),
        ("error", ctypes.c_int64),
    ]


def _build() -> Optional[str]:
    src = os.path.abspath(_SRC)
    so = os.path.abspath(_SO)
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", so, src, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so
    except Exception:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB, _LIB_FAILED
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        so = _build()
        if so is None:
            _LIB_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.parse_edgelist.restype = _ParseResult
            lib.parse_edgelist.argtypes = [ctypes.c_char_p, ctypes.c_int32]
            lib.free_edges.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.sort_dedup_degrees.restype = ctypes.c_int64
            lib.sort_dedup_degrees.argtypes = [
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64,
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ]
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
        return _LIB


def available() -> bool:
    return get_lib() is not None


def parse_edgelist_native(path: str, num_threads: int = 0):
    """mmap + multithreaded text edge-list parse. Returns (src, dst) int64
    arrays, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    res = lib.parse_edgelist(path.encode(), num_threads)
    if res.error == 1:
        raise FileNotFoundError(path)
    if res.error == 2:
        lib.free_edges(res.src, res.dst)
        raise ValueError(f"{path}: odd token count; not a src/dst list")
    if res.error == 3:
        lib.free_edges(res.src, res.dst)
        raise ValueError(f"{path}: non-integer token; not a src/dst list")
    e = res.count
    if e == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    src = np.ctypeslib.as_array(res.src, shape=(e,)).copy()
    dst = np.ctypeslib.as_array(res.dst, shape=(e,)).copy()
    lib.free_edges(res.src, res.dst)
    return src, dst


def sort_dedup_degrees_native(
    src: np.ndarray, dst: np.ndarray, n: int
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """dst-major radix sort + dedup + degree count. Returns (src32, dst32,
    out_degree, in_degree) or None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    e = src.shape[0]
    out_src = np.empty(max(e, 1), np.int32)
    out_dst = np.empty(max(e, 1), np.int32)
    out_deg = np.empty(n, np.int32)
    in_deg = np.empty(n, np.int32)
    k = lib.sort_dedup_degrees(src, dst, e, n, out_src, out_dst, out_deg, in_deg)
    return out_src[:k].copy(), out_dst[:k].copy(), out_deg, in_deg
