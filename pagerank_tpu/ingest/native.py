"""ctypes bindings for the native ingestion library (native/fast_ingest.cpp).

Compiled on demand with g++ (no pybind11 in this environment; C ABI +
ctypes instead). Every entry point has a numpy fallback, so the package
works without a toolchain — the native path exists because host-side
ingestion of billion-edge graphs must not dwarf the device budget
(SURVEY.md §7).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "fast_ingest.cpp")
_SO = os.path.join(os.path.dirname(__file__), "..", "..", "native", "libfast_ingest.so")


class _ParseResult(ctypes.Structure):
    _fields_ = [
        ("src", ctypes.POINTER(ctypes.c_int64)),
        ("dst", ctypes.POINTER(ctypes.c_int64)),
        ("count", ctypes.c_int64),
        ("error", ctypes.c_int64),
    ]


_SRC_CRAWL = os.path.join(
    os.path.dirname(__file__), "..", "..", "native", "crawl_ingest.cpp"
)


def _build() -> Optional[str]:
    srcs = [os.path.abspath(_SRC), os.path.abspath(_SRC_CRAWL)]
    so = os.path.abspath(_SO)
    try:
        if os.path.exists(so) and all(
            os.path.getmtime(so) >= os.path.getmtime(s) for s in srcs
        ):
            return so
    except OSError:
        # Sources absent (e.g. a deployment shipping only the prebuilt
        # .so): use the .so if it exists, else no native path.
        return so if os.path.exists(so) else None
    cmd = ["g++", "-std=c++17", "-O3", "-march=native", "-shared", "-fPIC",
           "-o", so] + srcs + ["-lpthread", "-lz"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=240)
        return so
    except Exception:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB, _LIB_FAILED
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        so = _build()
        if so is None:
            _LIB_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.parse_edgelist.restype = _ParseResult
            lib.parse_edgelist.argtypes = [ctypes.c_char_p, ctypes.c_int32]
            lib.crawl_new.restype = ctypes.c_void_p
            lib.crawl_free.argtypes = [ctypes.c_void_p]
            lib.crawl_ingest_file.restype = ctypes.c_int64
            lib.crawl_ingest_file.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32,
            ]
            lib.crawl_ingest_files.restype = ctypes.c_int64
            lib.crawl_ingest_files.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ]
            lib.crawl_error.restype = ctypes.c_char_p
            lib.crawl_error.argtypes = [ctypes.c_void_p]
            for fn in ("crawl_num_edges", "crawl_num_vertices",
                       "crawl_num_records", "crawl_names_blob_size",
                       "crawl_failed_index"):
                getattr(lib, fn).restype = ctypes.c_int64
                getattr(lib, fn).argtypes = [ctypes.c_void_p]
            lib.crawl_copy_edges.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ]
            if hasattr(lib, "crawl_drain_edges"):  # newer symbol
                lib.crawl_drain_edges.restype = ctypes.c_int64
                lib.crawl_drain_edges.argtypes = [
                    ctypes.c_void_p,
                    np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                    np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ]
            lib.crawl_copy_crawled.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ]
            lib.crawl_copy_names.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ]
            lib.free_edges.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.sort_dedup_degrees.restype = ctypes.c_int64
            lib.sort_dedup_degrees.argtypes = [
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64,
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ]
            # Newer symbol: guard so a prebuilt .so from older sources
            # keeps its existing entry points (only the formatter falls
            # back to Python then).
            if hasattr(lib, "format_rank_lines2"):
                lib.format_rank_lines2.restype = ctypes.c_int64
                lib.format_rank_lines2.argtypes = [
                    np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                    ctypes.c_int64,
                    ctypes.c_int64,   # key_base for integer keys
                    ctypes.c_char_p,  # names blob (or None)
                    ctypes.c_void_p,  # int64 offsets (or None)
                    np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
                    ctypes.c_int64,
                ]
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
        return _LIB


def available() -> bool:
    return get_lib() is not None


def parse_edgelist_native(path: str, num_threads: int = 0):
    """mmap + multithreaded text edge-list parse. Returns (src, dst) int64
    arrays, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    res = lib.parse_edgelist(path.encode(), num_threads)
    if res.error == 1:
        raise FileNotFoundError(path)
    if res.error == 2:
        lib.free_edges(res.src, res.dst)
        raise ValueError(f"{path}: odd token count; not a src/dst list")
    if res.error == 3:
        lib.free_edges(res.src, res.dst)
        raise ValueError(f"{path}: non-integer token; not a src/dst list")
    e = res.count
    if e == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    src = np.ctypeslib.as_array(res.src, shape=(e,)).copy()
    dst = np.ctypeslib.as_array(res.dst, shape=(e,)).copy()
    lib.free_edges(res.src, res.dst)
    return src, dst


#: crawl_ingest_file error categories -> the exception types the Python
#: ingest path raises for the same input (crash-class parity, pinned by
#: tests/test_native_crawl.py).
_CRAWL_KIND_SEQFILE = 0
_CRAWL_KIND_TSV = 1


class NativeUnsupported(Exception):
    """Input is valid for the Python path but unrepresentable natively
    (e.g. a non-string JSONL url, which Python keeps as a non-str dict
    key). Callers fall back to the Python path."""


def _crawl_raise(cat: int, msg: str, path: str):
    import json as _json
    import zlib as _zlib

    if cat == 2:
        raise _json.JSONDecodeError(f"{msg} (in {path})", "", 0)
    if cat == 3:
        raise KeyError(msg)
    if cat == 4:
        raise TypeError(f"{msg} (in {path})")
    if cat == 6:
        raise RuntimeError(f"{msg} (in {path})")
    if cat == 7:
        raise EOFError(f"{path}: {msg}")
    if cat == 8:
        raise _zlib.error(f"{path}: {msg}")
    if cat == 9:
        raise NativeUnsupported(f"{path}: {msg}")
    raise ValueError(f"{path}: {msg}")


def try_crawl_load(paths, kind: str, strict: bool = True,
                   threads: Optional[int] = None, raw: bool = False):
    """:func:`crawl_load` with the standard fallback gating applied:
    returns None when the native library is unavailable OR the input is
    valid-but-unrepresentable (NativeUnsupported) — callers then take
    the Python path. One copy of the rule for every loader."""
    try:
        return crawl_load(paths, kind, strict=strict, threads=threads,
                          raw=raw)
    except NativeUnsupported:
        return None


def iter_read_batches(paths, window: int, byte_cap: int):
    """Yield ``(batch_paths, datas)`` groups of whole-file reads bounded
    by ``window`` files AND ``byte_cap`` total bytes per batch. The cap
    is checked BEFORE appending: a file that would push the batch past
    byte_cap flushes the current batch first, so a batch exceeds the cap
    only when a SINGLE file does (each file is read whole into memory —
    see the crawl_load docstring note)."""
    from pagerank_tpu.utils import fsio

    batch_paths, datas, nbytes = [], [], 0
    for path in paths:
        with fsio.fopen(path, "rb") as f:
            data = f.read()
        if datas and nbytes + len(data) > byte_cap:
            yield batch_paths, datas
            batch_paths, datas, nbytes = [], [], 0
        batch_paths.append(path)
        datas.append(data)
        nbytes += len(data)
        if len(datas) >= window:
            yield batch_paths, datas
            batch_paths, datas, nbytes = [], [], 0
    if datas:
        yield batch_paths, datas


def _iter_ingest_batches(lib, h, paths, window, byte_cap, kind_code,
                         strict, threads):
    """Read file batches (prefetching the next while the native call
    parses the current — ctypes releases the GIL, so reads overlap
    parse) and ingest each into crawl handle ``h``, yielding after
    every successful batch. Raises the Python path's exception types on
    malformed input, naming the culprit file. THE one spelling of the
    batch/prefetch/error plumbing, shared by crawl_load and
    crawl_load_external."""
    import concurrent.futures

    gen = iter_read_batches(paths, window, byte_cap)
    with concurrent.futures.ThreadPoolExecutor(1) as prefetch:
        fut = prefetch.submit(next, gen, None)
        while True:
            item = fut.result()
            if item is None:
                return
            fut = prefetch.submit(next, gen, None)
            batch, datas = item
            arr = (ctypes.c_char_p * len(datas))(*datas)
            lens = (ctypes.c_int64 * len(datas))(*[len(d) for d in datas])
            cat = lib.crawl_ingest_files(
                h, len(datas), arr, lens, kind_code,
                1 if strict else 0, threads,
            )
            if cat != 0:
                msg = (lib.crawl_error(h) or b"").decode("utf-8", "replace")
                bad = lib.crawl_failed_index(h)
                culprit = batch[bad] if 0 <= bad < len(batch) else batch[0]
                _crawl_raise(cat, msg, culprit)
            yield batch


def crawl_load(paths, kind: str, strict: bool = True,
               threads: Optional[int] = None, raw: bool = False):
    """Native L1: parse crawl inputs (``kind`` = "seqfile" or "tsv") into
    a (Graph, IdMap) with the exact record/id order and quirk semantics
    of the Python path (crawljson.py + seqfile.py — differentially
    pinned by tests/test_native_crawl.py). Returns None when the native
    library is unavailable; raises the same exception types as the
    Python path on malformed input. File bytes are read through the
    fsio registry, so URI schemes (s3://, mock://) work identically.

    Multi-file inputs parse across ``threads`` C++ worker threads
    (default: one per core, capped by file count) with file-ordered
    interning, so the result is byte-identical at any thread count —
    the in-process analogue of the reference parsing its segment across
    the cluster (Sparky.java:61).

    ``raw=True`` skips the host graph build and returns
    ``(src, dst, crawled_mask, IdMap)`` int32/bool arrays — what the
    on-device build consumes (the dedup/sort/pack then runs on the TPU,
    ops/device_build.build_ell_device).

    Memory note: unlike the streaming Python reader, each file is read
    WHOLE into host memory before the native call (the C++ side parses
    from one contiguous buffer). Batches are bounded at ~256 MB — a
    batch flushes before a file that would exceed the cap — but one
    single file larger than the cap still occupies its full size.
    """
    lib = get_lib()
    if lib is None:
        return None
    from pagerank_tpu.graph import build_graph
    from pagerank_tpu.ingest.ids import IdMap
    from pagerank_tpu.utils import fsio

    kind_code = (
        _CRAWL_KIND_SEQFILE if kind == "seqfile" else _CRAWL_KIND_TSV
    )
    paths = list(paths)
    if threads is None:
        threads = min(len(paths), os.cpu_count() or 1)
    threads = max(int(threads), 1)
    # Feed the C++ side bounded batches: at most 2*threads files AND at
    # most ~256 MB of raw bytes per batch (the file-count bound alone
    # would scale peak RSS with the core count); see
    # _iter_ingest_batches for the prefetch overlap.
    window = max(2 * threads, 1)
    byte_cap = 256 << 20

    h = lib.crawl_new()
    try:
        for _ in _iter_ingest_batches(lib, h, paths, window, byte_cap,
                                      kind_code, strict, threads):
            pass
        n = lib.crawl_num_vertices(h)
        e = lib.crawl_num_edges(h)
        src = np.empty(max(e, 1), np.int32)
        dst = np.empty(max(e, 1), np.int32)
        lib.crawl_copy_edges(h, src, dst)
        crawled = np.zeros(max(n, 1), np.uint8)
        if n:
            lib.crawl_copy_crawled(h, crawled)
        names = _copy_names(lib, h, n)
    finally:
        lib.crawl_free(h)
    if raw:
        return (src[:e], dst[:e], crawled[:n].astype(bool),
                IdMap.from_names(names))
    graph = build_graph(
        src[:e], dst[:e], n=n,
        dangling_mask=~crawled[:n].astype(bool),
        vertex_names=names,
    )
    return graph, IdMap.from_names(names)


def format_rank_lines_native(
    ranks: np.ndarray,
    names_blob: Optional[bytes] = None,
    name_offsets: Optional[np.ndarray] = None,
    key_base: int = 0,
) -> Optional[bytes]:
    """Bulk "(key,repr(value))\\n" text formatting — the native L4 fast
    path behind utils/snapshot.TextDumper. Byte-identical to the Python
    per-line formatter (differentially fuzzed in tests/test_snapshot.py);
    returns None when the native library is unavailable (or predates
    the symbol, or was built by a toolchain without floating-point
    charconv — callers take the Python loop). ``key_base`` offsets the
    integer keys so callers can format bounded row chunks; with names,
    pass the chunk's rebased blob/offsets instead."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "format_rank_lines2"):
        return None
    ranks = np.ascontiguousarray(ranks, dtype=np.float64)
    n = ranks.shape[0]
    if names_blob is not None:
        offs = np.ascontiguousarray(name_offsets, dtype=np.int64)
        if offs.shape[0] != n + 1:
            raise ValueError(
                f"name_offsets must have length n+1={n + 1}, got {offs.shape[0]}"
            )
        cap = len(names_blob) + 28 * n + 1
        offs_p = offs.ctypes.data_as(ctypes.c_void_p)
    else:
        offs = None
        cap = 48 * n + 1
        offs_p = None
    out = np.empty(cap, np.uint8)
    wrote = lib.format_rank_lines2(
        ranks, n, key_base, names_blob, offs_p, out, cap
    )
    if wrote == -2:  # library built without floating-point charconv
        return None
    if wrote < 0:  # cap bound violated — impossible per the line math
        raise RuntimeError("format_rank_lines overflow")
    return out[:wrote].tobytes()


def _copy_names(lib, h, n):
    """Interned vertex names out of a crawl handle. surrogatepass: lone
    surrogates from \\uXXXX escapes round-trip (the C side stores them
    WTF-8, matching Python str contents)."""
    blob_size = lib.crawl_names_blob_size(h)
    blob = ctypes.create_string_buffer(max(blob_size, 1))
    offsets = np.empty(n + 1, np.int64)
    lib.crawl_copy_names(h, blob, offsets)
    blob_bytes = blob.raw[:blob_size]
    return [
        blob_bytes[offsets[i]:offsets[i + 1]].decode("utf-8",
                                                     "surrogatepass")
        for i in range(n)
    ]


def crawl_load_external(paths, kind: str, mem_cap_bytes: int = 2 << 30,
                        strict: bool = True, threads: Optional[int] = None,
                        tmp_dir: Optional[str] = None):
    """Out-of-core crawl ingestion (VERDICT r4 missing #2): the native
    L1 parses file batches as in :func:`crawl_load`, but after every
    batch the accumulated edges are DRAINED out of the C++ state
    (``crawl_drain_edges``) and spilled straight into the external-sort
    build (ingest/external.build_graph_external), so the edge set is
    never resident in one space — the reference streams its 301
    SequenceFile partitions the same way (Sparky.java:61,124). What
    stays in RAM for the whole run:

      - the interner (url -> id table + WTF-8 name blob): O(vertices),
        unavoidable — the IdMap is the product (and the reference
        collects the same set to the driver, Sparky.java:127);
      - up to TWO batches of file bytes (the current one plus the
        prefetched next — _iter_ingest_batches overlaps reads with
        parsing) and the current batch's drained edges;
      - the external sort's working set.

    The file-batch cap and the sort's budget are both carved out of
    ``mem_cap_bytes`` (2 x batch bytes reserved before the sort gets
    the rest), so the flag's promise covers the whole pipeline, not
    just the sort.

    Returns (Graph, IdMap) exactly field-identical to
    :func:`crawl_load` on the same inputs (the external sort and the
    in-memory build produce the same dedup order), or None when the
    native library is unavailable or predates ``crawl_drain_edges``.
    Raises the Python path's exception types on malformed input, like
    crawl_load.
    """
    # Loud floor, like build_graph_external's 64 MiB: the pipeline
    # needs 2 x 16 MiB file batches + the sort's 64 MiB minimum, and
    # silently running OVER a smaller promise would contradict the
    # flag's contract (the integer-edge path rejects such caps too).
    if mem_cap_bytes < (128 << 20):
        raise ValueError(
            "mem_cap_bytes must be at least 128 MiB for crawl inputs "
            "(2 file-batch buffers + the external sort's 64 MiB floor)"
        )
    lib = get_lib()
    if lib is None or not hasattr(lib, "crawl_drain_edges"):
        return None
    from pagerank_tpu.ingest.external import build_graph_external
    from pagerank_tpu.ingest.ids import IdMap

    kind_code = (
        _CRAWL_KIND_SEQFILE if kind == "seqfile" else _CRAWL_KIND_TSV
    )
    paths = list(paths)
    if threads is None:
        threads = min(len(paths), os.cpu_count() or 1)
    threads = max(int(threads), 1)
    window = max(2 * threads, 1)
    # Carve the file-byte batches out of the caller's cap: two batches
    # are live at once (current + prefetched), so the sort gets the
    # remainder and the promise covers the pipeline end to end.
    byte_cap = min(256 << 20, max(16 << 20, mem_cap_bytes // 4))
    sort_cap = max(64 << 20, mem_cap_bytes - 2 * byte_cap)

    h = lib.crawl_new()
    try:
        def chunk_gen():
            for _ in _iter_ingest_batches(lib, h, paths, window, byte_cap,
                                          kind_code, strict, threads):
                e = lib.crawl_num_edges(h)
                src = np.empty(max(e, 1), np.int32)
                dst = np.empty(max(e, 1), np.int32)
                got = lib.crawl_drain_edges(h, src, dst)
                assert got == e, (got, e)
                if e:
                    yield src[:e], dst[:e]

        crawled_box = {}

        def final_n():
            n = lib.crawl_num_vertices(h)
            crawled = np.zeros(max(n, 1), np.uint8)
            if n:
                lib.crawl_copy_crawled(h, crawled)
            crawled_box["mask"] = crawled[:n].astype(bool)
            crawled_box["n"] = n
            return n

        graph = build_graph_external(
            chunk_gen(),
            n=final_n,
            mem_cap_bytes=sort_cap,
            tmp_dir=tmp_dir,
            dangling_mask=lambda: ~crawled_box["mask"],
        )
        names = _copy_names(lib, h, crawled_box["n"])
    finally:
        lib.crawl_free(h)
    graph.vertex_names = names
    return graph, IdMap.from_names(names)


def sort_dedup_degrees_native(
    src: np.ndarray, dst: np.ndarray, n: int
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """dst-major radix sort + dedup + degree count. Returns (src32, dst32,
    out_degree, in_degree) or None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    e = src.shape[0]
    out_src = np.empty(max(e, 1), np.int32)
    out_dst = np.empty(max(e, 1), np.int32)
    out_deg = np.empty(n, np.int32)
    in_deg = np.empty(n, np.int32)
    k = lib.sort_dedup_degrees(src, dst, e, n, out_src, out_dst, out_deg, in_deg)
    return out_src[:k].copy(), out_dst[:k].copy(), out_deg, in_deg
