from pagerank_tpu.ingest.ids import IdMap, records_to_arrays, records_to_graph
from pagerank_tpu.ingest.edgelist import (
    load_edgelist,
    load_binary_edges,
    save_binary_edges,
)
from pagerank_tpu.ingest.crawljson import (
    load_crawl_file,
    load_crawl_file_arrays,
    parse_metadata_record,
)
from pagerank_tpu.ingest.seqfile import (
    load_crawl_seqfile,
    load_crawl_seqfile_arrays,
    read_sequence_file,
    write_sequence_file,
)

__all__ = [
    "IdMap",
    "records_to_arrays",
    "records_to_graph",
    "load_edgelist",
    "load_binary_edges",
    "save_binary_edges",
    "parse_metadata_record",
    "load_crawl_file",
    "load_crawl_file_arrays",
    "load_crawl_seqfile",
    "load_crawl_seqfile_arrays",
    "read_sequence_file",
    "write_sequence_file",
]
