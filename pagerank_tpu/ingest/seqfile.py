"""Hadoop SequenceFile ingestion — the reference's literal input format.

The reference reads the Common Crawl web graph as Hadoop SequenceFiles
of (Text url, Text json-metadata) pairs: ``ctx.sequenceFile(path,
Text.class, Text.class)`` over 301 `metadata-*` segments
(Sparky.java:44-58,61). This module reads that on-disk format directly
(and writes it, for tests and interop), so a dataset prepared for the
reference runs here unmodified.

Format implemented (the one the reference's inputs use): SequenceFile
version 6, record-oriented, uncompressed, ``org.apache.hadoop.io.Text``
keys and values:

    "SEQ" 0x06
    keyClassName: Hadoop writeString (Text-style VInt length + UTF-8)
    valueClassName: writeString
    compressed: bool byte      (must be 0 here)
    blockCompressed: bool byte (must be 0 here)
    metadata: int32-BE pair count, then (writeString k, writeString v)*
    sync: 16 random bytes
    records: int32-BE recordLen | int32-BE keyLen | key | value
             recordLen == -1 -> a 16-byte sync marker follows (verified)

``Text`` payloads inside a record carry their own Hadoop VInt length
prefix followed by UTF-8 bytes.

Compression: the reference inherits transparent codec support through
``ctx.sequenceFile`` (Sparky.java:61), so both Hadoop layouts of
DefaultCodec/DeflateCodec (plain zlib) are read AND written here:

- *record* compression (``compressed=1, blockCompressed=0``): each
  record's value bytes are a zlib stream; keys stay raw.
- *block* compression (``compressed=1, blockCompressed=1``): records
  are buffered and flushed as blocks — each block is a sync marker,
  a VInt record count, then FOUR length-prefixed zlib streams
  (key lengths, keys, value lengths, values), per Hadoop's
  ``SequenceFile.BlockCompressWriter``. Common Crawl segments of the
  reference's vintage commonly use this layout.

Other codecs (gzip framing, snappy, lzo) raise a clear error.
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import Iterable, Iterator, List, Optional, Tuple

from pagerank_tpu.utils import fsio

SEQ_MAGIC = b"SEQ"
TEXT_CLASS = "org.apache.hadoop.io.Text"
_DEFLATE_CODECS = (
    "org.apache.hadoop.io.compress.DefaultCodec",
    "org.apache.hadoop.io.compress.DeflateCodec",
)


# -- Hadoop primitive encodings ------------------------------------------


def _read_vint(f) -> int:
    """Hadoop WritableUtils.readVInt/VLong: single byte in [-112, 127]
    is the value; otherwise it encodes sign + byte count."""
    b0 = f.read(1)
    if not b0:
        raise EOFError("EOF inside VInt")
    first = struct.unpack("b", b0)[0]
    if first >= -112:
        return first
    if first >= -120:
        size, negative = first + 112, False
    else:
        size, negative = first + 120, True
    size = -size
    data = f.read(size)
    if len(data) != size:
        raise EOFError("EOF inside VInt body")
    value = 0
    for byte in data:
        value = (value << 8) | byte
    return ~value if negative else value


def _write_vint(out: io.BytesIO, value: int) -> None:
    if -112 <= value <= 127:
        out.write(struct.pack("b", value))
        return
    negative = value < 0
    if negative:
        value = ~value
    size = (value.bit_length() + 7) // 8
    out.write(struct.pack("b", (-120 if negative else -112) - size))
    out.write(value.to_bytes(size, "big"))


def _read_i32(f, what: str) -> int:
    data = f.read(4)
    if len(data) != 4:
        raise EOFError(f"EOF inside {what}")
    return struct.unpack(">i", data)[0]


def _read_exact(f, n: int, what: str) -> bytes:
    """Read exactly ``n`` bytes or raise EOFError — in bounded chunks,
    so a corrupt length field (a flipped VInt/int32 can claim 2^60
    bytes) fails with EOFError instead of a huge upfront allocation
    blowing up as MemoryError (found by the native-vs-Python container
    fuzz, tests/test_native_crawl.py)."""
    if n < (1 << 24):
        data = f.read(n)
        if len(data) != n:
            raise EOFError(f"EOF inside {what}")
        return data
    chunks = []
    remaining = n
    while remaining:
        chunk = f.read(min(remaining, 1 << 24))
        if not chunk:
            raise EOFError(f"EOF inside {what}")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def _read_text(f) -> bytes:
    n = _read_vint(f)
    if n < 0:
        raise ValueError(f"negative Text length {n}")
    return _read_exact(f, n, "Text payload")


def _text_bytes(s: str) -> bytes:
    out = io.BytesIO()
    payload = s.encode("utf-8")
    _write_vint(out, len(payload))
    out.write(payload)
    return out.getvalue()


# -- reading --------------------------------------------------------------


def read_sequence_file(path: str) -> Iterator[Tuple[str, str]]:
    """Yield (key, value) Text pairs from one SequenceFile.

    Supports version-6 files with Text/Text classes: uncompressed,
    per-record deflate, or block-compressed deflate (DefaultCodec —
    plain zlib). Other codecs and non-Text classes raise ValueError.
    ``path`` may use any registered URI scheme (utils/fsio) — the
    reference reads these straight off S3 (Sparky.java:44-61).
    """
    with fsio.fopen(path, "rb") as f:
        magic = f.read(4)
        # len guard: a file truncated inside the magic (e.g. exactly
        # b"SEQ") must raise the same FORMAT ValueError as the native
        # reader (crawl_ingest.cpp), not IndexError on magic[3].
        if len(magic) < 4 or magic[:3] != SEQ_MAGIC:
            raise ValueError(f"{path}: not a SequenceFile (magic {magic!r})")
        version = magic[3]
        if version != 6:
            raise ValueError(
                f"{path}: SequenceFile version {version}; only the "
                "version-6 layout (metadata header, Text class names) "
                "is supported"
            )
        key_cls = _read_text(f).decode("utf-8")
        val_cls = _read_text(f).decode("utf-8")
        if key_cls != TEXT_CLASS or val_cls != TEXT_CLASS:
            raise ValueError(
                f"{path}: expected Text/Text pairs "
                f"(Sparky.java:61), got {key_cls}/{val_cls}"
            )
        compressed = f.read(1) != b"\x00"
        block_compressed = f.read(1) != b"\x00"
        decompress = None
        if compressed:
            codec = _read_text(f).decode("utf-8")
            if codec not in _DEFLATE_CODECS:
                raise ValueError(f"{path}: unsupported codec {codec}")
            decompress = zlib.decompress
        n_meta = _read_i32(f, "metadata count")
        for _ in range(n_meta):
            _read_text(f)
            _read_text(f)
        sync = f.read(16)
        if len(sync) != 16:
            raise EOFError(f"{path}: truncated header (sync marker)")

        if block_compressed:
            yield from _read_blocks(f, path, sync, decompress)
            return

        while True:
            head = f.read(4)
            if len(head) < 4:
                return  # clean EOF
            rec_len = struct.unpack(">i", head)[0]
            if rec_len == -1:  # sync escape
                marker = f.read(16)
                if marker != sync:
                    raise ValueError(f"{path}: sync marker mismatch "
                                     "(corrupt file)")
                continue
            if rec_len < 0:
                raise ValueError(f"{path}: bad record length {rec_len}")
            key_len = _read_i32(f, "key length")
            if not (0 <= key_len <= rec_len):
                raise ValueError(f"{path}: bad key length {key_len}")
            key_raw = _read_exact(f, key_len, f"record ({path})")
            val_raw = _read_exact(f, rec_len - key_len, f"record ({path})")
            if decompress is not None:
                val_raw = decompress(val_raw)
            key = _read_text(io.BytesIO(key_raw)).decode("utf-8", "replace")
            val = _read_text(io.BytesIO(val_raw)).decode("utf-8", "replace")
            yield key, val


def _read_blocks(f, path: str, sync: bytes, decompress) -> Iterator[Tuple[str, str]]:
    """Iterate a block-compressed body: each block is SYNC_ESCAPE(-1) +
    sync + VInt recordCount + four VInt-length-prefixed compressed
    buffers (key lengths, keys, value lengths, values) — the layout
    Hadoop's ``SequenceFile.BlockCompressWriter.sync()`` emits."""
    if decompress is None:
        raise ValueError(f"{path}: block-compressed flag set without a codec")

    def read_buffer(what: str) -> io.BytesIO:
        n = _read_vint(f)
        if n < 0:
            raise ValueError(f"{path}: bad {what} buffer length {n}")
        data = _read_exact(f, n, f"{what} buffer ({path})")
        return io.BytesIO(decompress(data))

    while True:
        head = f.read(4)
        if len(head) < 4:
            return  # clean EOF between blocks
        if struct.unpack(">i", head)[0] != -1:
            raise ValueError(f"{path}: expected block sync escape, got {head!r}")
        marker = f.read(16)
        if marker != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
        n_rec = _read_vint(f)
        if n_rec < 0:
            raise ValueError(f"{path}: bad block record count {n_rec}")
        key_lens = read_buffer("key-lengths")
        keys = read_buffer("keys")
        val_lens = read_buffer("value-lengths")
        vals = read_buffer("values")
        for _ in range(n_rec):
            klen = _read_vint(key_lens)
            key_raw = keys.read(klen)
            vlen = _read_vint(val_lens)
            val_raw = vals.read(vlen)
            if len(key_raw) != klen or len(val_raw) != vlen:
                raise EOFError(f"{path}: truncated block record")
            key = _read_text(io.BytesIO(key_raw)).decode("utf-8", "replace")
            val = _read_text(io.BytesIO(val_raw)).decode("utf-8", "replace")
            yield key, val


def expand_seqfile_paths(spec: str) -> List[str]:
    """A path, a directory (all non-hidden files, sorted — the layout of
    a crawl segment like the reference's `metadata-00000..00300`), or a
    comma-joined list of either (the reference builds a comma-joined
    path string, Sparky.java:42-58)."""
    paths: List[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if fsio.isdir(part):
            paths.extend(
                full
                for name in sorted(fsio.listdir(part))
                if not name.startswith((".", "_"))
                and fsio.isfile(full := fsio.join(part, name))
            )
        else:
            paths.append(part)
    if not paths:
        raise ValueError(f"no input files in {spec!r}")
    return paths


def _parse_seqfile_worker(args):
    """One segment file -> parsed (url, targets) records; runs in a
    forked worker process (module-level so it pickles by reference)."""
    path, strict = args
    from pagerank_tpu.ingest.crawljson import parse_metadata_record

    return [
        parse_metadata_record(url, meta, strict=strict)
        for url, meta in read_sequence_file(path)
    ]


def iter_segment_records(
    paths, strict: bool = True, workers: Optional[int] = None
):
    """Parsed records from a multi-file segment, optionally in parallel.

    The reference parses its 301 segment files across the cluster
    (``ctx.sequenceFile``, Sparky.java:61); here the per-file work
    (VInt/codec decode + JSON anchor extraction, both pure-Python
    CPU-bound) fans out over a process pool. ``workers=None`` = auto:
    one per core, capped by the file count (serial on single-core hosts
    — this image's case, where the pool is pure overhead;
    docs/PERF_NOTES.md "Host ingest"). Record order — and therefore id
    assignment and every downstream array — is IDENTICAL to the serial
    path: files are yielded in input order, records in file order
    (tests/test_seqfile.py pins this).

    Workers inherit the fsio registry and parsed state by fork, so
    registered in-memory stores (mock://) keep working; platforms
    without fork fall back to serial.
    """
    import multiprocessing
    import os
    import threading

    paths = list(paths)
    if workers is None:
        workers = min(len(paths), os.cpu_count() or 1)
        # Auto mode degrades to serial once the parent is multi-threaded
        # (e.g. the async snapshot writer, or an engine already built):
        # forking a threaded process can clone a held lock into the
        # child and deadlock the pool. An EXPLICIT workers>1 is honored
        # as the caller's assertion that forking is safe here (the CLI
        # ingests before any engine/writer exists).
        if threading.active_count() > 1:
            workers = 1
    if (
        workers <= 1
        or len(paths) <= 1
        or "fork" not in multiprocessing.get_all_start_methods()
    ):
        import time as _time

        from pagerank_tpu.ingest.crawljson import parse_metadata_record
        from pagerank_tpu.obs import trace as obs_trace

        tracer = obs_trace.get_tracer()
        for path in paths:
            if tracer.enabled:
                # Per-file attribution (docs/OBSERVABILITY.md) WITHOUT
                # changing the memory profile: the stream stays lazy
                # (a production segment file holds millions of
                # records) and a pre-measured span is recorded when
                # the file's iterator is exhausted. The span covers
                # the file's streaming WINDOW — consumer work
                # interleaved by the generator is included — which is
                # the honest bound a lazy pipeline admits.
                t0 = _time.perf_counter()
                n = 0
                for url, meta in read_sequence_file(path):
                    n += 1
                    yield parse_metadata_record(url, meta, strict=strict)
                tracer.add_span(
                    "ingest/seqfile_file", t0,
                    _time.perf_counter() - t0, path=path, records=n,
                )
            else:
                for url, meta in read_sequence_file(path):
                    yield parse_metadata_record(url, meta, strict=strict)
        return
    import collections
    import concurrent.futures

    ctx = multiprocessing.get_context("fork")
    with concurrent.futures.ProcessPoolExecutor(
        max_workers=workers, mp_context=ctx
    ) as ex:
        # Bounded in-flight window (2x workers) instead of ex.map: map
        # submits every file at once, and since the consumer drains in
        # order, completed per-file record lists would pile up to the
        # whole parsed segment in RAM. The window keeps the speedup with
        # a bounded transient. Order is preserved (deque is FIFO over
        # the input order); a strict-mode parse error in any worker
        # propagates at its file's position, matching the serial crash.
        pending = collections.deque()
        it = iter(paths)
        for path in it:
            pending.append(ex.submit(_parse_seqfile_worker, (path, strict)))
            if len(pending) >= 2 * workers:
                break
        while pending:
            yield from pending.popleft().result()
            for path in it:
                pending.append(
                    ex.submit(_parse_seqfile_worker, (path, strict))
                )
                break


def load_crawl_seqfile(
    spec: str, strict: bool = True, workers: Optional[int] = None,
    native: str = "auto",
):
    """SequenceFile(s) of (url, crawl-metadata json) -> (Graph, IdMap).

    The exact pipeline the reference runs on these files: JSON anchor
    extraction with the Gson rendering quirks (crawljson.py), then the
    dedup/adjacency/dangling graph build (Sparky.java:61-124).

    ``native="auto"`` (default) uses the C++ L1 when the library builds
    (container decode + JSON extraction + interning in one pass — 7.5x
    the pure-Python record rate per core, docs/PERF_NOTES.md "Host
    ingest"); identical output is differentially pinned by
    tests/test_native_crawl.py. ``native="off"`` — or an EXPLICIT
    ``workers`` value, which is a request for the Python process pool —
    forces the Python path, where multi-file segments parse in parallel
    (see :func:`iter_segment_records`).
    """
    return _load_crawl_seqfile(spec, strict, workers, native, raw=False)


def load_crawl_seqfile_arrays(
    spec: str, strict: bool = True, workers: Optional[int] = None,
    native: str = "auto",
):
    """Like :func:`load_crawl_seqfile` but stops before the host graph
    build: returns raw ``(src, dst, crawled_mask, IdMap)`` integer
    arrays for the on-device build (`--device-build` on crawl inputs —
    the dedup/sort/pack then runs on the TPU)."""
    return _load_crawl_seqfile(spec, strict, workers, native, raw=True)


def _load_crawl_seqfile(spec, strict, workers, native, raw):
    """Shared native-try/Python-fallback gating for both return forms —
    one copy of the rules (auto + no explicit workers -> native;
    NativeUnsupported or no library -> Python path)."""
    from pagerank_tpu.obs import trace as obs_trace

    paths = expand_seqfile_paths(spec)
    with obs_trace.span("ingest/seqfile", files=len(paths)) as sp:
        if native == "auto" and workers is None:
            from pagerank_tpu.ingest import native as native_mod

            result = native_mod.try_crawl_load(paths, "seqfile",
                                               strict=strict, raw=raw)
            if result is not None:
                if sp is not None:
                    sp.attrs["parser"] = "native"
                return result
        from pagerank_tpu.ingest.ids import (records_to_arrays,
                                             records_to_graph)

        if sp is not None:
            sp.attrs["parser"] = "python"
        records = iter_segment_records(paths, strict, workers)
        return (records_to_arrays(records) if raw
                else records_to_graph(records))


# -- writing (tests + interop) -------------------------------------------


def write_sequence_file(
    path: str,
    pairs: Iterable[Tuple[str, str]],
    sync_every: int = 100,
    compression: str = "none",
    block_size: int = 1 << 20,
) -> int:
    """Write (key, value) Text pairs as a version-6 SequenceFile
    readable by Hadoop/Spark and :func:`read_sequence_file`. Returns the
    record count.

    ``compression``: "none", "record" (each value a zlib stream), or
    "block" (Hadoop block layout: records buffered until ~``block_size``
    raw bytes, then flushed as sync + VInt count + four compressed
    buffers). Both compressed modes declare DefaultCodec."""
    if compression not in ("none", "record", "block"):
        raise ValueError(f"unknown compression {compression!r}")
    sync = bytes((i * 89 + 41) % 256 for i in range(16))
    count = 0
    with fsio.fopen(path, "wb") as f:
        f.write(SEQ_MAGIC + bytes([6]))
        f.write(_text_bytes(TEXT_CLASS))
        f.write(_text_bytes(TEXT_CLASS))
        f.write(b"\x00" if compression == "none" else b"\x01")
        f.write(b"\x01" if compression == "block" else b"\x00")
        if compression != "none":
            f.write(_text_bytes(_DEFLATE_CODECS[0]))
        f.write(struct.pack(">i", 0))  # no metadata
        f.write(sync)

        if compression == "block":
            key_lens, keys = io.BytesIO(), io.BytesIO()
            val_lens, vals = io.BytesIO(), io.BytesIO()
            buffered = 0

            def flush():
                nonlocal buffered
                if not buffered:
                    return
                f.write(struct.pack(">i", -1))
                f.write(sync)
                _write_vint(f, buffered)
                for buf in (key_lens, keys, val_lens, vals):
                    comp = zlib.compress(buf.getvalue())
                    _write_vint(f, len(comp))
                    f.write(comp)
                    buf.seek(0)
                    buf.truncate()
                buffered = 0

            for key, value in pairs:
                k = _text_bytes(key)
                v = _text_bytes(value)
                _write_vint(key_lens, len(k))
                keys.write(k)
                _write_vint(val_lens, len(v))
                vals.write(v)
                buffered += 1
                count += 1
                if keys.tell() + vals.tell() >= block_size:
                    flush()
            flush()
            return count

        deflate = zlib.compress if compression == "record" else None
        for key, value in pairs:
            if count and sync_every and count % sync_every == 0:
                f.write(struct.pack(">i", -1))
                f.write(sync)
            k = _text_bytes(key)
            v = _text_bytes(value)
            if deflate is not None:
                v = deflate(v)
            f.write(struct.pack(">i", len(k) + len(v)))
            f.write(struct.pack(">i", len(k)))
            f.write(k)
            f.write(v)
            count += 1
    return count
