"""String-vertex id assignment (C8 in SURVEY.md §2).

The reference collects all source urls to the driver and broadcasts a
HashSet for membership tests (Sparky.java:127-135). The TPU-native
equivalent is a host-side url -> int32 id dictionary built once during
ingestion; devices only ever see integer ids.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np

from pagerank_tpu.graph import Graph, build_graph


class IdMap:
    """Insertion-ordered string -> int32 id assignment."""

    def __init__(self):
        self._ids = {}
        self._names: List[str] = []

    def get_or_add(self, name: str) -> int:
        i = self._ids.get(name)
        if i is None:
            i = len(self._names)
            self._ids[name] = i
            self._names.append(name)
        return i

    def get(self, name: str) -> Optional[int]:
        return self._ids.get(name)

    def __len__(self) -> int:
        return len(self._names)

    def __contains__(self, name: str) -> bool:
        return name in self._ids

    @property
    def names(self) -> List[str]:
        return self._names

    @classmethod
    def from_names(cls, names: List[str]) -> "IdMap":
        """Rebuild the map from an insertion-ordered name list (the
        native ingest path returns ids already assigned)."""
        m = cls()
        m._names = list(names)
        m._ids = {name: i for i, name in enumerate(m._names)}
        return m


def records_to_arrays(
    records: Iterable[Tuple[str, List[str]]],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, IdMap]:
    """Crawl records -> raw (src, dst, crawled_mask, ids) arrays —
    the id-assignment half of :func:`records_to_graph`, exposed so the
    on-device build can consume integer edges directly."""
    ids = IdMap()
    src: List[int] = []
    dst: List[int] = []
    crawled: List[int] = []
    for url, targets in records:
        u = ids.get_or_add(url)
        crawled.append(u)
        for t in targets:
            src.append(u)
            dst.append(ids.get_or_add(t))
    n = len(ids)
    crawled_mask = np.zeros(n, dtype=bool)
    if crawled:
        crawled_mask[np.asarray(crawled)] = True
    # int32: ids are int32 by construction (IdMap), and the device-build
    # path ships these over the host->device link — 8 bytes/edge.
    return (
        np.asarray(src, dtype=np.int32),
        np.asarray(dst, dtype=np.int32),
        crawled_mask,
        ids,
    )


def records_to_graph(
    records: Iterable[Tuple[str, List[str]]],
) -> Tuple[Graph, IdMap]:
    """Build a :class:`Graph` from (url, anchor-targets) crawl records.

    A record with no targets contributes a vertex with no out-edges — the
    reference's dangling sentinel (Sparky.java:114-118). Linked-to but
    never-crawled targets become vertices too (Sparky.java:137-161); that
    falls out of id assignment covering both endpoints.

    Dangling-mass membership follows the post-repair ``dangUrls``
    (Sparky.java:172-184): *uncrawled targets only*. A crawled page with
    no anchor links contributes nothing and is NOT in the dangling mass —
    its lookup value is a non-null Iterable([null]), so the repair pass
    removes it (see graph.py module docstring).
    """
    src, dst, crawled_mask, ids = records_to_arrays(records)
    graph = build_graph(
        src,
        dst,
        n=len(ids),
        dangling_mask=~crawled_mask,
        vertex_names=ids.names,
    )
    return graph, ids
