"""Seed-deterministic serving chaos harness (ISSUE 18).

Drives a :class:`~pagerank_tpu.serving.daemon.PprServer` in its
synchronous pump mode on a virtual clock, with faults from the same
:class:`~pagerank_tpu.testing.faults.DeviceFaultSchedule` machinery
the solver chaos tests use. Everything that shapes an admission or
shed decision is a pure function of the seed:

- arrivals, sources, per-query deadlines come from ``random.Random
  (seed)`` (:class:`QueryLoadGenerator`);
- time is a :class:`~pagerank_tpu.testing.schedules.VirtualClock` the
  harness advances explicitly (arrival gaps + a fixed per-batch
  service wall), so no real scheduler jitter leaks in;
- the batch wall model is FROZEN (``wall_alpha=0``) at the injected
  service wall, so the predictive shed compares the same numbers every
  run;
- the fault shim consults ``schedule.decide(batch_index, device_ids)``
  — and a post-rescue RE-RUN of the in-flight batch re-consults the
  SAME index, where the schedule's one-shot memory guarantees the
  killed device cannot die twice.

Contract the report makes checkable: same seed => same admissions,
same sheds, same casualty, bit-identical served results
(``results_digest``), and every submitted query in exactly one typed
terminal state (``unsettled == 0`` — the zero-silent-drops ledger).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from pagerank_tpu.parallel.elastic import DeviceLostError
from pagerank_tpu.serving import qtrace
from pagerank_tpu.serving.daemon import PprServer
from pagerank_tpu.testing.faults import DeviceFaultSchedule
from pagerank_tpu.testing.schedules import VirtualClock


@dataclass
class QueryLoadGenerator:
    """Open-loop arrival plan: ``plan()`` yields
    ``(gap_s, source, k, deadline_s)`` tuples, a pure function of the
    seed. ``repeat_frac`` of queries re-ask one of ``hot_set`` sources
    (the LRU cache's traffic); deadlines draw uniformly from
    ``deadline_range_s``."""

    seed: int = 0
    num_queries: int = 64
    n: int = 1 << 10              # source id space (graph order)
    mean_gap_s: float = 0.01      # open-loop exponential arrivals
    k: int = 8
    deadline_range_s: Tuple[float, float] = (0.25, 0.75)
    repeat_frac: float = 0.25
    hot_set: int = 4
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def plan(self) -> List[Tuple[float, int, int, float]]:
        rng = random.Random(self.seed)
        hot = [rng.randrange(self.n) for _ in range(max(1, self.hot_set))]
        out = []
        lo, hi = self.deadline_range_s
        for _ in range(self.num_queries):
            gap = rng.expovariate(1.0 / self.mean_gap_s) \
                if self.mean_gap_s > 0 else 0.0
            if rng.random() < self.repeat_frac:
                source = hot[rng.randrange(len(hot))]
            else:
                source = rng.randrange(self.n)
            deadline = lo + (hi - lo) * rng.random()
            out.append((gap, source, self.k, deadline))
        return out


def install_serve_faults(server: PprServer,
                         schedule: DeviceFaultSchedule,
                         clock: Optional[VirtualClock] = None,
                         service_s: float = 0.0) -> PprServer:
    """Wrap the server's ``_execute`` seam with the fault shim.

    The seam is SERVER-level (not engine-level) so it survives the
    rescue path's engine rebuild without re-installation. The shim
    consults the schedule once per batch *attempt*, keyed by the count
    of batches completed so far — a rescue re-run therefore re-consults
    the same index, and ``DeviceFaultSchedule``'s one-shot ``_fired``
    memory keeps the casualty list stable. ``kill`` actions raise
    :class:`DeviceLostError` BEFORE the dispatch (the device died
    mid-collective); ``delay`` actions stretch the virtual service
    wall; other action kinds are solver-plane and ignored here.
    ``service_s`` > 0 advances the virtual clock per completed dispatch
    so latency and deadline dynamics replay identically."""
    orig = getattr(server, "_prefault_execute", server._execute)
    server._prefault_execute = orig
    state = {"batch": 0}

    def shimmed(sources):
        i = state["batch"]
        actions = schedule.decide(i, server.device_ids())
        kills = [a for a in actions if a[0] == "kill"]
        if kills:
            raise DeviceLostError(
                f"injected device loss at serve batch {i} "
                f"(seed {schedule.seed})",
                device_ids=[a[1] for a in kills],
            )
        out = orig(sources)
        if clock is not None:
            extra = sum(a[2] for a in actions if a[0] == "delay")
            clock.advance(service_s + extra)
        state["batch"] = i + 1
        return out

    server._execute = shimmed
    return server


def run_serve_load(
    server: PprServer,
    clock: VirtualClock,
    plan: List[Tuple[float, int, int, float]],
    drain_at: Optional[int] = None,
    drain_deadline_s: float = 1.0,
    settle_step_s: float = 0.05,
    max_settle_steps: int = 10_000,
) -> Dict:
    """Replay ``plan`` against a started (pump-mode) server on the
    virtual clock; returns the determinism report.

    ``drain_at=j`` triggers the SIGTERM path right before query ``j``
    is submitted: :meth:`PprServer.drain` runs (queued batches finish
    inside ``drain_deadline_s``, the rest typed-reject), and the
    remaining arrivals still submit — exercising typed ``Draining``
    rejections at closed admission."""
    handles = []
    for idx, (gap, source, k, deadline_s) in enumerate(plan):
        if drain_at is not None and idx == drain_at:
            server.drain(deadline_s=drain_deadline_s)
        clock.advance(gap)
        handles.append(server.submit(source, k=k, deadline_s=deadline_s))
        server.pump()
    # Settle: advance virtual time until every queued batch closes
    # (deadline-margin closes need the clock to move).
    steps = 0
    while len(server.queue) > 0:
        steps += 1
        if steps > max_settle_steps:
            raise RuntimeError(
                f"queue failed to settle within {max_settle_steps} "
                f"virtual steps — a hang the serving contract forbids"
            )
        clock.advance(settle_step_s)
        server.pump()

    outcomes: Dict[str, int] = {}
    digest = hashlib.sha256()
    latencies_ms = []
    unsettled = 0
    admission_log = []
    for q in handles:
        out = q.outcome
        if not out:
            unsettled += 1
            out = "<unsettled>"
        outcomes[out] = outcomes.get(out, 0) + 1
        admission_log.append((q.qid, q.source, out))
        digest.update(f"{q.qid}:{q.source}:{out}".encode())
        if out.startswith("answered"):
            ids, scores = q.result(timeout=0)
            digest.update(np.ascontiguousarray(ids).tobytes())
            digest.update(np.ascontiguousarray(scores).tobytes())
            latencies_ms.append(1000.0 * (q.latency_s or 0.0))
    rep = {
        "queries": len(handles),
        "outcomes": outcomes,
        "unsettled": unsettled,
        "admission_log": admission_log,
        "results_digest": digest.hexdigest(),
        "latencies_ms": latencies_ms,
        "degraded": server.degraded,
        "device_count": server.device_count,
    }
    plane = qtrace.get_query_plane()
    if plane is not None:
        # Query plane armed (ISSUE 19): the timestamp-free span-tree
        # digest rides the determinism report — same seed must give
        # the same trace structure, not just the same outcomes.
        rep["trace_digest"] = plane.structure_digest()
    return rep


def chaos_run(seed: int = 7, queries: int = 40, iters: int = 5,
              kill_batch: int = 3, kill_device: int = 5,
              drain_at: Optional[int] = None,
              service_s: float = 0.05) -> Dict:
    """One canonical seed-deterministic chaos run (the acceptance
    smoke's shape, reusable from the CLI): 256-vertex R-MAT graph,
    pump-mode server on a virtual clock, frozen batch wall, one
    injected device kill, optional mid-load drain. The caller's
    environment must provide a (fake-)multi-device CPU mesh."""
    from pagerank_tpu import PageRankConfig, build_graph
    from pagerank_tpu.serving.daemon import ServeConfig
    from pagerank_tpu.utils import synth

    n = 256
    src, dst = synth.rmat_edges(8, edge_factor=8, seed=3)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(num_iters=iters)
    sc = ServeConfig(max_batch=4, queue_depth=16, deadline_ms=400.0,
                     topk=8, wall_alpha=0.0, wall_initial_s=0.05,
                     cache_capacity=64, batch_margin_s=0.01)
    clock = VirtualClock()
    sched = DeviceFaultSchedule(seed=seed, kill={kill_batch: kill_device})
    srv = PprServer(g, config=cfg, serve_config=sc,
                    liveness_probe=sched.liveness_probe, clock=clock)
    srv.start(dispatcher=False)
    install_serve_faults(srv, sched, clock=clock, service_s=service_s)
    plan = QueryLoadGenerator(seed=seed, num_queries=queries, n=n,
                              mean_gap_s=0.02, k=8).plan()
    return run_serve_load(srv, clock, plan, drain_at=drain_at,
                          drain_deadline_s=1.0)


def main(argv=None) -> int:
    """``python -m pagerank_tpu.testing.load``: run the canonical
    chaos load with the query plane armed; ``--trace PATH`` exports a
    Perfetto-loadable Chrome trace with per-thread lanes, and the
    JSON determinism report (with ``trace_digest``) prints to stdout.
    """
    import argparse
    import json
    import threading

    from pagerank_tpu.obs import trace as obs_trace

    p = argparse.ArgumentParser(
        description="seed-deterministic serving chaos harness")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--queries", type=int, default=40)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--kill-batch", type=int, default=3)
    p.add_argument("--kill-device", type=int, default=5)
    p.add_argument("--drain-at", type=int, default=None,
                   help="trigger the SIGTERM drain path before query N")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="export a Chrome trace (Perfetto-loadable) of "
                        "the run's query spans, one lane per thread")
    p.add_argument("--slow-query-ms", type=float, default=None,
                   help="log outliers >= this latency as strict JSONL "
                        "(requires --slow-query-log)")
    p.add_argument("--slow-query-log", default=None, metavar="PATH",
                   help="destination for the slow-query JSONL "
                        "(requires --slow-query-ms)")
    args = p.parse_args(argv)
    if (args.slow_query_ms is None) != (args.slow_query_log is None):
        # Half the pair silently counts-without-writing (or never arms
        # the threshold) — refuse it at parse time, like serve's CLI.
        p.error(
            "--slow-query-ms and --slow-query-log must be given together"
        )

    tracer = None
    if args.trace:
        tracer = obs_trace.enable_tracing()
        tracer.set_thread_label(threading.get_ident(), "serve-harness")
    qtrace.arm_query_plane(slow_query_ms=args.slow_query_ms,
                           slow_query_path=args.slow_query_log)
    try:
        rep = chaos_run(seed=args.seed, queries=args.queries,
                        iters=args.iters, kill_batch=args.kill_batch,
                        kill_device=args.kill_device,
                        drain_at=args.drain_at)
        plane = qtrace.get_query_plane()
        rep["phase_p99_ms"] = plane.phase_p99_ms()
    finally:
        qtrace.disarm_query_plane()
        if tracer is not None:
            obs_trace.disable_tracing()
            tracer.export_chrome(args.trace)
    print(json.dumps(rep, allow_nan=False))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
