"""Deterministic, seed-driven fault injection (docs/ROBUSTNESS.md).

The reference gets chaos testing for free — kill a Spark executor and
lineage recovery is exercised (SURVEY.md §5). This build's substrate is
utils/fsio + utils/s3 + utils/snapshot, so faults are injected at those
seams instead:

- :class:`FaultInjectingFileSystem` wraps ANY :class:`fsio.FileSystem`
  and injects failures / truncated writes / latency spikes;
- :class:`HttpFaultInjector` plugs into the S3 stub's wire level
  (tests/s3stub.S3Stub.fault_hook) to answer 5xx/SlowDown, drop
  connections mid-body, or lose a multipart-complete response;
- :class:`DeviceFaultSchedule` + :func:`install_device_faults`
  (ISSUE 7, ISSUE 15) inject DEVICE-plane faults — kill device k at
  iteration i, delay a step to simulate a straggler, poison the merged
  collective output, or silently FLIP one bit of one device's rank
  buffer (mantissa/exponent/sign; one-shot or sticky — the SDC
  plane's chaos substrate, pagerank_tpu/sdc.py) — through a mesh-aware
  shim over the engine's step, so the elastic rescue and SDC
  quarantine paths (parallel/elastic.py) are fully testable on CPU
  with ``XLA_FLAGS=--xla_force_host_platform_device_count=8``.

Everything is driven by a schedule whose decisions are a pure function
of (seed, call index) — device faults: (seed, iteration) — never of
wall clock or shared global randomness — and every decision is
appended to a ``log``, so a chaos run is REPRODUCIBLE: the same seed
yields the same schedule bit-for-bit across two runs (asserted in
tests/test_faults.py and tests/test_elastic.py; the acceptance chaos
smokes in scripts/acceptance.py gate on it).
"""

from __future__ import annotations

import io
import os
import random
import signal
import sys
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.utils import fsio


class FaultInjectedError(OSError):
    """An injected transient fault. An OSError so the default retry
    predicate (utils/retry.default_retryable) classifies it as
    transient — exactly how a real connection reset presents."""


class FaultSchedule:
    """Seeded decision stream for fault injection.

    Each ``decide(op, target)`` call advances a counter and draws a
    FIXED number of uniforms from the seeded stream (whether or not a
    fault fires), so the schedule depends only on the seed and the call
    SEQUENCE — reordering real work reorders faults, but re-running the
    same work reproduces them exactly.

    Triggers: ``fail_nth`` / ``truncate_nth`` / ``delay_nth`` fire on
    exact 1-based call indices; ``fail_rate`` / ``truncate_rate`` /
    ``delay_rate`` fire probabilistically. ``ops`` restricts which
    operations are eligible (None = all). ``max_faults`` caps the total
    number of injected faults — a chaos run with a finite fault budget
    below the consumer's retry budget is GUARANTEED to make progress.
    """

    def __init__(
        self,
        seed: int = 0,
        fail_nth: Iterable[int] = (),
        fail_rate: float = 0.0,
        truncate_nth: Iterable[int] = (),
        truncate_rate: float = 0.0,
        delay_nth: Iterable[int] = (),
        delay_rate: float = 0.0,
        delay_s: float = 0.0,
        ops: Optional[Iterable[str]] = None,
        max_faults: Optional[int] = None,
    ):
        self.seed = seed
        self._rng = random.Random(seed)
        self._fail_nth = frozenset(fail_nth)
        self._truncate_nth = frozenset(truncate_nth)
        self._delay_nth = frozenset(delay_nth)
        self._fail_rate = fail_rate
        self._truncate_rate = truncate_rate
        self._delay_rate = delay_rate
        self._delay_s = delay_s
        self._ops = None if ops is None else frozenset(ops)
        self._max_faults = max_faults
        self.calls = 0
        self.faults = 0
        #: (call_index, op, target, action) — the reproducibility record.
        self.log: List[Tuple[int, str, str, str]] = []

    def decide(self, op: str, target: str) -> Optional[Tuple]:
        self.calls += 1
        n = self.calls
        # Fixed draw count per call keeps the stream position a pure
        # function of the call index.
        u, v = self._rng.random(), self._rng.random()
        action: Optional[Tuple] = None
        eligible = (
            (self._ops is None or op in self._ops)
            and (self._max_faults is None or self.faults < self._max_faults)
        )
        if eligible:
            if n in self._fail_nth or u < self._fail_rate:
                action = ("fail",)
            elif n in self._truncate_nth or u < self._fail_rate + self._truncate_rate:
                action = ("truncate", v)  # keep this fraction of bytes
            elif (n in self._delay_nth
                  or u < self._fail_rate + self._truncate_rate + self._delay_rate):
                action = ("delay", self._delay_s * (0.5 + v))
        if action is not None:
            self.faults += 1
        self.log.append((n, op, target, action[0] if action else "-"))
        return action


class _FaultWriter(io.BytesIO):
    """Buffered writer committing through the wrapped store at close —
    the injection point for truncate-on-write faults (mirrors
    fsio._MemWriter, including abort-on-exception)."""

    def __init__(self, fs: "FaultInjectingFileSystem", path: str,
                 initial: bytes = b""):
        super().__init__()
        self.write(initial)
        self._fs = fs
        self._path = path
        self._aborted = False

    def abort(self):
        self._aborted = True

    def flush(self):
        super().flush()
        if (not self.closed and not self._aborted
                and self._fs.COMMIT_ON_FLUSH):
            self._fs._commit(self._path, self.getvalue(), final=False)

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        return super().__exit__(exc_type, exc, tb)

    def close(self):
        if not self.closed and not self._aborted:
            self._fs._commit(self._path, self.getvalue(), final=True)
        super().close()


class FaultInjectingFileSystem(fsio.FileSystem):
    """Wrap any :class:`fsio.FileSystem` with schedule-driven faults.

    Operations consult the schedule BEFORE delegating: ``("fail",)``
    raises :class:`FaultInjectedError` (transient — a retrying caller
    recovers), ``("delay", s)`` sleeps via the injectable ``sleep``
    (virtual in tests). Writes buffer in memory and commit at close;
    a ``("truncate", frac)`` decision at commit time publishes only a
    prefix of the bytes — the torn-object case checksummed snapshot
    loads must detect. Ops seen by the schedule: ``open_r``, ``commit``
    (write close), ``stat``, ``listdir``, ``replace``, ``makedirs``.
    """

    def __init__(self, inner: fsio.FileSystem, schedule: FaultSchedule,
                 sleep: Callable[[float], None] = time.sleep):
        self.inner = inner
        self.schedule = schedule
        self._sleep = sleep
        self.COMMIT_ON_FLUSH = getattr(inner, "COMMIT_ON_FLUSH", True)

    def _hit(self, op: str, path: str) -> Optional[Tuple]:
        act = self.schedule.decide(op, path)
        if act is None:
            return None
        if act[0] == "fail":
            raise FaultInjectedError(
                f"injected fault #{self.schedule.faults} on {op} {path!r} "
                f"(seed {self.schedule.seed}, call {self.schedule.calls})"
            )
        if act[0] == "delay":
            self._sleep(act[1])
            return None
        return act

    def _commit(self, path: str, data: bytes, final: bool = True) -> None:
        act = self._hit("commit", path) if final else None
        if act is not None and act[0] == "truncate":
            data = data[: int(len(data) * act[1])]
        with self.inner.open(path, "wb") as f:
            f.write(data)

    def open(self, path, mode="r", **kwargs):
        binary = "b" in mode
        kind = mode.replace("b", "").replace("t", "") or "r"
        if kind == "r":
            self._hit("open_r", path)
            return self.inner.open(path, mode, **kwargs)
        if kind not in ("w", "x", "a"):
            raise ValueError(f"unsupported mode {mode!r}")
        if kind == "x" and self.inner.isfile(path):
            raise FileExistsError(path)
        initial = b""
        if kind == "a" and self.inner.isfile(path):
            with self.inner.open(path, "rb") as f:
                initial = f.read()
        raw = _FaultWriter(self, path, initial)
        if kind == "a":
            raw.seek(0, io.SEEK_END)
        if binary:
            return raw
        kwargs.pop("newline", None)
        kwargs.setdefault("encoding", "utf-8")
        return fsio._MemTextWrapper(raw, **kwargs)

    def exists(self, path):
        self._hit("stat", path)
        return self.inner.exists(path)

    def isdir(self, path):
        self._hit("stat", path)
        return self.inner.isdir(path)

    def isfile(self, path):
        self._hit("stat", path)
        return self.inner.isfile(path)

    def listdir(self, path):
        self._hit("listdir", path)
        return self.inner.listdir(path)

    def makedirs(self, path, exist_ok=True):
        self._hit("makedirs", path)
        return self.inner.makedirs(path, exist_ok=exist_ok)

    def replace(self, src, dst):
        self._hit("replace", src)
        return self.inner.replace(src, dst)


class HttpFaultInjector:
    """Schedule adapter for the S3 stub's wire-level hook
    (tests/s3stub.S3Stub.fault_hook).

    ``plan`` maps 1-based request indices to stub action tuples —
    ``("status", 503, "SlowDown")``, ``("reset",)``,
    ``("truncate", nbytes)``, ``("commit_then_status", 500)`` — and
    ``fail_rate`` adds seeded probabilistic 5xx answers on top.
    ``methods`` restricts which HTTP verbs are eligible. Decisions are
    a pure function of (seed, request index) and are logged, so the
    wire-fault schedule reproduces bit-for-bit per seed."""

    def __init__(
        self,
        seed: int = 0,
        plan: Optional[Dict[int, Tuple]] = None,
        fail_rate: float = 0.0,
        fail_status: Tuple = ("status", 503, "SlowDown"),
        methods: Optional[Iterable[str]] = None,
        max_faults: Optional[int] = None,
    ):
        self.seed = seed
        self._rng = random.Random(seed)
        self._plan = dict(plan or {})
        self._fail_rate = fail_rate
        self._fail_status = fail_status
        self._methods = None if methods is None else frozenset(methods)
        self._max_faults = max_faults
        self.calls = 0
        self.faults = 0
        self.log: List[Tuple[int, str, str, str]] = []

    def __call__(self, method: str, path: str) -> Optional[Tuple]:
        self.calls += 1
        n = self.calls
        u = self._rng.random()
        action: Optional[Tuple] = None
        eligible = (
            (self._methods is None or method in self._methods)
            and (self._max_faults is None or self.faults < self._max_faults)
        )
        if eligible:
            action = self._plan.get(n)
            if action is None and u < self._fail_rate:
                action = self._fail_status
        if action is not None:
            self.faults += 1
        self.log.append((n, method, path, action[0] if action else "-"))
        return action


# -- process-plane faults (ISSUE 12; pagerank_tpu/jobs.py) -------------------


class ProcessKillPlan:
    """Seed-deterministic PROCESS-plane fault: kill THIS process with a
    real signal at a staged point of a resumable job (jobs.py stage
    boundaries; per-iteration inside the solve stage).

    The plan travels to the target process via :data:`KILL_ENV`
    (``stage=solve,iter=5,signal=TERM[,seed=N]``) so the chaos harness
    (:func:`run_job_subprocess`) can kill a REAL subprocess job at an
    exact, reproducible point — the self-delivery makes SIGKILL
    placement deterministic in a way an external watcher never is.
    ``signal=TERM`` exercises the graceful drain (handler installed
    around cli.main); ``signal=KILL`` is the no-warning preemption —
    the process dies mid-stage with nothing flushed beyond the durable
    artifacts already committed.

    Like every schedule here, the decision is a pure function of the
    plan's (stage, iteration), one-shot, and logged — two same-plan
    runs kill at the identical point bit-for-bit (the log is written to
    ``PAGERANK_TPU_KILL_LOG`` when set, so even a SIGKILL'd process
    leaves its reproducibility record: the log line is flushed BEFORE
    the signal is raised). ``seed`` is schedule IDENTITY only — it
    rides the env encoding and the log line so a kill record names
    which seeded chaos campaign produced it, but never perturbs the
    placement (there is nothing random to derive: the plan pins the
    exact point)."""

    ENV = "PAGERANK_TPU_KILL_PLAN"
    LOG_ENV = "PAGERANK_TPU_KILL_LOG"

    def __init__(self, stage: str, iteration: Optional[int] = None,
                 signum: int = 15, seed: int = 0,
                 log_path: Optional[str] = None):
        self.stage = stage
        self.iteration = iteration
        self.signum = int(signum)
        self.seed = int(seed)
        self.fired = False
        self.log: List[Tuple[str, str, int]] = []
        self._log_path = log_path

    @classmethod
    def from_env(cls, env=None) -> Optional["ProcessKillPlan"]:
        env = os.environ if env is None else env
        spec = env.get(cls.ENV)
        if not spec:
            return None
        fields = dict(
            tok.split("=", 1) for tok in spec.split(",") if "=" in tok
        )
        sig_name = fields.get("signal", "TERM").upper()
        signum = getattr(signal, f"SIG{sig_name}", None)
        if signum is None:
            raise ValueError(f"{cls.ENV}: unknown signal {sig_name!r}")
        it = fields.get("iter")
        return cls(
            stage=fields.get("stage", "solve"),
            iteration=int(it) if it is not None else None,
            signum=int(signum), seed=int(fields.get("seed", 0)),
            log_path=env.get(cls.LOG_ENV),
        )

    def to_env(self) -> Dict[str, str]:
        """The env var encoding of this plan (for the subprocess
        harness)."""
        sig = signal.Signals(self.signum).name.replace("SIG", "", 1)
        spec = f"stage={self.stage},signal={sig},seed={self.seed}"
        if self.iteration is not None:
            spec += f",iter={self.iteration}"
        return {self.ENV: spec}

    def check(self, stage: str, iteration: Optional[int] = None) -> None:
        """Deliver the signal when (stage, iteration) matches; one-shot.
        The reproducibility log line (and stdio) is flushed FIRST —
        a SIGKILL leaves no second chance."""
        if self.fired or stage != self.stage:
            return
        if self.iteration is not None and iteration != self.iteration:
            return
        self.fired = True
        entry = (stage, signal.Signals(self.signum).name,
                 -1 if iteration is None else int(iteration))
        self.log.append(entry)
        if self._log_path:
            with open(self._log_path, "a") as f:
                f.write(f"{entry[0]},{entry[1]},{entry[2]}\n")
                f.flush()
                os.fsync(f.fileno())
        obs_log.warn(
            f"chaos: delivering {entry[1]} at {stage}"
            + (f" iteration {iteration}" if iteration is not None else "")
            + f" (seed {self.seed})"
        )
        sys.stderr.flush()
        sys.stdout.flush()
        os.kill(os.getpid(), self.signum)


def run_job_subprocess(argv: Sequence[str],
                       kill: Optional[ProcessKillPlan] = None,
                       env: Optional[Dict[str, str]] = None,
                       kill_log: Optional[str] = None,
                       timeout: float = 600.0,
                       module: str = "pagerank_tpu.cli"):
    """Chaos harness: run ``python -m <module> <argv>`` as a REAL
    subprocess (default module: ``pagerank_tpu.cli``; the campaign
    chaos tests target ``pagerank_tpu.obs``), optionally carrying a
    seeded :class:`ProcessKillPlan` that makes the child kill itself
    (SIGTERM -> graceful drain path, SIGKILL -> nothing survives but
    the durable artifacts). Returns the CompletedProcess; a SIGKILL'd
    child's returncode is ``-9`` and a hard-exited SIGTERM child's is
    per the exit-code taxonomy (pagerank_tpu/exitcodes.py)."""
    import subprocess

    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        child_env.update(env)
    if kill is not None:
        child_env.update(kill.to_env())
        if kill_log:
            child_env[ProcessKillPlan.LOG_ENV] = kill_log
    else:
        child_env.pop(ProcessKillPlan.ENV, None)
    return subprocess.run(
        [sys.executable, "-m", module, *argv],
        env=child_env, capture_output=True, text=True, timeout=timeout,
    )


# -- device-plane faults (ISSUE 7; parallel/elastic.py) ----------------------


#: Bit-flip fault kinds (ISSUE 15; pagerank_tpu/sdc.py): which bit of
#: the targeted float element flips. "mantissa" flips the highest
#: mantissa bit (up to ~2x relative change — well above the derived
#: SDC tolerance, well below NaN territory); "exponent" flips the
#: LOWEST exponent bit (x2 / x0.5 magnitude — never produces Inf/NaN
#: at realistic rank magnitudes, so the SDC plane sees it, not the
#: NaN health check); "sign" negates the element.
FLIP_KINDS = ("mantissa", "exponent", "sign")


def _flip_bit_index(dtype, kind: str) -> int:
    dtype = np.dtype(dtype)
    bits = dtype.itemsize * 8
    try:
        mant = int(np.finfo(dtype).nmant)  # f16 10, bf16 7, f32 23...
    except (ValueError, TypeError):
        mant = {16: 10, 32: 23, 64: 52}[bits]
    if kind == "sign":
        return bits - 1
    if kind == "exponent":
        return mant  # lowest exponent bit
    if kind == "mantissa":
        return mant - 1  # highest mantissa bit
    raise ValueError(f"unknown flip kind {kind!r}; have {FLIP_KINDS}")


def mutate_rank_shard(engine, device_id: int, mutator):
    """Rewrite ONE device's buffer of the engine's rank vector through
    ``mutator(host_copy) -> host_copy`` — the silent-data-corruption
    injection primitive (ISSUE 15). The logical array is reassembled
    from per-device buffers with ONLY the targeted device's bytes
    changed, which is exactly what hardware SDC looks like: on
    replicated layouts the copies now disagree (the copy-consistency
    invariant's whole premise), on sharded layouts the owned block is
    silently wrong. Fake CPU devices make every shard addressable, so
    the whole machine tests without hardware. Returns the targeted
    shard's global start offset (localization ground truth for
    tests)."""
    import jax

    r = engine._r
    shards = list(r.addressable_shards)
    target = None
    for s in shards:
        if int(s.device.id) == int(device_id):
            target = s
            break
    if target is None:
        raise ValueError(
            f"device {device_id} holds no addressable shard of the "
            f"rank vector (mesh devices: "
            f"{sorted(int(s.device.id) for s in shards)})"
        )
    idx = target.index[0] if target.index else slice(0, None)
    lo = int(idx.start or 0)
    mutated = mutator(np.array(target.data, copy=True), lo)
    bufs = []
    for s in shards:
        arr = mutated if s is target else np.asarray(s.data)
        bufs.append(jax.device_put(arr, s.device))
    engine._r = jax.make_array_from_single_device_arrays(
        r.shape, r.sharding, bufs
    )
    return lo


def flip_rank_bit(engine, device_id: int, kind: str, frac: float):
    """Flip one bit (``kind``: mantissa/exponent/sign) of one element
    of ``device_id``'s rank buffer. ``frac`` in [0, 1) picks the
    element deterministically among the device's VALID lanes (the
    relabeled real-vertex prefix / the shard's non-padding lanes), so
    a seeded schedule reproduces the exact corrupted bit. Returns
    ``(global_element, bit)`` for the reproducibility log."""
    out = {}

    def mutator(data, lo):
        # Valid lanes of THIS buffer: the relabeled real-vertex prefix
        # intersected with the shard's global range (replicated
        # buffers hold the whole vector, lo == 0).
        n_valid = int(min(max(1, engine.graph.n - lo), data.size))
        element = min(max(0, int(float(frac) * n_valid)),
                      max(0, data.size - 1))
        bit = _flip_bit_index(data.dtype, kind)
        # Same-width unsigned view, whatever the float width (f64/f32
        # and the 16-bit dtypes alike) — a mismatched view would XOR a
        # bit of a DIFFERENT element than the record claims.
        u = data.view(np.dtype(f"uint{data.dtype.itemsize * 8}"))
        u[element] ^= np.asarray(1 << bit, u.dtype)
        out["element"] = element
        out["bit"] = bit
        return data

    lo = mutate_rank_shard(engine, device_id, mutator)
    return lo + out["element"], out["bit"]


class DeviceFaultSchedule:
    """Seed-deterministic DEVICE-plane fault plan, keyed by ITERATION.

    Explicit plan entries:

    - ``kill``:   {iteration: device_id or [device_ids]} — the device
      drops out of the mesh mid-step (the shim raises
      :class:`~pagerank_tpu.parallel.elastic.DeviceLostError`);
    - ``delay``:  {iteration: (device_id, seconds)} — that device's
      step runs ``seconds`` long (a straggler: the step COMPLETES,
      only slower — must produce telemetry, never a rescue);
    - ``poison``: iterable of iterations whose merged collective
      output is corrupted (NaN state + NaN step info — the numeric
      self-healing plane's rollback handles it, exactly the
      separation the decision table documents);
    - ``flip``:   {iteration: (device_id, kind)} — SILENT bit-flip
      corruption (ISSUE 15): one bit of one element of that device's
      rank buffer flips (``kind`` in :data:`FLIP_KINDS` —
      mantissa/exponent/sign; the element rides the seeded per-
      iteration draw, so the corrupted bit is reproducible). Injected
      BEFORE the step runs — a lying chip corrupts inputs, not
      verdicts — and aimed at the SDC plane (pagerank_tpu/sdc.py):
      no NaN, no error, nothing the ISSUE-3/7 planes can see.
      One-shot like every fault UNLESS the iteration is listed in
      ``sticky_flips``: a sticky entry re-fires every time its
      iteration is consulted, modeling a chip that corrupts every
      pass — the SDC redo then convicts it (transient-vs-sticky is
      EXACTLY "does the flip reproduce on re-execution").

    ``kill_rate``/``delay_rate``/``flip_rate`` add seeded
    probabilistic chaos on top. Every consulted iteration draws a FIXED number of uniforms
    from an RNG derived purely from ``(seed, iteration)``, so the
    schedule is a pure function of the seed and the iteration — NOT
    of how many times an iteration is consulted: a post-rescue
    recompute of iteration i sees the same decision, and the
    ``fired`` memory keeps one-shot faults one-shot (a killed device
    stays dead; it cannot die twice). Every decision lands in
    ``log`` as ``(iteration, action, detail)`` — two same-seed runs
    of the same scenario must produce identical logs bit-for-bit.
    """

    def __init__(
        self,
        seed: int = 0,
        kill: Optional[Dict[int, object]] = None,
        delay: Optional[Dict[int, Tuple[int, float]]] = None,
        poison: Iterable[int] = (),
        flip: Optional[Dict[int, Tuple[int, str]]] = None,
        sticky_flips: Iterable[int] = (),
        kill_rate: float = 0.0,
        delay_rate: float = 0.0,
        flip_rate: float = 0.0,
        delay_s: float = 0.1,
        max_faults: Optional[int] = None,
    ):
        self.seed = seed
        self._kill = {
            int(i): tuple(v) if isinstance(v, (list, tuple)) else (int(v),)
            for i, v in (kill or {}).items()
        }
        self._delay = {int(i): (int(d), float(s))
                       for i, (d, s) in (delay or {}).items()}
        self._poison = frozenset(int(i) for i in poison)
        self._flip = {int(i): (int(d), str(k))
                      for i, (d, k) in (flip or {}).items()}
        for _i, (_d, k) in self._flip.items():
            if k not in FLIP_KINDS:
                raise ValueError(
                    f"unknown flip kind {k!r}; have {FLIP_KINDS}"
                )
        self._sticky_flips = frozenset(int(i) for i in sticky_flips)
        self._kill_rate = kill_rate
        self._delay_rate = delay_rate
        self._flip_rate = flip_rate
        self._delay_s = delay_s
        self._max_faults = max_faults
        self.faults = 0
        #: Devices killed so far — the injectable liveness probe's
        #: ground truth (see :meth:`liveness_probe`).
        self.dead: set = set()
        self._fired: set = set()  # (kind, iteration) one-shot memory
        #: (iteration, action, detail) — the reproducibility record.
        self.log: List[Tuple[int, str, str]] = []

    def _rng(self, iteration: int) -> random.Random:
        # Pure function of (seed, iteration): consulting an iteration
        # twice (post-rescue recompute) re-derives the SAME stream.
        return random.Random((self.seed << 24) ^ (iteration + 1))

    def _budget_ok(self) -> bool:
        return self._max_faults is None or self.faults < self._max_faults

    def decide(self, iteration: int,
               device_ids: Sequence[int]) -> List[Tuple]:
        """Actions for ``iteration`` over the CURRENT mesh's device
        ids: ``("kill", dev)``, ``("delay", dev, seconds)``,
        ``("poison",)``. Deterministic per (seed, iteration); one-shot
        per (kind, iteration); killed devices never re-die."""
        rng = self._rng(iteration)
        u, v = rng.random(), rng.random()  # fixed draw count
        alive = [d for d in device_ids if d not in self.dead]
        actions: List[Tuple] = []

        def fire(kind: str, action: Tuple, detail: str):
            self._fired.add((kind, iteration))
            self.faults += 1
            actions.append(action)
            self.log.append((iteration, action[0], detail))

        if self._budget_ok() and ("kill", iteration) not in self._fired:
            targets = [d for d in self._kill.get(iteration, ()) if d in alive]
            if not targets and u < self._kill_rate and len(alive) > 1:
                targets = [alive[int(v * len(alive))]]
            for d in targets:
                self.dead.add(d)
                fire("kill", ("kill", d), f"device {d}")
        if self._budget_ok() and ("delay", iteration) not in self._fired:
            ent = self._delay.get(iteration)
            if ent is None and u < self._kill_rate + self._delay_rate and alive:
                ent = (alive[int(v * len(alive))], self._delay_s)
            if ent is not None:
                fire("delay", ("delay", ent[0], ent[1]),
                     f"device {ent[0]} +{ent[1]:g}s")
        if (self._budget_ok() and iteration in self._poison
                and ("poison", iteration) not in self._fired):
            fire("poison", ("poison",), "collective output")
        # Bit flips (ISSUE 15): one-shot unless the iteration is
        # sticky — a sticky chip re-corrupts on every consult
        # (including the SDC redo's re-execution, which is what
        # convicts it). The element fraction rides ``v`` so the exact
        # corrupted bit is a pure function of (seed, iteration).
        flip_ok = (("flip", iteration) not in self._fired
                   or iteration in self._sticky_flips)
        if self._budget_ok() and flip_ok:
            ent = self._flip.get(iteration)
            if (ent is None
                    and u < (self._kill_rate + self._delay_rate
                             + self._flip_rate)
                    and u >= self._kill_rate + self._delay_rate
                    and alive):
                ent = (alive[int(v * len(alive))],
                       FLIP_KINDS[int(u * 997) % len(FLIP_KINDS)])
            if ent is not None and ent[0] in alive:
                fire("flip", ("flip", ent[0], ent[1], v),
                     f"device {ent[0]} {ent[1]} bit, element frac "
                     f"{v:.6f}"
                     + (" (sticky)" if iteration in self._sticky_flips
                        else ""))
        if not actions:
            self.log.append((iteration, "-", ""))
        return actions

    def liveness_probe(self, devices, timeout_s: float = 0.0
                       ) -> Dict[int, bool]:
        """Injectable stand-in for mesh.probe_liveness on the fake CPU
        mesh (where every fake device shares one live process): a
        device is alive iff the schedule has not killed it."""
        return {int(d.id): int(d.id) not in self.dead for d in devices}


def install_device_faults(engine, schedule: DeviceFaultSchedule,
                          sleep: Callable[[float], None] = time.sleep,
                          monitor=None):
    """Wrap ``engine.step`` / ``engine.step_probed`` with the
    mesh-aware injection shim. Idempotent per engine instance — a
    repeat call REPLACES the shim (re-wrapping from the original
    unwrapped methods) instead of stacking, so the schedule is never
    consulted twice per iteration and the log-reproducibility
    contract holds. Call it again on the fresh engine after a rescue
    (ElasticRunner's ``on_rebuild`` hook exists for exactly this).

    Semantics per action at iteration i:

    - kill:   the step raises DeviceLostError BEFORE completing — the
      device died mid-collective; the elastic runner classifies and
      rescues;
    - delay:  the real step runs, then the straggler's extra wall is
      added via the injectable ``sleep`` (virtual in tests) and the
      per-device walls are reported to the health ``monitor``
      (straggler telemetry, never an error);
    - poison: the real step runs, then the merged output is corrupted
      (NaN state + NaN info) — the NUMERIC plane's health check +
      rollback owns this, not the rescue path;
    - flip:   the device's rank buffer is silently bit-corrupted
      BEFORE the step dispatches (a lying chip corrupts inputs) — the
      SDC plane (pagerank_tpu/sdc.py) owns detection; nothing else
      can see it.
    """
    from pagerank_tpu.parallel.elastic import DeviceLostError

    def device_ids():
        mesh = getattr(engine, "mesh", None)
        if mesh is None:
            return [0]
        return [int(d.id) for d in mesh.devices.reshape(-1)]

    def poison_engine(info):
        bad = {k: float("nan") for k in info}
        try:
            engine.set_ranks(
                np.asarray(engine.ranks()) * float("nan"),
                iteration=engine.iteration,
            )
        except NotImplementedError:
            pass
        return bad

    def apply(actions, info):
        for act in actions:
            if act[0] == "delay":
                sleep(act[2])
                if monitor is not None:
                    devs = device_ids()
                    walls = {d: 0.0 for d in devs}
                    walls[act[1]] = float(act[2])
                    monitor.record_device_times(engine.iteration, walls)
            elif act[0] == "poison":
                info = poison_engine(info)
        return info

    def split(actions):
        kills = [a for a in actions if a[0] == "kill"]
        flips = [a for a in actions if a[0] == "flip"]
        rest = [a for a in actions if a[0] not in ("kill", "flip")]
        return kills, flips, rest

    def pre_apply(iteration):
        """Consult the schedule and inject everything that happens
        BEFORE the step: kills raise, flips corrupt the input state
        (skipped on engines without a device rank buffer — the CPU
        oracle). Returns the post-step actions."""
        kills, flips, rest = split(
            schedule.decide(iteration, device_ids()))
        if kills:
            raise DeviceLostError(
                f"injected device loss at iteration {iteration} "
                f"(seed {schedule.seed})",
                device_ids=[a[1] for a in kills],
            )
        for a in flips:
            if hasattr(engine, "_r"):
                flip_rank_bit(engine, a[1], a[2], a[3])
        return rest

    # Re-installs rewrap from the ORIGINALS (stashed on first install),
    # never the previous shim — stacking would double-consult the
    # schedule and break bit-for-bit log reproducibility.
    orig_step = getattr(engine, "_prefault_step", engine.step)
    orig_probed = getattr(engine, "_prefault_step_probed",
                          engine.step_probed)
    engine._prefault_step = orig_step
    engine._prefault_step_probed = orig_probed

    def step():
        rest = pre_apply(engine.iteration)
        return apply(rest, orig_step())

    def step_probed(probes):
        rest = pre_apply(engine.iteration)
        info, ids = orig_probed(probes)
        return apply(rest, info), ids

    engine.step = step
    engine.step_probed = step_probed
    # The SDC-checked step (ISSUE 15) is a third dispatch surface of
    # the same iteration — shimmed identically so a checked boundary
    # sees the same one-consult-per-iteration schedule.
    orig_sdc = getattr(engine, "_prefault_step_sdc",
                       getattr(engine, "step_sdc", None))
    if orig_sdc is not None:
        engine._prefault_step_sdc = orig_sdc

        def step_sdc():
            rest = pre_apply(engine.iteration)
            info, chk = orig_sdc()
            return apply(rest, info), chk

        engine.step_sdc = step_sdc
    return engine
