"""Deterministic, seed-driven fault injection (docs/ROBUSTNESS.md).

The reference gets chaos testing for free — kill a Spark executor and
lineage recovery is exercised (SURVEY.md §5). This build's substrate is
utils/fsio + utils/s3 + utils/snapshot, so faults are injected at those
seams instead:

- :class:`FaultInjectingFileSystem` wraps ANY :class:`fsio.FileSystem`
  and injects failures / truncated writes / latency spikes;
- :class:`HttpFaultInjector` plugs into the S3 stub's wire level
  (tests/s3stub.S3Stub.fault_hook) to answer 5xx/SlowDown, drop
  connections mid-body, or lose a multipart-complete response.

Everything is driven by a :class:`FaultSchedule`: decisions are a pure
function of (seed, call index) — never of wall clock or shared global
randomness — and every decision is appended to a ``log``, so a chaos
run is REPRODUCIBLE: the same seed yields the same schedule bit-for-bit
across two runs (asserted in tests/test_faults.py; the acceptance
chaos smoke in scripts/acceptance.py gates on it).
"""

from __future__ import annotations

import io
import random
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from pagerank_tpu.utils import fsio


class FaultInjectedError(OSError):
    """An injected transient fault. An OSError so the default retry
    predicate (utils/retry.default_retryable) classifies it as
    transient — exactly how a real connection reset presents."""


class FaultSchedule:
    """Seeded decision stream for fault injection.

    Each ``decide(op, target)`` call advances a counter and draws a
    FIXED number of uniforms from the seeded stream (whether or not a
    fault fires), so the schedule depends only on the seed and the call
    SEQUENCE — reordering real work reorders faults, but re-running the
    same work reproduces them exactly.

    Triggers: ``fail_nth`` / ``truncate_nth`` / ``delay_nth`` fire on
    exact 1-based call indices; ``fail_rate`` / ``truncate_rate`` /
    ``delay_rate`` fire probabilistically. ``ops`` restricts which
    operations are eligible (None = all). ``max_faults`` caps the total
    number of injected faults — a chaos run with a finite fault budget
    below the consumer's retry budget is GUARANTEED to make progress.
    """

    def __init__(
        self,
        seed: int = 0,
        fail_nth: Iterable[int] = (),
        fail_rate: float = 0.0,
        truncate_nth: Iterable[int] = (),
        truncate_rate: float = 0.0,
        delay_nth: Iterable[int] = (),
        delay_rate: float = 0.0,
        delay_s: float = 0.0,
        ops: Optional[Iterable[str]] = None,
        max_faults: Optional[int] = None,
    ):
        self.seed = seed
        self._rng = random.Random(seed)
        self._fail_nth = frozenset(fail_nth)
        self._truncate_nth = frozenset(truncate_nth)
        self._delay_nth = frozenset(delay_nth)
        self._fail_rate = fail_rate
        self._truncate_rate = truncate_rate
        self._delay_rate = delay_rate
        self._delay_s = delay_s
        self._ops = None if ops is None else frozenset(ops)
        self._max_faults = max_faults
        self.calls = 0
        self.faults = 0
        #: (call_index, op, target, action) — the reproducibility record.
        self.log: List[Tuple[int, str, str, str]] = []

    def decide(self, op: str, target: str) -> Optional[Tuple]:
        self.calls += 1
        n = self.calls
        # Fixed draw count per call keeps the stream position a pure
        # function of the call index.
        u, v = self._rng.random(), self._rng.random()
        action: Optional[Tuple] = None
        eligible = (
            (self._ops is None or op in self._ops)
            and (self._max_faults is None or self.faults < self._max_faults)
        )
        if eligible:
            if n in self._fail_nth or u < self._fail_rate:
                action = ("fail",)
            elif n in self._truncate_nth or u < self._fail_rate + self._truncate_rate:
                action = ("truncate", v)  # keep this fraction of bytes
            elif (n in self._delay_nth
                  or u < self._fail_rate + self._truncate_rate + self._delay_rate):
                action = ("delay", self._delay_s * (0.5 + v))
        if action is not None:
            self.faults += 1
        self.log.append((n, op, target, action[0] if action else "-"))
        return action


class _FaultWriter(io.BytesIO):
    """Buffered writer committing through the wrapped store at close —
    the injection point for truncate-on-write faults (mirrors
    fsio._MemWriter, including abort-on-exception)."""

    def __init__(self, fs: "FaultInjectingFileSystem", path: str,
                 initial: bytes = b""):
        super().__init__()
        self.write(initial)
        self._fs = fs
        self._path = path
        self._aborted = False

    def abort(self):
        self._aborted = True

    def flush(self):
        super().flush()
        if (not self.closed and not self._aborted
                and self._fs.COMMIT_ON_FLUSH):
            self._fs._commit(self._path, self.getvalue(), final=False)

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        return super().__exit__(exc_type, exc, tb)

    def close(self):
        if not self.closed and not self._aborted:
            self._fs._commit(self._path, self.getvalue(), final=True)
        super().close()


class FaultInjectingFileSystem(fsio.FileSystem):
    """Wrap any :class:`fsio.FileSystem` with schedule-driven faults.

    Operations consult the schedule BEFORE delegating: ``("fail",)``
    raises :class:`FaultInjectedError` (transient — a retrying caller
    recovers), ``("delay", s)`` sleeps via the injectable ``sleep``
    (virtual in tests). Writes buffer in memory and commit at close;
    a ``("truncate", frac)`` decision at commit time publishes only a
    prefix of the bytes — the torn-object case checksummed snapshot
    loads must detect. Ops seen by the schedule: ``open_r``, ``commit``
    (write close), ``stat``, ``listdir``, ``replace``, ``makedirs``.
    """

    def __init__(self, inner: fsio.FileSystem, schedule: FaultSchedule,
                 sleep: Callable[[float], None] = time.sleep):
        self.inner = inner
        self.schedule = schedule
        self._sleep = sleep
        self.COMMIT_ON_FLUSH = getattr(inner, "COMMIT_ON_FLUSH", True)

    def _hit(self, op: str, path: str) -> Optional[Tuple]:
        act = self.schedule.decide(op, path)
        if act is None:
            return None
        if act[0] == "fail":
            raise FaultInjectedError(
                f"injected fault #{self.schedule.faults} on {op} {path!r} "
                f"(seed {self.schedule.seed}, call {self.schedule.calls})"
            )
        if act[0] == "delay":
            self._sleep(act[1])
            return None
        return act

    def _commit(self, path: str, data: bytes, final: bool = True) -> None:
        act = self._hit("commit", path) if final else None
        if act is not None and act[0] == "truncate":
            data = data[: int(len(data) * act[1])]
        with self.inner.open(path, "wb") as f:
            f.write(data)

    def open(self, path, mode="r", **kwargs):
        binary = "b" in mode
        kind = mode.replace("b", "").replace("t", "") or "r"
        if kind == "r":
            self._hit("open_r", path)
            return self.inner.open(path, mode, **kwargs)
        if kind not in ("w", "x", "a"):
            raise ValueError(f"unsupported mode {mode!r}")
        if kind == "x" and self.inner.isfile(path):
            raise FileExistsError(path)
        initial = b""
        if kind == "a" and self.inner.isfile(path):
            with self.inner.open(path, "rb") as f:
                initial = f.read()
        raw = _FaultWriter(self, path, initial)
        if kind == "a":
            raw.seek(0, io.SEEK_END)
        if binary:
            return raw
        kwargs.pop("newline", None)
        kwargs.setdefault("encoding", "utf-8")
        return fsio._MemTextWrapper(raw, **kwargs)

    def exists(self, path):
        self._hit("stat", path)
        return self.inner.exists(path)

    def isdir(self, path):
        self._hit("stat", path)
        return self.inner.isdir(path)

    def isfile(self, path):
        self._hit("stat", path)
        return self.inner.isfile(path)

    def listdir(self, path):
        self._hit("listdir", path)
        return self.inner.listdir(path)

    def makedirs(self, path, exist_ok=True):
        self._hit("makedirs", path)
        return self.inner.makedirs(path, exist_ok=exist_ok)

    def replace(self, src, dst):
        self._hit("replace", src)
        return self.inner.replace(src, dst)


class HttpFaultInjector:
    """Schedule adapter for the S3 stub's wire-level hook
    (tests/s3stub.S3Stub.fault_hook).

    ``plan`` maps 1-based request indices to stub action tuples —
    ``("status", 503, "SlowDown")``, ``("reset",)``,
    ``("truncate", nbytes)``, ``("commit_then_status", 500)`` — and
    ``fail_rate`` adds seeded probabilistic 5xx answers on top.
    ``methods`` restricts which HTTP verbs are eligible. Decisions are
    a pure function of (seed, request index) and are logged, so the
    wire-fault schedule reproduces bit-for-bit per seed."""

    def __init__(
        self,
        seed: int = 0,
        plan: Optional[Dict[int, Tuple]] = None,
        fail_rate: float = 0.0,
        fail_status: Tuple = ("status", 503, "SlowDown"),
        methods: Optional[Iterable[str]] = None,
        max_faults: Optional[int] = None,
    ):
        self.seed = seed
        self._rng = random.Random(seed)
        self._plan = dict(plan or {})
        self._fail_rate = fail_rate
        self._fail_status = fail_status
        self._methods = None if methods is None else frozenset(methods)
        self._max_faults = max_faults
        self.calls = 0
        self.faults = 0
        self.log: List[Tuple[int, str, str, str]] = []

    def __call__(self, method: str, path: str) -> Optional[Tuple]:
        self.calls += 1
        n = self.calls
        u = self._rng.random()
        action: Optional[Tuple] = None
        eligible = (
            (self._methods is None or method in self._methods)
            and (self._max_faults is None or self.faults < self._max_faults)
        )
        if eligible:
            action = self._plan.get(n)
            if action is None and u < self._fail_rate:
                action = self._fail_status
        if action is not None:
            self.faults += 1
        self.log.append((n, method, path, action[0] if action else "-"))
        return action
