"""Test-support subpackage: deterministic fault injection
(:mod:`pagerank_tpu.testing.faults`). Shipped inside the package — not
under tests/ — so downstream users can chaos-test their own deployments
against the same schedules (docs/ROBUSTNESS.md)."""

from pagerank_tpu.testing.faults import (  # noqa: F401
    FaultInjectedError,
    FaultInjectingFileSystem,
    FaultSchedule,
    HttpFaultInjector,
)
