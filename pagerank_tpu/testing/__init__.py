"""Test-support subpackage: deterministic fault injection
(:mod:`pagerank_tpu.testing.faults`) and seed-deterministic
interleaving replay (:mod:`pagerank_tpu.testing.schedules`). Shipped
inside the package — not under tests/ — so downstream users can
chaos-test their own deployments against the same schedules
(docs/ROBUSTNESS.md, docs/ANALYSIS.md "Concurrency rules")."""

from pagerank_tpu.testing.faults import (  # noqa: F401
    FaultInjectedError,
    FaultInjectingFileSystem,
    FaultSchedule,
    HttpFaultInjector,
)
from pagerank_tpu.testing.schedules import (  # noqa: F401
    DeadlockDetected,
    InterleavingScheduler,
    TrackedLock,
    VirtualClock,
)
