"""Seed-deterministic interleaving replay (ISSUE 14; docs/ANALYSIS.md
"PTR rules", docs/ROBUSTNESS.md).

The PTR static pass (analysis/concurrency.py) PROVES structural
discipline; this module lets tests *replay* the interleavings those
rules reason about, deterministically. The model is cooperative: each
concurrent actor (the solve loop, the rank-writer, the watchdog, a
signal delivery) is a GENERATOR that yields at its interaction points
— exactly the seams where the real threads interleave — and a seeded
scheduler picks which runnable actor advances next. Everything runs on
ONE real thread, so a schedule is a pure function of (seed, spawn
sequence): the same seed yields the same schedule bit-for-bit
(:attr:`InterleavingScheduler.log` — the testing/faults.py
reproducibility convention), and an "impossible" interleaving a stress
test might hit once a month is pinned as a one-seed regression.

Uses (tests/test_concurrency_analysis.py):

- **reproduce a fixed race**: the pre-fix ``GracefulDrain._handler``
  performed telemetry in signal context — delivered while the main
  thread held the tracer's lock, it re-acquired that lock on the same
  OS thread and self-deadlocked. :class:`TrackedLock` substitutes for
  the real lock and turns that re-acquisition into a loud
  :class:`DeadlockDetected` instead of a hung test; the fixed handler
  replays clean under the very same schedules.
- **demonstrate a waived race is benign**: the watchdog's
  ``rescue_requested`` handshake (a PTR001 allowlist entry) holds its
  invariants under every sampled schedule.

Virtual time rides the same discipline: :class:`VirtualClock` is an
injectable ``clock`` (the utils/retry.py idiom) the actors advance
explicitly, so timeout logic replays without wall-clock sleeps.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Iterator, List, Optional, Tuple


class DeadlockDetected(RuntimeError):
    """A cooperative replay acquired a lock its own schedule already
    holds. Everything runs on one real thread, so the blocking acquire
    the real program would perform can never be released — the exact
    self-deadlock a signal handler risks when it takes a lock the
    interrupted main thread holds (PTR003)."""


class VirtualClock:
    """Monotonic virtual time, advanced explicitly by the replay —
    inject as the ``clock`` of any component built on the
    utils/retry.py idiom (watchdog, drain, retry policies)."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def __call__(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        self._t += float(dt)
        return self._t


class TrackedLock:
    """A non-blocking stand-in for a ``threading.Lock`` inside a
    cooperative replay. Acquiring while held raises
    :class:`DeadlockDetected` naming holder and acquirer — on the
    replay's single real thread a blocking acquire of a held lock
    could never return, and for the signal-handler scenario that IS
    the modelled bug, not an artifact. Every acquisition is logged as
    ``(actor, "acquire"|"release")`` for assertions about WHICH
    context touched the lock."""

    def __init__(self, name: str = "lock",
                 scheduler: Optional["InterleavingScheduler"] = None):
        self.name = name
        self.scheduler = scheduler
        self.holder: Optional[str] = None
        self.events: List[Tuple[str, str]] = []

    def _actor(self) -> str:
        if self.scheduler is not None and self.scheduler.current:
            return self.scheduler.current
        return "<unscheduled>"

    def acquire(self) -> bool:
        actor = self._actor()
        if self.holder is not None:
            raise DeadlockDetected(
                f"{actor} acquired lock '{self.name}' already held by "
                f"{self.holder}: on one OS thread this blocks forever "
                f"(the PTR003 signal-handler hazard)"
            )
        self.holder = actor
        self.events.append((actor, "acquire"))
        return True

    def release(self) -> None:
        self.events.append((self._actor(), "release"))
        self.holder = None

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def acquirers(self) -> List[str]:
        return [actor for actor, ev in self.events if ev == "acquire"]


Task = Iterator  # an actor: a generator yielding at interaction points


class InterleavingScheduler:
    """Seeded cooperative scheduler over generator actors.

    ``spawn(name, gen)`` registers an actor; ``run()`` repeatedly picks
    a runnable actor with the seeded RNG and advances it to its next
    ``yield``. The yielded value (any str, e.g. ``"in-span"``) labels
    the point in :attr:`log` as ``(step, actor, label)`` — the
    bit-for-bit reproducibility record (same seed + same spawn sequence
    => identical log; the testing/faults.py convention). An exception
    raised by an actor aborts the run and propagates to the caller —
    a replayed deadlock/violation must fail the test loudly."""

    def __init__(self, seed: int = 0,
                 clock: Optional[VirtualClock] = None):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.clock = clock if clock is not None else VirtualClock()
        self._tasks: Dict[str, Task] = {}
        self._order: List[str] = []
        self.current: Optional[str] = None
        self.steps = 0
        #: (step, actor, label) per scheduling decision — the record
        #: two same-seed runs must reproduce bit-for-bit.
        self.log: List[Tuple[int, str, str]] = []

    def spawn(self, name: str, gen: Task) -> None:
        if name in self._tasks:
            raise ValueError(f"duplicate actor name {name!r}")
        self._tasks[name] = gen
        self._order.append(name)

    def run(self, max_steps: int = 100_000) -> List[Tuple[int, str, str]]:
        runnable = list(self._order)
        while runnable:
            self.steps += 1
            if self.steps > max_steps:
                raise RuntimeError(
                    f"schedule exceeded {max_steps} steps (livelocked "
                    f"actors?)"
                )
            name = runnable[self._rng.randrange(len(runnable))]
            self.current = name
            try:
                label = next(self._tasks[name])
            except StopIteration:
                runnable.remove(name)
                self.log.append((self.steps, name, "<done>"))
                continue
            finally:
                self.current = None
            self.log.append((self.steps, name, str(label)))
        return self.log


def replay(seed: int,
           build: Callable[["InterleavingScheduler"], None],
           max_steps: int = 100_000) -> InterleavingScheduler:
    """One seeded replay: construct a scheduler, let ``build`` spawn
    the actors against it (and wire TrackedLocks/VirtualClocks), run to
    completion, return the scheduler for log/invariant assertions."""
    sched = InterleavingScheduler(seed=seed)
    build(sched)
    sched.run(max_steps=max_steps)
    return sched


def rotation_actors(sched: InterleavingScheduler, *, steps: int = 6,
                    lag_cap: int = 1, prime_on_restore: bool = True,
                    rescue_after: Optional[int] = None,
                    reader_polls: int = 8) -> dict:
    """Spawn the ISSUE-17 boundary double-buffer ROTATION-PROTOCOL
    actors and return their shared state for invariant assertions.

    Models the host-side state the async halo engine rotates per step
    (engines/jax_engine.py): ``r`` is the version of the adopted rank
    plane, ``buf`` the rank version whose boundary the stale buffer
    holds. The protocol under test:

    - **adopt order**: ``_adopt_step_out`` assigns the rank plane FIRST
      and the carry (buffer) second, so a concurrent reader (watchdog
      telemetry, a signal-context probe) can never observe a buffer
      NEWER than the ranks (``buf <= r`` always). Mid-adoption a reader
      may transiently see lag ``2`` — benign, because nothing CONSUMES
      the buffer between the two assignments; only the solve loop
      consumes, and only at a step boundary.
    - **consumed-lag bound**: every step's boundary read lags the rank
      plane by at most ``lag_cap`` (= config.stale_max_lag).
    - **prime on state replacement**: a rescue/restore that replaces
      the rank plane must re-prime the buffer from the NEW ranks
      (engines' ``_prime_carry``), or the next step consumes a boundary
      of unbounded staleness. ``prime_on_restore=False`` is the
      booby-trapped protocol — tests assert it RECORDS a violation
      under the same seeds the honest protocol survives.

    The rescue rides the watchdog's ``rescue_requested`` handshake
    (the PTR001-allowlisted flag idiom): the watchdog actor only SETS
    the flag; the solve actor notices it at its own step boundary and
    performs the restore itself — mutation stays on one logical
    context, exactly the discipline the PTR pass certifies.

    Violations are RECORDED into ``state["violations"]`` rather than
    raised, so a test can assert the honest protocol yields none while
    the booby trap yields some, over the same seed set."""
    state: Dict[str, object] = {
        "r": 0, "buf": 0, "restores": 0,
        "violations": [], "observed": [],
    }

    def solver() -> Task:
        for _ in range(steps):
            if state.pop("rescue_requested", False):
                # Replacement ranks adopted (restore_state/set_ranks):
                # a version far from the buffer's, so a missing prime
                # is unmistakably a staleness violation.
                state["r"] = int(state["r"]) + 100
                yield "restore-r"
                if prime_on_restore:
                    state["buf"] = state["r"]
                    yield "restore-prime"
                state["restores"] = int(state["restores"]) + 1
            lag = int(state["r"]) - int(state["buf"])
            if not (0 <= lag <= lag_cap):
                state["violations"].append(
                    ("solver", "consumed-lag", lag)
                )
            yield f"consume:lag{lag}"
            cur = int(state["r"])
            state["r"] = cur + 1        # rank plane adopted FIRST...
            yield "adopt-r"
            state["buf"] = cur          # ...then the boundary carry
            yield "adopt-buf"

    def watchdog() -> Task:
        if rescue_after is None:
            return
        for _ in range(rescue_after):
            yield "tick"
        state["rescue_requested"] = True
        yield "request-rescue"

    def reader() -> Task:
        for _ in range(reader_polls):
            r, b = int(state["r"]), int(state["buf"])
            state["observed"].append((r, b))
            if b > r:
                state["violations"].append(("reader", "buf-ahead", r, b))
            yield "poll"

    sched.spawn("solver", solver())
    if rescue_after is not None:
        sched.spawn("watchdog", watchdog())
    sched.spawn("reader", reader())
    return state
