"""pagerank_tpu — a TPU-native PageRank framework.

A ground-up re-design of the capabilities of
`mayursharma/PageRank-using-Apache-Spark` (reference: `Sparky.java`) for
TPU hardware: JAX/XLA for the compute path, `shard_map` over a device
mesh + `psum` over ICI for the distributed substrate that the reference
inherits from Apache Spark (RDD shuffles, broadcasts, driver sync).

Layer map (mirrors SURVEY.md §1):
  L0 cluster runtime/comms -> jax.sharding.Mesh + XLA collectives (parallel/)
  L1 ingestion             -> host-side loaders (ingest/)
  L2 graph construction    -> CSC/COO arrays + masks (graph.py)
  L3 iterative solver      -> jitted power iteration (models/, engines/, ops/)
  L4 output/persistence    -> per-iteration snapshots (utils/snapshot.py)
"""

from pagerank_tpu.graph import Graph, build_graph
from pagerank_tpu.utils.config import PageRankConfig, RobustnessConfig
from pagerank_tpu.engine import PageRankEngine, SolverHealthError, make_engine
from pagerank_tpu.engines.cpu import ReferenceCpuEngine
from pagerank_tpu.engines.jax_engine import JaxTpuEngine

__version__ = "0.1.0"

__all__ = [
    "Graph",
    "build_graph",
    "PageRankConfig",
    "RobustnessConfig",
    "PageRankEngine",
    "SolverHealthError",
    "make_engine",
    "ReferenceCpuEngine",
    "JaxTpuEngine",
]
