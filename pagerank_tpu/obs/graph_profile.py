"""Data-plane observability (ISSUE 13): graph structure and rank
quality as first-class, diffable telemetry.

Three obs planes instrument the MACHINE — perf history (ISSUE 9),
devices (ISSUE 10), the compiler (ISSUE 11) — and none instrument the
DATA, yet the staged perf wins are data-shaped: the halo plan's head-K
replication and the partition-centric density gate both live or die on
the web graph's power-law degree skew (arXiv:1709.07122's
partition-centric premise; arXiv:1312.3020's sparse-allreduce case is
exactly "power-law data makes dense exchange wasteful"), and the
reference's whole relabel is in-degree-driven. This module is the
fourth plane:

  - :class:`GraphProfile` — log2-binned in/out-degree histograms,
    dangling/zero-in counts, self-loop and duplicate-edge counts
    (recovered from the build's dedup flags), top-K hub ids by
    in-degree, per-partition/stripe unique-edge counts and their
    max/mean skew, per-(stripe, dst-block) edge/row counts (the
    load-prediction substrate, parallel/comms.predict_from_profile),
    and a power-law tail estimate. On the device build the stats are
    ONE fused reduction pass over the already-sorted composite key
    (ops/device_build.build_ell_device) — never an O(E) host
    transfer; host graphs profile in numpy
    (:func:`profile_graph`).
  - the **rank-mass ledger** (:func:`mass_ledger_entry`) — an exact
    per-iteration decomposition of the rank update's mass flow (link
    mass, teleport mass, dangling redistribution, reference-mode
    zero-in retention) that must reconcile with the measured
    ``sum(ranks)`` within dtype tolerance, upgrading the opt-in
    ``--mass-tol`` scalar into a ledger with a NAMED leak location.
    The engines compute the raw sums inside the probed step
    (``step_probed`` — no extra dispatches, no extra collectives);
    obs/probes.py records the entries and the violation counter.

Arming discipline (the tracer/sampler/hlo contract): the profiler is
DISARMED by default and every computation site guards on
:func:`armed` — a disarmed run makes ZERO profile computations and is
bit-identical to a pre-ISSUE-13 run (tests/test_graph_profile.py
booby-traps :func:`device_stats`). Armed via CLI ``--graph-profile``,
``python -m pagerank_tpu.obs graph``, and bench.py (whose legs embed
the ``graph`` block).

The ledger half rides the PROBE arming instead (``--probe-every``):
probing off means zero ledger computations — the existing PTC007
probe-transparency contract covers it.

Import cost: stdlib + numpy + obs.metrics (jax stays lazy inside
:func:`device_stats`), mirroring obs/hlo.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from pagerank_tpu.obs import metrics as obs_metrics

#: log2 histogram shape: bin 0 counts degree-0 vertices, bin k >= 1
#: counts degrees in [2^(k-1), 2^k). 32 bins cover every int32 degree.
HIST_BINS = 32

#: Degree thresholds shared by the device and host histogram paths:
#: searchsorted(bounds, d, side="right") == bit_length(d) exactly (an
#: integer comparison ladder — float log2 misbins near 2^24+ where
#: f32 cannot represent the degree).
_HIST_BOUNDS = np.asarray([1 << k for k in range(HIST_BINS - 1)],
                          dtype=np.int64)

#: Default hub count captured by a profile.
DEFAULT_TOPK = 16

#: Mass-ledger tolerance factor: a term leaks when its relative
#: residual exceeds ``tol_factor * eps(accum) * max(1, sqrt(n))`` —
#: the sqrt(n) absorbs the reduction-order error of an n-term sum
#: while staying orders of magnitude below any real mass bug (a wrong
#: weight or mask moves whole rank fractions, not ulps).
LEDGER_TOL_FACTOR = 64.0


# -- arming (the tracer/sampler discipline) ---------------------------------

_ARMED = False
_PROFILE: Optional["GraphProfile"] = None


def armed() -> bool:
    """Whether graph profiling is armed. Every computation site guards
    on this — the disarmed path makes ZERO profile calls."""
    return _ARMED


def arm() -> None:
    global _ARMED
    _ARMED = True


def disarm() -> None:
    global _ARMED
    _ARMED = False


def reset() -> None:
    """Drop the published profile (per-run scoping, like the cost and
    hlo ledgers)."""
    global _PROFILE
    _PROFILE = None


def publish(profile: "GraphProfile") -> None:
    """Stash the latest profile and mirror its headline scalars into
    ``graph.*`` gauges (next to the measured ``comms.*`` /
    ``elastic.*`` values the predictions are diffed against)."""
    global _PROFILE
    _PROFILE = profile
    obs_metrics.gauge(
        "graph.dangling_fraction",
        "dangling vertices / n of the profiled graph",
    ).set(profile.dangling_fraction)
    skew = profile.partition_skew()
    if skew is not None:
        obs_metrics.gauge(
            "graph.partition_skew",
            "max/mean unique edges over source partitions/stripes",
        ).set(skew)
    if profile.self_loops is not None:
        obs_metrics.gauge(
            "graph.self_loops", "unique self-loop edges"
        ).set(profile.self_loops)
    if profile.duplicate_edges is not None:
        obs_metrics.gauge(
            "graph.duplicate_edges",
            "raw minus unique edges collapsed by the build's dedup",
        ).set(profile.duplicate_edges)
    alpha = profile.powerlaw_alpha()
    if alpha is not None:
        obs_metrics.gauge(
            "graph.powerlaw_alpha",
            "power-law tail exponent estimated from the log2 "
            "in-degree histogram",
        ).set(alpha)


def get_profile() -> Optional["GraphProfile"]:
    """The latest published profile (None when disarmed/not built)."""
    return _PROFILE


def report_section() -> Dict[str, object]:
    """The run report's ``graph`` data-plane block: profile summary +
    any published prediction — None-tolerant (a disarmed run embeds
    nothing)."""
    out: Dict[str, object] = {}
    if _PROFILE is not None:
        out["profile"] = _PROFILE.summary()
        if _PROFILE.prediction is not None:
            out["prediction"] = dict(_PROFILE.prediction)
    return out


# -- the profile ------------------------------------------------------------


@dataclass
class GraphProfile:
    """Structural profile of one graph at one packed layout.

    ``block_edges`` / ``block_rows`` are per-(stripe, 128-dst-block)
    UNIQUE-edge and slot-row counts in packed row order — small
    (n_padded/128 * n_stripes entries) but excluded from the JSON
    summary; they persist in the job artifact and feed the per-device
    load prediction (parallel/comms.predict_from_profile)."""

    n: int
    n_padded: int
    num_edges: int                       # unique
    raw_edges: Optional[int]             # pre-dedup (None when unknown)
    self_loops: Optional[int]
    dangling_count: int
    zero_in_count: int
    in_hist: List[int]                   # HIST_BINS log2 bins, unique degrees
    out_hist: List[int]
    top_hub_ids: List[int]               # ORIGINAL id space, in-degree desc
    top_hub_in_degrees: List[int]
    partition_edges: List[int]           # unique edges per source stripe
    stripe_span: int                     # 0 = single stripe
    group: int = 1
    block_edges: Optional[np.ndarray] = field(default=None, repr=False)
    block_rows: Optional[np.ndarray] = field(default=None, repr=False)
    fingerprint: Optional[str] = None
    source: str = "host"                 # host | device_build
    #: attached by parallel/comms.predict_from_profile consumers so
    #: the run report carries predicted-vs-measured in one block.
    prediction: Optional[Dict[str, object]] = None

    @property
    def duplicate_edges(self) -> Optional[int]:
        if self.raw_edges is None:
            return None
        return int(self.raw_edges) - int(self.num_edges)

    @property
    def dangling_fraction(self) -> float:
        return self.dangling_count / self.n if self.n else 0.0

    @property
    def initial_dangling_mass(self) -> float:
        """Dangling mass of the uniform textbook r0 (= the dangling
        fraction; reference semantics starts at rank 1.0 per vertex,
        so ITS initial dangling mass is ``dangling_count``)."""
        return self.dangling_fraction

    def partition_skew(self) -> Optional[float]:
        """max/mean unique edges over source partitions/stripes — the
        straggler-imbalance axis a partitioned/striped layout inherits
        from the data. None when the graph is edge-free."""
        pe = [int(v) for v in self.partition_edges]
        if not pe or sum(pe) == 0:
            return None
        return max(pe) / (sum(pe) / len(pe))

    def powerlaw_alpha(self) -> Optional[float]:
        """Tail exponent alpha of p(d) ~ d^-alpha from the log2
        in-degree histogram: bin k's count ~ C * 2^(k(1-alpha)), so
        the least-squares slope b of log2(count) over k >= 2 gives
        alpha = 1 - b. None with fewer than 3 populated tail bins
        (no tail to estimate)."""
        pts = [(k, math.log2(c)) for k, c in enumerate(self.in_hist)
               if k >= 2 and c > 0]
        if len(pts) < 3:
            return None
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        mx = sum(xs) / len(xs)
        my = sum(ys) / len(ys)
        denom = sum((x - mx) ** 2 for x in xs)
        if denom == 0:
            return None
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
        return 1.0 - slope

    def summary(self) -> Dict[str, object]:
        """JSON-safe headline view (run reports, bench legs, the CLI).
        The per-block arrays stay out — their size is geometry-bound,
        not content-bound, and the artifact carries them."""
        return {
            "source": self.source,
            "fingerprint": self.fingerprint,
            "n": int(self.n),
            "num_edges": int(self.num_edges),
            "raw_edges": (int(self.raw_edges)
                          if self.raw_edges is not None else None),
            "duplicate_edges": self.duplicate_edges,
            "self_loops": (int(self.self_loops)
                           if self.self_loops is not None else None),
            "dangling_count": int(self.dangling_count),
            "dangling_fraction": float(self.dangling_fraction),
            "initial_dangling_mass": float(self.initial_dangling_mass),
            "zero_in_count": int(self.zero_in_count),
            "in_hist": [int(v) for v in self.in_hist],
            "out_hist": [int(v) for v in self.out_hist],
            "top_hub_ids": [int(v) for v in self.top_hub_ids],
            "top_hub_in_degrees": [int(v) for v in
                                   self.top_hub_in_degrees],
            "partition_edges": [int(v) for v in self.partition_edges],
            "partition_skew": self.partition_skew(),
            "stripe_span": int(self.stripe_span),
            "group": int(self.group),
            "powerlaw_alpha": self.powerlaw_alpha(),
        }

    # -- job artifact (ISSUE 12 stage-machine format) ----------------------

    def to_arrays(self):
        """(arrays, meta) in the checksummed jobs.save_artifact format,
        keyed by graph fingerprint — the resume path validates the key
        before trusting the profile (tamper/corruption rejected by the
        artifact sha256)."""
        arrays = {
            "in_hist": np.asarray(self.in_hist, np.int64),
            "out_hist": np.asarray(self.out_hist, np.int64),
            "top_hub_ids": np.asarray(self.top_hub_ids, np.int64),
            "top_hub_in_degrees": np.asarray(self.top_hub_in_degrees,
                                             np.int64),
            "partition_edges": np.asarray(self.partition_edges,
                                          np.int64),
        }
        if self.block_edges is not None:
            arrays["block_edges"] = np.asarray(self.block_edges,
                                               np.int64)
        if self.block_rows is not None:
            arrays["block_rows"] = np.asarray(self.block_rows, np.int64)
        meta = {
            "kind": "graph_profile",
            "fingerprint": self.fingerprint,
            "source": self.source,
            "n": int(self.n),
            "n_padded": int(self.n_padded),
            "num_edges": int(self.num_edges),
            "raw_edges": (int(self.raw_edges)
                          if self.raw_edges is not None else None),
            "self_loops": (int(self.self_loops)
                           if self.self_loops is not None else None),
            "dangling_count": int(self.dangling_count),
            "zero_in_count": int(self.zero_in_count),
            "stripe_span": int(self.stripe_span),
            "group": int(self.group),
        }
        return arrays, meta

    @classmethod
    def from_arrays(cls, arrays, meta) -> "GraphProfile":
        if meta.get("kind") != "graph_profile":
            raise ValueError(
                f"not a graph-profile artifact: kind={meta.get('kind')!r}"
            )
        return cls(
            n=int(meta["n"]), n_padded=int(meta["n_padded"]),
            num_edges=int(meta["num_edges"]),
            raw_edges=meta.get("raw_edges"),
            self_loops=meta.get("self_loops"),
            dangling_count=int(meta["dangling_count"]),
            zero_in_count=int(meta["zero_in_count"]),
            in_hist=[int(v) for v in arrays["in_hist"]],
            out_hist=[int(v) for v in arrays["out_hist"]],
            top_hub_ids=[int(v) for v in arrays["top_hub_ids"]],
            top_hub_in_degrees=[int(v) for v in
                                arrays["top_hub_in_degrees"]],
            partition_edges=[int(v) for v in arrays["partition_edges"]],
            stripe_span=int(meta["stripe_span"]),
            group=int(meta.get("group", 1)),
            block_edges=arrays.get("block_edges"),
            block_rows=arrays.get("block_rows"),
            fingerprint=meta.get("fingerprint"),
            source=str(meta.get("source", "host")),
        )


def layout_profile_geometry(layout) -> tuple:
    """(group, span) a host profile should use for an engine's
    RESOLVED layout (``engine.layout_info()``) — THE one derivation,
    shared by the CLI, bench, and ``obs graph`` so the three surfaces
    cannot disagree: the partition span when the partitioned form
    engaged, else the stripe span when the layout is actually striped
    (per-stripe edge counts ARE the partition telemetry there), else
    a single partition."""
    layout = layout or {}
    span = int(layout.get("partition_span") or 0)
    if not span and (layout.get("n_stripes") or 1) > 1:
        span = int(layout.get("stripe_span") or 0)
    return int(layout.get("group") or 1), span


def log2_hist(deg: np.ndarray) -> np.ndarray:
    """Host log2 degree histogram — EXACT integer binning shared with
    the device path (searchsorted over power-of-two bounds ==
    bit_length per element)."""
    deg = np.asarray(deg, np.int64)
    k = np.searchsorted(_HIST_BOUNDS, deg, side="right")
    return np.bincount(k, minlength=HIST_BINS).astype(np.int64)


def _relabel_order(in_degree: np.ndarray):
    """(perm, inv): the engine's stable in-degree-descending relabel
    (ops/device_build._relabel_perm semantics) in numpy."""
    n = in_degree.shape[0]
    perm = np.argsort(-np.asarray(in_degree, np.int64), kind="stable")
    inv = np.empty(n, np.int64)
    inv[perm] = np.arange(n, dtype=np.int64)
    return perm, inv


def block_geometry(new_src: np.ndarray, new_dst: np.ndarray, *,
                   n_padded: int, stripe_span: int, group: int = 1):
    """Per-(stripe, 128-dst-block) unique-edge and slot-row counts
    from RELABELED deduplicated edges — the host twin of what the
    device build reads off its own sort (exact for deduplicated
    input; device builds count duplicate-occupied slots too, which
    the device path captures from the real ``sb_rows``)."""
    sz = stripe_span or n_padded
    n_stripes = -(-n_padded // sz) if n_padded else 1
    num_blocks = n_padded // 128
    stripe = new_src // sz if n_stripes > 1 else np.zeros_like(new_src)
    sb = stripe * num_blocks + new_dst // 128
    block_edges = np.bincount(sb, minlength=n_stripes * num_blocks)
    # Rows per (stripe, block) = max over its lane groups of
    # ceil(group_run / group) — the packer's exact row rule
    # (ops/device_build._slot_coords).
    log2g = group.bit_length() - 1
    grp = (stripe * n_padded + new_dst) >> log2g
    cnt = np.bincount(grp, minlength=(n_stripes * n_padded) >> log2g)
    rows_grp = -(-cnt // group)
    grp_ids = np.arange(cnt.shape[0], dtype=np.int64)
    sb_of_grp = ((grp_ids << log2g) // n_padded) * num_blocks + (
        (grp_ids << log2g) % n_padded
    ) // 128
    block_rows = np.zeros(n_stripes * num_blocks, np.int64)
    np.maximum.at(block_rows, sb_of_grp, rows_grp)
    return block_edges.astype(np.int64), block_rows


def profile_graph(graph, *, partition_span: int = 0, group: int = 1,
                  topk: int = DEFAULT_TOPK,
                  raw_edges: Optional[int] = None) -> GraphProfile:
    """Profile a HOST :class:`pagerank_tpu.graph.Graph` (already
    deduplicated) in numpy. ``partition_span`` records the per-source-
    partition edge counts at that span (0 = one partition spanning the
    padded range — the replicated single-stripe layout)."""
    n = int(graph.n)
    n_padded = -(-n // 128) * 128
    in_deg = np.asarray(graph.in_degree, np.int64)
    out_deg = np.asarray(graph.out_degree, np.int64)
    src = np.asarray(graph.src, np.int64)
    dst = np.asarray(graph.dst, np.int64)
    sz = min(partition_span, n_padded) if partition_span else n_padded
    n_stripes = -(-n_padded // sz) if n_padded else 1

    perm, inv = _relabel_order(in_deg)
    new_src, new_dst = inv[src], inv[dst]
    part_edges = np.bincount(new_src // sz, minlength=n_stripes)
    block_edges, block_rows = block_geometry(
        new_src, new_dst, n_padded=n_padded, stripe_span=sz if
        n_stripes > 1 else 0, group=group)

    k = max(1, min(int(topk), n))
    in_rel = in_deg[perm]
    # top-k by UNIQUE in-degree with ties broken by lowest relabeled
    # id — matching lax.top_k over the relabeled degree vector.
    top_rel = np.lexsort((np.arange(n), -in_rel))[:k]
    dangling = int((np.asarray(graph.dangling_mask, bool)).sum())
    return GraphProfile(
        n=n, n_padded=n_padded, num_edges=int(graph.num_edges),
        raw_edges=raw_edges,
        self_loops=int((src == dst).sum()),
        dangling_count=dangling,
        zero_in_count=int((in_deg == 0).sum()),
        in_hist=[int(v) for v in log2_hist(in_deg)],
        out_hist=[int(v) for v in log2_hist(out_deg)],
        top_hub_ids=[int(perm[i]) for i in top_rel],
        top_hub_in_degrees=[int(in_rel[i]) for i in top_rel],
        partition_edges=[int(v) for v in part_edges],
        stripe_span=int(sz if n_stripes > 1 else 0),
        group=int(group),
        block_edges=block_edges, block_rows=block_rows,
        fingerprint=graph.fingerprint(),
        source="host",
    )


# -- device-build fused stats (ops/device_build hooks) ----------------------


def device_stats(sb_dst, new_src, perm, *, n: int, n_padded: int,
                 stripe_size: int, num_blocks: int,
                 topk: int = DEFAULT_TOPK):
    """ONE fused on-device reduction pass over the composite-key-sorted
    edges (called by ops/device_build.build_ell_device between its
    sort and slot stages, ONLY when :func:`armed`): dedup flags fall
    out of key adjacency exactly as in ``_slot_coords``, and every
    profile stat reduces from them — no per-edge host transfer. Reads
    only (the sort products are donated into the NEXT stage untouched),
    so arming cannot perturb the build. Returns a dict of device
    arrays; the caller fetches them in one ``device_get`` at the end
    of the build (:func:`finish_device_profile`)."""
    import functools

    from pagerank_tpu.utils import compile_cache

    k = max(1, min(int(topk), n))
    out = compile_cache.stage_call(
        "graph_profile_stats",
        functools.partial(_device_stats_impl, n=n, n_padded=n_padded,
                          stripe_size=stripe_size,
                          num_blocks=num_blocks, topk=k),
        (sb_dst, new_src, perm),
        static_key=(n, n_padded, stripe_size, num_blocks, k),
    )
    names = ("num_edges", "raw_edges", "self_loops", "dangling_count",
             "zero_in_count", "in_hist", "out_hist",
             "top_hub_in_degrees", "top_hub_ids", "partition_edges",
             "block_edges")
    return dict(zip(names, out))


def _device_stats_impl(sb_dst, new_src, perm, *, n, n_padded,
                       stripe_size, num_blocks, topk):
    import jax
    import jax.numpy as jnp

    sz = stripe_size or n_padded
    n_stripes = -(-n_padded // sz) if n_padded else 1
    if n_stripes > 1:
        new_dst = sb_dst % n_padded
        stripe_of = sb_dst // n_padded
    else:
        new_dst = sb_dst
        stripe_of = None
    uniq = jnp.concatenate(
        [jnp.ones(1, bool),
         (sb_dst[1:] != sb_dst[:-1]) | (new_src[1:] != new_src[:-1])]
    )
    u32 = uniq.astype(jnp.int32)
    num_edges = jnp.sum(u32, dtype=jnp.int32)
    raw_edges = jnp.int32(sb_dst.shape[0])
    self_loops = jnp.sum(
        jnp.where(uniq & (new_dst == new_src), jnp.int32(1),
                  jnp.int32(0)), dtype=jnp.int32)
    # Unique degrees in RELABELED space (int32 throughout — the
    # PTC006 x64-pin discipline of every build stage).
    in_deg = jax.ops.segment_sum(u32, new_dst, num_segments=n)
    out_deg = jax.ops.segment_sum(u32, new_src, num_segments=n)
    bounds = jnp.asarray(_HIST_BOUNDS, jnp.int32)
    ones_n = jnp.ones(n, jnp.int32)
    in_hist = jax.ops.segment_sum(
        ones_n, jnp.searchsorted(bounds, in_deg, side="right"
                                 ).astype(jnp.int32),
        num_segments=HIST_BINS)
    out_hist = jax.ops.segment_sum(
        ones_n, jnp.searchsorted(bounds, out_deg, side="right"
                                 ).astype(jnp.int32),
        num_segments=HIST_BINS)
    dangling = jnp.sum((out_deg == 0).astype(jnp.int32),
                       dtype=jnp.int32)
    zero_in = jnp.sum((in_deg == 0).astype(jnp.int32), dtype=jnp.int32)
    top_deg, top_rel = jax.lax.top_k(in_deg, topk)
    top_orig = perm[top_rel.astype(jnp.int32)]
    if n_stripes > 1:
        part_edges = jax.ops.segment_sum(u32, stripe_of,
                                         num_segments=n_stripes)
        sb = stripe_of * num_blocks + new_dst // 128
    else:
        part_edges = jnp.reshape(num_edges, (1,))
        sb = new_dst // 128
    block_edges = jax.ops.segment_sum(
        u32, sb, num_segments=n_stripes * num_blocks,
        indices_are_sorted=True)
    return (num_edges, raw_edges, self_loops, dangling, zero_in,
            in_hist, out_hist, top_deg.astype(jnp.int32), top_orig,
            part_edges, block_edges)


def finish_device_profile(stats: Dict[str, object], *, stripe_size: int,
                          group: int, n: int, n_padded: int,
                          block_rows=None, dangling_count_override=None,
                          fingerprint: Optional[str] = None
                          ) -> GraphProfile:
    """Assemble the :class:`GraphProfile` from the device-stat arrays
    (ONE batched ``device_get`` — the build's only profile-side host
    sync). ``dangling_count_override`` carries the crawl inputs'
    explicit dangling-mask semantics (SURVEY §2a.3);
    ``block_rows`` is the build's own exact per-(stripe, block) row
    vector (``sb_rows``)."""
    import jax

    fetch = dict(stats)
    if block_rows is not None:
        fetch["block_rows"] = block_rows
    if dangling_count_override is not None:
        fetch["dangling_count"] = dangling_count_override
    host = jax.device_get(fetch)
    return GraphProfile(
        n=int(n), n_padded=int(n_padded),
        num_edges=int(host["num_edges"]),
        raw_edges=int(host["raw_edges"]),
        self_loops=int(host["self_loops"]),
        dangling_count=int(np.asarray(host["dangling_count"]).sum()),
        zero_in_count=int(host["zero_in_count"]),
        in_hist=[int(v) for v in host["in_hist"]],
        out_hist=[int(v) for v in host["out_hist"]],
        top_hub_ids=[int(v) for v in host["top_hub_ids"]],
        top_hub_in_degrees=[int(v) for v in
                            host["top_hub_in_degrees"]],
        partition_edges=[int(v) for v in host["partition_edges"]],
        stripe_span=int(stripe_size),
        group=int(group),
        block_edges=np.asarray(host["block_edges"], np.int64),
        block_rows=(np.asarray(host["block_rows"], np.int64)
                    if "block_rows" in host else None),
        fingerprint=fingerprint,
        source="device_build",
    )


# -- the rank-mass ledger ----------------------------------------------------


def ledger_tolerance(eps: float, n: int,
                     tol_factor: float = LEDGER_TOL_FACTOR) -> float:
    """Relative reconciliation tolerance for an n-vertex mass sum in a
    dtype with machine epsilon ``eps`` (see LEDGER_TOL_FACTOR)."""
    return tol_factor * float(eps) * max(1.0, math.sqrt(max(1, n)))


def mass_ledger_entry(*, damping: float, semantics: str, n: int,
                      eps: float, mass_prev: float, mass: float,
                      dangling_mass: float, contrib_total: float,
                      retained_total: float = 0.0,
                      tol_factor: float = LEDGER_TOL_FACTOR,
                      flow_slack: float = 0.0
                      ) -> Dict[str, object]:
    """One probe iteration's exact mass decomposition + reconciliation.

    The update (models/pagerank.apply_update) sums to

      textbook:  mass' = (1-d)      + d*contrib_total + d*m
      reference: mass' = (1-d)*n    + d*contrib_total
                         + d*retained_total + d*m

    where every right-hand term except the teleport is MEASURED inside
    the step (``step_probed`` ledger sums). Two reconciliations, each
    with a named leak:

      - **identity residual**: measured ``mass`` minus the term sum.
        The teleport term is the only one derived from the formula
        rather than measured, so a violation is attributed to
        ``teleport`` (the epilogue/mask path — e.g. a wrong valid
        mask zeroing live lanes).
      - **flow conservation** (textbook only, where the dangling mask
        IS out_degree == 0): every unit of ``mass_prev`` must leave
        through links or the dangling pool —
        ``unaccounted = mass_prev - m - contrib_total``. Positive
        unaccounted means mass silently fell out of the flow (a
        ``dangling``-mask leak: a sink vertex the mask misses);
        negative means the edges CREATED mass (a ``link`` leak: bad
        weights / duplicated slots). Reference semantics deliberately
        does not conserve mass (the zero-in retention re-feeds old
        rank, module docstring of models/pagerank), so only the
        identity check applies there.

    All residuals are reported relative to the mode's expected total
    (1 textbook, n reference). ``leak`` is the worst offender's name,
    None when the ledger reconciles within :func:`ledger_tolerance`.

    ``flow_slack`` (mass units, ISSUE 17) widens ONLY the flow-
    conservation check: under the stale-boundary step
    (config.halo_async) the measured contribution total mixes this
    iteration's own-block mass with LAST iteration's boundary mass,
    so flow conservation holds up to the previous step's L1 delta —
    the caller passes that bound and the check stays sharp as the
    solve converges (slack -> 0 with delta). The identity residual
    needs no slack: the update consumed the same measured contrib the
    ledger reports, stale or not.
    """
    reference = semantics == "reference"
    scale = float(n) if reference else 1.0
    teleport = (1.0 - damping) * scale
    link = damping * contrib_total
    retained = damping * retained_total if reference else 0.0
    dangling_term = damping * dangling_mass
    total = teleport + link + retained + dangling_term
    tol = ledger_tolerance(eps, n, tol_factor)
    residual = (mass - total) / scale
    violations = {}
    if abs(residual) > tol:
        violations["teleport"] = abs(residual)
    unaccounted = None
    if not reference:
        unaccounted = (mass_prev - dangling_mass - contrib_total) / scale
        flow_tol = tol + abs(flow_slack) / scale
        if unaccounted > flow_tol:
            violations["dangling"] = abs(unaccounted)
        elif unaccounted < -flow_tol:
            violations["link"] = abs(unaccounted)
    leak = (max(violations, key=violations.get) if violations else None)
    return {
        "mass_prev": float(mass_prev),
        "mass": float(mass),
        "normalized_mass": float(mass / scale),
        "teleport_mass": float(teleport / scale),
        "link_mass": float(link / scale),
        "retained_mass": float(retained / scale),
        "dangling_mass": float(dangling_term / scale),
        "residual": float(residual),
        "unaccounted": (float(unaccounted)
                        if unaccounted is not None else None),
        "tol": float(tol),
        "leak": leak,
        "ok": leak is None,
    }


def record_ledger(entry: Dict[str, object]) -> None:
    """Publish one ledger entry through the metrics registry: the
    decomposition gauges plus the violation counter the exporter and
    run report surface."""
    for key, name in (("link_mass", "ledger.link_mass"),
                      ("teleport_mass", "ledger.teleport_mass"),
                      ("dangling_mass", "ledger.dangling_mass"),
                      ("residual", "ledger.residual")):
        obs_metrics.gauge(
            name, f"rank-mass ledger: {key} (normalized)"
        ).set(entry[key])
    if not entry.get("ok", True):
        obs_metrics.counter(
            "ledger.violations",
            "probe iterations whose mass ledger failed to reconcile",
        ).inc()
