"""``python -m pagerank_tpu.obs`` — inspect run flight-recorder
artifacts.

  report A.json          pretty-print one run report
  report A.json B.json   diff two reports (phase-by-phase wall and
                         rate deltas; environment differences called
                         out first so backend drift is separable from
                         code regressions — docs/OBSERVABILITY.md)

Exit codes: 0 ok, 2 usage/unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys

from pagerank_tpu.obs import report as report_mod


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pagerank_tpu.obs",
        description="Run-report tooling for the observability layer "
        "(docs/OBSERVABILITY.md).",
    )
    sub = p.add_subparsers(dest="command", required=True)
    rp = sub.add_parser(
        "report", help="render one run_report.json, or diff two"
    )
    rp.add_argument("paths", nargs="+", metavar="REPORT.json",
                    help="one report to render, or two to diff (A B)")
    rp.add_argument("--json", action="store_true",
                    help="emit the loaded report (or {'a','b'} pair) "
                    "as JSON instead of the human rendering")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if len(args.paths) > 2:
        print("report takes one or two files", file=sys.stderr)
        return 2
    try:
        reports = [report_mod.load_report(p) for p in args.paths]
    except (OSError, json.JSONDecodeError) as e:
        print(f"obs report: cannot load report: {e}", file=sys.stderr)
        return 2
    if args.json:
        doc = (reports[0] if len(reports) == 1
               else {"a": reports[0], "b": reports[1]})
        print(json.dumps(doc, indent=2))
        return 0
    if len(reports) == 1:
        print(report_mod.render_report(reports[0]))
    else:
        print(report_mod.diff_reports(reports[0], reports[1]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
