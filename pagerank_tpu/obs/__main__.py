"""``python -m pagerank_tpu.obs`` — inspect run flight-recorder
artifacts, the perf-history ledger, and the OOM-preflight fit check.

  report A.json            pretty-print one run report
  report A.json B.json     diff two reports (phase-by-phase wall and
                           rate deltas; environment differences called
                           out first so backend drift is separable
                           from code regressions — docs/OBSERVABILITY.md)
  report A.json --against-history LEDGER
                           diff A against the ledger's robust baseline
                           for its dispatch form (same env-drift-first
                           rendering)

  history ingest LEDGER FILE...   normalize + append result artifacts
                                  (BENCH/MULTICHIP/run_report shapes,
                                  legacy wrappers included; content-
                                  hash dedupe)
  history trend LEDGER            ASCII per-(leg, metric) series with
                                  robust baselines + newest-record
                                  flags (--json for the records)
  history gate LEDGER             the CI perf gate: budgets +
                                  program-change regressions fail
                                  (exit 1); env-drift warns and passes

  fit --scale N [--ndev D]        OOM preflight (ISSUE 10): abstract-
                                  eval the build+step at the target
                                  geometry (XLA memory_analysis per
                                  stage, NOTHING allocates), compare
                                  per-chip peaks against bytes_limit /
                                  the device-kind HBM table, and exit
                                  nonzero with the per-stage table
                                  when it provably does not fit

  hlo --form F [--scale N]        compiler-plane inspection (ISSUE 11;
                                  obs/hlo.py): build the named
                                  dispatch form(s) at the target
                                  geometry, harvest the OPTIMIZED HLO
                                  of every iteration program, and
                                  print the lowering verdict — gather
                                  strategy (native vs while/scalar
                                  expansion), fusion count, collective
                                  multiset, bf16-stream presence,
                                  HLO-derived bytes/edge, fingerprint.
                                  Exit 1 when any program classifies
                                  EXPANDED (the fast-gather-defeated
                                  signature); --dump-hlo DIR writes
                                  the raw modules for offline diffing

Exit codes: 0 ok, 1 gate violation / does not fit / defeated gather,
2 usage/unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from pagerank_tpu.exitcodes import ExitCode
from pagerank_tpu.obs import history as history_mod
from pagerank_tpu.obs import report as report_mod


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pagerank_tpu.obs",
        description="Run-report and perf-history tooling for the "
        "observability layer (docs/OBSERVABILITY.md).",
    )
    sub = p.add_subparsers(dest="command", required=True)
    rp = sub.add_parser(
        "report", help="render one run_report.json, or diff two"
    )
    rp.add_argument("paths", nargs="+", metavar="REPORT.json",
                    help="one report to render, or two to diff (A B)")
    rp.add_argument("--json", action="store_true",
                    help="emit the loaded report (or {'a','b'} pair) "
                    "as JSON instead of the human rendering")
    rp.add_argument(
        "--against-history", default=None, metavar="LEDGER",
        help="diff ONE report against the perf-history ledger's "
        "baseline for its dispatch form (median of the trailing "
        "window) — the pairwise env-drift-first diff, with the ledger "
        "standing in for run A",
    )
    hp = sub.add_parser(
        "history",
        help="perf-history ledger: ingest results, render the trend, "
        "run the CI perf gate (docs/OBSERVABILITY.md 'Perf history & "
        "gating')",
    )
    hsub = hp.add_subparsers(dest="history_command", required=True)
    ing = hsub.add_parser(
        "ingest", help="normalize result JSONs into the ledger "
        "(append-only, content-hash deduped)")
    ing.add_argument("ledger", metavar="LEDGER.jsonl")
    ing.add_argument("files", nargs="+", metavar="RESULT.json",
                     help="bench couple/single/--build-only JSON, "
                     "MULTICHIP JSON (dryrun or promoted), "
                     "run_report.json, or a legacy {n,cmd,rc,tail,"
                     "parsed} wrapper")
    ing.add_argument("--json", action="store_true",
                     help="emit {'added','deduped'} as JSON")
    tr = hsub.add_parser(
        "trend", help="ASCII per-(leg, metric) series over the ledger "
        "with robust baselines and newest-record flags")
    tr.add_argument("ledger", metavar="LEDGER.jsonl")
    tr.add_argument("--json", action="store_true",
                    help="emit the ledger records as JSON instead of "
                    "the table")
    tr.add_argument("--budgets", default=None, metavar="BUDGETS.json",
                    help="read detection knobs (window/threshold/"
                    "min_samples) from this perf_budgets file")
    ga = hsub.add_parser(
        "gate", help="the CI perf gate: exits 1 on a budget breach or "
        "a program-change regression; env-drift flags warn and pass")
    ga.add_argument("ledger", metavar="LEDGER.jsonl")
    ga.add_argument("--budgets", default=None, metavar="BUDGETS.json",
                    help="perf_budgets.json: absolute env-scoped "
                    "floors/ceilings + detection knobs (default: "
                    "MAD detection only)")
    ga.add_argument("--record", default=None, metavar="RESULT.json",
                    help="gate this result artifact against the "
                    "ledger instead of the ledger's own newest record "
                    "(the artifact is normalized, not appended)")
    ga.add_argument("--json", action="store_true",
                    help="emit the GateResult as JSON")
    fp = sub.add_parser(
        "fit",
        help="OOM-preflight fit check (ISSUE 10; obs/devices.py): "
        "will the device build + solve at this geometry fit per-chip "
        "HBM? Exits 1 with the per-stage table when it won't — "
        "BEFORE any real allocation",
    )
    fp.add_argument("--scale", type=int, required=True,
                    help="R-MAT scale (2^scale vertices, "
                    "edge_factor<<scale raw edges) — the bench/ROADMAP "
                    "geometry vocabulary")
    fp.add_argument("--ndev", type=int, default=1,
                    help="target device count; >1 implies the "
                    "vertex-sharded (memory-scaling) solve")
    fp.add_argument("--vs-bounded", action="store_true",
                    help="size the owner-computes bounded mode "
                    "(config.vs_bounded): per-chip step transients "
                    "O(stripe_span + N/ndev) instead of O(N); "
                    "implies --host-build (the mode requires a "
                    "host-built graph)")
    fp.add_argument("--edge-factor", type=int, default=16)
    fp.add_argument("--dtype", default="float32")
    fp.add_argument("--accum-dtype", default=None,
                    help="defaults to --dtype")
    fp.add_argument("--wide-accum", default="auto",
                    choices=["auto", "pair", "native"])
    fp.add_argument("--host-build", action="store_true",
                    help="skip the device-build pipeline stages (the "
                    "graph arrives host-built; only the solve "
                    "residency gates)")
    fp.add_argument("--hbm-gb", type=float, default=None,
                    help="explicit per-chip HBM limit in GiB "
                    "(default: live bytes_limit, else the device-kind "
                    "capacity table, else 16 GiB v5e-class)")
    fp.add_argument("--device-kind", default=None,
                    help="size against this device kind's published "
                    "HBM capacity (e.g. 'TPU v4') instead of the "
                    "attached device")
    fp.add_argument("--headroom", type=float, default=None,
                    help="fraction of the limit usable after runtime "
                    "reserve (default 0.9)")
    fp.add_argument("--json", action="store_true",
                    help="emit the FitResult as JSON")
    hp2 = sub.add_parser(
        "hlo",
        help="compiler-plane lowering inspection (ISSUE 11; "
        "obs/hlo.py): classify the gather strategy / fusion "
        "structure of a dispatch form's optimized HLO — the "
        "'did XLA keep the fast gather' verdict read BEFORE a "
        "TPU session instead of hand-diffing HLO dumps",
    )
    hp2.add_argument(
        "--form", default="default", metavar="FORM",
        help="dispatch form(s) to inspect: comma-separated names from "
        "{default, pair, partitioned, partitioned_bf16, coo, "
        "vertex_sharded, vs_halo}, or 'all'",
    )
    hp2.add_argument("--scale", type=int, default=14,
                     help="R-MAT scale of the host-built probe graph "
                     "(default 14 — sub-second on CPU, big enough "
                     "that the hot gather is unambiguous)")
    hp2.add_argument("--edge-factor", type=int, default=16)
    hp2.add_argument("--json", action="store_true",
                     help="emit {form: {program: LoweringReport}} as "
                     "strict JSON")
    hp2.add_argument("--dump-hlo", default=None, metavar="DIR",
                     help="also write every inspected program's raw "
                     "optimized HLO to DIR as <form>.<program>.hlo")
    return p


def _cmd_hlo(args) -> int:
    from pagerank_tpu.obs import hlo as hlo_mod

    alias = {"ell": "default", "fast_bf16": "partitioned_bf16"}
    names = (
        # --form all: one entry per DISTINCT program (alias targets).
        [n for n in hlo_mod.FORM_CHOICES if n not in alias]
        if args.form == "all"
        else [f.strip() for f in args.form.split(",") if f.strip()])
    # Fail the usage error BEFORE any graph builds — a typo'd form at
    # --scale 22 must not cost minutes of R-MAT host work first.
    unknown = [n for n in names if n not in hlo_mod.FORM_CHOICES]
    if unknown or not names:
        print(
            "obs hlo: unknown dispatch form(s) "
            + (", ".join(repr(n) for n in unknown) or "(none given)")
            + " (choices: " + ", ".join(hlo_mod.FORM_CHOICES) + ")",
            file=sys.stderr,
        )
        return 2
    # Build each distinct program once (default/ell and
    # partitioned_bf16/fast_bf16 are aliases) but emit EVERY requested
    # name — `--form ell,default` returns both keys, sharing one
    # snapshot.
    built, out, defeated = {}, {}, []
    for form in names:
        canon = alias.get(form, form)
        if canon not in built:
            try:
                built[canon] = hlo_mod.inspect_form(
                    canon, args.scale, edge_factor=args.edge_factor)
            except ValueError as e:
                print(f"obs hlo: {e}", file=sys.stderr)
                return 2
            if args.dump_hlo:
                hlo_mod.dump_texts(args.dump_hlo, prefix=canon)
        snapshot = built[canon]
        if form in out:
            continue  # same name listed twice
        out[form] = snapshot
        for prog, rep in snapshot.items():
            if (rep.get("gather") or {}).get("strategy") == "expanded":
                defeated.append(f"{form}/{prog}")
    if args.json:
        print(json.dumps(report_mod._json_safe(out), indent=2,
                         allow_nan=False))
    else:
        for form, snapshot in out.items():
            if not snapshot:
                print(f"{form}: backend reports no optimized HLO "
                      f"(verdict unknown)")
            for prog in sorted(snapshot):
                rep = dict(snapshot[prog])
                rep["form"] = f"{form}/{prog}"
                print(hlo_mod.render_report(rep))
    if defeated:
        print("obs hlo: DEFEATED gather lowering in: "
              + ", ".join(defeated), file=sys.stderr)
        return 1
    return 0


def _cmd_fit(args) -> int:
    from pagerank_tpu.obs import devices as devices_mod

    kwargs = {}
    if args.hbm_gb is not None:
        if args.hbm_gb <= 0:
            print("obs fit: --hbm-gb must be positive", file=sys.stderr)
            return 2
        kwargs["limit_bytes"] = int(args.hbm_gb * (1 << 30))
    if args.headroom is not None:
        if not 0 < args.headroom <= 1:
            print("obs fit: --headroom must be in (0, 1]",
                  file=sys.stderr)
            return 2
        kwargs["headroom"] = args.headroom
    try:
        res = devices_mod.fit_check(
            args.scale, ndev=args.ndev, edge_factor=args.edge_factor,
            dtype=args.dtype, accum_dtype=args.accum_dtype,
            wide_accum=args.wide_accum,
            vertex_sharded=True if args.vs_bounded else None,
            vs_bounded=args.vs_bounded,
            device_build=not (args.host_build or args.vs_bounded),
            device_kind=args.device_kind, **kwargs,
        )
    except ValueError as e:
        print(f"obs fit: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report_mod._json_safe(res.to_json()),
                         indent=2, allow_nan=False))
    else:
        print(devices_mod.render_fit(res))
    return 0 if res.fits else 1


def _load_json(path: str):
    with open(path) as f:
        return json.load(f)


def _cmd_report(args) -> int:
    if len(args.paths) > 2:
        print("report takes one or two files", file=sys.stderr)
        return 2
    if args.against_history and len(args.paths) != 1:
        print("--against-history diffs exactly one report",
              file=sys.stderr)
        return 2
    try:
        reports = [report_mod.load_report(p) for p in args.paths]
    except (OSError, json.JSONDecodeError) as e:
        print(f"obs report: cannot load report: {e}", file=sys.stderr)
        return 2
    if args.against_history:
        try:
            records = history_mod.read_ledger(args.against_history)
        except ValueError as e:
            print(f"obs report: {e}", file=sys.stderr)
            return 2
        leg = history_mod.leg_name_for_config(
            reports[0].get("config") or {})
        baseline, n = history_mod.baseline_pseudo_report(
            records, leg, env=reports[0].get("environment"))
        if baseline is None:
            print(f"obs report: ledger {args.against_history} has no "
                  f"'{leg}' records to baseline against",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps({"baseline": baseline, "b": reports[0]},
                             indent=2, allow_nan=False))
            return 0
        print(f"against history: leg '{leg}', baseline = median of "
              f"{n} ledger record(s) (A = baseline, B = this run)")
        print(report_mod.diff_reports(baseline, reports[0]))
        return 0
    if args.json:
        doc = (reports[0] if len(reports) == 1
               else {"a": reports[0], "b": reports[1]})
        print(json.dumps(doc, indent=2))
        return 0
    if len(reports) == 1:
        print(report_mod.render_report(reports[0]))
    else:
        print(report_mod.diff_reports(reports[0], reports[1]))
    return 0


def _cmd_history(args) -> int:
    try:
        if args.history_command == "ingest":
            added, deduped = history_mod.ingest_paths(args.ledger,
                                                      args.files)
            if args.json:
                print(json.dumps({"added": added, "deduped": deduped},
                                 allow_nan=False))
            else:
                print(f"ingested {added} record(s) into {args.ledger}"
                      + (f" ({deduped} duplicate(s) skipped)"
                         if deduped else ""))
            return 0
        # trend/gate READ the ledger: a missing path is a usage error
        # (a mistyped ledger in CI must not gate green on "empty"),
        # while `ingest` legitimately creates it.
        records = history_mod.read_ledger(args.ledger)
        if not records and not os.path.exists(args.ledger):
            print(f"obs history: no such ledger: {args.ledger}",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
        budgets = (history_mod.load_budgets(args.budgets)
                   if args.budgets else None)
        if args.history_command == "trend":
            if args.json:
                print(json.dumps(records, indent=2, allow_nan=False))
            else:
                print(history_mod.render_trend(
                    records,
                    detection=(budgets or {}).get("detection")))
            return 0
        # gate
        if args.record:
            rec = history_mod.normalize_result(
                _load_json(args.record), source=args.record)
            records = list(records) + [rec]
        res = history_mod.evaluate_gate(records, budgets)
        if args.json:
            print(json.dumps(res.to_dict(), indent=2, allow_nan=False))
        else:
            for line in res.notes:
                print(f"gate: {line}")
            for line in res.improvements:
                print(f"gate: IMPROVEMENT {line}")
            for line in res.drift_warnings:
                print(f"gate: WARNING {line}")
            for line in res.violations:
                print(f"gate: FAIL {line}")
            print("gate: " + ("PASS" if res.ok else "FAIL")
                  + (f" ({len(res.drift_warnings)} drift warning(s))"
                     if res.drift_warnings else ""))
        # The exit-code taxonomy (pagerank_tpu/exitcodes.py): FAILURE
        # is a judged-bad gate, USAGE a bad/missing invocation.
        return int(ExitCode.OK if res.ok else ExitCode.FAILURE)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"obs history: {e}", file=sys.stderr)
        return int(ExitCode.USAGE)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "report":
        return _cmd_report(args)
    if args.command == "fit":
        return _cmd_fit(args)
    if args.command == "hlo":
        return _cmd_hlo(args)
    return _cmd_history(args)


if __name__ == "__main__":
    sys.exit(main())
