"""``python -m pagerank_tpu.obs`` — inspect run flight-recorder
artifacts, the perf-history ledger, and the OOM-preflight fit check.

  report A.json            pretty-print one run report
  report A.json B.json     diff two reports (phase-by-phase wall and
                           rate deltas; environment differences called
                           out first so backend drift is separable
                           from code regressions — docs/OBSERVABILITY.md)
  report A.json --against-history LEDGER
                           diff A against the ledger's robust baseline
                           for its dispatch form (same env-drift-first
                           rendering)

  history ingest LEDGER FILE...   normalize + append result artifacts
                                  (BENCH/MULTICHIP/run_report shapes,
                                  legacy wrappers included; content-
                                  hash dedupe)
  history trend LEDGER            ASCII per-(leg, metric) series with
                                  robust baselines + newest-record
                                  flags (--json for the records)
  history gate LEDGER             the CI perf gate: budgets +
                                  program-change regressions fail
                                  (exit 1); env-drift warns and passes

  fit --scale N [--ndev D]        OOM preflight (ISSUE 10): abstract-
                                  eval the build+step at the target
                                  geometry (XLA memory_analysis per
                                  stage, NOTHING allocates), compare
                                  per-chip peaks against bytes_limit /
                                  the device-kind HBM table, and exit
                                  nonzero with the per-stage table
                                  when it provably does not fit

  graph --scale N [--ndev D]      data-plane inspection (ISSUE 13;
                                  obs/graph_profile.py): build a
                                  synthetic graph with the profiler
                                  armed, print the structural profile
                                  (degree histograms, dedup/self-loop
                                  counts, hubs, partition skew,
                                  power-law tail), the skew-driven
                                  load prediction for --ndev devices
                                  (+ the measured per-device edge
                                  counts when the mesh exists), and
                                  run a short PROBED solve whose
                                  rank-mass ledger must reconcile —
                                  exit 1 on any ledger violation

  hlo --form F [--scale N]        compiler-plane inspection (ISSUE 11;
                                  obs/hlo.py): build the named
                                  dispatch form(s) at the target
                                  geometry, harvest the OPTIMIZED HLO
                                  of every iteration program, and
                                  print the lowering verdict — gather
                                  strategy (native vs while/scalar
                                  expansion), fusion count, collective
                                  multiset, bf16-stream presence,
                                  HLO-derived bytes/edge, fingerprint.
                                  Exit 1 when any program classifies
                                  EXPANDED (the fast-gather-defeated
                                  signature); --dump-hlo DIR writes
                                  the raw modules for offline diffing

  campaign run --campaign-dir D   the measurement-campaign
                                  orchestrator (ISSUE 20;
                                  obs/campaign.py): execute the
                                  checked-in ROADMAP campaign spec
                                  (hlo -> fit -> graph -> bench couple
                                  -> bench --multichip -> bench
                                  --ppr-serve -> history gate) as ONE
                                  resumable command — checksummed
                                  per-leg artifacts, atomic manifest,
                                  SIGTERM drains to exit 75, resume
                                  skips validated legs. With
                                  --fake-devices N every leg runs
                                  end-to-end on CPU fake devices and
                                  all verdicts are non-binding
  campaign status --campaign-dir D    per-leg progress from the
                                      manifest
  campaign report --campaign-dir D    the strict-JSON campaign report:
                                      five typed verdicts + the human
                                      decision ledger (--full adds
                                      measured evidence)

Exit codes: 0 ok, 1 gate violation / does not fit / defeated gather /
mass-ledger violation / failed or incomplete campaign, 2 usage/
unreadable input, 75 campaign drained on SIGTERM (resume with the
same command).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from pagerank_tpu.exitcodes import ExitCode
from pagerank_tpu.obs import history as history_mod
from pagerank_tpu.obs import report as report_mod


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pagerank_tpu.obs",
        description="Run-report and perf-history tooling for the "
        "observability layer (docs/OBSERVABILITY.md).",
    )
    sub = p.add_subparsers(dest="command", required=True)
    rp = sub.add_parser(
        "report", help="render one run_report.json, or diff two"
    )
    rp.add_argument("paths", nargs="+", metavar="REPORT.json",
                    help="one report to render, or two to diff (A B)")
    rp.add_argument("--json", action="store_true",
                    help="emit the loaded report (or {'a','b'} pair) "
                    "as JSON instead of the human rendering")
    rp.add_argument(
        "--against-history", default=None, metavar="LEDGER",
        help="diff ONE report against the perf-history ledger's "
        "baseline for its dispatch form (median of the trailing "
        "window) — the pairwise env-drift-first diff, with the ledger "
        "standing in for run A",
    )
    hp = sub.add_parser(
        "history",
        help="perf-history ledger: ingest results, render the trend, "
        "run the CI perf gate (docs/OBSERVABILITY.md 'Perf history & "
        "gating')",
    )
    hsub = hp.add_subparsers(dest="history_command", required=True)
    ing = hsub.add_parser(
        "ingest", help="normalize result JSONs into the ledger "
        "(append-only, content-hash deduped)")
    ing.add_argument("ledger", metavar="LEDGER.jsonl")
    ing.add_argument("files", nargs="+", metavar="RESULT.json",
                     help="bench couple/single/--build-only JSON, "
                     "MULTICHIP JSON (dryrun or promoted), "
                     "run_report.json, or a legacy {n,cmd,rc,tail,"
                     "parsed} wrapper")
    ing.add_argument("--json", action="store_true",
                     help="emit {'added','deduped'} as JSON")
    tr = hsub.add_parser(
        "trend", help="ASCII per-(leg, metric) series over the ledger "
        "with robust baselines and newest-record flags")
    tr.add_argument("ledger", metavar="LEDGER.jsonl")
    tr.add_argument("--json", action="store_true",
                    help="emit the ledger records as JSON instead of "
                    "the table")
    tr.add_argument("--budgets", default=None, metavar="BUDGETS.json",
                    help="read detection knobs (window/threshold/"
                    "min_samples) from this perf_budgets file")
    ga = hsub.add_parser(
        "gate", help="the CI perf gate: exits 1 on a budget breach or "
        "a program-change regression; env-drift flags warn and pass")
    ga.add_argument("ledger", metavar="LEDGER.jsonl")
    ga.add_argument("--budgets", default=None, metavar="BUDGETS.json",
                    help="perf_budgets.json: absolute env-scoped "
                    "floors/ceilings + detection knobs (default: "
                    "MAD detection only)")
    ga.add_argument("--record", default=None, metavar="RESULT.json",
                    help="gate this result artifact against the "
                    "ledger instead of the ledger's own newest record "
                    "(the artifact is normalized, not appended)")
    ga.add_argument("--json", action="store_true",
                    help="emit the GateResult as JSON")
    ga.add_argument("--propose-budgets", default=None,
                    metavar="OUT.json",
                    help="also derive refreshed floors/ceilings from "
                    "the measured medians of each budget entry's "
                    "matching env-class rows (requires --budgets), "
                    "write the full proposal doc to OUT.json, and "
                    "print the diff vs the input budgets — the "
                    "ROADMAP's 'refresh floors from real numbers' "
                    "step, mechanized")
    fp = sub.add_parser(
        "fit",
        help="OOM-preflight fit check (ISSUE 10; obs/devices.py): "
        "will the device build + solve at this geometry fit per-chip "
        "HBM? Exits 1 with the per-stage table when it won't — "
        "BEFORE any real allocation",
    )
    fp.add_argument("--scale", type=int, required=True,
                    help="R-MAT scale (2^scale vertices, "
                    "edge_factor<<scale raw edges) — the bench/ROADMAP "
                    "geometry vocabulary")
    fp.add_argument("--ndev", type=int, default=1,
                    help="target device count; >1 implies the "
                    "vertex-sharded (memory-scaling) solve")
    fp.add_argument("--vs-bounded", action="store_true",
                    help="size the owner-computes bounded mode "
                    "(config.vs_bounded): per-chip step transients "
                    "O(stripe_span + N/ndev) instead of O(N); "
                    "implies --host-build (the mode requires a "
                    "host-built graph)")
    fp.add_argument("--edge-factor", type=int, default=16)
    fp.add_argument("--dtype", default="float32")
    fp.add_argument("--accum-dtype", default=None,
                    help="defaults to --dtype")
    fp.add_argument("--wide-accum", default="auto",
                    choices=["auto", "pair", "native"])
    fp.add_argument("--host-build", action="store_true",
                    help="skip the device-build pipeline stages (the "
                    "graph arrives host-built; only the solve "
                    "residency gates)")
    fp.add_argument("--hbm-gb", type=float, default=None,
                    help="explicit per-chip HBM limit in GiB "
                    "(default: live bytes_limit, else the device-kind "
                    "capacity table, else 16 GiB v5e-class)")
    fp.add_argument("--device-kind", default=None,
                    help="size against this device kind's published "
                    "HBM capacity (e.g. 'TPU v4') instead of the "
                    "attached device")
    fp.add_argument("--headroom", type=float, default=None,
                    help="fraction of the limit usable after runtime "
                    "reserve (default 0.9)")
    fp.add_argument("--json", action="store_true",
                    help="emit the FitResult as JSON")
    gp = sub.add_parser(
        "graph",
        help="data-plane inspection (ISSUE 13; obs/graph_profile.py): "
        "structural profile + skew-driven load prediction + the "
        "rank-mass conservation ledger over a short probed solve — "
        "exit 1 on a ledger violation",
    )
    gp.add_argument("--scale", type=int, default=14,
                    help="R-MAT scale of the probe graph (default 14)")
    gp.add_argument("--edge-factor", type=int, default=16)
    gp.add_argument("--synthetic", default=None, metavar="SPEC",
                    help="synthetic spec (the CLI grammar: rmat:N | "
                    "uniform:N[:E]) instead of --scale")
    gp.add_argument("--ndev", type=int, default=1,
                    help="target device count for the load prediction; "
                    "> 1 also runs the vertex-sharded solve and "
                    "reports MEASURED per-device edge counts when the "
                    "mesh exists")
    gp.add_argument("--iters", type=int, default=4,
                    help="probed solve iterations for the ledger check")
    gp.add_argument("--device-build", action="store_true",
                    help="profile via the on-device build's fused "
                    "reduction pass (default: host build + numpy "
                    "profile)")
    gp.add_argument("--semantics", choices=["reference", "textbook"],
                    default="textbook",
                    help="solve semantics for the ledger check "
                    "(textbook sums to 1 — the default gate)")
    gp.add_argument("--topk", type=int, default=100,
                    help="hub count / rank-concentration k "
                    "(default 100)")
    gp.add_argument("--json", action="store_true",
                    help="emit {profile, prediction, measured, ledger} "
                    "as strict JSON")
    hp2 = sub.add_parser(
        "hlo",
        help="compiler-plane lowering inspection (ISSUE 11; "
        "obs/hlo.py): classify the gather strategy / fusion "
        "structure of a dispatch form's optimized HLO — the "
        "'did XLA keep the fast gather' verdict read BEFORE a "
        "TPU session instead of hand-diffing HLO dumps",
    )
    hp2.add_argument(
        "--form", default="default", metavar="FORM",
        help="dispatch form(s) to inspect: comma-separated names from "
        "{default, pair, partitioned, partitioned_bf16, coo, "
        "vertex_sharded, vs_halo}, or 'all'",
    )
    hp2.add_argument("--scale", type=int, default=14,
                     help="R-MAT scale of the host-built probe graph "
                     "(default 14 — sub-second on CPU, big enough "
                     "that the hot gather is unambiguous)")
    hp2.add_argument("--edge-factor", type=int, default=16)
    hp2.add_argument("--json", action="store_true",
                     help="emit {form: {program: LoweringReport}} as "
                     "strict JSON")
    hp2.add_argument("--dump-hlo", default=None, metavar="DIR",
                     help="also write every inspected program's raw "
                     "optimized HLO to DIR as <form>.<program>.hlo")
    cp = sub.add_parser(
        "campaign",
        help="the measurement-campaign orchestrator (ISSUE 20; "
        "obs/campaign.py): run/resume the checked-in ROADMAP "
        "campaign as one command with checksummed per-leg artifacts, "
        "typed verdicts, and a decision ledger",
    )
    csub = cp.add_subparsers(dest="campaign_command", required=True)
    cr = csub.add_parser(
        "run", help="run (or resume) the campaign; completed legs "
        "with validated artifacts are skipped, SIGTERM drains to "
        "exit 75 at the next leg boundary")
    cr.add_argument("--campaign-dir", required=True, metavar="DIR",
                    help="artifact + manifest directory (the resume "
                    "key: rerun with the same DIR to resume)")
    cr.add_argument("--fake-devices", type=int, default=0, metavar="N",
                    help="non-binding dry run: force JAX_PLATFORMS="
                    "cpu with N fake host devices (set BEFORE backend "
                    "init), run the smoke-scale profile, and mark "
                    "every verdict 'defer' — the tier-1-testable "
                    "rehearsal of the TPU session")
    cr.add_argument("--profile", choices=["auto", "roadmap", "smoke"],
                    default="auto",
                    help="campaign geometry (default auto: smoke when "
                    "--fake-devices is set, roadmap otherwise)")
    cr.add_argument("--ndev", type=int, default=8,
                    help="target device count for the fit/graph/"
                    "multichip legs (default 8)")
    cr.add_argument("--budgets", default=None, metavar="BUDGETS.json",
                    help="perf_budgets file the gate leg and verdict "
                    "floors read (default: the checked-in "
                    "perf_budgets.json)")
    cr.add_argument("--drain-deadline", type=float, default=8.0,
                    metavar="S",
                    help="seconds after SIGTERM before the hard exit "
                    "(default 8.0)")
    cr.add_argument("--json", action="store_true",
                    help="emit the stable campaign report as JSON "
                    "instead of the human rendering")
    cst = csub.add_parser(
        "status", help="per-leg progress from the campaign manifest")
    cst.add_argument("--campaign-dir", required=True, metavar="DIR")
    cst.add_argument("--json", action="store_true",
                     help="emit the manifest as JSON")
    crp = csub.add_parser(
        "report", help="rebuild the campaign report from the on-disk "
        "artifacts (never re-runs anything): typed verdicts + the "
        "decision ledger; exit 1 while the campaign is incomplete")
    crp.add_argument("--campaign-dir", required=True, metavar="DIR")
    crp.add_argument("--budgets", default=None, metavar="BUDGETS.json",
                     help="perf_budgets file the verdict floors read "
                     "(default: the checked-in perf_budgets.json)")
    crp.add_argument("--json", action="store_true",
                     help="emit the report as canonical strict JSON")
    crp.add_argument("--full", action="store_true",
                     help="include the volatile evidence: per-verdict "
                     "measurements, per-leg walls, raw leg docs "
                     "(NOT byte-stable across runs)")
    return p


def _cmd_graph(args) -> int:
    """Data-plane inspection (ISSUE 13): profile -> prediction ->
    measured -> ledger, nonzero exit on a ledger violation."""
    from pagerank_tpu import PageRankConfig, build_graph
    from pagerank_tpu.engine import make_engine
    from pagerank_tpu.obs import graph_profile
    from pagerank_tpu.obs.probes import ConvergenceProbes
    from pagerank_tpu.parallel import comms

    if args.iters < 1 or args.ndev < 1:
        print("obs graph: --iters and --ndev must be >= 1",
              file=sys.stderr)
        return 2
    # Synthetic geometry through THE shared spec grammar (cli.py) so
    # `obs graph` and the CLI can never disagree about what a spec
    # means; --scale is shorthand for rmat:N.
    kind, scale, n, e = "rmat", args.scale, 1 << args.scale, None
    if args.synthetic:
        from pagerank_tpu.cli import _parse_synthetic_geometry

        geo = _parse_synthetic_geometry(args.synthetic)
        if geo is None:
            print(f"obs graph: unknown synthetic spec "
                  f"{args.synthetic!r}", file=sys.stderr)
            return 2
        kind, n, e, scale = geo

    import jax

    avail = len(jax.devices())
    run_ndev = min(args.ndev, avail)
    if run_ndev < args.ndev:
        print(f"obs graph: {args.ndev} devices requested, {avail} "
              f"available — prediction targets {args.ndev}, the "
              f"measured solve runs on {run_ndev}", file=sys.stderr)

    graph_profile.reset()
    graph_profile.arm()
    try:
        cfg = PageRankConfig(
            num_iters=args.iters, semantics=args.semantics,
            probe_every=1, probe_topk=args.topk,
            vertex_sharded=run_ndev > 1,
            num_devices=run_ndev if run_ndev > 1 else None,
        ).validate()
        if args.device_build:
            from pagerank_tpu.ops import device_build as db

            if kind == "rmat":
                src, dst = db.rmat_edges_device(
                    scale, edge_factor=args.edge_factor, seed=0)
            else:
                src, dst = db.uniform_edges_device(n, e, seed=0)
            grp, stripe, _part = db.plan_build(cfg, n,
                                               num_edges=len(src))
            dg = db.build_ell_device(src, dst, n=n, group=grp,
                                     stripe_size=stripe)
            profile = graph_profile.get_profile()
            engine = make_engine("jax", cfg).build_device(dg)
        else:
            from pagerank_tpu.utils import synth

            if kind == "rmat":
                src, dst = synth.rmat_edges(scale, args.edge_factor,
                                            seed=0)
                g = build_graph(src, dst, n=n)
            else:
                src, dst = synth.uniform_edges(n, e)
                g = build_graph(src, dst, n=n)
            engine = make_engine("jax", cfg).build(g)
            # Profile at the layout the engine ACTUALLY packed (the
            # lane group shapes the row geometry the load prediction
            # walks; shared derivation — CLI/bench use the same one).
            group, span = graph_profile.layout_profile_geometry(
                engine.layout_info())
            profile = graph_profile.profile_graph(
                g, group=group, partition_span=span, topk=args.topk,
            )
            graph_profile.publish(profile)

        prediction = comms.predict_from_profile(profile, args.ndev)
        comms.publish_prediction(prediction)
        if profile is not None:
            profile.prediction = prediction

        probes = ConvergenceProbes(1, topk=args.topk)
        engine.run(probes=probes)

        measured = None
        if run_ndev > 1:
            counts = comms.measured_device_edges(engine)
            if counts is not None and counts.sum() > 0:
                mean = float(counts.sum()) / len(counts)
                measured = {
                    "ndev": int(len(counts)),
                    "device_edges": [int(v) for v in counts],
                    "straggler_skew": float(counts.max() / mean),
                }
    finally:
        graph_profile.disarm()

    residuals = [abs((r.get("mass_ledger") or {}).get("residual", 0.0))
                 for r in probes.history if r.get("mass_ledger")]
    entries = sum(1 for r in probes.history if r.get("mass_ledger"))
    ledger = {
        "probes": len(probes.history),
        "entries": entries,
        "max_abs_residual": max(residuals) if residuals else None,
        "violations": [
            {k: v for k, v in rec.items()}
            for rec in probes.ledger_violations
        ],
        # NOT vacuous: a run whose probed steps never measured the
        # ledger (a form without a ledger core) must FAIL the gate —
        # "no evidence" is not "reconciled".
        "ok": (entries == len(probes.history) and entries > 0
               and not probes.ledger_violations),
    }
    doc = {
        "profile": profile.summary() if profile is not None else None,
        "prediction": prediction,
        "measured": measured,
        "ledger": ledger,
    }
    if args.json:
        print(json.dumps(report_mod._json_safe(doc), indent=2,
                         allow_nan=False))
    else:
        prof = doc["profile"] or {}
        print(f"graph profile ({prof.get('source')}): n={prof.get('n'):,}, "
              f"{prof.get('num_edges'):,} unique edges"
              + (f" ({prof.get('duplicate_edges'):,} dups)"
                 if prof.get("duplicate_edges") is not None else "")
              + (f", {prof.get('self_loops'):,} self-loops"
                 if prof.get("self_loops") is not None else ""))
        print(f"  dangling {prof.get('dangling_fraction', 0):.3%} "
              f"({prof.get('dangling_count'):,}), zero-in "
              f"{prof.get('zero_in_count'):,}")
        print(f"  in-degree hist (log2 bins): "
              f"{_fmt_hist(prof.get('in_hist') or [])}")
        print(f"  out-degree hist (log2 bins): "
              f"{_fmt_hist(prof.get('out_hist') or [])}")
        hubs = list(zip(prof.get("top_hub_ids") or [],
                        prof.get("top_hub_in_degrees") or []))[:8]
        print("  top hubs (id:in-degree): "
              + ", ".join(f"{i}:{d}" for i, d in hubs))
        if prof.get("partition_skew") is not None:
            print(f"  partition skew (max/mean over "
                  f"{len(prof.get('partition_edges') or [])} "
                  f"partition(s)): {prof['partition_skew']:.3f}")
        if prof.get("powerlaw_alpha") is not None:
            print(f"  power-law tail alpha ~ "
                  f"{prof['powerlaw_alpha']:.2f}")
        if prediction:
            print(f"predicted @ ndev {prediction['ndev']}: straggler "
                  f"skew {prediction.get('predicted_straggler_skew')}, "
                  f"halo head-K "
                  f"{prediction.get('predicted_halo_head_k')}")
        if measured:
            print(f"measured  @ ndev {measured['ndev']}: straggler "
                  f"skew {measured['straggler_skew']:.4f} "
                  f"(per-device edges {measured['device_edges']})")
        print(f"mass ledger: {ledger['entries']}/{ledger['probes']} "
              f"probed iteration(s) reconciled"
              + (f", max |residual| {ledger['max_abs_residual']:.3e}"
                 if ledger["max_abs_residual"] is not None else "")
              + (" -> OK" if ledger["ok"] else
                 f" -> {len(ledger['violations'])} VIOLATION(S)"))
        for v in ledger["violations"]:
            print(f"  iteration {v.get('iteration')}: {v.get('leak')} "
                  f"term leaked (residual {v.get('residual'):.3e})")
    return 0 if ledger["ok"] else 1


def _fmt_hist(hist) -> str:
    top = max(len(hist) - 1, 0)
    while top > 0 and not hist[top]:
        top -= 1
    return "[" + " ".join(str(int(v)) for v in hist[:top + 1]) + "]"


def _cmd_hlo(args) -> int:
    from pagerank_tpu.obs import hlo as hlo_mod

    alias = {"ell": "default", "fast_bf16": "partitioned_bf16"}
    names = (
        # --form all: one entry per DISTINCT program (alias targets).
        [n for n in hlo_mod.FORM_CHOICES if n not in alias]
        if args.form == "all"
        else [f.strip() for f in args.form.split(",") if f.strip()])
    # Fail the usage error BEFORE any graph builds — a typo'd form at
    # --scale 22 must not cost minutes of R-MAT host work first.
    unknown = [n for n in names if n not in hlo_mod.FORM_CHOICES]
    if unknown or not names:
        print(
            "obs hlo: unknown dispatch form(s) "
            + (", ".join(repr(n) for n in unknown) or "(none given)")
            + " (choices: " + ", ".join(hlo_mod.FORM_CHOICES) + ")",
            file=sys.stderr,
        )
        return 2
    # Build each distinct program once (default/ell and
    # partitioned_bf16/fast_bf16 are aliases) but emit EVERY requested
    # name — `--form ell,default` returns both keys, sharing one
    # snapshot.
    built, out, defeated = {}, {}, []
    for form in names:
        canon = alias.get(form, form)
        if canon not in built:
            try:
                built[canon] = hlo_mod.inspect_form(
                    canon, args.scale, edge_factor=args.edge_factor)
            except ValueError as e:
                print(f"obs hlo: {e}", file=sys.stderr)
                return 2
            if args.dump_hlo:
                hlo_mod.dump_texts(args.dump_hlo, prefix=canon)
        snapshot = built[canon]
        if form in out:
            continue  # same name listed twice
        out[form] = snapshot
        for prog, rep in snapshot.items():
            if (rep.get("gather") or {}).get("strategy") == "expanded":
                defeated.append(f"{form}/{prog}")
    if args.json:
        print(json.dumps(report_mod._json_safe(out), indent=2,
                         allow_nan=False))
    else:
        for form, snapshot in out.items():
            if not snapshot:
                print(f"{form}: backend reports no optimized HLO "
                      f"(verdict unknown)")
            for prog in sorted(snapshot):
                rep = dict(snapshot[prog])
                rep["form"] = f"{form}/{prog}"
                print(hlo_mod.render_report(rep))
    if defeated:
        print("obs hlo: DEFEATED gather lowering in: "
              + ", ".join(defeated), file=sys.stderr)
        return 1
    return 0


def _cmd_fit(args) -> int:
    from pagerank_tpu.obs import devices as devices_mod

    kwargs = {}
    if args.hbm_gb is not None:
        if args.hbm_gb <= 0:
            print("obs fit: --hbm-gb must be positive", file=sys.stderr)
            return 2
        kwargs["limit_bytes"] = int(args.hbm_gb * (1 << 30))
    if args.headroom is not None:
        if not 0 < args.headroom <= 1:
            print("obs fit: --headroom must be in (0, 1]",
                  file=sys.stderr)
            return 2
        kwargs["headroom"] = args.headroom
    try:
        res = devices_mod.fit_check(
            args.scale, ndev=args.ndev, edge_factor=args.edge_factor,
            dtype=args.dtype, accum_dtype=args.accum_dtype,
            wide_accum=args.wide_accum,
            vertex_sharded=True if args.vs_bounded else None,
            vs_bounded=args.vs_bounded,
            device_build=not (args.host_build or args.vs_bounded),
            device_kind=args.device_kind, **kwargs,
        )
    except ValueError as e:
        print(f"obs fit: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report_mod._json_safe(res.to_json()),
                         indent=2, allow_nan=False))
    else:
        print(devices_mod.render_fit(res))
    return 0 if res.fits else 1


def _load_json(path: str):
    with open(path) as f:
        return json.load(f)


def _cmd_report(args) -> int:
    if len(args.paths) > 2:
        print("report takes one or two files", file=sys.stderr)
        return 2
    if args.against_history and len(args.paths) != 1:
        print("--against-history diffs exactly one report",
              file=sys.stderr)
        return 2
    try:
        reports = [report_mod.load_report(p) for p in args.paths]
    except (OSError, json.JSONDecodeError) as e:
        print(f"obs report: cannot load report: {e}", file=sys.stderr)
        return 2
    if args.against_history:
        try:
            records = history_mod.read_ledger(args.against_history)
        except ValueError as e:
            print(f"obs report: {e}", file=sys.stderr)
            return 2
        leg = history_mod.leg_name_for_config(
            reports[0].get("config") or {})
        baseline, n = history_mod.baseline_pseudo_report(
            records, leg, env=reports[0].get("environment"))
        if baseline is None:
            print(f"obs report: ledger {args.against_history} has no "
                  f"'{leg}' records to baseline against",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps({"baseline": baseline, "b": reports[0]},
                             indent=2, allow_nan=False))
            return 0
        print(f"against history: leg '{leg}', baseline = median of "
              f"{n} ledger record(s) (A = baseline, B = this run)")
        print(report_mod.diff_reports(baseline, reports[0]))
        return 0
    if args.json:
        doc = (reports[0] if len(reports) == 1
               else {"a": reports[0], "b": reports[1]})
        print(json.dumps(doc, indent=2))
        return 0
    if len(reports) == 1:
        print(report_mod.render_report(reports[0]))
    else:
        print(report_mod.diff_reports(reports[0], reports[1]))
    return 0


def _cmd_history(args) -> int:
    try:
        if args.history_command == "ingest":
            added, deduped = history_mod.ingest_paths(args.ledger,
                                                      args.files)
            if args.json:
                print(json.dumps({"added": added, "deduped": deduped},
                                 allow_nan=False))
            else:
                print(f"ingested {added} record(s) into {args.ledger}"
                      + (f" ({deduped} duplicate(s) skipped)"
                         if deduped else ""))
            return 0
        # trend/gate READ the ledger: a missing path is a usage error
        # (a mistyped ledger in CI must not gate green on "empty"),
        # while `ingest` legitimately creates it.
        records = history_mod.read_ledger(args.ledger)
        if not records and not os.path.exists(args.ledger):
            print(f"obs history: no such ledger: {args.ledger}",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
        budgets = (history_mod.load_budgets(args.budgets)
                   if args.budgets else None)
        if args.history_command == "trend":
            if args.json:
                print(json.dumps(records, indent=2, allow_nan=False))
            else:
                print(history_mod.render_trend(
                    records,
                    detection=(budgets or {}).get("detection")))
            return 0
        # gate
        if args.record:
            rec = history_mod.normalize_result(
                _load_json(args.record), source=args.record)
            records = list(records) + [rec]
        res = history_mod.evaluate_gate(records, budgets)
        prop = None
        if args.propose_budgets:
            if budgets is None:
                print("obs history: --propose-budgets needs --budgets "
                      "(there is nothing to refresh without the "
                      "checked-in floors)", file=sys.stderr)
                return int(ExitCode.USAGE)
            prop = history_mod.propose_budgets(records, budgets)
            with open(args.propose_budgets, "w") as f:
                f.write(json.dumps(
                    report_mod._json_safe(prop["proposal"]),
                    indent=2, allow_nan=False) + "\n")
        if args.json:
            doc = res.to_dict()
            if prop is not None:
                doc = {"gate": doc,
                       "proposal": {"changes": prop["changes"],
                                    "skipped": prop["skipped"],
                                    "out": args.propose_budgets}}
            print(json.dumps(doc, indent=2, allow_nan=False))
        else:
            for line in res.notes:
                print(f"gate: {line}")
            for line in res.improvements:
                print(f"gate: IMPROVEMENT {line}")
            for line in res.drift_warnings:
                print(f"gate: WARNING {line}")
            for line in res.violations:
                print(f"gate: FAIL {line}")
            print("gate: " + ("PASS" if res.ok else "FAIL")
                  + (f" ({len(res.drift_warnings)} drift warning(s))"
                     if res.drift_warnings else ""))
            if prop is not None:
                for c in prop["changes"]:
                    print(f"propose: {c['leg']}.{c['metric']} "
                          f"{c['bound']} {c['old']:.4g} -> "
                          f"{c['new']:.4g} (median {c['median']:.4g} "
                          f"over {c['n']} row(s))")
                for s in prop["skipped"]:
                    print(f"propose: {s['leg']}.{s['metric']} "
                          f"unchanged — {s['rows']} matching row(s), "
                          f"need {s['needed']}")
                print(f"propose: wrote {args.propose_budgets}")
        # The exit-code taxonomy (pagerank_tpu/exitcodes.py): FAILURE
        # is a judged-bad gate, USAGE a bad/missing invocation.
        return int(ExitCode.OK if res.ok else ExitCode.FAILURE)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"obs history: {e}", file=sys.stderr)
        return int(ExitCode.USAGE)


def _cmd_campaign(args) -> int:
    """The campaign orchestrator CLI (ISSUE 20; obs/campaign.py)."""
    from pagerank_tpu.obs import campaign as campaign_mod

    if args.campaign_command == "status":
        try:
            _spec, manifest, _docs, _metas = \
                campaign_mod.load_campaign(args.campaign_dir)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"obs campaign: no campaign in "
                  f"{args.campaign_dir}: {e}", file=sys.stderr)
            return int(ExitCode.USAGE)
        if args.json:
            print(json.dumps(report_mod._json_safe(manifest),
                             indent=2, allow_nan=False))
        else:
            print(campaign_mod.render_status(manifest))
        return int(ExitCode.OK)

    if args.campaign_command == "report":
        try:
            spec, manifest, docs, metas = \
                campaign_mod.load_campaign(args.campaign_dir)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"obs campaign: no campaign in "
                  f"{args.campaign_dir}: {e}", file=sys.stderr)
            return int(ExitCode.USAGE)
        budgets = campaign_mod._load_budgets_quiet(
            args.budgets or campaign_mod.default_budgets_path())
        rep = campaign_mod.build_report(spec, manifest, docs, metas,
                                        budgets, full=args.full)
        if args.json:
            sys.stdout.write(report_mod.canonical_json(rep))
        else:
            print(campaign_mod.render_report(rep))
        return int(ExitCode.OK if rep.get("complete")
                   else ExitCode.FAILURE)

    # run
    if args.fake_devices:
        # BEFORE any backend init: XLA reads these at first client
        # creation, so setting them here (not at import time) is safe
        # as long as nothing upstream touched jax.devices() yet.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                + str(args.fake_devices)).strip()
    from pagerank_tpu import jobs

    profile = args.profile
    if profile == "auto":
        profile = "smoke" if args.fake_devices else "roadmap"
    spec = campaign_mod.build_spec(profile=profile, ndev=args.ndev)
    runner = campaign_mod.CampaignRunner(
        args.campaign_dir, spec, fake_devices=args.fake_devices,
        budgets_path=args.budgets)
    drain = jobs.GracefulDrain(deadline_s=args.drain_deadline)
    with drain:
        try:
            runner.run(drain=drain,
                       progress=lambda line: print(line,
                                                   file=sys.stderr))
        except jobs.DrainInterrupt as e:
            runner.interrupt(str(e))
            print(f"obs campaign: drained on signal ({e}); completed "
                  "legs are durable — resume with the same command",
                  file=sys.stderr)
            return int(ExitCode.INTERRUPTED)
    rep = runner.write_report()
    if args.json:
        sys.stdout.write(report_mod.canonical_json(rep))
    else:
        print(campaign_mod.render_report(rep))
        print(f"report written to {runner.report_path}",
              file=sys.stderr)
    return int(ExitCode.OK if rep.get("complete")
               else ExitCode.FAILURE)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "report":
        return _cmd_report(args)
    if args.command == "fit":
        return _cmd_fit(args)
    if args.command == "hlo":
        return _cmd_hlo(args)
    if args.command == "graph":
        return _cmd_graph(args)
    if args.command == "campaign":
        return _cmd_campaign(args)
    return _cmd_history(args)


if __name__ == "__main__":
    sys.exit(main())
