"""Live monitoring: Prometheus text exporter + stall watchdog (ISSUE 5).

An hour-scale multichip solve that hangs in a collective dies silently:
nothing in the span/report machinery fires until the run ENDS. This
module is the live window into a run that hasn't:

  - :func:`render_prometheus` — the metrics registry as Prometheus
    text exposition format (zero dependencies: plain string building);
  - :class:`MetricsExporter` — ``--metrics-textfile PATH`` atomically
    rewrites the rendering every iteration (``fsio.atomic_write``, so a
    scraper's node-exporter textfile collector never reads a torn
    file), and ``--metrics-port N`` serves the same snapshot over HTTP
    ``GET /metrics`` from a daemon thread;
  - :class:`StallWatchdog` — a daemon thread fed by solve/step
    completions (``engine.run`` heartbeats it when armed). When no
    step completes within ``--stall-timeout`` seconds it logs a LOUD
    diagnostic — last-completed iteration, seconds since progress, and
    a per-device view — then optionally interrupts the run
    (``--stall-action raise``): a hung collective becomes visible
    instead of silent. The clock/sleep are injectable (the
    utils/retry.py discipline) so tests drive fire/no-fire in virtual
    time.

The solve hot path pays one ``is None`` check per iteration when the
watchdog is disarmed (the same discipline as the no-op tracer).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional, Sequence

from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.utils import fsio

# -- Prometheus text exposition ---------------------------------------------

_NAME_PREFIX = "pagerank_"


def _prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name: the dotted scheme maps
    onto underscores under one namespace prefix (``s3.request.retries``
    -> ``pagerank_s3_request_retries``)."""
    safe = "".join(
        c if c.isalnum() or c == "_" else "_" for c in name
    )
    return _NAME_PREFIX + safe


def _prom_help(text: str) -> str:
    """HELP line escaping per the exposition format: backslash and
    newline only."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_value(v) -> str:
    if v is None:
        return "NaN"  # Prometheus-legal unset sample value
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        # Exposition-format spellings, NOT Python repr: a diverging
        # solve legitimately puts NaN in a gauge (probe.rank_mass
        # under --no-health-checks), and 'nan'/'-inf' would fail the
        # format's own grammar (the acceptance smoke's strict parse).
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        return repr(v)
    return str(v)


def render_prometheus(registry: Optional[obs_metrics.MetricsRegistry]
                      = None) -> str:
    """The registry as Prometheus text format (version 0.0.4): one
    ``# HELP`` / ``# TYPE`` pair per metric, counters and gauges as
    single samples, histograms as cumulative ``_bucket{le=...}`` series
    plus ``_sum`` / ``_count`` (quantile estimates stay in the run
    report — the exposition format reserves ``quantile`` labels for
    summaries). Deterministic ordering (registry name order) so the
    output is golden-testable.

    Renders from ``registry.export_view()`` — a consistent snapshot
    copied under the registry/histogram locks — never from live
    internals: this function runs on the exporter's HTTP thread while
    the solve loop registers and records (PTR001; the pre-fix direct
    ``_metrics``/bucket iteration could race a concurrent insert)."""
    registry = registry if registry is not None else obs_metrics.get_registry()
    lines: List[str] = []
    for name, kind, help_text, snap in registry.export_view():
        pname = _prom_name(name)
        lines.append(f"# HELP {pname} {_prom_help(help_text or name)}")
        if kind in ("counter", "gauge"):
            lines.append(f"# TYPE {pname} {kind}")
            if kind == "gauge" and snap is None:
                continue  # unset gauge: publish nothing, not NaN
            lines.append(f"{pname} {_prom_value(snap)}")
        else:  # histogram -> cumulative le-buckets
            lines.append(f"# TYPE {pname} histogram")
            buckets = snap["buckets"]

            def bound(key: str) -> float:
                return float("inf") if key == "+inf" else float(int(key))
            cum = 0
            finite = (k for k in buckets if k != "+inf")
            for key in sorted(finite, key=bound):
                cum += buckets[key]
                lines.append(f'{pname}_bucket{{le="{key}"}} {cum}')
            # The +Inf bucket is total count by definition (covers the
            # registry's own "+inf" overflow bucket too).
            lines.append(f'{pname}_bucket{{le="+Inf"}} {snap["count"]}')
            lines.append(f"{pname}_sum {_prom_value(snap['sum'])}")
            lines.append(f"{pname}_count {snap['count']}")
    return "\n".join(lines) + "\n"


def _exemplar_suffix(ex: Optional[dict]) -> str:
    """OpenMetrics exemplar clause for one bucket line: `` # {labels}
    value``. Empty when the bucket has no exemplar (a series without
    exemplars is valid OpenMetrics)."""
    if not ex:
        return ""
    trace_id = str(ex.get("trace_id", ""))
    safe = trace_id.replace("\\", "\\\\").replace('"', '\\"')
    return f' # {{trace_id="{safe}"}} {_prom_value(ex.get("value"))}'


def render_openmetrics(registry: Optional[obs_metrics.MetricsRegistry]
                       = None) -> str:
    """The registry as OpenMetrics 1.0 text — the exemplar-capable
    sibling of :func:`render_prometheus` (ISSUE 19). Differences the
    format mandates: counter samples carry the ``_total`` suffix,
    histogram bucket lines may carry ``# {trace_id="..."} value``
    exemplar clauses (the query plane's bucket->trace links), and the
    exposition ends with ``# EOF``. Same deterministic ordering and
    the same NaN/+Inf/-Inf value spellings; scrapers that only speak
    plain Prometheus keep the 0.0.4 renderer (no exemplars) — the
    fallback mode :class:`MetricsExporter` defaults to."""
    registry = registry if registry is not None else obs_metrics.get_registry()
    lines: List[str] = []
    for name, kind, help_text, snap in registry.export_view():
        pname = _prom_name(name)
        lines.append(f"# HELP {pname} {_prom_help(help_text or name)}")
        lines.append(f"# TYPE {pname} {kind}")
        if kind == "counter":
            lines.append(f"{pname}_total {_prom_value(snap)}")
        elif kind == "gauge":
            if snap is None:
                continue  # unset gauge: publish nothing, not NaN
            lines.append(f"{pname} {_prom_value(snap)}")
        else:  # histogram -> cumulative le-buckets (+ exemplars)
            buckets = snap["buckets"]
            exemplars = snap.get("exemplars", {})

            def bound(key: str) -> float:
                return float("inf") if key == "+inf" else float(int(key))
            cum = 0
            finite = (k for k in buckets if k != "+inf")
            for key in sorted(finite, key=bound):
                cum += buckets[key]
                lines.append(
                    f'{pname}_bucket{{le="{key}"}} {cum}'
                    + _exemplar_suffix(exemplars.get(key))
                )
            lines.append(
                f'{pname}_bucket{{le="+Inf"}} {snap["count"]}'
                + _exemplar_suffix(exemplars.get("+inf"))
            )
            lines.append(f"{pname}_sum {_prom_value(snap['sum'])}")
            lines.append(f"{pname}_count {snap['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def update_solve_gauges(iteration: int, info: dict,
                        seconds: Optional[float] = None) -> None:
    """Publish one iteration's headline scalars as registry gauges (the
    live exporter's per-iteration feed) and file the step wall into the
    ``solve.step_seconds`` histogram — whose p50/p90/p99 the exporter
    and run report surface. With a perf-history baseline armed
    (``arm_history_baseline``; CLI ``--history``), also publishes the
    ``history.*`` baseline-delta gauges so a RUNNING solve shows its
    % vs the ledger's baseline, not just absolute rates."""
    obs_metrics.gauge(
        "solve.iteration", "iterations completed by the current solve"
    ).set(iteration + 1)
    for key, help_text in (
        ("l1_delta", "L1 residual of the latest iteration"),
        ("dangling_mass", "dangling mass of the latest iteration"),
        ("rank_mass", "sum(ranks) at the latest probe point"),
    ):
        v = info.get(key)
        if v is not None:
            obs_metrics.gauge("solve." + key, help_text).set(float(v))
    if seconds is not None:
        obs_metrics.histogram(
            "solve.step_seconds_ms",
            "per-iteration wall clock, milliseconds",
        ).record(seconds * 1e3)
        b = _HISTORY_BASELINE
        if b is not None and seconds > 0:
            b.publish(seconds)


# -- perf-history baseline deltas (obs/history.py; ISSUE 9) -----------------


@dataclasses.dataclass
class HistoryBaseline:
    """A ledger-derived throughput baseline armed for the current
    solve: per-step seconds become edges/s/chip against the baseline
    median for this run's leg, published as ``history.*`` gauges every
    iteration. The disarmed hot path pays one ``is None`` check (the
    watchdog/tracer discipline)."""

    leg: str
    baseline_eps: float       # ledger median edges/s/chip for the leg
    num_edges: int
    num_chips: int = 1
    n_baseline: int = 0       # ledger samples behind the median

    def publish(self, seconds: float) -> None:
        eps = self.num_edges / seconds / max(1, self.num_chips)
        obs_metrics.gauge(
            "history.baseline_edges_per_sec_per_chip",
            "perf-ledger baseline (median edges/s/chip) for this "
            "run's leg",
        ).set(self.baseline_eps)
        obs_metrics.gauge(
            "history.edges_per_sec_per_chip",
            "this run's latest per-iteration edges/s/chip",
        ).set(eps)
        if self.baseline_eps > 0:
            obs_metrics.gauge(
                "history.vs_baseline_pct",
                "latest iteration rate vs the perf-ledger baseline, "
                "percent (negative = slower than baseline)",
            ).set((eps / self.baseline_eps - 1.0) * 100.0)


_HISTORY_BASELINE: Optional[HistoryBaseline] = None


def arm_history_baseline(baseline: HistoryBaseline) -> HistoryBaseline:
    """Install the baseline the solve gauges publish deltas against
    (one per process, like the watchdog)."""
    global _HISTORY_BASELINE
    _HISTORY_BASELINE = baseline
    obs_log.info(
        f"perf-history baseline armed: leg '{baseline.leg}' at "
        f"{baseline.baseline_eps:.4g} edges/s/chip "
        f"(median of {baseline.n_baseline} ledger record(s))"
    )
    return baseline


def disarm_history_baseline() -> Optional[HistoryBaseline]:
    global _HISTORY_BASELINE
    prev = _HISTORY_BASELINE
    _HISTORY_BASELINE = None
    return prev


def get_history_baseline() -> Optional[HistoryBaseline]:
    return _HISTORY_BASELINE


class MetricsExporter:
    """Live registry publisher: an atomic textfile rewrite per call
    and/or an HTTP endpoint serving the same rendering. Zero
    dependencies (http.server); the HTTP thread renders on demand, so
    a scrape always sees the current registry."""

    FORMATS = ("prometheus", "openmetrics")
    _CONTENT_TYPES = {
        "prometheus": "text/plain; version=0.0.4",
        "openmetrics":
            "application/openmetrics-text; version=1.0.0; charset=utf-8",
    }

    def __init__(self, textfile: Optional[str] = None,
                 port: Optional[int] = None,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 format: str = "prometheus"):
        if format not in self.FORMATS:
            raise ValueError(
                f"format must be one of {self.FORMATS}, got {format!r}")
        self.textfile = textfile
        self.registry = registry
        self.format = format
        self._server = None
        self._thread = None
        self.port = None
        if port is not None:
            self._start_http(port)

    def render(self) -> str:
        if self.format == "openmetrics":
            return render_openmetrics(self.registry)
        return render_prometheus(self.registry)

    def write_textfile(self) -> None:
        """Atomic rewrite (tmp + rename via fsio.atomic_write): a
        concurrent scraper reads the previous complete rendering or
        the new one, never a torn file."""
        if not self.textfile:
            return
        with fsio.atomic_write(self.textfile, "w", suffix=".prom.tmp") as f:
            f.write(self.render())

    def _start_http(self, port: int) -> None:
        import http.server

        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = exporter.render().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    exporter._CONTENT_TYPES[exporter.format],
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self.port = self._server.server_address[1]  # resolved (port 0 ok)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="pagerank-metrics-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        """Final textfile flush + HTTP teardown (idempotent)."""
        try:
            self.write_textfile()
        finally:
            if self._server is not None:
                self._server.shutdown()
                self._server.server_close()
                self._server = None
                if self._thread is not None:
                    self._thread.join(timeout=5)
                    self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


# -- stall watchdog ---------------------------------------------------------


class StallWatchdog:
    """Heartbeat-fed stall detector for long solves.

    ``heartbeat(iteration)`` is called on every solve/step completion
    (engine.run reads the armed watchdog once per run). A daemon
    thread polls; when ``clock() - last_heartbeat > timeout`` it
    emits ONE loud diagnostic per stall episode — last-completed
    iteration, seconds stalled, per-device view — increments the
    ``watchdog.stalls`` counter, and under ``action='raise'``
    interrupts the main thread (KeyboardInterrupt at the next
    bytecode boundary; a stall wedged inside a C call surfaces the
    moment it returns). The episode re-arms on the next heartbeat, so
    a run that stalls twice logs twice.

    ``action='rescue'`` (ISSUE 7, parallel/elastic.py): the fire
    additionally classifies the stall — a deadline-bounded per-device
    liveness probe (mesh.probe_liveness) discriminates *hang* (every
    device answers) from *device-lost* — sets :attr:`rescue_requested`,
    and interrupts the main thread exactly like 'raise'. The elastic
    runner catches the interrupt, calls :meth:`consume_rescue`, and
    performs the mesh teardown + re-shard + warm-start; a plain run
    (no runner) sees an ordinary KeyboardInterrupt.

    ``clock``/``sleep`` are injectable: tests drive :meth:`check` in
    virtual time with no thread (utils/retry.py discipline).
    """

    ACTIONS = ("warn", "raise", "rescue")

    def __init__(self, timeout_s: float, action: str = "warn",
                 poll_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 interrupt: Optional[Callable[[], None]] = None,
                 liveness_timeout_s: float = 2.0,
                 device_source: Optional[Callable[[], Sequence]] = None):
        if timeout_s <= 0:
            raise ValueError(f"stall timeout must be > 0, got {timeout_s}")
        if action not in self.ACTIONS:
            raise ValueError(
                f"action must be one of {self.ACTIONS}, got {action!r}")
        self.timeout_s = float(timeout_s)
        self.action = action
        self.poll_s = poll_s if poll_s is not None else min(
            1.0, self.timeout_s / 4
        )
        self.clock = clock
        self._sleep = sleep
        self._interrupt = interrupt if interrupt is not None else (
            self._default_interrupt
        )
        self.liveness_timeout_s = float(liveness_timeout_s)
        #: Where classification gets its device list: a callable
        #: returning the SOLVE MESH's devices (the CLI wires the
        #: current engine's mesh — post-rescue it must track the
        #: rebuilt one). None falls back to every visible device,
        #: which can blame a chip the solve never uses.
        self.device_source = device_source
        self._last = self.clock()
        self.last_iteration: Optional[int] = None
        self.stalls = 0
        self._fired = False  # one diagnostic per stall episode
        self.rescue_requested = False
        self.last_classification: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_interrupt() -> None:
        import _thread

        _thread.interrupt_main()

    def heartbeat(self, iteration: Optional[int] = None) -> None:
        """Progress signal — one per completed solve step."""
        self._last = self.clock()
        if iteration is not None:
            self.last_iteration = iteration
        self._fired = False  # new progress re-arms the episode

    def consume_rescue(self) -> bool:
        """Whether the latest fire requested a rescue; reading it
        clears the flag (the elastic runner's one-shot handshake —
        a later unrelated KeyboardInterrupt must not rescue)."""
        req = self.rescue_requested
        self.rescue_requested = False
        return req

    def _classify(self) -> str:
        """Hang vs device-lost, best-effort: a deadline-bounded
        liveness probe per SOLVE-MESH device (``device_source``;
        parallel/mesh.probe_liveness). Never raises — classification
        is diagnostic input, and a probe that cannot run still leaves
        the stall loud."""
        try:
            from pagerank_tpu.parallel import mesh as mesh_lib

            devs = (self.device_source()
                    if self.device_source is not None else None)
            alive = mesh_lib.probe_liveness(
                devs, timeout_s=self.liveness_timeout_s
            )
            dead = sorted(d for d, ok in alive.items() if not ok)
            if dead:
                return f"DEVICE-LOST (no liveness echo from {dead})"
            return "hang (all devices answer liveness probes)"
        except Exception as e:
            return f"unclassified (liveness probe failed: {type(e).__name__})"

    def stalled_for(self) -> float:
        return self.clock() - self._last

    def _device_view(self) -> str:
        """Best-effort per-device line for the stall diagnostic (the
        'which chip is wedged' starting point). Never raises — a
        watchdog diagnostic must not die gathering its evidence."""
        try:
            from pagerank_tpu.parallel import mesh as mesh_lib

            return "; ".join(mesh_lib.device_view())
        except Exception as e:
            return f"(device view unavailable: {type(e).__name__})"

    def check(self) -> bool:
        """One poll: declare a stall if the heartbeat is older than the
        timeout. Returns whether THIS call declared one (tests drive
        this directly in virtual time)."""
        stalled = self.stalled_for()
        if stalled <= self.timeout_s or self._fired:
            return False
        self._fired = True
        self.stalls += 1
        obs_metrics.counter(
            "watchdog.stalls",
            "stall episodes declared by the solve watchdog",
        ).inc()
        it = ("none completed" if self.last_iteration is None
              else f"last completed iteration {self.last_iteration}")
        classified = ""
        if self.action == "rescue":
            self.last_classification = self._classify()
            classified = f"; classification: {self.last_classification}"
        obs_log.warn(
            f"STALL WATCHDOG: no solve progress for {stalled:.1f}s "
            f"(timeout {self.timeout_s:g}s); {it}; devices: "
            f"{self._device_view()}{classified}"
        )
        if self.action == "rescue":
            self.rescue_requested = True
            self._interrupt()
        elif self.action == "raise":
            self._interrupt()
        return True

    # -- thread lifecycle --------------------------------------------------

    def start(self) -> "StallWatchdog":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pagerank-stall-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sleep(self.poll_s)
            if self._stop.is_set():
                break
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- process-global arming (the engine.run hook point) ----------------------

_WATCHDOG: Optional[StallWatchdog] = None


def get_watchdog() -> Optional[StallWatchdog]:
    """The armed watchdog, or None (the default — engine.run reads this
    once per run; the disarmed hot path costs one ``is None`` check per
    iteration)."""
    return _WATCHDOG


def arm_watchdog(wd: StallWatchdog) -> StallWatchdog:
    """Install ``wd`` as the process watchdog and start its thread."""
    global _WATCHDOG
    disarm_watchdog()
    _WATCHDOG = wd
    wd.start()
    return wd


def disarm_watchdog() -> Optional[StallWatchdog]:
    """Stop and remove the armed watchdog (returns it; idempotent)."""
    global _WATCHDOG
    prev = _WATCHDOG
    _WATCHDOG = None
    if prev is not None:
        prev.stop()
    return prev
