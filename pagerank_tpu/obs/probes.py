"""In-loop convergence probes (ISSUE 5).

The reference has NO convergence check — ``for (iter = 0; iter < 10;
iter++)`` runs blind (Sparky.java:187) — while the iterative-PageRank
literature (Kollias et al., arXiv:cs/0606047, PAPERS.md) shows
residual / rank-movement telemetry is THE signal that makes solver
behaviour debuggable. This module adds opt-in probes at a configurable
cadence (``--probe-every K``): at each probe point the solver records

  - the **L1 residual** ``|r' - r|_1`` (the step already computes it);
  - the **rank mass** ``sum(r)`` (the conservation/diagnostic scalar);
  - the **top-k churn** — how many of the top-``topk`` ranked vertices
    entered the set since the previous probe (rank-movement telemetry:
    PageRank consumers care about ordering stability long before the
    residual hits machine precision).

On the JAX engine all three are computed ON DEVICE, fused into the
step's own dispatch at probe iterations (``JaxTpuEngine.step_probed``),
so probing adds zero extra host syncs between probe points and no
collectives beyond the step's own budget — enforced mechanically by
contract **PTC007** (pagerank_tpu/analysis/contracts.py). ``--probe-every
0`` / unset takes the exact pre-probe code path: the solve loop makes
zero probe calls (tests/test_telemetry.py booby-traps this, mirroring
the no-op tracer contract).

Probe records land in the per-iteration history (run report
``iterations``), the metrics registry (``probe.*`` gauges — the live
exporter publishes them), and the trace (``probe/convergence`` instant
events). ``--stop-tol X`` optionally early-exits when the probed
residual reaches X; None keeps exact Sparky semantics (no check at
all).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace


class ConvergenceProbes:
    """Probe cadence + state + history. The engines compute the
    values (``PageRankEngine.step_probed`` / ``probe_values``); this
    object owns WHEN to probe, the previous top-k baseline the churn
    compares against, and where records go (history, registry gauges,
    trace instants). One instance per run."""

    def __init__(self, every: int, topk: int = 64,
                 stop_tol: Optional[float] = None):
        if every < 0:
            raise ValueError(f"probe every must be >= 0, got {every}")
        if topk < 1:
            raise ValueError(f"probe topk must be >= 1, got {topk}")
        if stop_tol is not None and not (0.0 < stop_tol < float("inf")):
            raise ValueError(
                f"stop_tol must be a finite positive float, got {stop_tol}"
            )
        self.every = int(every)
        self.topk = int(topk)
        self.stop_tol = stop_tol
        #: Engine-space top-k ids of the previous probe (opaque to this
        #: class: a device array for the JAX engine, numpy for the CPU
        #: oracle). None before the first probe.
        self.prev_ids = None
        #: ORIGINAL-id-space top-k of the latest probe (numpy) — what
        #: consumers/tests compare across engines.
        self.last_topk_ids = None
        self.history: List[Dict[str, float]] = []
        #: Rank-mass-ledger violations observed this run (ISSUE 13;
        #: obs/graph_profile.mass_ledger_entry): one record per probe
        #: whose decomposition failed to reconcile, carrying the named
        #: leaking term.
        self.ledger_violations: List[Dict[str, object]] = []

    @property
    def enabled(self) -> bool:
        return self.every > 0

    def wants(self, iteration: int) -> bool:
        """Whether the step taking ``iteration`` -> ``iteration + 1``
        is a probe point (absolute cadence, like snapshot_every — a
        resumed run probes the same iterations)."""
        return self.every > 0 and (iteration + 1) % self.every == 0

    def commit(self, iteration: int, info: Dict[str, float],
               ids_engine, ids_original) -> Dict[str, float]:
        """Record one probe: ``info`` already carries the probe scalars
        (``rank_mass``, ``topk_churn`` — stuffed by the engine's probed
        step next to ``l1_delta``). Updates the churn baseline, appends
        the history record, publishes ``probe.*`` gauges, and emits a
        ``probe/convergence`` trace instant."""
        self.prev_ids = ids_engine
        self.last_topk_ids = ids_original
        l1 = info.get("l1_delta")
        rec = {
            "iteration": iteration,
            "l1_residual": None if l1 is None else float(l1),
            "rank_mass": float(info["rank_mass"]),
            "topk_churn": int(info["topk_churn"]),
        }
        # Top-k rank concentration (ISSUE 13): what fraction of the
        # total mass the top-k hold — a convergence-quality signal
        # (ordering stabilizes long before the residual bottoms out).
        tm = info.get("topk_mass")
        if tm is not None and rec["rank_mass"]:
            rec["topk_concentration"] = float(tm) / rec["rank_mass"]
        ledger = info.get("mass_ledger")
        if ledger is not None:
            rec["mass_ledger"] = dict(ledger)
        self.history.append(rec)
        obs_metrics.counter(
            "probe.points", "convergence probes taken this run"
        ).inc()
        if rec["l1_residual"] is not None:
            obs_metrics.gauge(
                "probe.l1_residual",
                "L1 residual |r' - r| at the latest probe point",
            ).set(rec["l1_residual"])
        obs_metrics.gauge(
            "probe.rank_mass", "sum(ranks) at the latest probe point"
        ).set(rec["rank_mass"])
        obs_metrics.gauge(
            "probe.topk_churn",
            "top-k entries new since the previous probe point",
        ).set(rec["topk_churn"])
        if rec.get("topk_concentration") is not None:
            obs_metrics.gauge(
                "probe.topk_concentration",
                "fraction of rank mass held by the probe top-k",
            ).set(rec["topk_concentration"])
        if ledger is not None:
            from pagerank_tpu.obs import graph_profile

            graph_profile.record_ledger(ledger)
            if not ledger.get("ok", True):
                self.ledger_violations.append(
                    {"iteration": iteration, **ledger})
        tracer = obs_trace.get_tracer()
        if tracer.enabled:
            tracer.add_event("probe/convergence", **{
                k: v for k, v in rec.items() if k != "mass_ledger"})
        return rec

    def should_stop(self, rec: Dict[str, float]) -> bool:
        """``--stop-tol`` early exit: the probed residual reached the
        tolerance. None (the default) never stops — exact Sparky
        semantics."""
        return (
            self.stop_tol is not None
            and rec.get("l1_residual") is not None
            and rec["l1_residual"] <= self.stop_tol
        )

    def probe_boundary(self, engine, iteration: int,
                       l1_delta=None) -> Dict[str, float]:
        """Probe at a fused-chunk boundary (run_fused_chunked): no step
        to fuse into, so this dispatches the engine's standalone probe
        program over the current state. ``l1_delta`` is the boundary's
        last on-device trace value (the residual was already
        computed)."""
        mass, churn, ids_engine, ids_original, topk_mass = \
            engine.probe_values(self.topk, self.prev_ids)
        info = {
            "rank_mass": mass,
            "topk_churn": 0 if self.prev_ids is None else churn,
            "topk_mass": topk_mass,
        }
        if l1_delta is not None:
            info["l1_delta"] = float(l1_delta)
        # No mass ledger at a fused boundary: the decomposition's link
        # sum lives inside the step dispatch, which already retired.
        return self.commit(iteration, info, ids_engine, ids_original)
