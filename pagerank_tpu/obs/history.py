"""Perf-regression sentry: bench-history ledger + noise-aware gating.

The ROADMAP re-anchor's central finding — "f32 flat at ~3.5e8
edges/s/chip since r1" — was computed BY HAND across five incompatible
JSON files (the wrapped ``BENCH_r*.json``, the flat ``MULTICHIP_*``
schema, ``run_report.json``). Nothing in the repo could state, guard,
or attribute that trend mechanically. This module is the durable
landing place every perf number now has:

  - a canonical :func:`normalize_result` — ONE ``RunRecord`` shape
    (env fingerprint + git rev, per-leg edges/s/chip, s/iter, build
    seconds, accuracy L1, cost-model bytes/edge, comms bytes, resolved
    layout) recovered from ALL the historical schemas, legacy
    unversioned files included;
  - an append-only JSONL **ledger** (:func:`append_record` /
    :func:`read_ledger`) with content-hash dedupe and a
    ``schema_version``, strict JSON like every other obs emitter;
  - per-(leg, metric) **robust baselines** — median + MAD over a
    trailing window, direction-aware thresholds, minimum-sample
    handling (:func:`detect_changes`) — with every flagged change
    **classified** program-change vs env-drift vs noise by the same
    logic ``obs report`` applies pairwise (obs/report.diff_reports),
    generalized to a series: the cost model moved ⇒ the PROGRAM
    changed; the wall moved, the cost model is flat, and the env
    fingerprint drifted ⇒ the ENVIRONMENT moved;
  - a CI **gate** (:func:`evaluate_gate`) against a checked-in
    ``perf_budgets.json``: absolute floors/ceilings (env-scoped, so a
    TPU budget never fires on a CPU smoke record) plus the MAD
    regression flags — program-change regressions fail the gate,
    env-drift flags warn and pass.

Surfaces: ``python -m pagerank_tpu.obs history ingest|trend|gate``
(obs/__main__.py), ``bench.py --history PATH`` auto-append,
``obs report --against-history``, and the live exporter's
``history.*`` baseline-delta gauges (obs/live.py). The checked-in
``PERF_HISTORY.jsonl`` carries BENCH_r01–r05 + the MULTICHIP rounds,
so the r1→r5 plateau is mechanically reproducible
(docs/OBSERVABILITY.md "Perf history & gating").
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from pagerank_tpu.obs.report import _json_safe
from pagerank_tpu.utils import fsio

#: Version of the LEDGER record shape (not of the source artifacts —
#: those carry their own ``schema_version`` since ISSUE 9, and the
#: unversioned r01-r05 files still ingest).
LEDGER_SCHEMA_VERSION = 1

#: Canonical per-leg metrics a RunRecord carries (the ISSUE-9 axis
#: set). Every one is optional per leg — legacy artifacts recorded a
#: subset — but the KEY vocabulary is closed so series never fork on
#: spelling.
LEG_METRICS = (
    "edges_per_sec_per_chip",
    "seconds_per_iter",
    "build_s",
    "build_warm_s",
    "accuracy_l1",
    "cost_bytes_per_edge",
    "comms_bytes_per_iter",
    # ISSUE 10: the multichip legs' per-chip-rate-retained figure
    # (recorded since r06 but invisible in the trend until now), and
    # the comms-vs-compute attribution axes — the r06+ trend carries
    # whether the sharded step is exchange-bound.
    "scaling_efficiency",
    "exchange_fraction",
    "comms_achieved_bytes_per_sec",
    # ISSUE 11: the compiler plane's HLO-derived traffic estimate —
    # reconciles the analytic cost model against what the optimized
    # HLO actually schedules (legs also carry the non-numeric
    # ``lowering_fingerprint`` / ``gather_strategy`` the trend and
    # classifier read).
    "hlo_bytes_per_edge",
    # ISSUE 13: the data plane's profile scalars — a DATA change (new
    # crawl segment, different synthetic seed/skew) gates distinctly
    # from a program or env change: classify_change attributes a flag
    # whose cost model is flat but whose profile scalars moved as
    # **data-change** (warns, never fails). Pre-ISSUE-13 ledger rows
    # simply lack the keys (no re-ingest, no fork).
    "graph_dangling_fraction",
    "graph_partition_skew",
    "graph_topk_concentration",
    # ISSUE 15: per-checked-iteration SDC detection overhead (percent
    # extra wall vs the plain step) — present only on legs measured
    # with ``bench.py --sdc-check-every`` armed; None-tolerant like
    # every leg metric (disarmed legs simply lack the key).
    "sdc_check_overhead_pct",
    # ISSUE 17: iterations-to-tol of the stale-boundary async solve —
    # what the one-iteration boundary lag COSTS in convergence, priced
    # in iterations (textbook semantics, bench --multichip staleness
    # sweep). Present only on the sparse_async_f32 leg.
    "iters_to_tol",
    # ISSUE 18: the ppr_serve leg (bench.py --ppr-serve) — sustained
    # serving throughput and tail latency of the deadline-honest query
    # daemon, plus the shed fraction (admission honesty: what fraction
    # of offered load the predictive shed refused).
    "queries_per_sec",
    "p50_ms",
    "p99_ms",
    "shed_fraction",
    # ISSUE 19: the query plane's p99 phase decomposition — WHERE the
    # serving tail lives (admission decision / queue wait / device
    # dispatch / top-k fetch), carried on the same ppr_serve leg the
    # chip-time session already gates, so a p99 miss names its phase.
    "admission_wait_p99_ms",
    "batch_wait_p99_ms",
    "dispatch_p99_ms",
    "fetch_p99_ms",
)

#: Profile scalars whose motion marks the DATA axis (classify_change
#: rule 1c) — and the relative motion treated as "the data changed"
#: (the profile is exact arithmetic over the graph, so anything beyond
#: float formatting noise is a real data delta).
GRAPH_DATA_KEYS = ("graph_dangling_fraction", "graph_partition_skew")
DATA_MOVED_REL = 0.01

#: Which direction is BAD, per metric (direction-aware thresholds:
#: a throughput DROP is a regression, a build-time RISE is).
METRIC_BAD_DIRECTION = {
    "edges_per_sec_per_chip": "down",
    "seconds_per_iter": "up",
    "build_s": "up",
    "build_warm_s": "up",
    "accuracy_l1": "up",
    "cost_bytes_per_edge": "up",
    "comms_bytes_per_iter": "up",
    "scaling_efficiency": "down",
    "exchange_fraction": "up",
    "comms_achieved_bytes_per_sec": "down",
    "hlo_bytes_per_edge": "up",
    # Data-plane directions are nominal (a moved profile is DRIFT to
    # attribute, not a regression to gate): more dangling mass, more
    # partition skew, and more top-k concentration all make the solve
    # harder, so "up" renders as the worse direction.
    "graph_dangling_fraction": "up",
    "graph_partition_skew": "up",
    "graph_topk_concentration": "up",
    "sdc_check_overhead_pct": "up",
    # More iterations to the same tolerance = the staleness cost grew.
    "iters_to_tol": "up",
    # Serving (ISSUE 18): throughput down = regression; latency tails
    # and shed fraction up = regression (shedding MORE at the same
    # offered load means the modeled batch wall grew).
    "queries_per_sec": "down",
    "p50_ms": "up",
    "p99_ms": "up",
    "shed_fraction": "up",
    # Query plane (ISSUE 19): any phase's tail growing is a regression
    # in that leg of the serving pipeline.
    "admission_wait_p99_ms": "up",
    "batch_wait_p99_ms": "up",
    "dispatch_p99_ms": "up",
    "fetch_p99_ms": "up",
}

#: Env-fingerprint keys that define the SERIES a record belongs to:
#: numbers measured on different backends/device kinds are never
#: baselined against each other (a CPU smoke is not a regression of a
#: TPU cell — the r5 hand-separation, now structural).
ENV_CLASS_KEYS = ("backend", "device_kind")

#: Env keys whose WITHIN-class drift marks the environment axis
#: (jax/jaxlib upgrades, x64 flips, host moves). git_rev is excluded:
#: a code-rev change is the PROGRAM axis, exactly as in
#: obs/report.diff_reports.
ENV_DRIFT_KEYS = ("jax_version", "jaxlib_version", "x64", "device_count",
                  "process_count", "python", "platform")

#: Relative cost-model motion treated as "the program changed" — the
#: model is analytic (XLA's own accounting of the compiled program),
#: so anything beyond float formatting noise is a real program delta.
COST_MOVED_REL = 0.01

#: Detection defaults (perf_budgets.json "detection" overrides).
DEFAULT_DETECTION = {
    "window": 8,          # trailing baseline samples per (leg, metric)
    "threshold_mads": 4.0,  # flag beyond k scaled MADs...
    "rel_floor": 0.05,      # ...but never inside this relative band
    "min_samples": 3,       # refuse to flag on thinner history
}


# -- normalization: every historical schema -> one RunRecord ---------------


def _num(v) -> Optional[float]:
    """Finite float or None (strict-JSON discipline: the ledger never
    stores NaN/Inf — obs/report._json_safe does the same for reports)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    return f if f == f and f not in (float("inf"), float("-inf")) else None


def _round_of(source: str) -> Optional[int]:
    m = re.search(r"_r(\d+)", source or "")
    return int(m.group(1)) if m else None


def _rate_leg(d: dict) -> dict:
    """One bench/multichip rate-leg dict -> canonical leg metrics.
    Tolerates every vintage: r01-r05 legs carry value (+build_s);
    modern legs add costs/layout/comms."""
    leg: Dict[str, object] = {}
    for src_key, dst_key in (("value", "edges_per_sec_per_chip"),
                             ("build_s", "build_s")):
        v = _num(d.get(src_key))
        if v is not None:
            leg[dst_key] = v
    ms = _num(d.get("ms_per_iter"))
    if ms is not None:
        leg["seconds_per_iter"] = ms / 1e3
    step = (d.get("costs") or {}).get("step") or {}
    if "seconds_per_iter" not in leg:
        spi = _num(step.get("seconds_per_iter"))
        if spi is not None:
            leg["seconds_per_iter"] = spi
    bpe = _num(step.get("bytes_per_edge"))
    if bpe is not None:
        leg["cost_bytes_per_edge"] = bpe
    comms = d.get("comms") or {}
    cb = _num(comms.get("bytes_per_iter"))
    if cb is not None:
        leg["comms_bytes_per_iter"] = cb
    # Comms-vs-compute attribution block (ISSUE 10; bench --multichip
    # legs since r10): the exchange-bound verdict joins the series.
    att = d.get("attribution") or {}
    ef = _num(att.get("exchange_fraction"))
    if ef is not None:
        leg["exchange_fraction"] = ef
    ab = _num(att.get("achieved_bytes_per_sec"))
    if ab is not None:
        leg["comms_achieved_bytes_per_sec"] = ab
    if isinstance(d.get("layout"), dict):
        leg["layout"] = _json_safe(d["layout"])
    # Compiler-plane block (ISSUE 11; bench legs since r11): the
    # whole-iteration form's lowering fingerprint + gather verdict
    # joins the series, so a jax/libtpu upgrade that changes the
    # LOWERING is attributable as program-change, not noise. Pre-
    # ISSUE-11 artifacts simply lack the key (back-compat: no
    # re-ingest, the series starts when the instrument did).
    _leg_lowering(d.get("lowering"), leg)
    # Data-plane block (ISSUE 13; bench legs since r13): the graph
    # profile's headline scalars join the series so classify_change
    # can attribute a move to the DATA axis. Pre-ISSUE-13 artifacts
    # lack the key (back-compat, same discipline as lowering).
    _leg_graph(d.get("graph"), leg)
    # SDC-plane overhead (ISSUE 15; bench legs since r15): present
    # only when the leg was measured with the checked step armed —
    # disarmed legs lack the key (None-tolerant by schema contract,
    # tests/test_bench_contract.py).
    so = _num(d.get("sdc_check_overhead_pct"))
    if so is not None:
        leg["sdc_check_overhead_pct"] = so
    # Staleness convergence cost (ISSUE 17; the sparse_async multichip
    # leg since r17) — absent on every synchronous leg.
    itt = _num(d.get("iters_to_tol"))
    if itt is not None:
        leg["iters_to_tol"] = itt
    nd = d.get("n_devices")
    if isinstance(nd, int):
        leg["n_devices"] = nd
    return leg


def _leg_graph(graph_block, leg: dict) -> None:
    """Fold one ``graph`` data-plane block (obs/graph_profile
    report_section shape: {"profile": summary, "prediction": ...})
    into canonical leg metrics."""
    if not isinstance(graph_block, dict):
        return
    prof = graph_block.get("profile")
    if not isinstance(prof, dict):
        return
    for src_key, dst_key in (
        ("dangling_fraction", "graph_dangling_fraction"),
        ("partition_skew", "graph_partition_skew"),
    ):
        v = _num(prof.get(src_key))
        if v is not None:
            leg[dst_key] = v


def _leg_lowering(lowering, leg: dict) -> None:
    """Fold one per-form ``lowering`` block (obs/hlo.ledger_snapshot
    shape) into canonical leg metrics: the WHOLE-ITERATION form's
    fingerprint, gather strategy, and HLO bytes/edge."""
    if not isinstance(lowering, dict):
        return
    whole = lowering.get("step") or lowering.get("final") or {}
    if not isinstance(whole, dict):
        return
    fp = whole.get("fingerprint")
    if isinstance(fp, str) and fp:
        leg["lowering_fingerprint"] = fp
    strategy = (whole.get("gather") or {}).get("strategy")
    if isinstance(strategy, str):
        leg["gather_strategy"] = strategy
    hb = _num(whole.get("hlo_bytes_per_edge"))
    if hb is not None:
        leg["hlo_bytes_per_edge"] = hb


def _leg_name_from_layout(layout: Optional[dict], default: str = "f32") -> str:
    """Single-config bench leg name from the RESOLVED layout record.
    Legacy single-mode files (r01) have no layout: they predate the
    couple schema and measured the plain-f32 config (ROADMAP r1 cell),
    so the documented default is ``f32``."""
    if not isinstance(layout, dict):
        return default
    if layout.get("form") == "pallas_partitioned":
        return "pallas_partitioned_f32"
    if layout.get("stream_dtype") == "bfloat16":
        return "fast_bf16"
    if (layout.get("partition_span") or 0) > 0:
        return "partitioned_f32"
    if layout.get("pair"):
        return "pair_f64"
    accum = layout.get("accum_dtype")
    if accum == "float64":
        return "f64"
    return "fast_f32"


def leg_name_for_config(cfg) -> str:
    """The ledger leg a CLI/run-report solve belongs to, derived from
    the resolved config (dataclass or its _json_safe dict) — the same
    vocabulary bench.py's couple legs use, so a live run's % -vs-
    baseline compares against the right series."""
    def get(key, default=None):
        if isinstance(cfg, dict):
            return cfg.get(key, default)
        return getattr(cfg, key, default)

    if get("vertex_sharded"):
        if get("halo_async"):
            # The stale-boundary async exchange (ISSUE 17): its own
            # series — one-iteration-lagged boundary reads change both
            # the rate AND the convergence cost, so its numbers never
            # baseline against the synchronous sparse series.
            return "sparse_async_f32"
        return ("multichip_sparse" if get("halo_exchange")
                else "multichip_dense")
    if get("kernel") == "pallas" and get("partition_span"):
        # The fused Mosaic kernel leg (ISSUE 16): its own series —
        # comparing it against partitioned_f32's XLA pipeline is the
        # whole point of the ledger entry.
        return "pallas_partitioned_f32"
    if get("stream_dtype") == "bfloat16" and get("partition_span"):
        return "fast_bf16"
    if get("partition_span"):
        return "partitioned_f32"
    if get("dtype") == "float64":
        # "auto" resolves to pair on TPU backends — the backend every
        # f64 series in the ledger was measured on — and the CLI can't
        # set wide_accum at all, so auto joins the headline pair_f64
        # series; explicit native wide f64 is its own (rare) series,
        # matching _leg_name_from_layout's "f64".
        return ("pair_f64" if get("wide_accum") in ("pair", "auto", None)
                else "f64")
    if get("dtype") == "float32":
        return "fast_f32"
    return str(get("dtype") or "f32")


def _normalize_bench_couple(doc: dict, rec: dict) -> None:
    rec["kind"] = "bench_couple"
    legs = rec["legs"]
    legs["pair_f64"] = _rate_leg(doc)
    warm = _num(doc.get("build_warm_s"))
    if warm is not None:
        legs["pair_f64"]["build_warm_s"] = warm
    for key, name in (("fast_f32", "fast_f32"),
                      ("partitioned_f32", "partitioned_f32"),
                      ("pallas_partitioned", "pallas_partitioned_f32"),
                      ("fast_bf16", "fast_bf16")):
        if isinstance(doc.get(key), dict):
            legs[name] = _rate_leg(doc[key])
    acc = doc.get("accuracy") or {}
    l1 = _num(acc.get("normalized_l1_vs_f64_oracle"))
    if l1 is not None:
        legs["pair_f64"]["accuracy_l1"] = l1
    bf = acc.get("fast_bf16") or {}
    l1b = _num(bf.get("normalized_l1_vs_f64_oracle"))
    if l1b is not None and "fast_bf16" in legs:
        legs["fast_bf16"]["accuracy_l1"] = l1b


def _normalize_bench_single(doc: dict, rec: dict) -> None:
    rec["kind"] = "bench_single"
    name = _leg_name_from_layout(doc.get("layout"))
    rec["legs"][name] = _rate_leg(doc)
    acc = doc.get("accuracy") or {}
    l1 = _num(acc.get("normalized_l1_vs_f64_oracle"))
    if l1 is not None:
        # Single mode's standing accuracy probe certifies the pair-f64
        # config, not the measured leg (bench.run_accuracy).
        rec["legs"].setdefault("pair_f64", {})["accuracy_l1"] = l1


def _normalize_multichip(doc: dict, rec: dict) -> None:
    rec["kind"] = "multichip"
    legs = rec["legs"]
    # The pallas leg joins the SAME pallas_partitioned_f32 series the
    # couple mode feeds (its replicated-rank form measures per-chip
    # rate like every other series entry); the exchange legs keep
    # their multichip_* names.
    for key, name in (("single_chip", "multichip_single"),
                      ("dense_exchange", "multichip_dense"),
                      ("sparse_exchange", "multichip_sparse"),
                      ("sparse_async", "sparse_async_f32"),
                      ("pallas_partitioned", "pallas_partitioned_f32")):
        if isinstance(doc.get(key), dict):
            legs[name] = _rate_leg(doc[key])
    acc = doc.get("accuracy") or {}
    l1 = _num(acc.get("normalized_l1_vs_f64_oracle"))
    if l1 is not None and "multichip_sparse" in legs:
        legs["multichip_sparse"]["accuracy_l1"] = l1
    # scaling_efficiency joins the LEG metrics (ISSUE 10 satellite:
    # the field existed since r06 but was invisible in the trend) AND
    # stays in extras — already-ingested ledger records carry only the
    # extras spelling, and metric_value() reads both.
    for k, leg in (("scaling_efficiency", "multichip_sparse"),
                   ("scaling_efficiency_dense", "multichip_dense")):
        v = _num(doc.get(k))
        if v is not None:
            rec["extras"][k] = v
            if leg in legs:
                legs[leg]["scaling_efficiency"] = v


def _normalize_build_only(doc: dict, rec: dict) -> None:
    rec["kind"] = "bench_build"
    for key, name in (("pair", "build_pair"), ("f32", "build_f32"),
                      ("pair_warm", "build_pair_warm")):
        b = _num((doc.get(key) or {}).get("build_s"))
        if b is not None:
            rec["legs"][name] = {"build_s": b}


def _normalize_run_report(doc: dict, rec: dict) -> None:
    rec["kind"] = "run_report"
    rec["env"] = _json_safe(doc.get("environment") or {})
    created = _num(doc.get("created_unix"))
    if created is not None:
        rec["created_unix"] = created
    cfg = doc.get("config") or {}
    leg: Dict[str, object] = {}
    summ = doc.get("summary") or {}
    eps = _num(summ.get("edges_per_sec_per_chip"))
    if eps is not None:
        leg["edges_per_sec_per_chip"] = eps
    spi = _num(summ.get("mean_iter_seconds"))
    if spi is not None:
        leg["seconds_per_iter"] = spi
    step = (doc.get("costs") or {}).get("step") or {}
    bpe = _num(step.get("bytes_per_edge"))
    if bpe is not None:
        leg["cost_bytes_per_edge"] = bpe
    gauges = (doc.get("metrics") or {}).get("gauges") or {}
    cb = _num(gauges.get("comms.bytes_per_iter"))
    if cb is not None:
        leg["comms_bytes_per_iter"] = cb
    for gauge_key, metric in (
        ("comms.exchange_fraction", "exchange_fraction"),
        ("comms.achieved_bytes_per_sec", "comms_achieved_bytes_per_sec"),
    ):
        v = _num(gauges.get(gauge_key))
        if v is not None:
            leg[metric] = v
    _leg_lowering(doc.get("lowering"), leg)
    _leg_graph(doc.get("graph"), leg)
    # Top-k rank concentration (ISSUE 13): the last probe record's
    # convergence-quality signal joins the leg when the run probed.
    conc = [_num((p or {}).get("topk_concentration"))
            for p in (doc.get("probes") or [])]
    conc = [c for c in conc if c is not None]
    if conc:
        leg["graph_topk_concentration"] = conc[-1]
    if leg:
        rec["legs"][leg_name_for_config(cfg)] = leg
    iters = cfg.get("num_iters") if isinstance(cfg, dict) else None
    if isinstance(iters, int):
        rec["workload"]["iters"] = iters


def _normalize_ppr_serve(doc: dict, rec: dict) -> None:
    rec["kind"] = "bench_ppr_serve"
    leg: Dict[str, object] = {}
    qps = _num(doc.get("value"))
    if qps is not None:
        leg["queries_per_sec"] = qps
    for key in ("p50_ms", "p99_ms", "shed_fraction"):
        v = _num(doc.get(key))
        if v is not None:
            leg[key] = v
    # Query plane (ISSUE 19): the per-phase p99 decomposition, folded
    # into the same leg so the trend/gate read WHERE the tail lives.
    phase = doc.get("phase_p99_ms")
    if isinstance(phase, dict):
        for short in ("admission_wait", "batch_wait", "dispatch",
                      "fetch"):
            v = _num(phase.get(short))
            if v is not None:
                leg[short + "_p99_ms"] = v
    if leg:
        rec["legs"]["ppr_serve"] = leg
    for key in ("queries", "rescues", "max_batch", "deadline_ms", "topk"):
        if doc.get(key) is not None:
            rec["extras"][key] = doc[key]


def normalize_result(doc: dict, source: str = "") -> dict:
    """Any historical result artifact -> one canonical RunRecord dict.

    Accepted shapes (detected, never declared):
      - the legacy driver wrapper ``{n, cmd, rc, tail, parsed}``
        (BENCH_r01-r05) — ``parsed`` is unwrapped and normalized;
      - flat bench couple/single JSON (``metric ==
        edges_per_sec_per_chip``), versioned or not;
      - ``--build-only`` JSON (``metric == build_s``);
      - ``--ppr-serve`` JSON (``metric == ppr_serve_queries_per_sec``,
        ISSUE 18);
      - flat MULTICHIP JSON (``metric ==
        multichip_edges_per_sec_per_chip``) and the r01-r05 dryrun
        shape ``{n_devices, rc, ok, skipped, tail}``;
      - ``run_report.json`` (the flight recorder).

    Raises ValueError on a shape none of the readers claim.
    """
    if not isinstance(doc, dict):
        raise ValueError(f"perf history: not a JSON object: {type(doc)}")
    rec: Dict[str, object] = {
        "schema_version": LEDGER_SCHEMA_VERSION,
        "kind": None,
        "source": source or "",
        "round": _round_of(source),
        "env": {},
        "workload": {},
        "legs": {},
        "extras": {},
        "legacy": False,
    }
    inner = doc
    if "cmd" in doc and "tail" in doc:  # the legacy driver wrapper
        rec["legacy"] = True
        rec["extras"]["wrapper_rc"] = doc.get("rc")
        inner = doc.get("parsed")
        if not isinstance(inner, dict):
            rec["kind"] = "bench_failed"
            return _finish(rec)
    metric = inner.get("metric")
    if metric == "edges_per_sec_per_chip":
        if "fast_f32" in inner:
            _normalize_bench_couple(inner, rec)
        else:
            _normalize_bench_single(inner, rec)
    elif metric == "multichip_edges_per_sec_per_chip":
        _normalize_multichip(inner, rec)
    elif metric == "build_s":
        _normalize_build_only(inner, rec)
    elif metric == "ppr_serve_queries_per_sec":
        _normalize_ppr_serve(inner, rec)
    elif "environment" in inner and "spans" in inner:
        _normalize_run_report(inner, rec)
    elif set(inner) >= {"n_devices", "rc", "ok"}:  # multichip dryrun
        rec["kind"] = "multichip_dryrun"
        rec["extras"].update(
            ok=bool(inner.get("ok")), rc=inner.get("rc"),
            n_devices=inner.get("n_devices"),
        )
    else:
        raise ValueError(
            f"perf history: unrecognized result shape (keys "
            f"{sorted(inner)[:8]}) in {source or '<inline>'}"
        )
    if rec["kind"] != "run_report":
        if isinstance(inner.get("env"), dict):
            rec["env"] = _json_safe(inner["env"])
        for k in ("scale", "iters", "edge_factor"):
            if isinstance(inner.get(k), int):
                rec["workload"][k] = inner[k]
        v = inner.get("schema_version")
        if isinstance(v, int):
            rec["extras"]["source_schema_version"] = v
    return _finish(rec)


def content_hash(rec: dict) -> str:
    """Dedupe key: sha256 over the canonical record content. Ingest
    metadata (``ingested_unix``) is excluded so re-ingesting the same
    artifact is a no-op; ``source`` is INCLUDED so two rounds that
    happened to measure identical values both stay in the series."""
    body = {k: v for k, v in rec.items()
            if k not in ("content_hash", "ingested_unix")}
    return hashlib.sha256(
        json.dumps(_json_safe(body), sort_keys=True,
                   allow_nan=False).encode()
    ).hexdigest()[:16]


def _finish(rec: dict) -> dict:
    rec = _json_safe(rec)
    rec["content_hash"] = content_hash(rec)
    return rec


# -- the ledger -------------------------------------------------------------


def read_ledger(path: str) -> List[dict]:
    """All records, oldest first. A MISSING ledger is an empty one
    (the first ingest creates it); any other read failure — permission,
    a directory, a bad mount — RAISES, and a malformed line raises
    too: a CI gate silently passing on an unreadable ledger is exactly
    the failure mode this module exists to prevent."""
    try:
        with fsio.fopen(path) as f:
            text = f.read()
    except FileNotFoundError:
        return []
    records = []
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{ln}: malformed ledger line: {e}")
    return records


def append_record(path: str, rec: dict,
                  existing: Optional[List[dict]] = None,
                  now: Optional[float] = None) -> bool:
    """Append one RunRecord; returns False when its content hash is
    already present (dedupe). Strict JSON (``allow_nan=False``), one
    record per line, append-only — history is never rewritten."""
    import time

    if existing is None:
        existing = read_ledger(path)
    h = rec.get("content_hash") or content_hash(rec)
    if any(r.get("content_hash") == h for r in existing):
        return False
    out = dict(rec)
    out["content_hash"] = h
    out["ingested_unix"] = float(now if now is not None else time.time())
    line = json.dumps(_json_safe(out), sort_keys=True, allow_nan=False)
    with fsio.fopen(path, "a") as f:
        f.write(line + "\n")
    existing.append(out)
    return True


def ingest_paths(ledger: str, paths: Sequence[str]) -> Tuple[int, int]:
    """Normalize + append each artifact; returns (added, deduped)."""
    existing = read_ledger(ledger)
    added = deduped = 0
    for p in paths:
        with fsio.fopen(p) as f:
            doc = json.load(f)
        rec = normalize_result(doc, source=os.path.basename(p))
        if append_record(ledger, rec, existing=existing):
            added += 1
        else:
            deduped += 1
    return added, deduped


# -- robust baselines + change detection ------------------------------------


def env_class(rec: dict) -> Optional[Tuple]:
    """The comparability class of a record: (backend, device_kind), or
    None when the fingerprint was never recorded (legacy rounds).
    Baselines never mix classes — and legacy records, whose class is
    unknowable, only baseline each other."""
    env = rec.get("env") or {}
    vals = tuple(env.get(k) for k in ENV_CLASS_KEYS)
    return None if all(v is None for v in vals) else vals


def metric_value(rec: dict, leg: str, metric: str) -> Optional[float]:
    v = _num((rec.get("legs") or {}).get(leg, {}).get(metric))
    if v is None and metric == "scaling_efficiency":
        # Back-compat: records ingested before ISSUE 10 carry the
        # multichip scaling figure only under extras (the r06 ledger
        # rows) — the series must not fork on ingest vintage.
        extras = rec.get("extras") or {}
        if leg == "multichip_sparse":
            v = _num(extras.get("scaling_efficiency"))
        elif leg == "multichip_dense":
            v = _num(extras.get("scaling_efficiency_dense"))
    return v


def series(records: Sequence[dict], leg: str, metric: str,
           klass=...) -> List[Tuple[int, float]]:
    """(record index, value) pairs for one (leg, metric), optionally
    restricted to one env class (pass ``klass``; default: all)."""
    out = []
    for i, r in enumerate(records):
        if klass is not ... and env_class(r) != klass:
            continue
        v = metric_value(r, leg, metric)
        if v is not None:
            out.append((i, v))
    return out


def median_mad(values: Sequence[float]) -> Tuple[float, float]:
    """Median and RAW median-absolute-deviation (callers scale by
    1.4826 for the normal-consistent sigma). Robust to the exact
    outliers we hunt — one bad round cannot drag its own baseline."""
    vs = sorted(values)
    n = len(vs)
    med = (vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2]))
    dev = sorted(abs(v - med) for v in vs)
    mad = (dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
    return med, mad


@dataclass
class Change:
    """One flagged (or clean) per-(leg, metric) verdict on the newest
    record vs its trailing baseline."""

    leg: str
    metric: str
    value: float
    baseline_median: float
    baseline_mad: float
    n_baseline: int
    rel_delta: float                    # (value - median) / median
    flagged: bool
    direction: str = "flat"             # regression | improvement | flat
    # program-change | env-drift | data-change | noise
    classification: str = "noise"
    evidence: str = ""

    def to_dict(self) -> dict:
        return _json_safe(dataclasses.asdict(self))


def _mode(values):
    """Most common non-None value (newest wins ties) — the baseline
    window's consensus env field."""
    known = [v for v in values if v is not None]
    if not known:
        return None
    counts: Dict[object, int] = {}
    for v in known:
        counts[json.dumps(_json_safe(v), sort_keys=True)] = (
            counts.get(json.dumps(_json_safe(v), sort_keys=True), 0) + 1
        )
    best = max(counts.values())
    for v in reversed(known):
        if counts[json.dumps(_json_safe(v), sort_keys=True)] == best:
            return v
    return known[-1]


def classify_change(target: dict, baseline: Sequence[dict],
                    leg: str) -> Tuple[str, str]:
    """(classification, evidence) for a flagged wall/metric move —
    the obs-report pairwise logic generalized to a series:

      1. the leg's cost model (bytes/edge) moved vs its baseline
         median ⇒ **program-change** (the compiled program itself
         costs differently — XLA's model is deterministic);
      1b. the leg's LOWERING FINGERPRINT (obs/hlo.py; ISSUE 11) moved
         vs the baseline consensus ⇒ **program-change** — the compiler
         emitted a structurally different program (a jax/libtpu
         upgrade that changes the lowering is a program change even
         when the analytic cost model is flat, e.g. a defeated
         gather);
      1c. cost flat but the leg's GRAPH-PROFILE scalars (ISSUE 13;
         obs/graph_profile) moved vs their baseline medians ⇒
         **data-change** — the INPUT changed shape (new crawl
         segment, different skew), which explains a perf move without
         indicting the program or the backend; the gate warns, never
         fails;
      2. cost flat (or unmeasurable) and the env fingerprint drifted
         within the class ⇒ **env-drift**;
      3. cost flat and the baseline never recorded a fingerprint ⇒
         conservatively **env-drift** (unattributable — the legacy
         rounds predate the fingerprint; a gate must not fail on
         evidence nobody recorded);
      4. cost flat and env provably identical ⇒ **program-change**
         (same backend, same flags: what remains is the code axis —
         obs/report prints the matching "deltas below are code or
         load" banner).
    """
    cost_now = metric_value(target, leg, "cost_bytes_per_edge")
    cost_base = [metric_value(r, leg, "cost_bytes_per_edge")
                 for r in baseline]
    cost_base = [c for c in cost_base if c is not None]
    if cost_now is not None and cost_base:
        med, _ = median_mad(cost_base)
        if med > 0 and abs(cost_now - med) / med > COST_MOVED_REL:
            return ("program-change",
                    f"cost model moved: {med:.1f} -> {cost_now:.1f} "
                    f"B/edge ({(cost_now - med) / med:+.1%})")
    fp_now = (target.get("legs") or {}).get(leg, {}).get(
        "lowering_fingerprint")
    fp_base = _mode([
        (r.get("legs") or {}).get(leg, {}).get("lowering_fingerprint")
        for r in baseline
    ])
    if fp_now and fp_base and fp_now != fp_base:
        strat = (target.get("legs") or {}).get(leg, {}).get(
            "gather_strategy")
        return ("program-change",
                f"lowering fingerprint moved: {fp_base} -> {fp_now} — "
                f"the compiler emitted a different program shape"
                + (f" (gather now {strat})" if strat else ""))
    # Rule 1c (ISSUE 13): cost model flat but the DATA moved — the
    # graph profile scalars are exact arithmetic over the input, so a
    # move beyond formatting noise means the graph itself changed.
    for data_metric in GRAPH_DATA_KEYS:
        d_now = metric_value(target, leg, data_metric)
        d_base = [metric_value(r, leg, data_metric) for r in baseline]
        d_base = [v for v in d_base if v is not None]
        if d_now is None or not d_base:
            continue
        med, _ = median_mad(d_base)
        moved = (abs(d_now - med) / abs(med) > DATA_MOVED_REL
                 if med else d_now != 0)
        if moved:
            return ("data-change",
                    f"cost model flat; graph profile moved "
                    f"({data_metric}: {med:.4g} -> {d_now:.4g}) — the "
                    f"input data changed shape")
    t_env = target.get("env") or {}
    drifted = []
    baseline_known = False
    for k in ENV_DRIFT_KEYS:
        base_v = _mode([(r.get("env") or {}).get(k) for r in baseline])
        now_v = t_env.get(k)
        if base_v is None and now_v is None:
            continue
        baseline_known = baseline_known or base_v is not None
        if base_v is not None and now_v is not None and base_v != now_v:
            drifted.append(f"{k}: {base_v!r} -> {now_v!r}")
    if drifted:
        return ("env-drift",
                "cost model flat; environment drifted (" +
                "; ".join(drifted) + ")")
    if not baseline_known:
        return ("env-drift",
                "unattributable: baseline records carry no environment "
                "fingerprint (legacy rounds) — treated as drift, not "
                "gated")
    git_a = _mode([r.get("env", {}).get("git_rev") for r in baseline])
    git_b = t_env.get("git_rev")
    return ("program-change",
            "cost model flat/unreported and environment identical — "
            f"attributed to the program (git {git_a} -> {git_b})")


def detect_changes(records: Sequence[dict],
                   detection: Optional[dict] = None) -> List[Change]:
    """Evaluate the NEWEST record's legs against trailing per-(leg,
    metric) baselines drawn from the same env class. Returns one
    :class:`Change` per evaluable series (flagged or clean); series
    with fewer than ``min_samples`` baseline points are skipped — a
    two-point history cannot define noise."""
    det = dict(DEFAULT_DETECTION)
    det.update(detection or {})
    if not records:
        return []
    target = records[-1]
    prior = records[:-1]
    klass = env_class(target)
    out: List[Change] = []
    for leg, metrics in sorted((target.get("legs") or {}).items()):
        for metric in LEG_METRICS:
            value = _num(metrics.get(metric))
            if value is None:
                continue
            pts = series(prior, leg, metric, klass=klass)
            pts = pts[-det["window"]:]
            if len(pts) < det["min_samples"]:
                continue
            base_recs = [prior[i] for i, _ in pts]
            med, mad = median_mad([v for _, v in pts])
            if med == 0:
                continue
            threshold = max(det["threshold_mads"] * 1.4826 * mad,
                            det["rel_floor"] * abs(med))
            delta = value - med
            rel = delta / med
            ch = Change(leg=leg, metric=metric, value=value,
                        baseline_median=med, baseline_mad=mad,
                        n_baseline=len(pts), rel_delta=rel,
                        flagged=abs(delta) > threshold)
            if ch.flagged:
                bad = METRIC_BAD_DIRECTION.get(metric, "up")
                worse = (delta < 0) if bad == "down" else (delta > 0)
                ch.direction = "regression" if worse else "improvement"
                ch.classification, ch.evidence = classify_change(
                    target, base_recs, leg)
            out.append(ch)
    return out


# -- budgets + the CI gate --------------------------------------------------


def load_budgets(path: str) -> dict:
    with fsio.fopen(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "budgets" not in doc:
        raise ValueError(f"{path}: not a perf_budgets file "
                         "(expected a 'budgets' list)")
    return doc


def _budget_applies(budget: dict, rec: dict) -> bool:
    """Env-scoped budgets fire only on records that PROVABLY match:
    an unrecorded fingerprint field never satisfies a constraint (a
    TPU floor must not fail — or pass — a legacy/CPU record), and a
    ``min_scale`` budget skips records of smaller (or unrecorded)
    workloads — throughput floors are statements about the headline
    geometry, not a scale-14 smoke."""
    env = rec.get("env") or {}
    for k, want in (budget.get("env") or {}).items():
        if env.get(k) != want:
            return False
    ms = budget.get("min_scale")
    if ms is not None:
        sc = (rec.get("workload") or {}).get("scale")
        if sc is None or sc < ms:
            return False
    return True


def check_budgets(rec: dict, budgets: dict) -> List[str]:
    """Absolute floor/ceiling violations of the newest record."""
    violations = []
    for b in budgets.get("budgets", []):
        leg, metric = b.get("leg"), b.get("metric")
        v = metric_value(rec, leg, metric)
        if v is None or not _budget_applies(b, rec):
            continue
        lo, hi = _num(b.get("min")), _num(b.get("max"))
        if lo is not None and v < lo:
            violations.append(
                f"{leg}.{metric} = {v:.4g} below budget min {lo:.4g}"
                + (f" ({b['note']})" if b.get("note") else ""))
        if hi is not None and v > hi:
            violations.append(
                f"{leg}.{metric} = {v:.4g} above budget max {hi:.4g}"
                + (f" ({b['note']})" if b.get("note") else ""))
    return violations


def _round_sig(v: float, digits: int = 3) -> float:
    """3-significant-figure rounding for proposed bounds — a floor of
    5.4e8 is a statement a human can defend; 543217890.3 is noise."""
    return float(f"{float(v):.{digits}g}")


def propose_budgets(records: Sequence[dict], budgets: dict,
                    safety: float = 0.9) -> dict:
    """The ROADMAP's "refresh floors from real numbers" step,
    mechanized (ISSUE 20): for every budget entry whose env/min_scale
    scope matches enough ledger rows (the entry's OWN scoping rule —
    :func:`_budget_applies` — so a TPU floor is only ever derived
    from TPU rows), derive the refreshed bound from the trailing
    window's median: ``min`` -> safety * median (a floor the measured
    plateau clears with 1/safety headroom), ``max`` -> median / safety.
    Entries with fewer than ``min_samples`` matching measurements are
    skipped, never guessed. Returns::

        {"proposal": <a valid perf_budgets doc with updated bounds,
                      each changed entry annotated with its
                      derivation>,
         "changes": [{leg, metric, bound, old, new, median, n}, ...],
         "skipped": [{leg, metric, rows, needed}, ...]}

    The proposal is diffed against the checked-in file by
    ``obs history gate --propose-budgets`` and rendered as the
    campaign decision ledger's perf_budgets diff (obs/campaign.py).
    """
    if not 0 < safety <= 1:
        raise ValueError(f"safety must be in (0, 1], got {safety}")
    det = dict(DEFAULT_DETECTION)
    det.update(budgets.get("detection") or {})
    window = int(det.get("window", 8))
    min_samples = int(det.get("min_samples", 3))
    proposal = json.loads(json.dumps(_json_safe(budgets)))
    changes: List[dict] = []
    skipped: List[dict] = []
    for b in proposal.get("budgets", []):
        leg, metric = b.get("leg"), b.get("metric")
        vals = [metric_value(r, leg, metric) for r in records
                if _budget_applies(b, r)]
        vals = [v for v in vals if v is not None][-window:]
        if len(vals) < min_samples:
            skipped.append({"leg": leg, "metric": metric,
                            "rows": len(vals), "needed": min_samples})
            continue
        med, mad = median_mad(vals)
        derived = False
        for bound, new in (("min", _round_sig(med * safety)),
                           ("max", _round_sig(med / safety))):
            old = _num(b.get(bound))
            if old is None or new == old:
                continue
            b[bound] = new
            derived = True
            changes.append({"leg": leg, "metric": metric,
                            "bound": bound, "old": old, "new": new,
                            "median": med, "n": len(vals)})
        if derived:
            b["derived"] = {"median": med, "mad": mad,
                            "n": len(vals), "safety": safety}
    return {"proposal": proposal, "changes": changes,
            "skipped": skipped}


@dataclass
class GateResult:
    """One gate evaluation: violations fail CI; drift warnings and
    improvements pass with a note."""

    ok: bool = True
    violations: List[str] = field(default_factory=list)
    drift_warnings: List[str] = field(default_factory=list)
    improvements: List[str] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)
    changes: List[Change] = field(default_factory=list)

    def to_dict(self) -> dict:
        return _json_safe({
            "ok": self.ok,
            "violations": self.violations,
            "drift_warnings": self.drift_warnings,
            "improvements": self.improvements,
            "notes": self.notes,
            "changes": [dataclasses.asdict(c) for c in self.changes],
        })


def evaluate_gate(records: Sequence[dict],
                  budgets: Optional[dict] = None) -> GateResult:
    """The CI perf gate over a ledger's newest record:

      - absolute budget floors/ceilings (env-scoped);
      - MAD regression flags classified **program-change** fail;
      - flags classified **env-drift** warn and PASS (backend drift is
        not a code regression — the r5 lesson);
      - improvements and clean series are reported, never gated.
    """
    res = GateResult()
    if not records:
        res.notes.append("empty ledger: nothing to gate")
        return res
    target = records[-1]
    label = target.get("source") or target.get("kind") or "latest"
    res.notes.append(
        f"gating {label} (kind {target.get('kind')}, "
        f"{len(records) - 1} prior record(s))")
    if budgets:
        res.violations.extend(check_budgets(target, budgets))
    detection = (budgets or {}).get("detection")
    res.changes = detect_changes(records, detection)
    evaluated = 0
    for ch in res.changes:
        evaluated += 1
        if not ch.flagged:
            continue
        line = (f"{ch.leg}.{ch.metric}: {ch.value:.4g} vs baseline "
                f"{ch.baseline_median:.4g} (n={ch.n_baseline}, "
                f"{ch.rel_delta:+.1%}) [{ch.classification}] "
                f"{ch.evidence}")
        if ch.direction == "improvement":
            res.improvements.append(line)
        elif ch.classification == "env-drift":
            res.drift_warnings.append("DRIFT " + line)
        elif ch.classification == "data-change":
            # ISSUE 13: the INPUT changed shape — not a code
            # regression; warn like drift, with the distinct tag.
            res.drift_warnings.append("DATA " + line)
        else:
            res.violations.append("REGRESSION " + line)
    if not evaluated:
        res.notes.append(
            "no series had enough same-environment history to "
            "baseline (min_samples) — budgets only")
    res.ok = not res.violations
    return res


# -- trend rendering --------------------------------------------------------

_METRIC_SHORT = {
    "edges_per_sec_per_chip": "edges/s/chip",
    "seconds_per_iter": "s/iter",
    "build_s": "build s",
    "build_warm_s": "warm build s",
    "accuracy_l1": "accuracy L1",
    "cost_bytes_per_edge": "cost B/edge",
    "comms_bytes_per_iter": "comms B/iter",
    "scaling_efficiency": "scaling eff",
    "exchange_fraction": "exch frac",
    "comms_achieved_bytes_per_sec": "achieved B/s",
    "hlo_bytes_per_edge": "hlo B/edge",
    "graph_dangling_fraction": "dangling frac",
    "graph_partition_skew": "part skew",
    "graph_topk_concentration": "topk conc",
    "sdc_check_overhead_pct": "sdc ovh %",
    "iters_to_tol": "iters to tol",
    "queries_per_sec": "queries/s",
    "p50_ms": "p50 ms",
    "p99_ms": "p99 ms",
    "shed_fraction": "shed frac",
    "admission_wait_p99_ms": "adm p99 ms",
    "batch_wait_p99_ms": "bwait p99 ms",
    "dispatch_p99_ms": "disp p99 ms",
    "fetch_p99_ms": "fetch p99 ms",
}


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    a = abs(v)
    if a != 0 and (a >= 1e5 or a < 1e-3):
        return f"{v:.3e}"
    return f"{v:.4g}"


def record_label(rec: dict, index: int) -> str:
    rnd = rec.get("round")
    if rnd is not None:
        prefix = "m" if str(rec.get("kind", "")).startswith("multichip") \
            else "r"
        return f"{prefix}{rnd:02d}"
    return f"#{index}"


def render_trend(records: Sequence[dict],
                 detection: Optional[dict] = None,
                 metrics: Sequence[str] = LEG_METRICS) -> str:
    """ASCII trend: the record roster, then one series row per (leg,
    metric) — every leg ever recorded renders (no dropped legs), with
    the robust baseline and the newest record's flags below. The
    mechanical form of the ROADMAP's hand-computed plateau read."""
    if not records:
        return "perf history: empty ledger"
    lines = [f"perf history: {len(records)} record(s)"]
    for i, r in enumerate(records):
        env = r.get("env") or {}
        legs = sorted((r.get("legs") or {}))
        lines.append(
            f"  {record_label(r, i):<5} {str(r.get('kind')):<17} "
            f"git {str(env.get('git_rev') or '-'):<9} "
            f"backend {str(env.get('backend') or '?'):<5} "
            f"{r.get('source') or ''}"
            + (f"  legs: {', '.join(legs)}" if legs else "  (no legs)")
        )
    leg_names = sorted({leg for r in records
                        for leg in (r.get("legs") or {})})
    rows = []
    for leg in leg_names:
        for metric in metrics:
            pts = series(records, leg, metric)
            if not pts:
                continue
            vals = [v for _, v in pts]
            med, mad = median_mad(vals)
            label = f"{leg} {_METRIC_SHORT.get(metric, metric)}"
            cells = " ".join(
                f"{record_label(records[i], i)}={_fmt(v)}"
                for i, v in pts)
            rows.append((label, len(pts), med, mad, cells))
    if rows:
        w = max(len(r[0]) for r in rows)
        lines.append("")
        lines.append(f"{'series':<{w}}  {'n':>2}  {'median':>10}  "
                     f"{'MAD':>9}  oldest -> newest")
        for label, n, med, mad, cells in rows:
            lines.append(f"{label:<{w}}  {n:>2}  {_fmt(med):>10}  "
                         f"{_fmt(mad):>9}  {cells}")
    # Lowering fingerprints (ISSUE 11): the compiler-plane series —
    # a fingerprint change next to a rate shift attributes the shift
    # to the emitted program (a jax/libtpu lowering change), the
    # attribution the MAD classifier also applies mechanically.
    low_rows = []
    for leg in leg_names:
        fps = [
            (i, (r.get("legs") or {}).get(leg, {}).get(
                "lowering_fingerprint"))
            for i, r in enumerate(records)
        ]
        fps = [(i, f) for i, f in fps if isinstance(f, str) and f]
        if not fps:
            continue
        cells = " ".join(
            f"{record_label(records[i], i)}={f[:8]}" for i, f in fps
        )
        changed = len({f for _, f in fps}) > 1
        low_rows.append(f"  {leg}: {cells}"
                        + ("  << LOWERING CHANGED" if changed else ""))
    if low_rows:
        lines.append("")
        lines.append("lowering fingerprints (optimized-HLO structure "
                     "per leg):")
        lines.extend(low_rows)
    changes = detect_changes(records, detection)
    flagged = [c for c in changes if c.flagged]
    lines.append("")
    if flagged:
        lines.append("flags on the newest record:")
        for c in flagged:
            lines.append(
                f"  {c.direction.upper()}: {c.leg}.{c.metric} "
                f"{_fmt(c.value)} vs {_fmt(c.baseline_median)} "
                f"({c.rel_delta:+.1%}) [{c.classification}] {c.evidence}")
    elif changes:
        lines.append(f"newest record: {len(changes)} series within "
                     "noise of their baselines")
    else:
        lines.append("newest record: no series had enough "
                     "same-environment history to baseline")
    return "\n".join(lines)


# -- obs report x history ---------------------------------------------------


def baseline_pseudo_report(records: Sequence[dict], leg: str,
                           detection: Optional[dict] = None,
                           env: Optional[dict] = None) -> Tuple[
                               Optional[dict], int]:
    """A synthetic run-report-shaped dict standing in for 'the
    ledger's baseline of this form', so ``obs report --against-history``
    can reuse diff_reports' env-drift-first rendering verbatim.
    ``env`` (the target report's fingerprint) prefers SAME-CLASS
    ledger records when any exist; otherwise every record of the leg
    stands in and the diff's env banner calls the drift out.
    Returns (pseudo_report | None, n_baseline_records)."""
    det = dict(DEFAULT_DETECTION)
    det.update(detection or {})
    hits = [r for r in records if leg in (r.get("legs") or {})]
    if env:
        vals = tuple(env.get(k) for k in ENV_CLASS_KEYS)
        if not all(v is None for v in vals):
            same = [r for r in hits if env_class(r) == vals]
            if same:
                hits = same
    hits = hits[-det["window"]:]
    if not hits:
        return None, 0
    env = {}
    for k in set(ENV_CLASS_KEYS) | set(ENV_DRIFT_KEYS) | {"git_rev"}:
        env[k] = _mode([(r.get("env") or {}).get(k) for r in hits])
    summary = {}
    for metric, key in (("edges_per_sec_per_chip",
                         "edges_per_sec_per_chip"),
                        ("seconds_per_iter", "mean_iter_seconds")):
        vals = [v for _, v in series(hits, leg, metric)]
        if vals:
            summary[key] = median_mad(vals)[0]
    costs = {}
    bpe = [v for _, v in series(hits, leg, "cost_bytes_per_edge")]
    if bpe:
        costs["step"] = {"bytes_per_edge": median_mad(bpe)[0]}
    return ({"schema_version": 1, "environment": env, "spans": {},
             "summary": summary, "costs": costs, "metrics": {},
             "iterations": [], "robustness": {}}, len(hits))
