"""The ONE sanctioned diagnostic channel for library modules.

Lint rule PTL007 bans bare ``print(...)`` / direct ``sys.stderr.write``
in library modules (CLI entry points are exempt): ad-hoc prints are
invisible to the observability layer — they don't land in traces or run
reports, and they can't be silenced or redirected as a unit. Library
diagnostics route through :func:`info` / :func:`warn` instead, which

  - write one line to stderr (prefixed ``pagerank_tpu:`` — the
    historical spelling of these messages), and
  - record an instant event on the active tracer, so one-off
    diagnostics ("enabling x64", "pallas unavailable, falling back")
    show up IN the trace next to the spans they explain.

This module's own ``sys.stderr.write`` carries the single PTL007
allowlist entry (analysis/allowlist.txt).
"""

from __future__ import annotations

import sys

from pagerank_tpu.obs import trace as _trace


def _emit(level: str, msg: str) -> None:
    tr = _trace.get_tracer()
    if tr.enabled:
        tr.add_event("log/" + level, message=msg)
    sys.stderr.write(f"pagerank_tpu: {msg}\n")


def info(msg: str) -> None:
    """One-off informational diagnostic (configuration notices,
    fallbacks taken)."""
    _emit("info", msg)


def warn(msg: str) -> None:
    """Diagnostic for a degraded-but-continuing condition (an
    out-of-regime layout, an unavailable optimization)."""
    _emit("warn", msg)
