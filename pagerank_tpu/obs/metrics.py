"""Central metrics registry — the counter half of the observability
layer (docs/OBSERVABILITY.md).

Before this module the run counters were scattered one-per-subsystem:
S3 request retries in ``S3FileSystem.retry_stats``, health-check
failures and rollbacks in ``engine.health``, dead-letters in
``SinkGuard.dropped``, compile-cache behavior invisible entirely. Each
stayed (they are the subsystems' own API), but every one is now ALSO
registered here, so one ``snapshot()`` captures the whole run and the
flight recorder (obs/report.py) can embed it.

Typed instruments:

  - :class:`Counter` — monotone count (``s3.request.retries``);
  - :class:`Gauge` — last-set value (``engine.num_chips``);
  - :class:`Histogram` — count/sum/min/max plus power-of-two bucket
    counts (``snapshot.bytes_written`` per save).

Naming scheme mirrors the span scheme: ``subsystem.thing[.verb]``,
dot-separated (docs/OBSERVABILITY.md has the full catalogue).

Counter updates are plain in-GIL arithmetic (the same discipline as
``SinkGuard.retries``): the writer thread and the solve loop may both
increment, and a lost update under a hypothetical no-GIL runtime would
cost a count, never a crash — these are telemetry, not ledgers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "help", "value")

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-written value."""

    __slots__ = ("name", "help", "value")

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value: Optional[float] = None

    def set(self, v) -> None:
        self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Summary stats + power-of-two buckets. ``record(v)`` files ``v``
    under the smallest bucket bound ``2**k >= v`` (one ``+inf`` bucket
    past 2**63); the snapshot keeps only non-empty buckets."""

    __slots__ = ("name", "help", "count", "sum", "min", "max", "buckets")

    kind = "histogram"

    _MAX_EXP = 63

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.buckets: Dict[str, int] = {}

    def record(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= 0:
            key = "0"
        else:
            e = 0
            while (1 << e) < v and e < self._MAX_EXP:
                e += 1
            key = str(1 << e) if (1 << e) >= v else "+inf"
        self.buckets[key] = self.buckets.get(key, 0) + 1

    #: Fixed quantile summaries published by snapshot() — what the
    #: Prometheus exporter (obs/live.py) and the run report surface as
    #: latency distributions, not just count/sum/max.
    QUANTILES = (0.5, 0.9, 0.99)

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound estimate of the ``q``-quantile from the
        power-of-two buckets: the smallest bucket bound whose cumulative
        count reaches ``q * count``. Exact observed extremes clamp it —
        the estimate is never below ``min`` or above ``max`` (a
        one-bucket histogram answers the true range, not the bucket
        ceiling)."""
        if not self.count:
            return None
        target = q * self.count

        def bound(key: str) -> float:
            return float("inf") if key == "+inf" else float(int(key))

        cum = 0
        for key in sorted(self.buckets, key=bound):
            cum += self.buckets[key]
            if cum >= target:
                est = bound(key)
                return float(min(max(est, self.min), self.max))
        return float(self.max)  # pragma: no cover - cum always reaches

    def snapshot(self):
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": (self.sum / self.count) if self.count else None,
            # Bucket-estimated (upper-bound) latency quantiles — see
            # quantile(); None when empty, like min/max.
            **{f"p{int(q * 100)}": self.quantile(q)
               for q in self.QUANTILES},
            "buckets": dict(self.buckets),
        }


Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Get-or-create registry of typed metrics, snapshot-able to a
    plain-JSON dict and renderable as a human table."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}

    def _get(self, cls, name: str, help: str) -> Metric:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, help)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}"
            )
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def reset(self) -> None:
        """Drop every metric — one run's counters must not bleed into
        the next in-process run (cli.main resets at entry)."""
        self._metrics.clear()

    def snapshot(self) -> Dict[str, dict]:
        """``{"counters": {...}, "gauges": {...}, "histograms": {...}}``
        — pure JSON-able values, stable key order."""
        out: Dict[str, dict] = {"counters": {}, "gauges": {},
                                "histograms": {}}
        for name in self.names():
            m = self._metrics[name]
            out[m.kind + "s"][name] = m.snapshot()
        return out

    def render_table(self) -> str:
        """Aligned human-readable table of the current values."""
        rows = []
        for name in self.names():
            m = self._metrics[name]
            if m.kind == "histogram":
                s = m.snapshot()
                val = (f"count={s['count']} sum={s['sum']:g} "
                       f"min={s['min']:g} max={s['max']:g}"
                       if s["count"] else "count=0")
            else:
                val = str(m.snapshot())
            rows.append((name, m.kind, val))
        if not rows:
            return "(no metrics registered)"
        w_name = max(len(r[0]) for r in rows)
        w_kind = max(len(r[1]) for r in rows)
        return "\n".join(
            f"{n:<{w_name}}  {k:<{w_kind}}  {v}" for n, k, v in rows
        )


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every instrumented subsystem reports
    into."""
    return _REGISTRY


def counter(name: str, help: str = "") -> Counter:
    """Get-or-create a counter on the global registry (the one-line
    idiom instrumentation sites use)."""
    return _REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "") -> Histogram:
    return _REGISTRY.histogram(name, help)
