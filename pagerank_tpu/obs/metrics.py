"""Central metrics registry — the counter half of the observability
layer (docs/OBSERVABILITY.md).

Before this module the run counters were scattered one-per-subsystem:
S3 request retries in ``S3FileSystem.retry_stats``, health-check
failures and rollbacks in ``engine.health``, dead-letters in
``SinkGuard.dropped``, compile-cache behavior invisible entirely. Each
stayed (they are the subsystems' own API), but every one is now ALSO
registered here, so one ``snapshot()`` captures the whole run and the
flight recorder (obs/report.py) can embed it.

Typed instruments:

  - :class:`Counter` — monotone count (``s3.request.retries``);
  - :class:`Gauge` — last-set value (``engine.num_chips``);
  - :class:`Histogram` — count/sum/min/max plus power-of-two bucket
    counts (``snapshot.bytes_written`` per save).

Naming scheme mirrors the span scheme: ``subsystem.thing[.verb]``,
dot-separated (docs/OBSERVABILITY.md has the full catalogue).

Thread discipline (PTR001, docs/ANALYSIS.md "PTR rules"): the registry
MAP and every histogram's bucket dict are lock-protected — the metrics
HTTP exporter thread renders (`registry.export_view()`) while the
solve loop, the rank-writer, and the watchdog register and record, and
an unguarded dict would let a scrape iterate mid-insert. Counter/Gauge
SCALAR updates stay plain in-GIL arithmetic by design (the same
discipline as ``SinkGuard.retries``, waived in the analysis allowlist
with this reason): a lost update under a hypothetical no-GIL runtime
would cost a count, never a crash — these are telemetry, not ledgers.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple, Union


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "help", "value")

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-written value."""

    __slots__ = ("name", "help", "value")

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value: Optional[float] = None

    def set(self, v) -> None:
        self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Summary stats + power-of-two buckets. ``record(v)`` files ``v``
    under the smallest bucket bound ``2**k >= v`` (one ``+inf`` bucket
    past 2**63); the snapshot keeps only non-empty buckets.

    Lock-protected (PTR001): the bucket dict is mutated on the solve
    loop (``solve.step_seconds_ms`` per iteration) while the exporter
    HTTP thread renders a snapshot — every field access happens under
    ``_lock``, and readers work from a consistent copy taken there.
    The per-record cost is one uncontended acquire, noise next to a
    device dispatch."""

    __slots__ = ("name", "help", "count", "sum", "min", "max", "buckets",
                 "exemplars", "_lock")

    kind = "histogram"

    _MAX_EXP = 63

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.buckets: Dict[str, int] = {}
        # bucket key -> {"value", "trace_id"}: the last exemplar filed
        # per bucket (ISSUE 19 — the OpenMetrics renderer attaches them
        # so a tail bucket names a concrete trace to pull). Populated
        # ONLY by trace-id-carrying records: the plain record(v) path
        # is unchanged, which is the disarmed-tracing zero-cost pin.
        self.exemplars: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def record(self, v: float, trace_id: Optional[str] = None) -> None:
        v = float(v)
        if v <= 0:
            key = "0"
        else:
            e = 0
            while (1 << e) < v and e < self._MAX_EXP:
                e += 1
            key = str(1 << e) if (1 << e) >= v else "+inf"
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self.buckets[key] = self.buckets.get(key, 0) + 1
            if trace_id is not None:
                self.exemplars[key] = {"value": v, "trace_id": trace_id}

    #: Fixed quantile summaries published by snapshot() — what the
    #: Prometheus exporter (obs/live.py) and the run report surface as
    #: latency distributions, not just count/sum/max.
    QUANTILES = (0.5, 0.9, 0.99)

    def _state(self) -> Tuple[int, float, Optional[float], Optional[float],
                              Dict[str, int]]:
        """One consistent (count, sum, min, max, buckets-copy) read —
        the only place readers touch the fields."""
        with self._lock:
            return (self.count, self.sum, self.min, self.max,
                    dict(self.buckets))

    def exemplars_view(self) -> Dict[str, dict]:
        """Consistent copy of the per-bucket exemplars (empty unless
        trace-id-carrying records happened — i.e. the query plane was
        armed)."""
        with self._lock:
            return {k: dict(v) for k, v in self.exemplars.items()}

    @staticmethod
    def _estimate(count: int, mn: float, mx: float,
                  buckets: Dict[str, int], q: float) -> float:
        """Upper-bound ``q``-quantile from power-of-two buckets: the
        smallest bucket bound whose cumulative count reaches
        ``q * count``. Exact observed extremes clamp it — the estimate
        is never below ``min`` or above ``max`` (a one-bucket histogram
        answers the true range, not the bucket ceiling)."""
        target = q * count

        def bound(key: str) -> float:
            return float("inf") if key == "+inf" else float(int(key))

        cum = 0
        for key in sorted(buckets, key=bound):
            cum += buckets[key]
            if cum >= target:
                return float(min(max(bound(key), mn), mx))
        return float(mx)  # pragma: no cover - cum always reaches

    def quantile(self, q: float) -> Optional[float]:
        count, _sum, mn, mx, buckets = self._state()
        if not count:
            return None
        return self._estimate(count, mn, mx, buckets, q)

    def snapshot(self):
        count, total, mn, mx, buckets = self._state()
        out = {
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
            "mean": (total / count) if count else None,
            # Bucket-estimated (upper-bound) latency quantiles — see
            # quantile(); None when empty, like min/max.
            **{f"p{int(q * 100)}":
               (self._estimate(count, mn, mx, buckets, q)
                if count else None)
               for q in self.QUANTILES},
            "buckets": buckets,
        }
        ex = self.exemplars_view()
        if ex:
            # Key present only when armed: the snapshot shape every
            # existing consumer pins stays byte-identical otherwise.
            out["exemplars"] = ex
        return out


Metric = Union[Counter, Gauge, Histogram]


class MetricsRegistry:
    """Get-or-create registry of typed metrics, snapshot-able to a
    plain-JSON dict and renderable as a human table.

    The map is lock-protected (PTR001): get-or-create runs on every
    context that instruments anything (solve loop, rank-writer worker,
    stall watchdog), while the exporter's HTTP thread iterates the map
    per scrape — an unguarded dict would let the iteration race an
    insert. Readers consume :meth:`export_view`/:meth:`snapshot`,
    which copy the map under the lock; lock order is always registry
    -> histogram, never the reverse (PTR002)."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}"
            )
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Drop every metric — one run's counters must not bleed into
        the next in-process run (cli.main resets at entry)."""
        with self._lock:
            self._metrics.clear()

    def _items(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[name] for name in sorted(self._metrics)]

    def export_view(self) -> List[Tuple[str, str, str, object]]:
        """One consistent ``(name, kind, help, snapshot)`` row per
        metric — what the Prometheus renderer (obs/live.py) consumes,
        so a scrape never iterates live registry internals while
        another context registers or records."""
        return [(m.name, m.kind, m.help, m.snapshot())
                for m in self._items()]

    def snapshot(self) -> Dict[str, dict]:
        """``{"counters": {...}, "gauges": {...}, "histograms": {...}}``
        — pure JSON-able values, stable key order."""
        out: Dict[str, dict] = {"counters": {}, "gauges": {},
                                "histograms": {}}
        for name, kind, _help, snap in self.export_view():
            out[kind + "s"][name] = snap
        return out

    def render_table(self) -> str:
        """Aligned human-readable table of the current values."""
        rows = []
        for name, kind, _help, s in self.export_view():
            if kind == "histogram":
                val = (f"count={s['count']} sum={s['sum']:g} "
                       f"min={s['min']:g} max={s['max']:g}"
                       if s["count"] else "count=0")
            else:
                val = str(s)
            rows.append((name, kind, val))
        if not rows:
            return "(no metrics registered)"
        w_name = max(len(r[0]) for r in rows)
        w_kind = max(len(r[1]) for r in rows)
        return "\n".join(
            f"{n:<{w_name}}  {k:<{w_kind}}  {v}" for n, k, v in rows
        )


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every instrumented subsystem reports
    into."""
    return _REGISTRY


def counter(name: str, help: str = "") -> Counter:
    """Get-or-create a counter on the global registry (the one-line
    idiom instrumentation sites use)."""
    return _REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "") -> Histogram:
    return _REGISTRY.histogram(name, help)
