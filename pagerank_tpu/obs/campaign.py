"""The campaign plane (ISSUE 20): a resumable measurement-campaign
orchestrator with typed verdicts and a decision ledger.

Every staged win — the partition-centric layout (arXiv:1709.07122),
the bf16-stream kernel (arXiv:2009.10443), async halo, PPR serving —
is built, gated, and "awaiting chip time", and the ROADMAP names the
TPU measurement campaign the single highest-value session. Before
this module that campaign existed only as prose: ~8 ordered commands
(``obs hlo`` -> ``obs fit`` -> ``obs graph`` -> ``bench --multichip
--history`` -> ``obs history gate``) whose verdicts a human had to
extract, compare against the cost models, and hand-apply to defaults
and perf_budgets.json. One preempted VM or one mis-ordered step and
the session's evidence was partial and unrecorded — the exact failure
mode the job plane (jobs.py, PR 12) armors everything else against.

This module makes the campaign a DATA STRUCTURE executed through that
same job machinery:

* :class:`CampaignSpec` — ordered :class:`LegSpec` legs, each naming
  an in-process entrypoint (the obs CLI / bench, stdout-captured),
  preconditions over EARLIER legs' documents, a wall budget, and the
  typed verdicts extracted from its JSON artifact.
* :class:`CampaignRunner` — runs the legs in order; every completed
  leg's document is persisted as a checksummed npz artifact
  (jobs.save_artifact + doc_to_arrays) keyed by a content hash of the
  leg's full parameterization, next to an atomic ``campaign.json``
  manifest. SIGTERM drains to exit 75 at the next leg boundary
  (jobs.GracefulDrain, wired in obs/__main__); SIGKILL loses at most
  the in-flight leg. Resume validates each artifact's checksum + key
  and SKIPS completed legs — truth lives in the artifacts, the
  manifest is advisory (the JobSupervisor discipline).
* Five typed verdict extractors (:data:`VERDICTS`) — pure functions
  over the leg documents + perf_budgets.json, returning a CLOSED
  decision vocabulary (never prose): ``partitioned_vs_default``,
  ``halo_vs_dense``, ``pallas_keep_or_delete``, ``async_overlap``,
  ``ppr_serve_floors``. Degraded inputs (missing lowering block,
  None cost fields, a leg that blew its wall budget in a binding run)
  produce ``inconclusive`` with the missing input named, not a crash
  and not a silently-confident verdict.
* :func:`build_report` — the strict-JSON campaign report plus the
  human decision ledger (flip X to default / delete Y / proposed
  perf_budgets floors). The STABLE report is a pure function of spec
  identity + leg statuses + verdict decisions: it excludes walls,
  timestamps, resume counts, and (in non-binding runs) every measured
  number, so an interrupted-then-resumed dry-run campaign renders a
  report BYTE-IDENTICAL (report.canonical_json) to an uninterrupted
  one — pinned by tests/test_campaign.py's SIGKILL chaos test.
  Measured evidence rides ``report --full`` and the artifacts.

Non-binding mode: ``campaign run --fake-devices 8`` executes every
leg end-to-end on CPU fake devices at smoke scale — preconditions
downgrade to warnings, every verdict's decision is ``defer`` (the
would-be decision is preserved in its evidence block) — so the whole
orchestration is tier-1-testable today and the real TPU session
becomes ONE resumable command. docs/OBSERVABILITY.md "Campaign
plane" is the operator walkthrough.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from pagerank_tpu import jobs
from pagerank_tpu.utils import fsio

SCHEMA_VERSION = 1
MANIFEST_NAME = "campaign.json"
REPORT_NAME = "report.json"
LEDGER_NAME = "campaign_ledger.jsonl"

#: The partition-centric cost model (ISSUE 6 / arXiv:1709.07122):
#: modeled bytes touched per edge for the default 'step' gather
#: pipeline vs the partitioned layout. The measured couple ratio is
#: judged against the model's memory-bound headroom, not a bare
#: threshold pulled from the air.
MODEL_BYTES_PER_EDGE = {"default_step": 588.6, "partitioned": 165.7}

#: Flip thresholds — deliberately far below the model ratio (~3.55x):
#: a default flip needs a REAL, reproducible win, not a tie broken in
#: the new code's favor.
PARTITIONED_FLIP_MIN_RATIO = 1.10
#: PTH004 (analysis/lint.py): a hand kernel must hold >= this fraction
#: of the XLA leg it replaces, on top of its absolute budget floor —
#: otherwise it is deleted, not kept as a trophy.
PALLAS_KEEP_MIN_RATIO = 0.95
#: Async halo flips the default only when overlap buys >= 5% of step
#: wall AND stale boundaries did not blow up iterations-to-tol.
ASYNC_FLIP_MIN_GAIN = 0.05
ASYNC_MAX_ITER_PENALTY = 1.5
#: Serving floors are TIGHTENED (not just kept) when measured
#: throughput clears the current floor by >= 20%.
SERVE_TIGHTEN_MARGIN = 1.20

NONBINDING_REASON = (
    "non-binding dry run on fake devices; the measured would-be "
    "decision is preserved in this verdict's evidence block"
)


# -- spec --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LegSpec:
    """One campaign leg: an in-process entrypoint + params, a wall
    budget, preconditions over earlier legs' documents, and the typed
    verdicts extracted from this leg's artifact."""

    name: str
    entrypoint: str                       # ENTRYPOINTS key
    params: Dict[str, object]             # JSON-able entrypoint input
    budget_s: float
    preconditions: Tuple[str, ...] = ()   # PRECONDITIONS keys
    verdicts: Tuple[str, ...] = ()        # VERDICTS keys

    def to_doc(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "entrypoint": self.entrypoint,
            "params": self.params,
            "budget_s": self.budget_s,
            "preconditions": list(self.preconditions),
            "verdicts": list(self.verdicts),
        }


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    name: str
    legs: Tuple[LegSpec, ...]

    def to_doc(self) -> Dict[str, object]:
        return {
            "schema_version": SCHEMA_VERSION,
            "name": self.name,
            "legs": [leg.to_doc() for leg in self.legs],
        }

    @staticmethod
    def from_doc(doc: Dict[str, object]) -> "CampaignSpec":
        legs = tuple(
            LegSpec(
                name=d["name"], entrypoint=d["entrypoint"],
                params=d.get("params") or {},
                budget_s=float(d.get("budget_s", 0.0)),
                preconditions=tuple(d.get("preconditions") or ()),
                verdicts=tuple(d.get("verdicts") or ()),
            )
            for d in doc.get("legs", [])
        )
        return CampaignSpec(name=str(doc.get("name", "campaign")),
                            legs=legs)


def default_budgets_path() -> str:
    """The checked-in perf_budgets.json at the repo root."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "perf_budgets.json")


def build_spec(profile: str = "roadmap", ndev: int = 8) -> CampaignSpec:
    """THE checked-in campaign: the ROADMAP's order of operations
    (`obs hlo` -> `obs fit` -> `obs graph` -> bench couple ->
    bench --multichip -> bench --ppr-serve -> `obs history gate`)
    as a declarative spec. ``roadmap`` is the real-TPU-session
    geometry; ``smoke`` is the CPU-fake-device dry-run geometry the
    tier-1 tests and acceptance smoke AA execute end-to-end."""
    if profile not in ("roadmap", "smoke"):
        raise ValueError(f"unknown campaign profile {profile!r} "
                         "(choices: roadmap, smoke)")
    smoke = profile == "smoke"
    ndev = max(int(ndev), 1)
    # Geometry: smoke stays tiny (every leg compiles + runs on CPU in
    # seconds); roadmap is the ROADMAP's measured-session geometry.
    hlo_scale = 8 if smoke else 14
    couple_scale = 8 if smoke else 23
    mc_scale = 8 if smoke else 24
    serve_scale = 8 if smoke else 22
    iters = 2 if smoke else 40
    graph_scale = 8 if smoke else 20
    acc_scale = 8 if smoke else 20
    serve_queries = 24 if smoke else 400
    serve_qps = 400 if smoke else 100
    # Wall budgets: smoke budgets are GENEROUS (an over-budget flag in
    # the stable report would break dry-run byte-identity on a slow
    # CI box); roadmap budgets bound a wedged TPU leg.
    legs = (
        LegSpec(
            "hlo", "obs_cli",
            {"argv": ["hlo", "--form",
                      "default,partitioned,partitioned_bf16",
                      "--scale", str(hlo_scale), "--json"]},
            budget_s=120.0 if smoke else 600.0,
        ),
        LegSpec(
            "fit", "obs_cli",
            {"argv": ["fit", "--scale", str(mc_scale),
                      "--ndev", str(ndev), "--json"]},
            budget_s=120.0 if smoke else 300.0,
        ),
        LegSpec(
            "graph", "obs_cli",
            {"argv": ["graph", "--scale", str(graph_scale),
                      "--ndev", str(ndev),
                      "--iters", "2" if smoke else "4", "--json"]},
            budget_s=180.0 if smoke else 1800.0,
        ),
        LegSpec(
            "bench_couple", "bench",
            {"argv": ["--scale", str(couple_scale),
                      "--iters", str(iters),
                      "--accuracy-scale", str(acc_scale)]},
            budget_s=600.0 if smoke else 3600.0,
            preconditions=("gather_native",),
            verdicts=("partitioned_vs_default", "pallas_keep_or_delete"),
        ),
        LegSpec(
            "bench_multichip", "bench",
            {"argv": ["--multichip", "--scale", str(mc_scale),
                      "--multichip-devices", str(ndev),
                      "--iters", str(iters),
                      "--accuracy-scale", str(acc_scale)]},
            budget_s=600.0 if smoke else 3600.0,
            preconditions=("fits", "gather_native"),
            verdicts=("halo_vs_dense", "async_overlap"),
        ),
        LegSpec(
            "ppr_serve", "bench",
            {"argv": ["--ppr-serve", "--scale", str(serve_scale),
                      "--serve-queries", str(serve_queries),
                      "--serve-qps", str(serve_qps)]},
            budget_s=300.0 if smoke else 1800.0,
            verdicts=("ppr_serve_floors",),
        ),
        LegSpec(
            "history_gate", "history_gate",
            {"ingest": ["bench_couple", "bench_multichip", "ppr_serve"]},
            budget_s=60.0 if smoke else 120.0,
            preconditions=("have_bench_evidence",),
        ),
    )
    return CampaignSpec(name=f"roadmap-{profile}", legs=legs)


# -- entrypoints -------------------------------------------------------------
# Each entrypoint runs IN-PROCESS (the campaign is one resumable
# command, not a shell script), captures the command's one-JSON-object
# stdout, and returns the leg document:
#   {"command": [...], "exit_code": int, "output": <parsed JSON>}
# Meaningful nonzero exits (fit says "won't fit", hlo says "gather
# defeated", gate says "budget breached") are DATA the preconditions
# and verdicts read, not leg failures; only an unparseable/absent
# document fails the leg.


def _import_bench():
    """bench.py lives at the repo root (driver contract), not in the
    package — resolve it the way scripts/acceptance.py does."""
    try:
        import bench
    except ImportError:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if root not in sys.path:
            sys.path.insert(0, root)
        import bench
    return bench


def _ep_obs_cli(params: Dict[str, object], ctx: Dict[str, object]):
    from pagerank_tpu.obs import __main__ as obs_cli

    argv = [str(a) for a in params["argv"]]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = obs_cli.main(list(argv))
    text = buf.getvalue().strip()
    if not text:
        raise RuntimeError(f"obs {argv[0]} produced no JSON document "
                           f"(exit {rc})")
    return {"command": ["obs", *argv], "exit_code": int(rc),
            "output": json.loads(text)}


def _ep_bench(params: Dict[str, object], ctx: Dict[str, object]):
    from pagerank_tpu.obs import report as report_mod

    bench = _import_bench()
    argv = [str(a) for a in params["argv"]]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        doc = bench.main(list(argv))
    if doc is None:
        raise RuntimeError(f"bench {argv} produced no record")
    return {"command": ["bench", *argv], "exit_code": 0,
            "output": report_mod._json_safe(doc)}


def _ep_history_gate(params: Dict[str, object], ctx: Dict[str, object]):
    """The campaign's own gate leg: normalize the earlier bench legs'
    documents into a campaign-local ledger, run the CI perf gate
    against the session budgets, and (when budgets exist) derive the
    refreshed-floor proposal (history.propose_budgets) the decision
    ledger renders as a perf_budgets.json diff."""
    from pagerank_tpu.obs import history

    ledger = os.path.join(str(ctx["dir"]), LEDGER_NAME)
    ingested = 0
    for leg_name in params.get("ingest", []):
        doc = (ctx["docs"].get(leg_name) or {})
        out = doc.get("output")
        if not isinstance(out, dict):
            continue
        rec = history.normalize_result(out, source=f"campaign:{leg_name}")
        ingested += int(history.append_record(ledger, rec))
    records = history.read_ledger(ledger)
    budgets = None
    budgets_path = ctx.get("budgets_path")
    if budgets_path:
        try:
            budgets = history.load_budgets(str(budgets_path))
        except (OSError, ValueError, json.JSONDecodeError):
            budgets = None
    res = history.evaluate_gate(records, budgets)
    output = {
        "gate": res.to_dict(),
        "ingested": ingested,
        "records": len(records),
        "budgets_path": budgets_path,
    }
    if budgets is not None:
        prop = history.propose_budgets(records, budgets)
        output["proposal"] = {"changes": prop["changes"],
                              "skipped": prop["skipped"]}
    return {"command": ["obs", "history", "gate"],
            "exit_code": 0 if res.ok else 1, "output": output}


ENTRYPOINTS: Dict[str, Callable] = {
    "obs_cli": _ep_obs_cli,
    "bench": _ep_bench,
    "history_gate": _ep_history_gate,
}


# -- preconditions -----------------------------------------------------------
# Pure predicates over the documents of EARLIER legs. In a binding
# run a failed precondition BLOCKS the leg (no point burning an hour
# of chip time on a geometry that provably won't fit); in a
# non-binding dry run it downgrades to a recorded warning and the leg
# runs anyway — the dry run's whole job is exercising every leg.


def _get(doc, *path):
    """None-tolerant nested lookup: any missing key / non-dict hop
    yields None instead of a KeyError — degraded artifacts are a
    first-class verdict input, not a crash."""
    cur = doc
    for key in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(key)
    return cur


def _pc_gather_native(docs) -> Tuple[bool, str]:
    doc = docs.get("hlo")
    if doc is None:
        return False, "hlo leg produced no artifact"
    out = _get(doc, "output")
    if not isinstance(out, dict) or not out:
        return False, "hlo leg carries no lowering snapshots"
    defeated = []
    for form, snapshot in sorted(out.items()):
        if not isinstance(snapshot, dict):
            continue
        for prog in sorted(snapshot):
            if _get(snapshot, prog, "gather", "strategy") == "expanded":
                defeated.append(f"{form}/{prog}")
    if defeated:
        return False, ("gather lowering DEFEATED in "
                       + ", ".join(defeated))
    return True, "gather native in every inspected program"


def _pc_fits(docs) -> Tuple[bool, str]:
    doc = docs.get("fit")
    if doc is None:
        return False, "fit leg produced no artifact"
    fits = _get(doc, "output", "fits")
    if fits is None:
        return False, "fit leg carries no fits field"
    if not fits:
        return False, "fit check says the geometry does NOT fit per-chip HBM"
    return True, "fit check passed"


def _pc_have_bench_evidence(docs) -> Tuple[bool, str]:
    have = [name for name in ("bench_couple", "bench_multichip",
                              "ppr_serve")
            if isinstance(_get(docs.get(name), "output"), dict)]
    if not have:
        return False, "no bench leg produced a record to gate"
    return True, "bench evidence present: " + ", ".join(have)


PRECONDITIONS: Dict[str, Callable] = {
    "gather_native": _pc_gather_native,
    "fits": _pc_fits,
    "have_bench_evidence": _pc_have_bench_evidence,
}


# -- typed verdicts ----------------------------------------------------------
# Each extractor is a pure function (leg output doc, budgets doc) ->
# (decision, reason, evidence). Decisions come from a CLOSED
# vocabulary (ACTION_TEXT) — a campaign report can be diffed and
# machine-applied; prose cannot.


def _budget_bound(budgets, leg: str, metric: str, bound: str):
    for b in (budgets or {}).get("budgets") or []:
        if b.get("leg") == leg and b.get("metric") == metric \
                and bound in b:
            try:
                return float(b[bound])
            except (TypeError, ValueError):
                return None
    return None


def _ratio(num, den):
    try:
        num, den = float(num), float(den)
    except (TypeError, ValueError):
        return None
    if den == 0:
        return None
    return num / den


def _v_partitioned_vs_default(out, budgets):
    part = _get(out, "partitioned_f32", "value")
    base = _get(out, "fast_f32", "value")
    ratio = _ratio(part, base)
    model_ratio = (MODEL_BYTES_PER_EDGE["default_step"]
                   / MODEL_BYTES_PER_EDGE["partitioned"])
    evidence = {
        "partitioned_f32_value": part,
        "fast_f32_value": base,
        "measured_ratio": ratio,
        "model_bytes_per_edge": dict(MODEL_BYTES_PER_EDGE),
        "model_ratio": model_ratio,
        "flip_min_ratio": PARTITIONED_FLIP_MIN_RATIO,
        "partitioned_hlo_bytes_per_edge": _get(
            out, "partitioned_f32", "lowering", "step",
            "hlo_bytes_per_edge"),
    }
    if ratio is None:
        return ("inconclusive",
                "bench_couple record lacks partitioned_f32/fast_f32 "
                "rate values", evidence)
    evidence["model_fraction_realized"] = _ratio(ratio - 1.0,
                                                 model_ratio - 1.0)
    if ratio >= PARTITIONED_FLIP_MIN_RATIO:
        return ("flip_partitioned_to_default",
                f"partitioned layout measured {ratio:.2f}x the fast_f32 "
                f"step form (model headroom {model_ratio:.2f}x)",
                evidence)
    return ("keep_step_default",
            f"partitioned layout measured {ratio:.2f}x, below the "
            f"{PARTITIONED_FLIP_MIN_RATIO:.2f}x flip threshold",
            evidence)


def _v_pallas_keep_or_delete(out, budgets):
    value = _get(out, "pallas_partitioned", "value")
    xla = _get(out, "partitioned_f32", "value")
    kernel = _get(out, "pallas_partitioned", "layout", "kernel")
    requested = _get(out, "pallas_partitioned", "layout",
                     "kernel_requested")
    floor = _budget_bound(budgets, "pallas_partitioned_f32",
                          "edges_per_sec_per_chip", "min")
    ratio = _ratio(value, xla)
    evidence = {
        "pallas_value": value,
        "partitioned_f32_value": xla,
        "ratio_vs_xla": ratio,
        "kernel": kernel,
        "kernel_requested": requested,
        "budget_floor": floor,
        "keep_min_ratio": PALLAS_KEEP_MIN_RATIO,
    }
    if requested == "pallas" and kernel != "pallas":
        return ("inconclusive",
                "pallas probe downgraded to the XLA path on this "
                "backend; the kernel never ran", evidence)
    if value is None or ratio is None:
        return ("inconclusive",
                "bench_couple record lacks the pallas_partitioned leg",
                evidence)
    if floor is not None and value < floor:
        return ("delete_pallas_kernel",
                f"pallas leg {value:.3g} edges/s/chip is below its "
                f"perf_budgets floor {floor:.3g} (PTH004)", evidence)
    if ratio < PALLAS_KEEP_MIN_RATIO:
        return ("delete_pallas_kernel",
                f"pallas leg holds only {ratio:.2f}x of the XLA "
                f"partitioned leg (< {PALLAS_KEEP_MIN_RATIO:.2f}x "
                "keep threshold, PTH004)", evidence)
    return ("keep_pallas_kernel",
            f"pallas leg holds {ratio:.2f}x of the XLA partitioned leg"
            + (f" and clears its floor {floor:.3g}"
               if floor is not None else ""), evidence)


def _v_halo_vs_dense(out, budgets):
    sparse = _get(out, "sparse_exchange", "value")
    dense = _get(out, "dense_exchange", "value")
    ratio = _ratio(sparse, dense)
    evidence = {
        "sparse_value": sparse,
        "dense_value": dense,
        "measured_ratio": ratio,
        "exchange_fraction": _get(out, "sparse_exchange",
                                  "attribution", "exchange_fraction"),
        "achieved_bytes_per_sec": _get(out, "sparse_exchange",
                                       "attribution",
                                       "achieved_bytes_per_sec"),
        "halo_fraction": _get(out, "exchanged_bytes", "halo_fraction"),
        "head_k": _get(out, "exchanged_bytes", "head_k"),
        "sparse_below_dense_bytes": _get(out, "exchanged_bytes",
                                         "sparse_below_dense"),
    }
    if ratio is None:
        return ("inconclusive",
                "multichip record lacks sparse/dense exchange rate "
                "values", evidence)
    if ratio >= 1.0 and evidence["sparse_below_dense_bytes"] is not False:
        return ("keep_sparse_halo_default",
                f"sparse halo exchange measured {ratio:.2f}x the dense "
                "all-gather at the session geometry", evidence)
    return ("prefer_dense_exchange",
            f"sparse halo exchange measured {ratio:.2f}x the dense "
            "all-gather — the halo bookkeeping does not pay for "
            "itself here", evidence)


def _v_async_overlap(out, budgets):
    below = _get(out, "exchange_overlap", "async_below_sync_sum")
    gain = _get(out, "exchange_overlap", "gain")
    sync_iters = _get(out, "staleness_sweep", "legs", "sync",
                      "iters_to_tol")
    async_iters = _get(out, "staleness_sweep", "legs", "async_lag1",
                       "iters_to_tol")
    converged = _get(out, "staleness_sweep", "legs", "async_lag1",
                     "converged")
    iter_penalty = _ratio(async_iters, sync_iters)
    evidence = {
        "async_below_sync_sum": below,
        "gain": gain,
        "sync_compute_plus_exchange_s": _get(
            out, "exchange_overlap", "sync_compute_plus_exchange_s"),
        "async_step_s": _get(out, "exchange_overlap", "async_step_s"),
        "sync_iters_to_tol": sync_iters,
        "async_lag1_iters_to_tol": async_iters,
        "async_lag1_converged": converged,
        "iter_penalty": iter_penalty,
        "flip_min_gain": ASYNC_FLIP_MIN_GAIN,
        "max_iter_penalty": ASYNC_MAX_ITER_PENALTY,
    }
    if below is None or gain is None:
        return ("inconclusive",
                "multichip record lacks the exchange_overlap "
                "attribution block", evidence)
    if converged is False:
        return ("keep_synchronous_exchange",
                "lag-1 stale boundaries failed to converge at the gate "
                "tolerance — wall gain is moot", evidence)
    if iter_penalty is not None and iter_penalty > ASYNC_MAX_ITER_PENALTY:
        return ("keep_synchronous_exchange",
                f"async convergence penalty {iter_penalty:.2f}x "
                f"iterations exceeds the {ASYNC_MAX_ITER_PENALTY:.1f}x "
                "bound — overlap gain is eaten by extra iterations",
                evidence)
    if below and gain >= ASYNC_FLIP_MIN_GAIN:
        return ("flip_halo_async_default",
                f"async step wall sits {gain:.1%} below the sync "
                "compute+exchange sum with acceptable convergence",
                evidence)
    return ("keep_synchronous_exchange",
            f"overlap gain {gain:.1%} below the "
            f"{ASYNC_FLIP_MIN_GAIN:.0%} flip threshold", evidence)


def _v_ppr_serve_floors(out, budgets):
    qps = _get(out, "value")
    p99 = _get(out, "p99_ms")
    shed = _get(out, "shed_fraction")
    floors = {
        "queries_per_sec_min": _budget_bound(budgets, "ppr_serve",
                                             "queries_per_sec", "min"),
        "p99_ms_max": _budget_bound(budgets, "ppr_serve", "p99_ms",
                                    "max"),
        "shed_fraction_max": _budget_bound(budgets, "ppr_serve",
                                           "shed_fraction", "max"),
    }
    evidence = {
        "queries_per_sec": qps,
        "p99_ms": p99,
        "shed_fraction": shed,
        "floors": floors,
        "tighten_margin": SERVE_TIGHTEN_MARGIN,
    }
    if qps is None or p99 is None or shed is None:
        return ("inconclusive",
                "ppr_serve record lacks qps/p99/shed fields", evidence)
    if not any(v is not None for v in floors.values()):
        return ("inconclusive",
                "no ppr_serve floors in the budgets file to adjudicate "
                "against", evidence)
    violations = []
    if floors["queries_per_sec_min"] is not None \
            and qps < floors["queries_per_sec_min"]:
        violations.append("queries_per_sec below floor")
    if floors["p99_ms_max"] is not None and p99 > floors["p99_ms_max"]:
        violations.append("p99_ms above ceiling")
    if floors["shed_fraction_max"] is not None \
            and shed > floors["shed_fraction_max"]:
        violations.append("shed_fraction above ceiling")
    evidence["violations"] = violations
    if violations:
        return ("investigate_serve_regression",
                "serving floors violated: " + "; ".join(violations),
                evidence)
    if floors["queries_per_sec_min"] is not None \
            and qps >= floors["queries_per_sec_min"] * SERVE_TIGHTEN_MARGIN:
        return ("tighten_serve_floors",
                f"measured {qps:.3g} q/s clears the current floor "
                f"{floors['queries_per_sec_min']:.3g} by >= "
                f"{SERVE_TIGHTEN_MARGIN - 1:.0%} — adopt the proposed "
                "floors from the gate leg", evidence)
    return ("keep_serve_floors",
            "serving floors met without enough margin to tighten",
            evidence)


VERDICTS: Dict[str, Callable] = {
    "partitioned_vs_default": _v_partitioned_vs_default,
    "pallas_keep_or_delete": _v_pallas_keep_or_delete,
    "halo_vs_dense": _v_halo_vs_dense,
    "async_overlap": _v_async_overlap,
    "ppr_serve_floors": _v_ppr_serve_floors,
}

#: The decision ledger's closed decision -> human action vocabulary.
ACTION_TEXT = {
    "defer": "DEFER — non-binding dry run on fake devices; rerun on "
             "TPU quota to adjudicate",
    "inconclusive": "INCONCLUSIVE — evidence missing or suspect; see "
                    "the verdict reason",
    "flip_partitioned_to_default": "flip the partition-centric layout "
        "to the couple default (engine auto-span; retire the step "
        "form from the headline)",
    "keep_step_default": "keep the step-form couple default; the "
        "partitioned layout did not clear the flip threshold",
    "keep_pallas_kernel": "keep ops/pallas_spmv and its bench leg "
        "(cleared the floor and held against the XLA partitioned leg)",
    "delete_pallas_kernel": "delete ops/pallas_spmv, its bench leg, "
        "and its perf_budgets floor (PTH004: the hand kernel lost to "
        "XLA on real chips)",
    "keep_sparse_halo_default": "keep sparse halo exchange as the "
        "multichip default",
    "prefer_dense_exchange": "flip the multichip default to dense "
        "all-gather exchange at this geometry",
    "flip_halo_async_default": "flip async halo overlap on by default "
        "(parallel plane) and pin the staleness budget",
    "keep_synchronous_exchange": "keep synchronous halo exchange as "
        "the default",
    "tighten_serve_floors": "tighten the ppr_serve floors in "
        "perf_budgets.json to the gate leg's proposed values",
    "keep_serve_floors": "keep the current ppr_serve floors",
    "investigate_serve_regression": "serving floors violated — "
        "investigate the query plane before tightening anything",
}


def extract_verdict(vname: str, leg_name: str, doc, budgets,
                    binding: bool, over_budget: bool) -> Dict[str, object]:
    """Run one extractor and apply the campaign-level overrides: a
    missing artifact or (in a binding run) a blown wall budget forces
    ``inconclusive``; a non-binding run forces ``defer`` and demotes
    the measured would-be decision into the evidence block."""
    if doc is None:
        decision, reason, evidence = (
            "inconclusive", f"leg {leg_name} produced no artifact", {})
    else:
        decision, reason, evidence = VERDICTS[vname](
            _get(doc, "output"), budgets)
        if over_budget and binding:
            decision = "inconclusive"
            reason = (f"leg {leg_name} exceeded its wall budget; its "
                      "measurements are suspect and do not bind")
    if not binding:
        evidence = dict(evidence)
        evidence["would_decide"] = decision
        evidence["would_reason"] = reason
        decision, reason = "defer", NONBINDING_REASON
    return {"verdict": vname, "binding": binding, "decision": decision,
            "reason": reason, "evidence": evidence}


# -- runner ------------------------------------------------------------------


class CampaignRunner:
    """Execute a :class:`CampaignSpec` through the job-plane
    machinery: checksummed per-leg artifacts, an atomic advisory
    manifest, seeded process-kill chaos, drain checks at leg
    boundaries, and resume-by-artifact-validation."""

    def __init__(self, directory: str, spec: CampaignSpec,
                 fake_devices: int = 0,
                 budgets_path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.directory = directory
        self.spec = spec
        self.fake_devices = int(fake_devices)
        self.budgets_path = budgets_path or default_budgets_path()
        self.clock = clock
        self.docs: Dict[str, Dict] = {}
        self.metas: Dict[str, Dict] = {}
        fsio.makedirs(directory)
        self.manifest = self._load_or_init_manifest()
        # Seeded process-kill chaos (testing/faults.py): active only
        # when the env plan is set — zero cost otherwise. Leg names
        # are the chaos stages.
        from pagerank_tpu.testing.faults import ProcessKillPlan

        self.chaos = ProcessKillPlan.from_env()

    # -- manifest ------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    @property
    def report_path(self) -> str:
        return os.path.join(self.directory, REPORT_NAME)

    def _load_or_init_manifest(self) -> Dict:
        try:
            with open(self.manifest_path) as f:
                m = json.load(f)
        except (OSError, json.JSONDecodeError):
            m = None
        if isinstance(m, dict) and m.get("kind") == "campaign":
            m["resumes"] = int(m.get("resumes", 0)) + 1
            m["status"] = "running"
            # The spec is re-stamped every run: artifact keys (not the
            # manifest) decide what survives a spec edit.
            m["spec"] = self.spec.to_doc()
            m["fake_devices"] = self.fake_devices
            m.setdefault("legs", {})
        else:
            m = {
                "schema_version": SCHEMA_VERSION,
                "kind": "campaign",
                "campaign": self.spec.name,
                "created_unix": time.time(),
                "resumes": 0,
                "status": "running",
                "fake_devices": self.fake_devices,
                "spec": self.spec.to_doc(),
                "legs": {},
            }
        return m

    def _write_manifest(self) -> None:
        with fsio.atomic_write(self.manifest_path, "w",
                               suffix=".tmp") as f:
            json.dump(self.manifest, f, indent=2, sort_keys=True)
            f.write("\n")

    def _set_leg(self, name: str, **fields) -> None:
        leg = self.manifest["legs"].setdefault(name, {})
        leg.update(fields)
        self._write_manifest()

    # -- artifacts -----------------------------------------------------------

    def leg_key(self, leg: LegSpec) -> str:
        return jobs.key_hash({
            "campaign": self.spec.name,
            "leg": leg.name,
            "entrypoint": leg.entrypoint,
            "params": leg.params,
            "fake_devices": self.fake_devices,
            "schema": SCHEMA_VERSION,
        })

    def artifact_path(self, idx: int, leg: LegSpec) -> str:
        return os.path.join(self.directory,
                            f"leg_{idx:02d}_{leg.name}.npz")

    def _try_resume_leg(self, idx: int, leg: LegSpec) -> Optional[Dict]:
        """A validated artifact with the expected key IS the leg —
        checksum + key mismatch both mean recompute, never trust."""
        path = self.artifact_path(idx, leg)
        try:
            arrays, meta = jobs.load_artifact(path)
        except FileNotFoundError:
            return None
        except jobs.ArtifactCorruptError:
            return None
        if meta.get("leg") != leg.name \
                or meta.get("key") != self.leg_key(leg):
            return None
        doc = jobs.doc_from_arrays(arrays)
        if doc is None:
            return None
        self.metas[leg.name] = meta
        return doc

    # -- execution -----------------------------------------------------------

    def run(self, drain=None,
            progress: Optional[Callable[[str], None]] = None) -> Dict:
        """Run (or resume) the campaign. Raises jobs.DrainInterrupt
        out of a SIGTERM drain at the next leg boundary — the caller
        (obs/__main__) owns the exit-75 translation."""
        say = progress or (lambda line: None)
        ctx = {"dir": self.directory, "docs": self.docs,
               "fake_devices": self.fake_devices,
               "budgets_path": self.budgets_path}
        failed = False
        for idx, leg in enumerate(self.spec.legs):
            if drain is not None:
                drain.check(f"campaign/{leg.name}")
            resumed = self._try_resume_leg(idx, leg)
            if resumed is not None:
                self.docs[leg.name] = resumed
                self._set_leg(leg.name, status="done", skipped=True)
                say(f"campaign: leg {leg.name} — validated artifact, "
                    "skipping")
                continue
            warnings: List[str] = []
            blocked = None
            for pc in leg.preconditions:
                ok, reason = PRECONDITIONS[pc](self.docs)
                if ok:
                    continue
                if self.fake_devices:
                    warnings.append(
                        f"{pc}: {reason} (non-binding dry run: leg "
                        "runs anyway)")
                else:
                    blocked = f"{pc}: {reason}"
                    break
            if blocked is not None:
                self._set_leg(leg.name, status="blocked", skipped=False,
                              error=blocked, warnings=warnings)
                say(f"campaign: leg {leg.name} BLOCKED — {blocked}")
                failed = True
                continue
            self._set_leg(leg.name, status="running", skipped=False,
                          warnings=warnings)
            say(f"campaign: leg {leg.name} — running "
                f"({leg.entrypoint} {leg.params})")
            if self.chaos is not None:
                self.chaos.check(leg.name)
            t0 = self.clock()
            try:
                doc = ENTRYPOINTS[leg.entrypoint](leg.params, ctx)
            except jobs.DrainInterrupt:
                raise
            except (Exception, SystemExit) as e:
                self._set_leg(leg.name, status="failed",
                              error=repr(e), wall_s=self.clock() - t0)
                say(f"campaign: leg {leg.name} FAILED — {e!r}")
                failed = True
                continue
            wall = self.clock() - t0
            meta = {
                "leg": leg.name,
                "key": self.leg_key(leg),
                "wall_s": wall,
                "budget_s": leg.budget_s,
                "over_budget": wall > leg.budget_s,
                "fake_devices": self.fake_devices,
            }
            jobs.save_artifact(self.artifact_path(idx, leg),
                               jobs.doc_to_arrays(doc), meta)
            self.docs[leg.name] = doc
            self.metas[leg.name] = meta
            self._set_leg(leg.name, status="done", skipped=False,
                          wall_s=wall, over_budget=meta["over_budget"])
            say(f"campaign: leg {leg.name} done in {wall:.1f}s"
                + (" (OVER BUDGET)" if meta["over_budget"] else ""))
        self.manifest["status"] = "failed" if failed else "complete"
        self._write_manifest()
        return self.docs

    def interrupt(self, where: str) -> None:
        """SIGTERM drain landed: record it without downgrading any
        completed leg — the artifacts already on disk are the truth
        resume trusts."""
        self.manifest["status"] = "interrupted"
        self.manifest["interrupted_at"] = where
        self._write_manifest()

    def write_report(self, budgets=None) -> Dict:
        """Render + atomically persist the STABLE report (canonical
        bytes — the resume byte-identity contract)."""
        from pagerank_tpu.obs import report as report_mod

        if budgets is None:
            budgets = _load_budgets_quiet(self.budgets_path)
        rep = build_report(self.spec, self.manifest, self.docs,
                           self.metas, budgets)
        with fsio.atomic_write(self.report_path, "w",
                               suffix=".tmp") as f:
            f.write(report_mod.canonical_json(rep))
        return rep


def _load_budgets_quiet(path: Optional[str]):
    if not path:
        return None
    from pagerank_tpu.obs import history

    try:
        return history.load_budgets(path)
    except (OSError, ValueError, json.JSONDecodeError):
        return None


# -- report ------------------------------------------------------------------


def load_campaign(directory: str):
    """Rebuild (spec, manifest, docs, metas) from a campaign dir —
    report/status never re-run anything. Raises FileNotFoundError
    when the directory holds no campaign manifest."""
    path = os.path.join(directory, MANIFEST_NAME)
    with open(path) as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict) \
            or manifest.get("kind") != "campaign":
        raise ValueError(f"{path} is not a campaign manifest")
    spec = CampaignSpec.from_doc(manifest.get("spec") or {})
    docs: Dict[str, Dict] = {}
    metas: Dict[str, Dict] = {}
    for idx, leg in enumerate(spec.legs):
        apath = os.path.join(directory,
                             f"leg_{idx:02d}_{leg.name}.npz")
        try:
            arrays, meta = jobs.load_artifact(apath)
        except (FileNotFoundError, jobs.ArtifactCorruptError):
            continue
        if meta.get("leg") != leg.name:
            continue
        doc = jobs.doc_from_arrays(arrays)
        if doc is None:
            continue
        docs[leg.name] = doc
        metas[leg.name] = meta
    return spec, manifest, docs, metas


def build_report(spec: CampaignSpec, manifest: Dict, docs: Dict,
                 metas: Dict, budgets=None,
                 full: bool = False) -> Dict:
    """The campaign report. The stable form (full=False) is a pure
    function of spec identity + leg statuses + verdict DECISIONS —
    no walls, no timestamps, no resume counts, and (non-binding) no
    measured numbers — so resumed and uninterrupted dry runs render
    byte-identical documents. ``full`` adds the volatile evidence:
    per-verdict measurements, per-leg walls, and the raw leg docs."""
    binding = not manifest.get("fake_devices")
    leg_states = manifest.get("legs") or {}
    legs_out = []
    verdicts: Dict[str, Dict] = {}
    for leg in spec.legs:
        st = leg_states.get(leg.name) or {}
        meta = metas.get(leg.name) or {}
        over = bool(meta.get("over_budget", False))
        legs_out.append({
            "name": leg.name,
            "entrypoint": leg.entrypoint,
            "status": st.get("status", "pending"),
            "within_budget": not over,
            "warnings": list(st.get("warnings") or []),
        })
        for vname in leg.verdicts:
            verdicts[vname] = extract_verdict(
                vname, leg.name, docs.get(leg.name), budgets,
                binding, over)
    complete = bool(legs_out) and all(
        e["status"] == "done" for e in legs_out)
    ledger = [f"[{v['verdict']}] {ACTION_TEXT[v['decision']]}"
              for v in (verdicts[k] for k in sorted(verdicts))]
    rep: Dict[str, object] = {
        "schema_version": SCHEMA_VERSION,
        "kind": "campaign_report",
        "campaign": spec.name,
        "binding": binding,
        "fake_devices": int(manifest.get("fake_devices") or 0),
        "complete": complete,
        "legs": legs_out,
        "verdicts": {
            k: {f: v[f] for f in ("verdict", "binding", "decision",
                                  "reason")}
            for k, v in verdicts.items()
        },
        "decision_ledger": ledger,
    }
    if binding:
        # The proposed perf_budgets diff (satellite: gate
        # --propose-budgets shares the derivation) — measured numbers,
        # so binding reports only.
        changes = _get(docs.get("history_gate"), "output", "proposal",
                       "changes")
        rep["budget_proposal"] = {"changes": changes or []}
    if full:
        rep["evidence"] = {k: v["evidence"]
                           for k, v in verdicts.items()}
        rep["measured"] = {
            name: {"wall_s": meta.get("wall_s"),
                   "budget_s": meta.get("budget_s"),
                   "over_budget": meta.get("over_budget")}
            for name, meta in metas.items()
        }
        rep["resumes"] = manifest.get("resumes")
        rep["status"] = manifest.get("status")
        rep["leg_docs"] = docs
    return rep


def render_report(rep: Dict) -> str:
    """Human rendering of a campaign report: leg table + verdict
    table + the decision ledger."""
    lines = [
        f"campaign {rep.get('campaign')} — "
        + ("BINDING" if rep.get("binding") else
           f"non-binding dry run ({rep.get('fake_devices')} fake "
           "devices)")
        + (", complete" if rep.get("complete") else ", INCOMPLETE"),
    ]
    for leg in rep.get("legs") or []:
        mark = {"done": "ok", "failed": "FAILED",
                "blocked": "BLOCKED", "running": "running",
                "pending": "pending"}.get(leg.get("status"),
                                          str(leg.get("status")))
        lines.append(
            f"  leg {leg.get('name'):<16} {mark:<8}"
            + ("" if leg.get("within_budget", True)
               else " OVER BUDGET"))
        for w in leg.get("warnings") or []:
            lines.append(f"       warning: {w}")
    lines.append("verdicts:")
    for name in sorted(rep.get("verdicts") or {}):
        v = rep["verdicts"][name]
        lines.append(f"  {name:<24} -> {v.get('decision')}"
                     f" ({v.get('reason')})")
    lines.append("decision ledger:")
    for entry in rep.get("decision_ledger") or []:
        lines.append(f"  {entry}")
    changes = (rep.get("budget_proposal") or {}).get("changes")
    if changes:
        lines.append("proposed perf_budgets.json changes:")
        for c in changes:
            lines.append(
                f"  {c.get('leg')}/{c.get('metric')} {c.get('bound')}: "
                f"{c.get('old')} -> {c.get('new')} "
                f"(median {c.get('median')}, n={c.get('n')})")
    return "\n".join(lines)


def render_status(manifest: Dict) -> str:
    lines = [
        f"campaign {manifest.get('campaign')}: "
        f"{manifest.get('status')} "
        f"(resumes {manifest.get('resumes', 0)}, fake_devices "
        f"{manifest.get('fake_devices', 0)})",
    ]
    spec = manifest.get("spec") or {}
    states = manifest.get("legs") or {}
    for leg in spec.get("legs") or []:
        st = states.get(leg.get("name")) or {}
        extra = ""
        if st.get("wall_s") is not None:
            extra = f" ({st['wall_s']:.1f}s"
            extra += (" OVER BUDGET)" if st.get("over_budget")
                      else ")")
        if st.get("skipped"):
            extra += " [resumed: validated artifact]"
        if st.get("error"):
            extra += f" — {st['error']}"
        lines.append(f"  {leg.get('name'):<16} "
                     f"{st.get('status', 'pending'):<9}{extra}")
    return "\n".join(lines)
