"""pagerank_tpu.obs — the unified observability layer (ISSUE 4;
docs/OBSERVABILITY.md).

Three pieces, one subsystem:

  - **span tracing** (obs/trace.py): nested context-manager spans over
    every layer — ingest, device build, engine setup/compile,
    solve, snapshot I/O — exportable as JSONL or Chrome trace-event
    JSON (Perfetto). A process-global default tracer that is a NO-OP
    unless enabled, so the hot path pays nothing when off.
  - **metrics registry** (obs/metrics.py): typed counters / gauges /
    histograms in one place; the formerly scattered counters (S3
    request retries, health-check failures, rollbacks, dead-letters,
    compile-cache hits/misses, snapshot bytes) all register here.
  - **run flight-recorder** (obs/report.py): ``run_report.json`` per
    run — environment fingerprint, resolved config, span summary,
    registry snapshot, per-iteration history, robustness summary —
    with ``python -m pagerank_tpu.obs report A.json [B.json]`` to
    render one or diff two.

ISSUE 5 adds the live/predictive half:

  - **cost accounting** (obs/costs.py): XLA's own ``cost_analysis`` /
    ``memory_analysis`` per compiled dispatch form — FLOPs, HBM bytes,
    peak allocation, bytes-per-edge, achieved-vs-roofline;
  - **convergence probes** (obs/probes.py): opt-in in-loop L1
    residual / rank mass / top-k churn, computed on device inside the
    step (contract PTC007);
  - **live monitoring** (obs/live.py): a zero-dependency Prometheus
    text exporter (atomic textfile + HTTP endpoint) and the stall
    watchdog that makes hung collectives loud.

ISSUE 9 adds the longitudinal half — the **perf-regression sentry**
(obs/history.py): a canonical RunRecord ledger over every bench /
MULTICHIP / run-report artifact, robust (median+MAD) per-(leg, metric)
baselines with program-change vs env-drift attribution, and the CI
gate ``python -m pagerank_tpu.obs history ingest|trend|gate``.

ISSUE 10 adds the **device plane** (obs/devices.py): the structured
per-device HBM sampler (``device.<id>.*`` gauges, per-device Chrome
counter tracks, the run report's OOM-forensics watermark),
comms-vs-compute wall attribution for the sharded step
(``comms.exchange_fraction`` / ``comms.achieved_bytes_per_sec``), and
the OOM-preflight fit check (``python -m pagerank_tpu.obs fit``).

ISSUE 11 adds the **compiler plane** (obs/hlo.py): optimized-HLO
lowering inspection per compiled dispatch form — gather-strategy
classification (native vs while-loop/scalar expansion, the "fast
gather defeated" signature), fusion/collective structure, bf16-stream
verification, an HLO-derived traffic estimate reconciled against the
analytic cost model, and a lowering fingerprint carried through the
perf-history ledger. Surfaced via ``engine.lowering_reports()``,
bench/CLI ``--dump-hlo``, contracts PTH001-003, and
``python -m pagerank_tpu.obs hlo``.

ISSUE 13 adds the **data plane** (obs/graph_profile.py): the graph
itself as telemetry — on-device structural profiling during the
build (log2 degree histograms, dedup/self-loop counts, hub ids,
partition-skew geometry, a power-law tail estimate), the rank-mass
conservation LEDGER riding the convergence probes (link / teleport /
dangling decomposition with a named leak location), and skew-driven
load prediction (parallel/comms.predict_from_profile: per-device
imbalance + halo head-K predicted BEFORE any build). Surfaced via
``python -m pagerank_tpu.obs graph``, CLI ``--graph-profile``, the
run report's ``graph`` section (diffed FIRST as data drift), bench
legs' ``graph`` blocks, and per-leg profile scalars in the perf
ledger (a data change gates distinctly from a program or env change).

Plus :func:`profiler_session` (obs/profiler.py), the jax.profiler
lifecycle as a tracer-composed context manager, and :mod:`obs.log`,
the sanctioned stderr channel for library diagnostics (lint PTL007).

Import cost: stdlib only (jax is imported lazily inside the functions
that need it), so any utils module can depend on obs without cycles.
"""

from pagerank_tpu.obs import costs, devices, graph_profile, history, hlo
from pagerank_tpu.obs.devices import (
    DeviceSampler,
    arm_sampler,
    disarm_sampler,
    get_sampler,
)
from pagerank_tpu.obs.live import (
    HistoryBaseline,
    MetricsExporter,
    StallWatchdog,
    arm_history_baseline,
    arm_watchdog,
    disarm_history_baseline,
    disarm_watchdog,
    get_history_baseline,
    get_watchdog,
    render_prometheus,
)
from pagerank_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from pagerank_tpu.obs.probes import ConvergenceProbes
from pagerank_tpu.obs.profiler import profiler_session
from pagerank_tpu.obs.report import (
    build_run_report,
    diff_reports,
    environment_fingerprint,
    load_report,
    render_report,
    write_run_report,
)
from pagerank_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    Span,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
    span,
)

__all__ = [
    "costs",
    "devices",
    "graph_profile",
    "history",
    "hlo",
    "DeviceSampler",
    "arm_sampler",
    "disarm_sampler",
    "get_sampler",
    "HistoryBaseline",
    "MetricsExporter",
    "StallWatchdog",
    "arm_history_baseline",
    "arm_watchdog",
    "disarm_history_baseline",
    "disarm_watchdog",
    "get_history_baseline",
    "get_watchdog",
    "render_prometheus",
    "ConvergenceProbes",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "profiler_session",
    "build_run_report",
    "diff_reports",
    "environment_fingerprint",
    "load_report",
    "render_report",
    "write_run_report",
    "NULL_TRACER",
    "NullTracer",
    "Span",
    "Tracer",
    "disable_tracing",
    "enable_tracing",
    "get_tracer",
    "span",
]
