"""Span tracing — the structural half of the observability layer
(docs/OBSERVABILITY.md).

The reference's entire observability is one println per iteration
(Sparky.java:188); partition-centric PageRank work (Lakhotia et al.,
arXiv:1709.07122, PAPERS.md) shows per-stage timing ATTRIBUTION is what
drives the next optimisation. This module is the attribution substrate:
a zero-dependency :class:`Tracer` whose nested context-manager spans
(``with tracer.span("build/sort"):``) record wall time, attributes and
parent/child structure, exportable as JSONL or Chrome trace-event JSON
(loadable in Perfetto / ``chrome://tracing``).

Design constraints, in priority order:

  1. **The hot path pays nothing when tracing is off.** The process
     default is :data:`NULL_TRACER` (``enabled`` False); its ``span()``
     returns ONE shared no-op context manager (no allocation, no
     recording), and per-iteration call sites gate on ``.enabled`` so a
     production solve makes zero tracer-induced host calls per
     iteration (tests/test_obs.py::test_noop_tracer_hot_path).
  2. **Thread-correct nesting.** The AsyncRankWriter worker records
     spans concurrently with the solve loop; span stacks are
     thread-local and the finished-span list is lock-protected, so
     parent/child linkage never crosses threads.
  3. **One timebase.** Spans are measured on ``time.perf_counter``
     relative to the tracer's epoch; the epoch's wall-clock
     (``time.time``) is exported once in the trace header so tools can
     anchor absolute time without per-span clock mixing.

Naming scheme (docs/OBSERVABILITY.md): ``layer/stage`` with ``/`` as
the hierarchy separator — ``ingest/edgelist``, ``build/sort``,
``engine/compile``, ``solve/step``, ``snapshot/save``,
``writer/queue_wait``, ``retry/attempt``, ``profile``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from pagerank_tpu.utils import fsio


class Span:
    """One finished (or live) span. ``start``/``duration`` are seconds
    on the owning tracer's perf_counter timebase (relative to its
    epoch); ``attrs`` is a plain JSON-able dict.

    ``trace_id`` / ``links`` are the cross-thread half (ISSUE 19):
    spans opened by handle (:meth:`Tracer.start_span`) can belong to a
    logical trace that hops threads — one served query's causal
    timeline — and link to spans of OTHER traces (batch membership).
    Both stay None on the classic context-manager path, so the
    existing export shapes are byte-identical for untouched callers.
    """

    __slots__ = ("span_id", "name", "start", "duration", "parent_id",
                 "tid", "attrs", "trace_id", "links")

    def __init__(self, span_id: int, name: str, start: float,
                 parent_id: Optional[int], tid: int, attrs: dict,
                 trace_id: Optional[str] = None,
                 links: Optional[List[str]] = None):
        self.span_id = span_id
        self.name = name
        self.start = start
        self.duration = 0.0
        self.parent_id = parent_id
        self.tid = tid
        self.attrs = attrs
        self.trace_id = trace_id
        self.links = links

    @property
    def end(self) -> float:
        return self.start + self.duration

    def to_json(self) -> dict:
        out = {
            "type": "span",
            "id": self.span_id,
            "name": self.name,
            "start_s": self.start,
            "duration_s": self.duration,
            "parent": self.parent_id,
            "tid": self.tid,
            "attrs": self.attrs,
        }
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.links:
            out["links"] = list(self.links)
        return out


class _SpanCm:
    """The live-span context manager. Yields the :class:`Span` so the
    body can attach attributes (``sp.attrs["bytes"] = n``); records the
    span on exit. On an exception the span is still recorded, with
    ``error`` set to the exception type — a failing stage is exactly
    the one the trace must show."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._tracer._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._span.attrs["error"] = exc_type.__name__
        self._tracer._pop(self._span)
        return False


class _NullCm:
    """The shared no-op context manager NULL_TRACER.span() returns:
    nothing is allocated or recorded, and the body receives None (call
    sites that attach attributes must gate on ``tracer.enabled``)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullCm()


class NullTracer:
    """Disabled tracer — the process default. Every operation is a
    no-op; ``span()`` returns one shared context manager so the
    disabled path allocates nothing."""

    enabled = False

    def span(self, name: str, **attrs):
        return _NULL_CM

    def start_span(self, name: str, parent=None,
                   trace_id: Optional[str] = None,
                   tid: Optional[int] = None,
                   start_s: Optional[float] = None,
                   links: Optional[List[str]] = None, **attrs):
        return None

    def finish_span(self, span, end_s: Optional[float] = None) -> None:
        pass

    def set_thread_label(self, tid: int, label: str) -> None:
        pass

    def add_span(self, name: str, start_pc: float, duration: float,
                 **attrs) -> None:
        pass

    def add_event(self, name: str, **attrs) -> None:
        pass

    def add_counter(self, name: str, values: Dict[str, float],
                    track: Optional[int] = None,
                    track_label: Optional[str] = None) -> None:
        pass

    def spans(self) -> List[Span]:
        return []

    def events(self) -> List[dict]:
        return []

    def counters(self) -> List[dict]:
        return []

    def summary(self) -> Dict[str, dict]:
        return {}


NULL_TRACER = NullTracer()


class Tracer:
    """Recording tracer: nested context-manager spans with thread-local
    stacks, instant events, aggregation, and JSONL / Chrome trace-event
    export.

    ``max_spans`` bounds retention: when set, finished spans live in a
    ring (oldest dropped first) instead of an unbounded list — the mode
    long-running captures (the serving daemon's ``--query-trace``) use
    so an armed tracer cannot grow memory without bound with query
    count. Solver runs are finite, so the default stays unbounded and
    exports every span."""

    enabled = True

    def __init__(self, max_spans: Optional[int] = None):
        self.epoch_pc = time.perf_counter()
        self.epoch_unix = time.time()
        self._lock = threading.Lock()
        self._spans = (deque(maxlen=int(max_spans))
                       if max_spans else [])
        self._events: List[dict] = []
        self._counters: List[dict] = []
        self._track_labels: Dict[int, str] = {}
        self._thread_labels: Dict[int, str] = {}
        self._local = threading.local()
        self._next_id = 0

    # -- recording --------------------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def span(self, name: str, **attrs) -> _SpanCm:
        """Open a nested span; use as ``with tracer.span("build/sort",
        edges=m) as sp:``. Parent is the innermost live span on THIS
        thread."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        sp = Span(self._new_id(), name,
                  time.perf_counter() - self.epoch_pc, parent,
                  threading.get_ident(), dict(attrs))
        return _SpanCm(self, sp)

    def _push(self, sp: Span) -> None:
        self._stack().append(sp)

    def _pop(self, sp: Span) -> None:
        sp.duration = (time.perf_counter() - self.epoch_pc) - sp.start
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        else:  # defensive: out-of-order exit must not corrupt linkage
            try:
                stack.remove(sp)
            except ValueError:
                pass
        with self._lock:
            self._spans.append(sp)

    # -- explicit span handles (ISSUE 19: cross-thread traces) -------------

    def start_span(self, name: str, parent=None,
                   trace_id: Optional[str] = None,
                   tid: Optional[int] = None,
                   start_s: Optional[float] = None,
                   links: Optional[List[str]] = None, **attrs) -> Span:
        """Open a span BY HANDLE, parented explicitly instead of by the
        thread-local stack — the primitive that lets one logical trace
        cross the ingress -> admission -> dispatch -> response thread
        hops (the serving query plane). ``parent`` is a Span or a span
        id (None = root); ``tid`` pins the Chrome lane (default: the
        calling thread); ``start_s`` is an explicit start on the
        tracer's epoch timebase for pre-measured phases (default: now).
        The handle is NOT pushed on any thread-local stack — nested
        ``span()`` context managers on this thread are unaffected.
        Finish with :meth:`finish_span`."""
        if parent is not None and isinstance(parent, Span):
            parent = parent.span_id
        sp = Span(
            self._new_id(), name,
            (time.perf_counter() - self.epoch_pc
             if start_s is None else float(start_s)),
            parent,
            threading.get_ident() if tid is None else int(tid),
            dict(attrs),
            trace_id=trace_id,
            links=list(links) if links else None,
        )
        return sp

    def finish_span(self, span: Span,
                    end_s: Optional[float] = None) -> None:
        """Record a handle opened by :meth:`start_span`; ``end_s`` is
        an explicit end on the epoch timebase (default: now). Safe from
        any thread — the handle carries its own parentage."""
        end = (time.perf_counter() - self.epoch_pc
               if end_s is None else float(end_s))
        span.duration = max(0.0, end - span.start)
        with self._lock:
            self._spans.append(span)

    def set_thread_label(self, tid: int, label: str) -> None:
        """Name one tid's lane in the Chrome export (a ``thread_name``
        metadata event) — the per-thread lanes of the serving trace
        (ingress / dispatch / harness)."""
        with self._lock:
            self._thread_labels.setdefault(int(tid), label)

    def add_span(self, name: str, start_pc: float, duration: float,
                 **attrs) -> None:
        """Record a PRE-MEASURED span from raw ``time.perf_counter``
        readings — for stages whose timing already exists (the device
        build's fenced stage walls) so the measurement is made once and
        the trace is a faithful view of it, never a second clock."""
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        sp = Span(self._new_id(), name, start_pc - self.epoch_pc, parent,
                  threading.get_ident(), dict(attrs))
        sp.duration = duration
        with self._lock:
            self._spans.append(sp)

    def add_event(self, name: str, **attrs) -> None:
        """Record an instant event (Chrome ``ph: "i"``) — log lines,
        retries, rollbacks."""
        ev = {
            "type": "event",
            "name": name,
            "ts_s": time.perf_counter() - self.epoch_pc,
            "tid": threading.get_ident(),
            "attrs": attrs,
        }
        with self._lock:
            self._events.append(ev)

    def add_counter(self, name: str, values: Dict[str, float],
                    track: Optional[int] = None,
                    track_label: Optional[str] = None) -> None:
        """Record a sampled counter point (Chrome ``ph: "C"``) — the
        per-device HBM tracks (ISSUE 10; obs/devices.DeviceSampler).
        ``track`` pins the sample to its own pid lane in the Chrome
        export so Perfetto renders one counter track PER DEVICE
        instead of mixing every chip into the process row;
        ``track_label`` names the lane once (a ``process_name``
        metadata event)."""
        rec = {
            "type": "counter",
            "name": name,
            "ts_s": time.perf_counter() - self.epoch_pc,
            "track": track,
            "values": dict(values),
        }
        with self._lock:
            self._counters.append(rec)
            if track is not None and track_label:
                self._track_labels.setdefault(track, track_label)

    # -- views ------------------------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def counters(self) -> List[dict]:
        with self._lock:
            return list(self._counters)

    def summary(self) -> Dict[str, dict]:
        """Per-name aggregation (count / total / mean / max seconds),
        ordered by total wall descending — the span-tree summary the
        run flight-recorder embeds. Names are hierarchical by the
        ``layer/stage`` convention, so sorting by name prefix recovers
        the tree."""
        agg: Dict[str, dict] = {}
        for sp in self.spans():
            a = agg.setdefault(
                sp.name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            a["count"] += 1
            a["total_s"] += sp.duration
            a["max_s"] = max(a["max_s"], sp.duration)
        for a in agg.values():
            a["mean_s"] = a["total_s"] / a["count"]
        return dict(
            sorted(agg.items(), key=lambda kv: -kv[1]["total_s"])
        )

    def timings_view(self, prefix: str = "build/") -> Dict[str, float]:
        """Total seconds per stage under ``prefix``, keyed the
        historical ``{stage}_s`` way — the --build-only breakdown as a
        VIEW over the trace (ops/device_build fills its ``timings``
        dict from the very same fence measurements)."""
        out: Dict[str, float] = {}
        for sp in self.spans():
            if sp.name.startswith(prefix):
                key = sp.name[len(prefix):] + "_s"
                out[key] = out.get(key, 0.0) + sp.duration
        return out

    # -- export -----------------------------------------------------------

    def _header(self) -> dict:
        return {
            "type": "trace_header",
            "schema_version": 1,
            "epoch_unix": self.epoch_unix,
            "pid": os.getpid(),
        }

    def export_jsonl(self, path: str) -> None:
        """One JSON object per line: a trace_header, then every span and
        instant event. Strict JSON (no NaN/Infinity) by construction —
        durations are finite perf_counter differences."""
        with fsio.fopen(path, "w") as f:
            f.write(json.dumps(self._header()) + "\n")
            for sp in self.spans():
                f.write(json.dumps(sp.to_json()) + "\n")
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
            for c in self.counters():
                f.write(json.dumps(c) + "\n")

    def chrome_events(self) -> List[dict]:
        """Chrome trace-event list: complete ("X") events for spans,
        instant ("i") events for events. ``ts``/``dur`` are MICROSECONDS
        (the format's unit), pid/tid integers."""
        pid = os.getpid()
        out = []
        for sp in self.spans():
            args = sp.attrs
            if sp.trace_id is not None or sp.links:
                args = dict(sp.attrs)
                if sp.trace_id is not None:
                    args["trace_id"] = sp.trace_id
                if sp.links:
                    args["links"] = list(sp.links)
            out.append({
                "name": sp.name,
                "cat": sp.name.split("/", 1)[0],
                "ph": "X",
                "ts": sp.start * 1e6,
                "dur": sp.duration * 1e6,
                "pid": pid,
                "tid": sp.tid,
                "args": args,
            })
        for ev in self.events():
            out.append({
                "name": ev["name"],
                "cat": ev["name"].split("/", 1)[0],
                "ph": "i",
                "ts": ev["ts_s"] * 1e6,
                "pid": pid,
                "tid": ev["tid"],
                "s": "t",
                "args": ev["attrs"],
            })
        # Counter samples: tracked counters (per-device HBM) render on
        # their OWN pid lane, named once by a process_name metadata
        # event, so Perfetto shows one track per device; untracked
        # counters ride the process pid.
        with self._lock:
            labels = dict(self._track_labels)
            thread_labels = dict(self._thread_labels)
        for track, label in sorted(labels.items()):
            out.append({
                "name": "process_name",
                "ph": "M",
                "pid": track,
                "args": {"name": label},
            })
        # Thread lanes: set_thread_label names a tid's row (the serving
        # trace's ingress / dispatch / harness lanes).
        for tid, label in sorted(thread_labels.items()):
            out.append({
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": label},
            })
        for c in self.counters():
            out.append({
                "name": c["name"],
                "cat": c["name"].split("/", 1)[0].split(".", 1)[0],
                "ph": "C",
                "ts": c["ts_s"] * 1e6,
                "pid": c["track"] if c["track"] is not None else pid,
                "args": c["values"],
            })
        return out

    def export_chrome(self, path: str) -> None:
        """Write the Chrome trace-event JSON object form (Perfetto /
        ``chrome://tracing`` load it directly)."""
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "metadata": {"epoch_unix": self.epoch_unix},
        }
        with fsio.fopen(path, "w") as f:
            json.dump(doc, f)

    def export(self, path: str) -> None:
        """Dispatch on extension: ``.jsonl`` -> JSONL, anything else ->
        Chrome trace-event JSON."""
        if path.endswith(".jsonl"):
            self.export_jsonl(path)
        else:
            self.export_chrome(path)


# -- process-global default tracer -----------------------------------------

_TRACER = NULL_TRACER


def get_tracer():
    """The process-global tracer — NULL_TRACER unless
    :func:`enable_tracing` installed a recording one."""
    return _TRACER


def enable_tracing(tracer: Optional[Tracer] = None) -> Tracer:
    """Install (and return) a recording tracer as the process default.
    Instrumented call sites across the package pick it up on their next
    ``get_tracer()`` read."""
    global _TRACER
    _TRACER = tracer if tracer is not None else Tracer()
    return _TRACER


def disable_tracing():
    """Restore the no-op default; returns the tracer that was active
    (so a caller can still export what it recorded)."""
    global _TRACER
    prev = _TRACER
    _TRACER = NULL_TRACER
    return prev


def span(name: str, **attrs):
    """Convenience: a span on the CURRENT process-global tracer (no-op
    context manager when tracing is disabled)."""
    return _TRACER.span(name, **attrs)
