"""Compiler-plane observability (ISSUE 11): optimized-HLO inspection.

The two staged perf wins (the partition-centric restage and the sparse
halo) are gated on one documented unknown — PERF_NOTES records that an
in-body ``dynamic_slice`` table once lost XLA's fast-gather lowering,
and the partitioned window is exactly an in-body dynamic slice. Until
now the only instrument that could answer "did the compiler do what
the cost model assumes" was a TPU wall-clock. This module is the
missing third plane of the obs stack (perf history → device plane →
**compiler plane**): it harvests the OPTIMIZED HLO of every compiled
dispatch form (``compiled.as_text()`` via the ``utils/jax_compat``
degrade-to-None shim) and parses it into a typed
:class:`LoweringReport` —

  - **op histogram** + fusion/while counts of the scheduled module;
  - **gather-strategy classification**: ``native`` (a real ``gather``
    op carries the hot traffic), ``expanded`` (the while-loop /
    scalar-dynamic-slice emulation — the exact "fast gather defeated"
    signature), or ``none``;
  - the **hot gather's** facts: output size, table operand dtype and
    the NARROWEST float dtype in its operand chain (``bf16`` there is
    the mechanical "the bf16 stream actually reaches the gather"
    verification for the ``fast_bf16`` leg), whether it sits inside a
    while body;
  - the **collective multiset** with operand byte widths — the wire
    shape of the program, comparable across jax upgrades;
  - an **entry-schedule traffic estimate** (operand + output bytes of
    every scheduled entry instruction; fusion internals stay in
    registers, so the call-site bytes are the honest HBM proxy),
    reconciled against the analytic obs/costs model as the
    ``cost.<form>.hlo_bytes_per_edge`` gauge;
  - a structural **fingerprint** (op histogram + gather strategy +
    fusion count + collective multiset) carried per leg in the
    perf-history RunRecords, so a jax/libtpu upgrade that changes the
    lowering is attributed as program-change, not noise
    (obs/history.classify_change).

Harvest is LAZY and booby-trapped like the tracer and the device
sampler: the inspector is DISARMED by default, every compile point
guards on :func:`armed` (zero inspector calls, zero extra compiles on
a plain run — tests/test_hlo.py traps every entry point), and arming
reuses the SAME compiled handles the cost-accounting harvest already
holds. Consumers: ``engine.lowering_reports()``, the per-leg
``lowering`` block in bench JSON, the run report's ``lowering``
section (diffed by ``obs report``), contracts PTH001-003
(analysis/contracts.py), and ``python -m pagerank_tpu.obs hlo``.

Import cost: stdlib + obs.metrics/obs.log only (jax stays lazy), so
obs/__init__ re-exports this module without dragging a backend in.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics

#: Bytes per element by HLO dtype token. Extend here if a new dtype
#: ever shows up in a lowering; unknown tokens yield None bytes (an
#: unreported size, never a zero).
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_FLOAT_DTYPES = ("bf16", "f16", "f32", "f64")

#: A gather only counts as the HOT gather when its output reaches this
#: many elements — index fix-ups and probe top-k gathers are not the
#: slot-table traffic the classifier is about.
HOT_GATHER_MIN_ELEMENTS = 128

#: A while loop is an expansion CANDIDATE only past this trip bound:
#: the engine's own chunk scans run tens of trips at contract
#: geometries, while a scalarized gather loops once per index
#: (thousands+). Below the bound a scalar slice is loop bookkeeping.
EXPANSION_MIN_TRIPS = 256

#: "Scalar" for the expansion signature: a float dynamic-slice /
#: dynamic-update-slice moving at most this many elements per trip.
#: The chunk scans' smallest float slices move a full 128-lane row.
SCALAR_SLICE_MAX_ELEMENTS = 8

#: Ops that only re-view or move a buffer — walking the hot gather's
#: table operand back through these finds the dtype the table is
#: actually STREAMED at (the bf16 verification), without crediting
#: recomputation.
_VIEW_OPS = {
    "convert", "bitcast", "copy", "reshape", "slice", "dynamic-slice",
    "pad", "transpose", "broadcast", "get-tuple-element",
}

#: Cross-device collectives as they appear in optimized HLO (the
#: async-pair start forms included; done forms carry no new operands).
_COLLECTIVE_OPS = {
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "all-reduce-start", "all-gather-start",
    "collective-permute-start",
}

#: Entry-schedule opcodes that move no HBM bytes of their own.
_FREE_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
             "bitcast", "iota"}


# -- HLO text parsing --------------------------------------------------------


@dataclass
class HloInstr:
    """One parsed instruction line of an HLO module text."""

    name: str
    opcode: str
    dtype: Optional[str]          # None for tuple-typed results
    shape: Tuple[int, ...]
    #: [(dtype, shape, %name)] per typed operand in source order.
    operands: List[Tuple[Optional[str], Tuple[int, ...], str]]
    attrs: str                    # raw text after the operand list
    computation: str
    #: Integer literal of a scalar ``constant(N)`` — the while-trip
    #: bound extraction reads these off condition computations.
    literal: Optional[int] = None

    @property
    def out_elements(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def out_bytes(self) -> Optional[int]:
        w = DTYPE_BYTES.get(self.dtype or "")
        return None if w is None else w * self.out_elements


@dataclass
class ParsedModule:
    """An HLO module as computations of instructions, plus the call
    edges the expansion detector walks (fusion ``calls=``, while
    ``body=``/``condition=``, reduce ``to_apply=``)."""

    computations: Dict[str, List[HloInstr]] = field(default_factory=dict)
    entry: Optional[str] = None
    calls: Dict[str, List[str]] = field(default_factory=dict)

    def instructions(self):
        for instrs in self.computations.values():
            yield from instrs

    def producer(self, computation: str, name: str) -> Optional[HloInstr]:
        for i in self.computations.get(computation, ()):
            if i.name == name:
                return i
        return None

    def reachable(self, root: str) -> List[str]:
        """Computation names reachable from ``root`` through call
        edges, root included."""
        seen, stack = [], [root]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.append(c)
            stack.extend(self.calls.get(c, ()))
        return seen


_COMP_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(.*\{\s*$")
_TYPE_TOK = r"(?:[a-z]+[0-9]*)\[[0-9,]*\](?:\{[^}]*\})?"
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|" + _TYPE_TOK + r"|[a-z]+[0-9]*\[\])"
    r"\s+([a-z][\w\-]*)\((.*)$"
)
_OPERAND_RE = re.compile(r"(" + _TYPE_TOK + r"|[a-z]+[0-9]*\[\])\s+"
                         r"%([\w.\-]+)")
_CALL_RE = re.compile(r"(?:calls|body|condition|to_apply)=%([\w.\-]+)")


def _parse_type(tok: str) -> Tuple[Optional[str], Tuple[int, ...]]:
    """'f32[4096,128]{1,0}' -> ('f32', (4096, 128)); tuple types ->
    (None, ())."""
    m = re.match(r"([a-z]+[0-9]*)\[([0-9,]*)\]", tok)
    if not m:
        return None, ()
    dims = tuple(int(d) for d in m.group(2).split(",") if d)
    return m.group(1), dims


def _split_operands(rest: str) -> Tuple[str, str]:
    """Split the text after the opening '(' into (operand list, trailing
    attrs) at the matching close paren."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1:]
    return rest, ""


def parse_hlo_text(text: str) -> ParsedModule:
    """Parse one HLO module text (the ``as_text()`` of an optimized /
    scheduled module) into a :class:`ParsedModule`. Tolerant by
    construction: unrecognized lines are skipped — the classifier
    works off what parses, and the degrade path is the caller's."""
    mod = ParsedModule()
    comp = None
    for raw in text.splitlines():
        if not raw.strip() or raw.lstrip().startswith("//"):
            continue
        if not raw.startswith(" "):
            m = _COMP_RE.match(raw.strip())
            if m:
                comp = m.group(2)
                mod.computations.setdefault(comp, [])
                if m.group(1):
                    mod.entry = comp
            continue
        if comp is None:
            continue
        m = _INSTR_RE.match(raw)
        if not m:
            continue
        name, type_tok, opcode, rest = m.groups()
        dtype, shape = _parse_type(type_tok)
        operand_text, attrs = _split_operands(rest)
        operands = [
            (*_parse_type(t), n)
            for t, n in _OPERAND_RE.findall(operand_text)
        ]
        literal = None
        if opcode == "constant":
            lm = re.match(r"\s*(-?\d+)\s*$", operand_text)
            if lm:
                literal = int(lm.group(1))
        instr = HloInstr(name=name, opcode=opcode, dtype=dtype,
                         shape=shape, operands=operands,
                         attrs=attrs, computation=comp, literal=literal)
        mod.computations[comp].append(instr)
        for callee in _CALL_RE.findall(attrs):
            mod.calls.setdefault(comp, []).append(callee)
    return mod


# -- analysis ----------------------------------------------------------------


def op_histogram(mod: ParsedModule) -> Dict[str, int]:
    hist: Dict[str, int] = {}
    for i in mod.instructions():
        hist[i.opcode] = hist.get(i.opcode, 0) + 1
    return hist


def collective_multiset(mod: ParsedModule) -> List[dict]:
    """One record per collective instruction: the op, the widest
    operand's byte count (None when the dtype is unknown), and its
    dtype — the wire shape ``obs report`` / the history fingerprint
    compare across upgrades."""
    out = []
    for i in mod.instructions():
        if i.opcode not in _COLLECTIVE_OPS:
            continue
        best_bytes, best_dtype = None, None
        for dt, shape, _name in i.operands:
            w = DTYPE_BYTES.get(dt or "")
            if w is None:
                continue
            n = 1
            for d in shape:
                n *= d
            b = w * n
            if best_bytes is None or b > best_bytes:
                best_bytes, best_dtype = b, dt
        out.append({"op": i.opcode, "operand_bytes": best_bytes,
                    "dtype": best_dtype})
    return sorted(out, key=lambda r: (r["op"], -(r["operand_bytes"] or 0)))


def _while_trip_bound(mod: ParsedModule, wh: HloInstr) -> Optional[int]:
    """Best-effort trip bound of a while op: the largest integer
    constant in its condition computation (the counter compare's
    bound). None when the condition doesn't parse to one."""
    m = re.search(r"condition=%([\w.\-]+)", wh.attrs)
    if not m:
        return None
    best = None
    for i in mod.computations.get(m.group(1), ()):
        if i.opcode == "constant" and i.literal is not None:
            best = i.literal if best is None else max(best, i.literal)
    return best


def expansion_sites(mod: ParsedModule) -> List[str]:
    """While bodies carrying gather-class traffic as SCALAR float
    dynamic-slices — the emulated-gather lowering (one trip per index,
    a scalar table load + scalar result update each). Returns the body
    computation names; empty = no expansion anywhere.

    A scalarized SCATTER loop (CPU XLA expands scatter-add this way —
    coo's merge at contract geometries) shares the scalar-load +
    scalar-store skeleton but read-modify-writes its target: the
    dynamic-update-slice's destination buffer is ALSO read by a scalar
    dynamic-slice in the same computation. A defeated gather's output
    is write-only inside the loop. Only write-only scalar stores count
    — scatter expansion is a different (and on CPU, expected) lowering,
    not the fast-gather-defeated signature."""
    sites = []
    for wh in mod.instructions():
        if wh.opcode != "while":
            continue
        m = re.search(r"body=%([\w.\-]+)", wh.attrs)
        if not m:
            continue
        trips = _while_trip_bound(mod, wh)
        if trips is not None and trips < EXPANSION_MIN_TRIPS:
            continue
        scalar_load = False
        #: (computation, source buffer name) of every scalar float load
        #: — the RMW discriminator keys on these.
        load_sources = set()
        #: (computation, target buffer name) of every scalar float store.
        store_targets = []
        for comp in mod.reachable(m.group(1)):
            for i in mod.computations.get(comp, ()):
                if (i.opcode == "dynamic-slice"
                        and i.dtype in _FLOAT_DTYPES
                        and i.out_elements <= SCALAR_SLICE_MAX_ELEMENTS):
                    scalar_load = True
                    if i.operands:
                        load_sources.add((comp, i.operands[0][2]))
                if (i.opcode == "dynamic-update-slice"
                        and i.dtype in _FLOAT_DTYPES):
                    # The dus RESULT is the whole buffer — scalarness
                    # lives in the UPDATE operand (operand 1).
                    upd = (i.operands[1] if len(i.operands) > 1
                           else None)
                    if (upd is not None and upd[0] in _FLOAT_DTYPES
                            and _prod(upd[1])
                            <= SCALAR_SLICE_MAX_ELEMENTS):
                        store_targets.append((comp, i.operands[0][2]))
        write_only_store = any(t not in load_sources
                               for t in store_targets)
        # An UNKNOWN trip bound still counts when both halves of the
        # signature are present — a real expansion's bound is the
        # (dynamic) index count, which often doesn't parse.
        if scalar_load and write_only_store:
            sites.append(m.group(1))
    return sorted(set(sites))


def _stream_dtype(mod: ParsedModule, gather: HloInstr) -> Optional[str]:
    """The NARROWEST float dtype in the hot gather's table operand
    chain (walked back through view/convert ops inside the gather's
    own computation). ``bf16`` here is the mechanical proof that the
    reduced-precision stream actually reaches the gather — the
    fast_bf16 verification PERF_NOTES could only promise."""
    if not gather.operands:
        return None
    dt, _shape, name = gather.operands[0]
    best = dt if dt in _FLOAT_DTYPES else None

    def width(d):
        return DTYPE_BYTES.get(d or "", 1 << 30)

    for _hop in range(8):
        prod = mod.producer(gather.computation, name)
        if prod is None or prod.opcode not in _VIEW_OPS:
            break
        if prod.dtype in _FLOAT_DTYPES and (
            best is None or width(prod.dtype) < width(best)
        ):
            best = prod.dtype
        for odt, _os, oname in prod.operands:
            if odt in _FLOAT_DTYPES and (
                best is None or width(odt) < width(best)
            ):
                best = odt
            name = oname  # follow the first typed operand
            break
        else:
            break
    return best


def _while_reachable(mod: ParsedModule) -> set:
    """Computations reachable from any while BODY (the in-loop set)."""
    out = set()
    for wh in mod.instructions():
        if wh.opcode != "while":
            continue
        m = re.search(r"body=%([\w.\-]+)", wh.attrs)
        if m:
            out.update(mod.reachable(m.group(1)))
    return out


def classify_gather(mod: ParsedModule) -> dict:
    """The gather-strategy verdict of one module:

      - ``native``: at least one real ``gather`` op at hot-traffic
        size — XLA kept the gather a gather;
      - ``expanded``: no hot native gather, but a while-loop/scalar
        dynamic-slice expansion site exists — the "fast gather
        defeated" signature;
      - ``none``: neither (a program with no gather-class traffic,
        e.g. a prescale).

    Plus the hot gather's facts when present (size, table dtype, the
    narrowest streamed float dtype, in-while placement, slice sizes).
    """
    gathers = [i for i in mod.instructions() if i.opcode == "gather"]
    hot = None
    for g in gathers:
        if g.out_elements < HOT_GATHER_MIN_ELEMENTS:
            continue
        if hot is None or (g.out_bytes or 0) > (hot.out_bytes or 0):
            hot = g
    sites = expansion_sites(mod)
    if hot is None:
        strategy = "expanded" if sites else "none"
    else:
        strategy = "native"
    out = {
        "strategy": strategy,
        "n_gathers": len(gathers),
        "expansion_sites": sites,
        "hot_gather": None,
    }
    if hot is not None:
        table = hot.operands[0] if hot.operands else (None, (), "")
        m = re.search(r"slice_sizes=\{([0-9,]*)\}", hot.attrs)
        out["hot_gather"] = {
            "computation": hot.computation,
            "output_elements": hot.out_elements,
            "output_bytes": hot.out_bytes,
            "table_dtype": table[0],
            "table_elements": _prod(table[1]),
            "stream_dtype": _stream_dtype(mod, hot),
            "slice_sizes": ([int(d) for d in m.group(1).split(",") if d]
                            if m else None),
            "in_while": hot.computation in _while_reachable(mod),
        }
    return out


def _prod(shape) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def entry_traffic_bytes(mod: ParsedModule) -> Optional[float]:
    """Operand + output bytes of every scheduled ENTRY instruction
    (parameters/constants/views excluded). Fusion internals live in
    registers, so the call-site bytes of the entry schedule are the
    HLO-derived HBM-traffic estimate the ``hlo_bytes_per_edge`` gauge
    reconciles against the analytic cost model. While bodies count
    once (trip counts are not modeled) — an ESTIMATE, stated as such.
    None when the module has no parsed entry computation."""
    if mod.entry is None:
        return None
    total = 0
    for i in mod.computations.get(mod.entry, ()):
        if i.opcode in _FREE_OPS:
            continue
        b = i.out_bytes
        if b is not None:
            total += b
        for dt, shape, _name in i.operands:
            w = DTYPE_BYTES.get(dt or "")
            if w is not None:
                total += w * _prod(shape)
    return float(total)


# -- the typed report --------------------------------------------------------


@dataclass
class LoweringReport:
    """One compiled program's lowering facts (strict-JSON shaped via
    :meth:`to_json`). ``text`` keeps the raw HLO for ``--dump-hlo``
    offline diffing but never enters JSON artifacts."""

    form: str
    op_histogram: Dict[str, int] = field(default_factory=dict)
    fusion_count: int = 0
    while_count: int = 0
    gather: dict = field(default_factory=dict)
    collectives: List[dict] = field(default_factory=list)
    hlo_bytes: Optional[float] = None
    num_edges: Optional[int] = None
    text: Optional[str] = field(default=None, repr=False)

    @property
    def hlo_bytes_per_edge(self) -> Optional[float]:
        if self.hlo_bytes is None or not self.num_edges:
            return None
        return self.hlo_bytes / self.num_edges

    @property
    def fingerprint(self) -> str:
        """Short structural hash: op histogram + gather strategy/dtypes
        + fusion count + collective multiset. Stable across re-compiles
        of the same program; moves when the LOWERING moves — the
        program-change attribution signal obs/history carries per
        leg."""
        g = self.gather or {}
        hg = g.get("hot_gather") or {}
        body = {
            "ops": sorted(self.op_histogram.items()),
            "fusions": self.fusion_count,
            "whiles": self.while_count,
            "strategy": g.get("strategy"),
            "table_dtype": hg.get("table_dtype"),
            "stream_dtype": hg.get("stream_dtype"),
            "slice_sizes": hg.get("slice_sizes"),
            "collectives": [(c["op"], c["dtype"], c["operand_bytes"])
                            for c in self.collectives],
        }
        return hashlib.sha256(
            json.dumps(body, sort_keys=True).encode()
        ).hexdigest()[:12]

    def to_json(self) -> dict:
        out = {k: v for k, v in dataclasses.asdict(self).items()
               if k != "text"}
        out["hlo_bytes_per_edge"] = self.hlo_bytes_per_edge
        out["fingerprint"] = self.fingerprint
        return out


def inspect_text(form: str, text: str, *, num_edges: Optional[int] = None,
                 record: bool = False) -> LoweringReport:
    """Parse + classify one HLO module text into a
    :class:`LoweringReport` (the pure core — tests and the contract
    checker feed synthetic texts through here)."""
    mod = parse_hlo_text(text)
    hist = op_histogram(mod)
    report = LoweringReport(
        form=form,
        op_histogram=hist,
        fusion_count=hist.get("fusion", 0),
        while_count=hist.get("while", 0),
        gather=classify_gather(mod),
        collectives=collective_multiset(mod),
        hlo_bytes=entry_traffic_bytes(mod),
        num_edges=num_edges,
        text=text,
    )
    if record:
        record_report(report)
    return report


def inspect_compiled(form: str, compiled, *,
                     num_edges: Optional[int] = None,
                     record: bool = True) -> Optional[LoweringReport]:
    """Harvest one AOT-compiled program's optimized HLO into the
    ledger. Never raises, never compiles: the text comes off the
    ALREADY-COMPILED handle via the jax_compat shim, and backends that
    report no HLO degrade to a logged None (the same contract as the
    cost/memory harvest — telemetry cannot fail a run)."""
    from pagerank_tpu.utils import jax_compat

    text = jax_compat.compiled_hlo_text(compiled)
    if not text:
        obs_log.info(
            f"lowering inspection: backend reports no optimized HLO "
            f"for '{form}' (verdict unknown)"
        )
        return None
    try:
        report = inspect_text(form, text, num_edges=num_edges)
    except Exception as e:  # a parser gap must not fail a run
        obs_log.warn(
            f"lowering inspection failed for '{form}' "
            f"({type(e).__name__}: {str(e)[:120]})"
        )
        return None
    if record:
        record_report(report)
    return report


# -- arming + the process ledger --------------------------------------------

_ARMED = False
_LEDGER: Dict[str, LoweringReport] = {}


def armed() -> bool:
    """Whether the compile points harvest lowering reports. DISARMED
    (the default), a run makes ZERO inspector calls and ZERO extra
    compiles — the tracer/sampler booby-trap discipline
    (tests/test_hlo.py traps every entry point)."""
    return _ARMED


def arm() -> None:
    global _ARMED
    _ARMED = True


def disarm() -> None:
    global _ARMED
    _ARMED = False


def maybe_inspect(form: str, compiled, *,
                  num_edges: Optional[int] = None) -> None:
    """The compile-point hook (stage_call, the engine's fused/step
    compiles): a bare armed-flag read when disarmed — no inspector
    call, no text fetch."""
    if _ARMED:
        inspect_compiled(form, compiled, num_edges=num_edges)


def record_report(report: LoweringReport) -> LoweringReport:
    """File under the form (last write wins, like the cost ledger) and
    publish the reconciliation gauge when the report carries both an
    HLO traffic estimate and an edge count."""
    _LEDGER[report.form] = report
    bpe = report.hlo_bytes_per_edge
    if bpe is not None:
        obs_metrics.gauge(
            f"cost.{report.form}.hlo_bytes_per_edge",
            f"optimized-HLO entry-schedule bytes per edge of the "
            f"'{report.form}' program (reconciles the analytic cost "
            f"model)",
        ).set(bpe)
    return report


def get_report(form: str) -> Optional[LoweringReport]:
    return _LEDGER.get(form)


def ledger_snapshot() -> Dict[str, dict]:
    """``{form: LoweringReport.to_json()}``, stable key order — the
    per-leg ``lowering`` block of bench JSON and the run report's
    ``lowering`` section."""
    return {form: _LEDGER[form].to_json() for form in sorted(_LEDGER)}


def dump_texts(directory: str, prefix: str = "") -> List[str]:
    """Write every ledgered report's raw HLO text to
    ``directory/[prefix.]<form>.hlo`` for offline diffing (bench/CLI
    ``--dump-hlo``). Returns the written paths."""
    import os

    os.makedirs(directory, exist_ok=True)
    written = []
    for form in sorted(_LEDGER):
        rep = _LEDGER[form]
        if not rep.text:
            continue
        stem = (f"{prefix}." if prefix else "") + form.replace("/", "_")
        path = os.path.join(directory, stem + ".hlo")
        with open(path, "w") as f:
            f.write(rep.text)
        written.append(path)
    return written


def reset() -> None:
    """Drop the ledger and disarm — one run's lowering reports must
    not bleed into the next in-process run (cli.main resets at entry
    alongside the metrics registry and the cost ledger)."""
    global _ARMED
    _LEDGER.clear()
    _ARMED = False


# -- form inspection (the `obs hlo` CLI + acceptance smoke) ------------------

#: Dispatch-form vocabulary ``python -m pagerank_tpu.obs hlo --form``
#: accepts (a deliberate subset of the contract sweep's: the forms a
#: TPU session actually benches). ``default`` is the plain replicated
#: ELL step.
FORM_CHOICES = ("default", "ell", "pair", "partitioned",
                "partitioned_bf16", "fast_bf16", "coo",
                "vertex_sharded", "vs_halo")


def _form_config(form: str, n: int, ndev: int):
    """PageRankConfig for one named dispatch form at an n-vertex
    geometry (the quarter-range fallback span keeps the partitioned
    forms running at small scales, mirroring bench's dedicated legs)."""
    from pagerank_tpu import PageRankConfig

    n_padded = -(-n // 128) * 128
    span = max(128, (n_padded // 4) & ~127)
    kw = {
        "default": {}, "ell": {},
        "pair": dict(dtype="float64", accum_dtype="float64",
                     wide_accum="pair"),
        "partitioned": dict(partition_span=span),
        "partitioned_bf16": dict(partition_span=span,
                                 stream_dtype="bfloat16"),
        "fast_bf16": dict(partition_span=span, stream_dtype="bfloat16"),
        "coo": dict(kernel="coo"),
        "vertex_sharded": dict(vertex_sharded=True, num_devices=ndev),
        "vs_halo": dict(vertex_sharded=True, halo_exchange=True,
                        halo_head=128, num_devices=ndev),
    }.get(form)
    if kw is None:
        raise ValueError(
            f"unknown dispatch form {form!r} (choices: "
            + ", ".join(FORM_CHOICES) + ")"
        )
    return PageRankConfig(num_iters=2, **kw)


def inspect_form(form: str, scale: int, edge_factor: int = 16,
                 seed: int = 0) -> Dict[str, dict]:
    """Build one named dispatch form on an R-MAT graph at ``scale``
    and return its lowering-ledger snapshot (the ``obs hlo`` CLI core;
    the acceptance smoke calls this directly). Host-built graph — the
    instrument must run on any backend, CPU included."""
    import jax

    from pagerank_tpu import build_graph
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine
    from pagerank_tpu.utils.synth import rmat_edges

    ndev = min(2, len(jax.devices()))
    # Resolve the config FIRST: an unknown form name must raise before
    # the R-MAT build (minutes of host work at real scales), and the
    # geometry inputs (n = 1 << scale) are known without it.
    cfg = _form_config(form, 1 << scale, ndev)
    src, dst = rmat_edges(scale, edge_factor, seed=seed)
    g = build_graph(src, dst, n=1 << scale)
    engine = JaxTpuEngine(cfg).build(g)
    reset()
    return engine.lowering_reports()


# -- human rendering ---------------------------------------------------------


def render_report(report) -> str:
    """One form's verdict as the ``obs hlo`` CLI prints it. Accepts a
    :class:`LoweringReport` or its :meth:`~LoweringReport.to_json`
    dict (the CLI renders snapshots after the per-form ledger reset)."""
    rep = report.to_json() if isinstance(report, LoweringReport) else report
    g = rep.get("gather") or {}
    hg = g.get("hot_gather") or {}
    lines = [
        f"{rep.get('form')}: gather "
        f"{str(g.get('strategy', '?')).upper()}"
        + (f" ({hg['output_elements']:,} el out, table "
           f"{hg.get('table_dtype')}, streamed "
           f"{hg.get('stream_dtype')}"
           + (", in while body" if hg.get("in_while") else "")
           + ")" if hg else "")
    ]
    if g.get("expansion_sites"):
        lines.append(
            "  EXPANSION sites (while-loop scalar dynamic-slice): "
            + ", ".join(g["expansion_sites"])
        )
    lines.append(
        f"  fusions {rep.get('fusion_count')}, whiles "
        f"{rep.get('while_count')}, fingerprint {rep.get('fingerprint')}"
    )
    if rep.get("collectives"):
        parts = [
            f"{c['op']}({c['dtype']}, "
            + (f"{c['operand_bytes']:,}B" if c["operand_bytes"]
               is not None else "?")
            + ")"
            for c in rep["collectives"]
        ]
        lines.append("  collectives: " + ", ".join(parts))
    bpe = rep.get("hlo_bytes_per_edge")
    if bpe is not None:
        lines.append(f"  entry-schedule traffic ~{bpe:.1f} B/edge "
                     f"(vs the analytic cost model's bytes_per_edge)")
    return "\n".join(lines)
