"""Device-plane observability (ISSUE 10): per-device HBM telemetry,
comms-vs-compute wall attribution, and the OOM-preflight fit check.

The next TPU session opens on three questions the rest of the obs
stack cannot answer: *is the sharded step exchange-bound or
compute-bound* (the Sparse Allreduce trade, arXiv:1312.3020, only pays
when comms time is measured separately from compute), *which chip is
the straggler and why* (per-device evidence, not one aggregate), and
*will scale 24/25 even fit in HBM before we pay a 75 s build* (the
FPGA streaming-SpMV paper, arXiv:2009.10443, sizes layout choices
against a memory roofline — which needs the memory numbers FIRST).
This module is that device plane, in three pieces:

  - :class:`DeviceSampler` — a structured per-device sampler over
    ``parallel/mesh.device_stats()`` (typed; None-tolerant on CPU):
    ``device.<id>.*`` exporter gauges, per-device HBM counter tracks
    in the Chrome trace (one Perfetto lane per chip), and a
    high-water mark kept across the run that the run report embeds —
    **failure-marked reports included**, so an OOM post-mortem has
    evidence. Process-global arm/disarm like the watchdog: DISARMED,
    the solve hot loop makes ZERO sampler calls per iteration (the
    tracer's booby-trap contract, tests/test_devices.py).
  - :func:`attribute_exchange` — comms-vs-compute wall attribution
    for the vertex-sharded/halo step: fenced sub-dispatch timing of
    the engine's exchange-only program vs the full step (the honest
    scalar-device_get fence discipline, engines/jax_engine.py),
    combined with the parallel/comms.py byte model into
    ``comms.achieved_bytes_per_sec`` and ``comms.exchange_fraction``
    gauges and the per-leg ``attribution`` block of
    ``bench.py --multichip``.
  - :func:`fit_check` — the OOM preflight: abstract-eval the device
    build pipeline at the TARGET geometry (AOT lowering over
    ShapeDtypeStructs — XLA's own ``memory_analysis`` per stage, via
    obs/costs.harvest_abstract; nothing allocates) plus an analytic
    per-chip solve-residency model, compared against per-chip
    ``bytes_limit`` (or the device-kind HBM capacity table when no
    accelerator is attached). ``python -m pagerank_tpu.obs fit
    --scale N [--ndev D]`` exits nonzero with the per-stage table
    before any real allocation; ``bench.py --preflight`` and the CLI
    ``--preflight`` run the same check before building.

Import cost: stdlib + obs modules only (jax and parallel/mesh are
imported lazily inside the functions that need them), so obs/__init__
can re-export this module without dragging a backend in.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional, Sequence

from pagerank_tpu.obs import costs as obs_costs
from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace

# -- per-device sampler ------------------------------------------------------

#: Chrome-trace pid base for per-device counter tracks: device id d
#: renders on pid TRACK_PID_BASE + d, above the kernel's maximum
#: pid_max (2^22 on Linux), so the HBM lanes can never collide with
#: the process's own span rows in Perfetto.
TRACK_PID_BASE = 1 << 23


class DeviceSampler:
    """Structured per-device memory sampler.

    Each :meth:`sample` reads ``mesh.device_stats()`` once and fans the
    typed records out to every device-plane surface:

      - ``device.<id>.bytes_in_use`` / ``.bytes_limit`` /
        ``.peak_bytes`` registry gauges — registered EAGERLY (the name
        exists in the snapshot even when a CPU backend reports None;
        an unset gauge publishes no sample, and the exporter output
        still strict-parses);
      - a ``device.<id>.hbm`` counter point on the active tracer's
        per-device track (Chrome ``ph:"C"``, one Perfetto lane per
        chip) — skipped entirely when tracing is off;
      - the cross-run high-water mark (:meth:`watermark`) the run
        report embeds, folded with the backend's own
        ``peak_bytes_in_use`` when it keeps one.

    ``on_step(iteration)`` is the engine.run hook: it samples at the
    ``every`` cadence. The hook only runs when a sampler is ARMED
    (:func:`arm_sampler`); disarmed, engine.run reads
    :func:`get_sampler` once per run and the loop body makes zero
    sampler calls (the no-op tracer discipline)."""

    def __init__(self, every: int = 1, devices: Optional[Sequence] = None):
        if every < 1:
            raise ValueError(f"sample cadence must be >= 1, got {every}")
        self.every = int(every)
        # A sequence pins the device set; a zero-arg CALLABLE resolves
        # it at each sweep (the watchdog's device_source idiom — the
        # solve mesh only exists after build, and tracks the rebuilt
        # engine after an elastic rescue). None sweeps every visible
        # device. The watermark must report the chips THIS run uses —
        # on a shared host, a foreign job's HBM peak in our OOM
        # post-mortem is worse than no watermark at all.
        self._devices = (devices if devices is None or callable(devices)
                         else list(devices))
        self.samples = 0
        self.last: List = []
        #: Per-device high-water ``bytes_in_use`` across every sample
        #: of this sampler's life (plus the backend's own peak field).
        self.peak_bytes: Dict[int, int] = {}

    def sample(self, iteration: Optional[int] = None) -> List:
        """One sweep over the devices; returns the typed
        :class:`~pagerank_tpu.parallel.mesh.DeviceStats` list. Never
        raises past the stats read itself degrading to None fields —
        telemetry must not fail a run."""
        from pagerank_tpu.parallel import mesh as mesh_lib

        devs = self._devices
        if callable(devs):
            try:
                devs = list(devs())
            except Exception:
                # Pre-build boundary samples (or a source reading a
                # torn-down engine) degrade to the full sweep — a
                # telemetry source must never fail a run.
                devs = None
        stats = mesh_lib.device_stats(devs)
        self.samples += 1
        self.last = stats
        tracer = obs_trace.get_tracer()
        for s in stats:
            # Eager registration: the per-device names exist in the
            # registry snapshot from the first sample even when every
            # value is None (CPU) — the same discipline as the elastic
            # monitor's eager elastic.* registration.
            g_use = obs_metrics.gauge(
                f"device.{s.id}.bytes_in_use",
                f"live HBM bytes in use on device {s.id}",
            )
            g_lim = obs_metrics.gauge(
                f"device.{s.id}.bytes_limit",
                f"HBM byte limit the backend reports for device {s.id}",
            )
            g_peak = obs_metrics.gauge(
                f"device.{s.id}.peak_bytes",
                f"high-water HBM bytes observed on device {s.id} "
                f"(max of sampled bytes_in_use and the backend's own "
                f"peak counter)",
            )
            if s.bytes_limit is not None:
                g_lim.set(s.bytes_limit)
            peak = self.peak_bytes.get(s.id)
            for candidate in (s.bytes_in_use, s.peak_bytes_in_use):
                if candidate is not None:
                    peak = candidate if peak is None else max(peak,
                                                              candidate)
            if s.bytes_in_use is not None:
                g_use.set(s.bytes_in_use)
            if peak is not None:
                self.peak_bytes[s.id] = peak
                g_peak.set(peak)
            if tracer.enabled:
                # Counter points only when the backend reported real
                # byte values: a CPU run must not fill the trace with
                # empty HBM lanes (the ts axis already orders samples;
                # no iteration field needed).
                values = {
                    k: v for k, v in (
                        ("bytes_in_use", s.bytes_in_use),
                        ("bytes_limit", s.bytes_limit),
                    ) if v is not None
                }
                if values:
                    tracer.add_counter(
                        f"device.{s.id}.hbm", values,
                        track=TRACK_PID_BASE + s.id,
                        track_label=(
                            f"device {s.platform}:{s.id} ({s.kind})"
                        ),
                    )
        if self.peak_bytes:
            obs_metrics.gauge(
                "device.hbm_high_water_bytes",
                "max HBM bytes_in_use observed on any device this run",
            ).set(max(self.peak_bytes.values()))
        return stats

    def on_step(self, iteration: int) -> None:
        """engine.run's per-completed-step hook (armed samplers only):
        sample at the ``every`` cadence, starting from the first
        step."""
        if iteration % self.every == 0:
            self.sample(iteration)

    def watermark(self) -> dict:
        """The run report's ``devices`` section: the high-water mark,
        per-device peaks, and the LAST full sample — the OOM-forensics
        record a failure-marked report carries (cli._export_observability
        embeds this on the failure path too)."""
        overall = max(self.peak_bytes.values()) if self.peak_bytes else None
        return {
            "samples": self.samples,
            "hbm_high_water_bytes": overall,
            "per_device_peak_bytes": {
                str(k): v for k, v in sorted(self.peak_bytes.items())
            },
            "last": [s.to_json() for s in self.last],
        }


_SAMPLER: Optional[DeviceSampler] = None


def get_sampler() -> Optional[DeviceSampler]:
    """The armed sampler, or None (the default — engine.run reads this
    once per run; disarmed, the hot loop makes zero sampler calls)."""
    return _SAMPLER


def arm_sampler(sampler: DeviceSampler) -> DeviceSampler:
    """Install ``sampler`` as the process sampler (one per process,
    like the watchdog) and take an immediate baseline sample."""
    global _SAMPLER
    _SAMPLER = sampler
    sampler.sample()
    return sampler


def disarm_sampler() -> Optional[DeviceSampler]:
    global _SAMPLER
    prev = _SAMPLER
    _SAMPLER = None
    return prev


def report_section(sample_now: bool = True) -> Optional[dict]:
    """The ``devices`` section every run report carries (success AND
    failure paths): the armed sampler's watermark — refreshed with one
    final sample so the report's last record reflects teardown-time
    state — or, with no sampler armed, a one-shot sample (still real
    OOM evidence, just without in-run history). Never raises: a report
    must be writable when the backend is the thing that broke."""
    try:
        s = get_sampler()
        if s is None:
            s = DeviceSampler()
            s.sample()
        elif sample_now:
            s.sample()
        return s.watermark()
    except Exception as e:  # a broken backend must not block the report
        return {"error": repr(e)}


# -- comms-vs-compute attribution -------------------------------------------


def attribute_exchange(engine, iters: int = 10, warmup: int = 2,
                       ) -> Optional[dict]:
    """Wall attribution of the vertex-sharded step: time the engine's
    EXCHANGE-ONLY sub-program (the same all_gather / head-psum +
    ppermute rounds and the same merge collectives, compute replaced
    by a zero accumulator — engines/jax_engine._make_exchange_core)
    against the full step, both under the honest scalar-device_get
    fence, and combine with the static comms byte model
    (parallel/comms.py):

      - ``exchange_s`` / ``compute_s`` / ``step_s`` (per iteration);
      - ``exchange_fraction`` = exchange / step — the is-it-wire-bound
        verdict, published as the ``comms.exchange_fraction`` gauge;
      - ``achieved_bytes_per_sec`` = modeled wire bytes per iteration
        over the measured exchange seconds — what the interconnect
        actually delivered, published as
        ``comms.achieved_bytes_per_sec``. On fake CPU devices this is
        shared-memory bandwidth, not ICI — the number is honest about
        WHERE it was measured (the env fingerprint rides every
        artifact that embeds this block).

    Returns None when the engine has no exchange-only program
    (replicated modes, multi-dispatch layouts). Out-of-band by
    construction: nothing here touches the solve hot loop, and the
    engine's exchange program is compiled lazily on the first call —
    attribution off costs zero calls AND zero compiles (the
    transparency contract, tests/test_devices.py)."""
    has = getattr(engine, "has_exchange_program", None)
    if has is None or not has():
        return None
    exchange_s, step_s = engine.time_exchange_split(
        iters=iters, warmup=warmup
    )
    model = engine.comms_model() or {}
    model_bytes = model.get("bytes_per_iter") or 0
    # Clamped like compute_s: the two walls are measured independently
    # and at dispatch-overhead-dominated toy geometries timing noise
    # can push the raw ratio past 1 — a fraction is a fraction.
    fraction = (min(1.0, exchange_s / step_s)) if step_s > 0 else None
    achieved = (model_bytes / exchange_s
                if exchange_s > 0 and model_bytes else None)
    out = {
        "iters": int(iters),
        "exchange_s": exchange_s,
        "step_s": step_s,
        "compute_s": max(0.0, step_s - exchange_s),
        "exchange_fraction": fraction,
        "model_bytes_per_iter": int(model_bytes) if model_bytes else None,
        "achieved_bytes_per_sec": achieved,
        "mode": model.get("mode"),
    }
    if fraction is not None:
        obs_metrics.gauge(
            "comms.exchange_fraction",
            "measured exchange wall over the full step wall "
            "(vertex-sharded attribution)",
        ).set(fraction)
    if achieved is not None:
        obs_metrics.gauge(
            "comms.achieved_bytes_per_sec",
            "modeled exchange bytes over the measured exchange "
            "seconds — delivered interconnect bandwidth",
        ).set(achieved)
    return out


# -- OOM-preflight fit check -------------------------------------------------

#: Slot-row estimate slack over the raw-edge lower bound e/128: ELL
#: rows pad to the max lane-group run per (stripe, 128-dst block), and
#: R-MAT skew makes hub blocks ragged — measured slots/edge lands
#: 1.1-1.5 at bench geometries (docs/PERF_NOTES.md "Partition-centric
#: restage"); 1.6 upper-bounds it (soundness pinned by
#: tests/test_devices.py::test_fit_slot_row_estimate_upper_bounds_real_build).
SLOT_ROW_SLACK = 1.6

#: Fit-check limit of last resort when nothing is attached and no kind
#: was named: the v5e-class 16 GiB chip the repo's measured numbers
#: come from (BASELINE.md).
DEFAULT_FIT_LIMIT_BYTES = 16 << 30
DEFAULT_FIT_HEADROOM = 0.9  # runtime/framework reserve off the top


@dataclasses.dataclass
class FitStage:
    """One stage of the preflight table: the modeled per-chip peak
    bytes and where the number came from (``xla`` = AOT-compiled
    memory_analysis at the target shapes; ``model`` = the documented
    analytic formula; ``unknown`` = the backend compiled the stage but
    reports no memory analysis — surfaced, never blocking; ``error`` =
    the stage cannot even lower at this geometry, which is itself a
    does-not-fit verdict)."""

    stage: str
    bytes: Optional[int]
    source: str
    detail: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FitResult:
    fits: bool
    limit_bytes: int
    limit_source: str
    headroom: float
    n: int
    num_edges: int
    ndev: int
    dtype: str
    accum_dtype: str
    vertex_sharded: bool
    stages: List[FitStage] = dataclasses.field(default_factory=list)
    scale: Optional[int] = None

    @property
    def effective_limit(self) -> float:
        return self.limit_bytes * self.headroom

    @property
    def peak_stage(self) -> Optional[FitStage]:
        known = [s for s in self.stages if s.bytes is not None]
        return max(known, key=lambda s: s.bytes) if known else None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["effective_limit_bytes"] = self.effective_limit
        peak = self.peak_stage
        d["peak_stage"] = peak.stage if peak else None
        d["peak_bytes"] = peak.bytes if peak else None
        return d


def _gib(v) -> str:
    return f"{v / (1 << 30):.2f} GiB" if v is not None else "-"


def resolve_hbm_limit(limit_bytes: Optional[int] = None,
                      device_kind: Optional[str] = None):
    """(per-chip limit bytes, source string), resolved in evidence
    order: an explicit byte limit, an EXPLICIT ``device_kind`` through
    the capacity table (``--device-kind`` exists precisely to size for
    a chip that is NOT attached — it must beat whatever happens to be
    plugged in), the live backend's own ``bytes_limit`` (minimum over
    devices — the most constrained chip gates the mesh), the attached
    device's kind through the table, and finally the documented
    v5e-class default."""
    if limit_bytes:
        return int(limit_bytes), "explicit"
    if device_kind:
        cap = obs_costs.hbm_capacity_bytes(device_kind)
        if cap is not None:
            return int(cap), f"device-kind table ({device_kind})"
        obs_log.warn(
            f"fit check: device kind {device_kind!r} is not in the "
            f"HBM capacity table; falling back to live/default limits"
        )
    kind = None
    try:
        from pagerank_tpu.parallel import mesh as mesh_lib

        stats = mesh_lib.device_stats()
        limits = [s.bytes_limit for s in stats if s.bytes_limit]
        if limits:
            return int(min(limits)), "device bytes_limit"
        # The same sweep already carries the attached kind — no second
        # jax.devices() pass for the table fallback.
        kind = stats[0].kind if stats else None
    except Exception as e:  # no backend: fall through to the default
        obs_log.info(f"fit check: no live device limits "
                     f"({type(e).__name__}); using the capacity table")
    cap = obs_costs.hbm_capacity_bytes(kind)
    if cap is not None:
        return int(cap), f"device-kind table (attached {kind})"
    return DEFAULT_FIT_LIMIT_BYTES, "default (TPU v5e-class 16 GiB)"


def estimate_slot_rows(num_edges: int, n_padded: int, n_stripes: int,
                       ) -> int:
    """Upper-bound estimate of the packed slot-row count (the one
    build quantity that is data-dependent — build_ell_device syncs it
    off device): the raw-edge lower bound ceil(e/128) times
    :data:`SLOT_ROW_SLACK`, plus one row per (stripe, 128-dst block)
    for ragged-tail padding."""
    num_blocks = max(1, n_padded // 128)
    return (int(math.ceil(num_edges * SLOT_ROW_SLACK / 128))
            + max(1, n_stripes) * num_blocks)


def _build_stage_reports(cfg, n: int, num_edges: int, scale: Optional[int],
                         group: int, stripe: int) -> List[FitStage]:
    """Abstract-eval the device-build pipeline at the target geometry:
    the REAL stage programs (ops/device_build) AOT-lowered over
    ShapeDtypeStructs — XLA's own memory_analysis per stage, no
    allocation (obs/costs.harvest_abstract). The scatter stage uses the
    estimated row count (the only host-synced quantity)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pagerank_tpu.ops import device_build as db

    sds = jax.ShapeDtypeStruct
    n_padded = -(-n // 128) * 128
    sz = min(stripe, n_padded) if stripe else n_padded
    n_stripes = -(-n_padded // sz) if n_padded else 1
    stripe_arg = sz if n_stripes > 1 else 0
    num_blocks = n_padded // 128
    e = sds((num_edges,), jnp.int32)
    stages: List[FitStage] = []

    def add(name, fn, args, donate=(), static=None, detail=""):
        try:
            rep = obs_costs.harvest_abstract(
                f"build/{name}", fn, args, static_kwargs=static,
                donate_argnums=donate,
            )
            if rep.peak_bytes is not None:
                stages.append(FitStage(
                    stage=f"build/{name}", bytes=rep.peak_bytes,
                    source="xla", detail=detail,
                ))
            else:
                # The backend compiled the stage but reports no memory
                # analysis (older jaxlib / bare PJRT plugins): an
                # UNKNOWN, not a verdict — telemetry degradation must
                # never hard-block a run (the module contract; only
                # "error" stages, which could not even lower, force
                # does-not-fit).
                stages.append(FitStage(
                    stage=f"build/{name}", bytes=None, source="unknown",
                    detail=(detail + " — backend reports no "
                            "memory_analysis").strip(" —"),
                ))
        except Exception as err:
            stages.append(FitStage(
                stage=f"build/{name}", bytes=None, source="error",
                detail=f"{type(err).__name__}: {str(err)[:160]}",
            ))

    # The same int32-capacity guards the real builder enforces: a
    # geometry the packer would refuse is a preflight verdict, not a
    # compile crash.
    if n_stripes > 1 and n_stripes * n_padded > np.iinfo(np.int32).max:
        stages.append(FitStage(
            stage="build/sort", bytes=None, source="error",
            detail=f"striped sort key overflows int32 ({n_stripes} "
                   f"stripes x n_padded {n_padded}) — the device build "
                   f"refuses this geometry (build_ell_device)",
        ))
        return stages

    if scale is not None:
        key_aval = jax.eval_shape(
            lambda: jax.random.key(0, impl="rbg"))

        add("gen",
            functools.partial(db._rmat_gen, scale=scale,
                              n_edges=num_edges),
            (key_aval, sds((), jnp.float32), sds((), jnp.float32),
             sds((), jnp.float32)),
            detail=f"R-MAT gen, {num_edges:,} raw edges")
    add("in_degree", functools.partial(db._raw_in_degree, n=n), (e,),
        detail="raw in-degree scatter-add")
    add("relabel", db._relabel_perm, (sds((n,), jnp.int32),),
        detail="stable in-degree relabel sort")
    add("sort",
        functools.partial(db._relabel_sort, n_padded=n_padded,
                          stripe_size=stripe_arg),
        (e, e, sds((n,), jnp.int32)), donate=(0, 1),
        detail="THE composite-key full-edge sort")
    add("slots",
        functools.partial(db._slot_coords, n=n, n_padded=n_padded,
                          weight_dtype=jnp.dtype(cfg.dtype), group=group,
                          stripe_size=stripe_arg, with_weights=False),
        (e, e), donate=(0, 1),
        detail="slot coordinates + dedup flags")
    rows_est = estimate_slot_rows(num_edges, n_padded, n_stripes)
    log2g = group.bit_length() - 1
    add("scatter",
        functools.partial(db._scatter_slots, rows_total=rows_est,
                          num_blocks=num_blocks, n_stripes=n_stripes,
                          fill=sz << log2g),
        (e, e, sds((num_edges,), jnp.int8),
         sds((n_stripes * num_blocks,), jnp.int32)),
        detail=f"slot-plane scatter at ~{rows_est:,} estimated rows "
               f"(slack {SLOT_ROW_SLACK})")
    return stages


def _solve_stage_report(cfg, n: int, num_edges: int, ndev: int,
                        vertex_sharded: bool, stripe: int = 0) -> FitStage:
    """Analytic per-chip residency of the solve: the packed tables and
    per-vertex state (edge/vertex-sharded over the mesh in the
    vertex-sharded mode), plus the step's transient gathered-z image
    and merge accumulators. A MODEL, not an XLA harvest — the step
    program only exists after an engine build, which is exactly the
    allocation the preflight must precede. Formula (per chip):

      tables     = rows_est*128*4 + rows_est*4         [/ ndev sharded]
      vertexstate= n_padded * (dtype + z_item + 3)     [/ ndev sharded]
      z image    = 2 * n_padded * z_item   (gathered z is FULL-width
                   per chip in both the dense AND halo exchange — the
                   halo saves wire bytes, not the z image)
      merge      = 2 * n_padded * accum_item

    ``rows_est`` counts the STRIPED table (one pad row per (stripe,
    dst block)): ``stripe`` is the planned span when the caller has
    one (device builds), 0 re-derives the engine's own striping rule
    — the host packer ignores explicit spans, so the plan's stripe=0
    there must not collapse the model to a single stripe (a scale-24
    table near the ceiling carries hundreds of MB of stripe padding).

    ``cfg.vs_bounded`` (owner-computes dst partitioning) replaces the
    full-width transients with their bounded forms — z planes of one
    stripe span plus the zero-extended local shard, and the local
    [num_blocks/ndev, 128] accumulator — the O(stripe_span + N/ndev)
    contract of ``_setup_ell_vs_bounded``; modeling the plain mode
    there would refuse exactly the geometries the flag exists to fit.

    The vertex-sharded step's z image is what caps scale per chip —
    the reason --ndev matters even though per-vertex state shards."""
    import numpy as np

    from pagerank_tpu.engines.jax_engine import JaxTpuEngine

    n_padded = -(-n // 128) * 128
    pair = JaxTpuEngine.resolve_pair(cfg)
    z_item = JaxTpuEngine.gather_z_item(cfg, pair)
    dt_item = np.dtype(cfg.dtype).itemsize
    ac_item = np.dtype(cfg.accum_dtype).itemsize
    fast_cap, stripe_target = JaxTpuEngine.stripe_limits(z_item, pair)
    if stripe:
        sz = min(stripe, n_padded)
    elif n_padded > fast_cap:
        sz = min(JaxTpuEngine.occupancy_span(
            stripe_target, n_padded, num_edges, pair, z_item), n_padded)
    else:
        sz = n_padded
    n_stripes = max(1, -(-n_padded // sz)) if n_padded else 1
    rows_est = estimate_slot_rows(num_edges, n_padded, n_stripes)
    share = ndev if vertex_sharded and ndev > 1 else 1
    tables = (rows_est * 128 * 4 + rows_est * 4) // share
    vertex_state = n_padded * (dt_item + z_item + 3) // share
    bounded = bool(vertex_sharded and getattr(cfg, "vs_bounded", False))
    if bounded:
        local = n_padded // share
        z_image = 2 * (sz + local) * z_item
        merge = 2 * local * ac_item
    else:
        z_image = 2 * n_padded * z_item
        merge = 2 * n_padded * ac_item
    total = tables + vertex_state + z_image + merge
    return FitStage(
        stage="solve/step", bytes=int(total), source="model",
        detail=(f"tables {_gib(tables)} + state {_gib(vertex_state)} "
                f"+ z image {_gib(z_image)} + merge {_gib(merge)}"
                + (f" (vs-bounded over {ndev})" if bounded
                   else f" (vertex-sharded over {ndev})" if share > 1
                   else "")),
    )


def fit_check(scale: Optional[int] = None, *, n: Optional[int] = None,
              num_edges: Optional[int] = None, edge_factor: int = 16,
              ndev: int = 1, dtype: str = "float32",
              accum_dtype: Optional[str] = None,
              wide_accum: str = "auto",
              vertex_sharded: Optional[bool] = None,
              vs_bounded: bool = False,
              device_build: bool = True,
              stripe_size: int = 0, lane_group: int = 0,
              partition_span: int = 0,
              limit_bytes: Optional[int] = None,
              device_kind: Optional[str] = None,
              headroom: float = DEFAULT_FIT_HEADROOM) -> FitResult:
    """The OOM preflight: will (build +) solve at this geometry fit in
    per-chip HBM? Pass ``scale`` for the bench R-MAT geometry
    (``2^scale`` vertices, ``edge_factor << scale`` raw edges) or
    explicit ``n``/``num_edges`` (a loaded graph — the CLI's
    ``--preflight``). ``vertex_sharded`` defaults to ``ndev > 1`` (the
    memory-scaling mode a multi-chip run means); ``vs_bounded`` sizes
    the owner-computes bounded step instead of the plain mode's
    full-width transients. ``device_build=False``
    skips the build-pipeline stages (host-built graphs: host RAM is
    not this check's axis).

    Nothing allocates: build stages are AOT-lowered over abstract
    shapes, the solve stage is an analytic model, and the limit comes
    from live ``bytes_limit`` / the device-kind capacity table
    (:func:`resolve_hbm_limit`). The verdict is per STAGE — the table
    names which stage busts the budget, which is what decides between
    "bigger mesh", "host build", or "don't bother"."""
    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.ops.device_build import plan_build

    if scale is None and n is None:
        raise ValueError("fit_check needs scale= or n=")
    if n is None:
        n = 1 << scale
    if num_edges is None:
        num_edges = (edge_factor << scale if scale is not None
                     else edge_factor * n)
    if vertex_sharded is None:
        vertex_sharded = ndev > 1
    cfg = PageRankConfig(
        num_iters=1, dtype=dtype, accum_dtype=accum_dtype or dtype,
        wide_accum=wide_accum, vertex_sharded=vertex_sharded,
        vs_bounded=vs_bounded,
        num_devices=ndev if vertex_sharded else None,
    ).validate()
    # THE shared planner at the CALLER's layout flags (stripe/group/
    # partition span) — the preflight must gate the build the run will
    # actually execute, not the default layout's.
    group, stripe, _part = plan_build(
        cfg, n, num_edges=num_edges, host=not device_build,
        stripe_size=stripe_size, lane_group=lane_group,
        partition_span=partition_span,
    )
    limit, limit_source = resolve_hbm_limit(limit_bytes, device_kind)

    t0 = time.perf_counter()
    stages: List[FitStage] = []
    if device_build:
        # The device build is a SINGLE-chip pipeline regardless of the
        # solve mesh (ops/device_build packs on one device; multichip
        # bench legs host-build and pass device_build=False) — so its
        # stages gate at full width even when ndev > 1. Skipping them
        # for a wide mesh would pass a preflight whose build then OOMs
        # — the exact failure this check exists to prevent.
        stages += _build_stage_reports(
            cfg, n, num_edges, scale, group, stripe)
    stages.append(_solve_stage_report(cfg, n, num_edges, ndev,
                                      vertex_sharded, stripe))
    effective = limit * headroom
    # Verdict: every MEASURED stage must fit and nothing may have
    # ERRORED (a stage that cannot lower at this geometry is a
    # refusal); "unknown" stages — the backend reported no memory
    # analysis — do not block (degraded telemetry is not an OOM).
    fits = bool(stages) and not any(
        s.source == "error" for s in stages
    ) and all(
        s.bytes <= effective for s in stages if s.bytes is not None
    )
    res = FitResult(
        fits=fits, limit_bytes=limit, limit_source=limit_source,
        headroom=headroom, n=n, num_edges=num_edges, ndev=ndev,
        dtype=str(cfg.dtype), accum_dtype=str(cfg.accum_dtype),
        vertex_sharded=vertex_sharded, stages=stages, scale=scale,
    )
    obs_log.info(
        f"fit check: {len(stages)} stage(s) in "
        f"{time.perf_counter() - t0:.2f}s -> "
        f"{'fits' if fits else 'DOES NOT FIT'}"
    )
    return res


def render_fit(res: FitResult) -> str:
    """The per-stage preflight table (what ``obs fit`` prints and the
    CLI shows before refusing a doomed build)."""
    head = (f"OOM preflight: "
            + (f"scale {res.scale} " if res.scale is not None else "")
            + f"({res.n:,} vertices, ~{res.num_edges:,} raw edges), "
            f"{res.ndev} device(s), {res.dtype}/{res.accum_dtype}"
            + (", vertex-sharded" if res.vertex_sharded else ""))
    lines = [head,
             f"per-chip limit {_gib(res.limit_bytes)} "
             f"[{res.limit_source}] x headroom {res.headroom:g} = "
             f"{_gib(res.effective_limit)}"]
    w = max((len(s.stage) for s in res.stages), default=5)
    effective = res.effective_limit
    for s in res.stages:
        if s.bytes is None:
            verdict = "ERROR" if s.source == "error" else "?"
        else:
            verdict = "ok" if s.bytes <= effective else "OVER"
        lines.append(
            f"  {s.stage:<{w}}  {_gib(s.bytes):>12}  {s.source:<5}  "
            f"{verdict:<5}"
            + (f"  {s.detail}" if s.detail else "")
        )
    peak = res.peak_stage
    lines.append(
        ("FITS" if res.fits else "DOES NOT FIT")
        + (f": peak stage {peak.stage} at {_gib(peak.bytes)} vs "
           f"{_gib(effective)}" if peak else ": no stage evaluated")
    )
    return "\n".join(lines)
