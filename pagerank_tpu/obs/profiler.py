"""jax.profiler lifecycle as a context manager, composed with the span
tracer.

The CLI's ``--profile-dir`` handling used to be hand-rolled start/stop
around only part of the run (cli.py pre-obs): the stop lived in a
``finally`` that had to be manually kept in sync with the writer-close
ordering, and nothing tied the profiler window to the rest of the
run's telemetry. :func:`profiler_session` owns both:

  - ``jax.profiler.start_trace`` on entry, ``stop_trace`` ALWAYS on
    exit — including the failure path, where the trace of the failing
    run is exactly what the user wants to inspect
    (tests/test_obs.py::test_profiler_session_stops_on_failure);
  - a ``profile`` span on the active tracer with the trace directory
    as an attribute, so a run report / Chrome trace shows WHEN the
    profiler window was open relative to every other phase.
"""

from __future__ import annotations

import contextlib

from pagerank_tpu.obs import trace as _trace


@contextlib.contextmanager
def profiler_session(profile_dir, tracer=None):
    """Run the body under a ``jax.profiler`` trace written to
    ``profile_dir``; no-op (still yields) when ``profile_dir`` is
    falsy, so callers wrap unconditionally::

        with obs.profiler_session(args.profile_dir):
            ... the run ...

    Yields True when profiling is active, False otherwise. The profiler
    is stopped on EVERY exit path; a stop failure never masks the
    body's own exception (it is swallowed only while one is already
    propagating)."""
    if not profile_dir:
        yield False
        return
    import jax

    tr = tracer if tracer is not None else _trace.get_tracer()
    with tr.span("profile", dir=str(profile_dir)):
        jax.profiler.start_trace(profile_dir)
        try:
            yield True
        except BaseException:
            # The body failed: stop (and flush) the trace of the failing
            # run, but never let a secondary stop failure mask the
            # primary error.
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            raise
        else:
            jax.profiler.stop_trace()
