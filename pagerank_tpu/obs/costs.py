"""Static cost accounting — what a dispatch *should* cost (ISSUE 5).

The r5 backend-variance incident was diagnosed by diffing wall times
with no model to anchor "fast enough"; Lakhotia et al. (arXiv:1709.07122,
PAPERS.md) show bytes-per-edge against a roofline is the right lens for
PageRank performance work. This module gives the repo that lens
natively: after every engine / build-stage compile, the caller harvests
XLA's own cost model (``compiled.cost_analysis()`` — FLOPs, HBM bytes
accessed) and memory breakdown (``memory_analysis()`` — argument /
output / temp / peak allocation) into a typed :class:`CostReport`,
via the ``utils/jax_compat`` shims that degrade to None on backends
that don't report (PJRT plugins legitimately vary).

Reports land in three places at once:

  - a process-global **ledger** (one report per compiled form —
    ``step``, ``fused_scan``, ``prescale``/``stripe{i}``/``final`` on
    multi-dispatch layouts, ``build/{stage}`` for the device build),
    reset per run like the metrics registry;
  - the **MetricsRegistry** as ``cost.<form>.*`` gauges, so the live
    exporter (obs/live.py) publishes the model next to the measured
    rates;
  - the **run report** (``costs`` section; ``python -m pagerank_tpu.obs
    report A B`` diffs it — "did the model change or just the wall
    time" becomes mechanical) and ``bench.py``'s JSON.

The analytic layer: ``bytes_per_edge = bytes_accessed / num_edges`` per
iteration, and — once a measured wall time is attached
(:func:`attach_measurement`) — ``achieved_bytes_per_s`` against the
device's HBM roofline (:data:`HBM_PEAK_BYTES_PER_S`), i.e. what
fraction of the memory-bound ceiling the dispatch actually reached.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, Optional

from pagerank_tpu.obs import metrics as obs_metrics

#: Published peak HBM bandwidth per chip, bytes/s, keyed by substring
#: of ``device_kind`` (matched case-insensitively, longest key first).
#: The roofline denominator for memory-bound SpMV work — PageRank at
#: graph scale is bandwidth-bound, so achieved-bytes/s over this peak
#: is the honest utilization number (Lakhotia et al.). Unlisted kinds
#: (CPU, unknown TPUs) yield None fractions rather than a wrong model.
HBM_PEAK_BYTES_PER_S = {
    "tpu v6": 1_640e9,
    "tpu v5p": 2_765e9,
    "tpu v5": 819e9,  # v5e ("TPU v5 lite" / "TPU v5e")
    "tpu v4": 1_228e9,
    "tpu v3": 900e9,
    "tpu v2": 700e9,
}


def hbm_peak_bytes_per_s(device_kind: Optional[str]) -> Optional[float]:
    """Roofline peak for a ``device_kind`` string, or None when the
    kind is unknown (no guess: a wrong roofline is worse than none)."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    for key in sorted(HBM_PEAK_BYTES_PER_S, key=len, reverse=True):
        if key in kind:
            return HBM_PEAK_BYTES_PER_S[key]
    return None


#: Published per-chip HBM CAPACITY, bytes, keyed like
#: :data:`HBM_PEAK_BYTES_PER_S` — the LIMIT side of the OOM-preflight
#: fit check (ISSUE 10; obs/devices.fit_check) when no live device
#: reports ``bytes_limit`` (CPU test substrate, or sizing a run for a
#: TPU that isn't attached yet). v3 is per-core (the unit jax exposes
#: as a device).
HBM_CAPACITY_BYTES = {
    "tpu v6": 32 << 30,
    "tpu v5p": 95 << 30,
    "tpu v5": 16 << 30,  # v5e ("TPU v5 lite" / "TPU v5e")
    "tpu v4": 32 << 30,
    "tpu v3": 16 << 30,
    "tpu v2": 8 << 30,
}


def hbm_capacity_bytes(device_kind: Optional[str]) -> Optional[int]:
    """Per-chip HBM capacity for a ``device_kind`` string (same
    longest-substring match as the roofline table), or None when the
    kind is unknown."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    for key in sorted(HBM_CAPACITY_BYTES, key=len, reverse=True):
        if key in kind:
            return HBM_CAPACITY_BYTES[key]
    return None


#: Per-core VMEM capacity, bytes, keyed like the tables above — the
#: LIMIT side of the kernel-plane PTK001 budget (ISSUE 16;
#: analysis/kernels.py) and of the engine's pallas-probe refusal. VMEM
#: is the on-chip scratchpad a Pallas kernel's resident blocks +
#: scratch must fit (the Mosaic compiler also carves its own
#: temporaries out of it — see :data:`PALLAS_VMEM_HEADROOM`).
VMEM_CAPACITY_BYTES = {
    "tpu v6": 32 << 20,
    "tpu v5p": 16 << 20,
    "tpu v5": 16 << 20,  # v5e ("TPU v5 lite" / "TPU v5e")
    "tpu v4": 16 << 20,
    "tpu v3": 16 << 20,
    "tpu v2": 16 << 20,
}

#: Fraction of VMEM a kernel's accounted residency may claim: Mosaic
#: keeps compiler temporaries (vector spills, DMA staging) in the same
#: space, so budgeting the full capacity OOMs at compile time. 0.75 of
#: the 16MB v5e core is the 12MB bound the engine's pallas probe has
#: enforced since the legacy kernel landed.
PALLAS_VMEM_HEADROOM = 0.75

#: Budget target when no TPU is attached (CPU test substrate, or
#: sizing a kernel for a TPU that isn't attached yet): the repo's
#: measured platform (v5e). A per-kind budget must never come from a
#: guess at an UNKNOWN kind — but a missing device is different: the
#: pre-mesh checker exists precisely to run off-TPU, so it sizes for
#: the campaign's default target.
DEFAULT_VMEM_TARGET_KIND = "tpu v5"


def vmem_capacity_bytes(device_kind: Optional[str]) -> Optional[int]:
    """Per-core VMEM capacity for a ``device_kind`` string (same
    longest-substring match as the HBM tables), or None when the kind
    is unknown."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    for key in sorted(VMEM_CAPACITY_BYTES, key=len, reverse=True):
        if key in kind:
            return VMEM_CAPACITY_BYTES[key]
    return None


def pallas_vmem_budget(device_kind: Optional[str] = None) -> int:
    """The VMEM byte budget a Pallas kernel's accounted residency
    (resident blocks x pipeline buffering + scratch) must stay under:
    the device kind's capacity (falling back to
    :data:`DEFAULT_VMEM_TARGET_KIND` when the kind is unknown or no
    device is attached) times :data:`PALLAS_VMEM_HEADROOM`. Shared by
    the PTK001 rule (analysis/kernels.py) and the engine's pallas
    probe refusal, so the static verdict and the runtime downgrade
    can never disagree on the bound."""
    cap = vmem_capacity_bytes(device_kind)
    if cap is None:
        cap = VMEM_CAPACITY_BYTES[DEFAULT_VMEM_TARGET_KIND]
    return int(cap * PALLAS_VMEM_HEADROOM)


@dataclass
class CostReport:
    """One compiled program's static cost model (+ optional measured
    achievement). Every analysis-derived field is Optional — backends
    without ``cost_analysis`` report None, never zero (a zero would
    read as "free", a None as "unreported")."""

    form: str                    # dispatch-form / program label
    #: Iterations ONE dispatch of this program executes (a k-iteration
    #: fused scan is k) — the per-iteration fields divide by it.
    iters: int = 1
    flops: Optional[float] = None          # whole-program FLOPs
    bytes_accessed: Optional[float] = None  # whole-program HBM bytes
    peak_bytes: Optional[int] = None       # peak device allocation
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    num_edges: Optional[int] = None
    #: Measured seconds per iteration (attach_measurement) — turns the
    #: static model into an achieved-vs-roofline fraction.
    seconds_per_iter: Optional[float] = None
    device_kind: Optional[str] = None

    # -- analytic views ----------------------------------------------------

    def _per_iter(self, total: Optional[float]) -> Optional[float]:
        return None if total is None else total / max(1, self.iters)

    @property
    def flops_per_iter(self) -> Optional[float]:
        return self._per_iter(self.flops)

    @property
    def bytes_per_iter(self) -> Optional[float]:
        return self._per_iter(self.bytes_accessed)

    @property
    def bytes_per_edge(self) -> Optional[float]:
        """Analytic HBM bytes per edge per iteration — the layout-
        efficiency number PERF_NOTES' per-form table tracks."""
        b = self.bytes_per_iter
        if b is None or not self.num_edges:
            return None
        return b / self.num_edges

    @property
    def flops_per_edge(self) -> Optional[float]:
        f = self.flops_per_iter
        if f is None or not self.num_edges:
            return None
        return f / self.num_edges

    @property
    def achieved_bytes_per_s(self) -> Optional[float]:
        b = self.bytes_per_iter
        if b is None or not self.seconds_per_iter:
            return None
        return b / self.seconds_per_iter

    @property
    def roofline_fraction(self) -> Optional[float]:
        """achieved HBM bytes/s over the device's published peak —
        how close the dispatch runs to the memory-bound ceiling (None
        off-roofline-table or unmeasured)."""
        a = self.achieved_bytes_per_s
        peak = hbm_peak_bytes_per_s(self.device_kind)
        if a is None or peak is None:
            return None
        return a / peak

    def to_json(self) -> dict:
        """Flat strict-JSON dict: stored fields plus the derived
        analytics — the shape the run report / bench JSON embed."""
        out = dataclasses.asdict(self)
        out["flops_per_iter"] = self.flops_per_iter
        out["bytes_per_iter"] = self.bytes_per_iter
        out["bytes_per_edge"] = self.bytes_per_edge
        out["flops_per_edge"] = self.flops_per_edge
        out["achieved_bytes_per_s"] = self.achieved_bytes_per_s
        out["roofline_fraction"] = self.roofline_fraction
        return out


def _device_kind() -> Optional[str]:
    try:
        import jax

        devs = jax.devices()
        return devs[0].device_kind if devs else None
    except Exception:
        return None


def harvest(form: str, compiled, *, num_edges: Optional[int] = None,
            iters: int = 1, record: bool = True) -> CostReport:
    """Harvest one AOT-compiled program's cost/memory analysis into a
    :class:`CostReport` (fields None where the backend doesn't report)
    and — by default — record it in the ledger + registry. Never
    raises: the jax_compat shims are the degrade-to-None boundary for
    every backend-facing call, so accounting cannot fail a build."""
    from pagerank_tpu.utils import jax_compat

    report = CostReport(form=form, iters=max(1, int(iters)),
                        num_edges=num_edges, device_kind=_device_kind())
    ca = jax_compat.compiled_cost_analysis(compiled)
    if ca is not None:
        report.flops = ca.get("flops")
        report.bytes_accessed = ca.get("bytes accessed")
    ma = jax_compat.compiled_memory_analysis(compiled)
    if ma is not None:
        report.peak_bytes = ma.get("peak_bytes")
        report.argument_bytes = ma.get("argument_bytes")
        report.output_bytes = ma.get("output_bytes")
        report.temp_bytes = ma.get("temp_bytes")
        report.generated_code_bytes = ma.get("generated_code_bytes")
    if record:
        record_report(report)
    return report


def harvest_abstract(form: str, fn, args, *, static_kwargs=None,
                     donate_argnums=(), num_edges: Optional[int] = None,
                     ) -> CostReport:
    """Harvest a program's cost/memory model WITHOUT executing or
    allocating it: AOT-lower ``fn`` over abstract ``args``
    (ShapeDtypeStructs are fine — nothing is device_put) and read the
    compiled handle's analyses. The OOM-preflight fit check
    (obs/devices.fit_check) runs the whole device-build pipeline
    through this at the TARGET geometry before any real buffer exists.
    Unlike :func:`harvest` this does NOT record into the ledger (a
    what-if geometry must not overwrite the live run's model) and DOES
    propagate compile errors — a stage that cannot even lower at the
    target shapes is itself a preflight verdict the caller reports."""
    import functools

    import jax

    if static_kwargs:
        fn = functools.partial(fn, **static_kwargs)
    compiled = jax.jit(fn, donate_argnums=tuple(donate_argnums)).lower(
        *args).compile()
    return harvest(form, compiled, num_edges=num_edges, record=False)


# -- process-global ledger --------------------------------------------------

_LEDGER: Dict[str, CostReport] = {}


def record_report(report: CostReport) -> CostReport:
    """File ``report`` under its form (last write wins — a recompile of
    the same form replaces the stale model) and mirror the headline
    numbers into the metrics registry as ``cost.<form>.*`` gauges, so
    the live exporter publishes the model alongside measured rates."""
    _LEDGER[report.form] = report
    for metric, value in (
        ("flops", report.flops_per_iter),
        ("hbm_bytes", report.bytes_per_iter),
        ("peak_bytes", report.peak_bytes),
    ):
        if value is not None:
            obs_metrics.gauge(
                f"cost.{report.form}.{metric}",
                f"XLA cost model: per-iteration {metric} of the "
                f"'{report.form}' program",
            ).set(value)
    return report


def attach_measurement(form: str, seconds_per_iter: float,
                       num_edges: Optional[int] = None) -> Optional[CostReport]:
    """Attach a measured per-iteration wall to a ledgered form —
    activates the achieved-vs-roofline view. Returns the report (None
    when the form was never harvested)."""
    report = _LEDGER.get(form)
    if report is None:
        return None
    report.seconds_per_iter = float(seconds_per_iter)
    if num_edges is not None:
        report.num_edges = num_edges
    frac = report.roofline_fraction
    if frac is not None:
        obs_metrics.gauge(
            f"cost.{form}.roofline_fraction",
            f"achieved HBM bytes/s over the device peak for "
            f"'{form}'",
        ).set(frac)
    return report


def get_report(form: str) -> Optional[CostReport]:
    return _LEDGER.get(form)


def ledger_snapshot() -> Dict[str, dict]:
    """``{form: CostReport.to_json()}``, stable key order — the
    ``costs`` section of the run report and bench JSON."""
    return {form: _LEDGER[form].to_json() for form in sorted(_LEDGER)}


def reset() -> None:
    """Drop every ledgered report — one run's cost model must not
    bleed into the next in-process run (cli.main resets at entry,
    alongside the metrics registry)."""
    _LEDGER.clear()
