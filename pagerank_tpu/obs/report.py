"""Run flight-recorder: one JSON artifact that explains a run.

VERDICT r5 documented an hour-scale backend degradation that
contaminated several bench cells and had to be controlled for BY HAND —
nothing recorded which jaxlib, which device kind, or which phase slowed
down. The flight recorder turns that into a mechanical comparison:
every CLI/bench run can write ``run_report.json`` carrying

  - an **environment fingerprint** (jax/jaxlib version, backend +
    device kind, device/process count, x64 flag, git rev) — the
    backend-drift axis;
  - the **resolved config** — the code-change axis;
  - the **span-tree summary** (obs/trace.Tracer.summary) — where the
    wall went, phase by phase;
  - the **metrics registry snapshot** (obs/metrics) — retries,
    rollbacks, dead-letters, cache hits;
  - the **per-iteration history** (utils/metrics.MetricsLogger) and
    run summary — convergence telemetry (asynchronous-iteration
    analyses, Kollias et al., arXiv:cs/0606047: convergence telemetry
    is what makes solver behaviour debuggable);
  - the **robustness summary** (docs/ROBUSTNESS.md counters).

``python -m pagerank_tpu.obs report A.json [B.json]`` pretty-prints one
report or diffs two phase-by-phase (wall and rate deltas), separating
code regressions from backend drift.

Reports are STRICT JSON: every float is sanitized (non-finite -> null)
and dumped with ``allow_nan=False``, so no consumer ever sees a bare
``Infinity`` (the defect class fixed in utils/metrics.py — ISSUE 4
satellite 1).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import subprocess
import time
from typing import Dict, List, Optional

from pagerank_tpu.utils import fsio

SCHEMA_VERSION = 1

#: Top-level keys every run report carries (schema-stability contract,
#: tests/test_obs.py::test_cli_run_report_schema). ``devices`` (ISSUE
#: 10) is the device-plane section: per-device HBM watermark + last
#: sample — present on FAILURE-marked reports too (OOM forensics).
#: ``lowering`` (ISSUE 11) is the compiler-plane section: per-form
#: optimized-HLO lowering reports (obs/hlo.py) — empty unless the
#: inspector was armed (``--dump-hlo`` / ``engine.lowering_reports``).
#: ``job`` (ISSUE 12) is the resumable-job section: stage statuses,
#: resume count, skip/wall per stage (pagerank_tpu/jobs.py) — empty on
#: runs without ``--job-dir``.
#: ``graph`` (ISSUE 13) is the data-plane section: the graph's n/edge
#: counts plus — when ``--graph-profile`` armed the profiler — the
#: structural profile and the skew-driven load prediction
#: (obs/graph_profile.py; diffed FIRST by ``obs report A B`` as data
#: drift, like env drift).
#: ``sdc`` (ISSUE 15) is the silent-data-corruption section:
#: check/breach/transient/sticky counts, the quarantined device ids,
#: and the last breach's invariant detail (pagerank_tpu/sdc.py) —
#: empty unless ``--sdc-check-every`` armed the plane.
#: ``serving`` (ISSUE 19) is the query-plane section: settled-query
#: count, phase p99 decomposition, and the flight-recorder dumps
#: (serving/qtrace.report_section) — ``{"enabled": false}`` unless the
#: query plane was armed.
REPORT_KEYS = (
    "schema_version", "created_unix", "environment", "config", "spans",
    "metrics", "iterations", "summary", "robustness", "costs",
    "devices", "lowering", "job", "graph", "sdc", "serving",
)


def _json_safe(obj):
    """Recursively coerce to strict-JSON values: non-finite floats ->
    None, dataclasses -> dicts, unknown scalars -> repr strings."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _json_safe(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    try:  # numpy scalars: sanitize through their python value
        return _json_safe(obj.item())
    except (AttributeError, ValueError):
        return repr(obj)


def canonical_json(doc) -> str:
    """THE canonical serialization: strict JSON, sorted keys, fixed
    2-space indent, trailing newline. Two runs that produced the same
    document produce the same BYTES — the campaign plane's
    byte-identical resumed-report contract (obs/campaign.py) hangs off
    this, so change it only with a schema bump."""
    return json.dumps(_json_safe(doc), sort_keys=True, indent=2,
                      allow_nan=False) + "\n"


def git_rev(repo_dir: Optional[str] = None) -> Optional[str]:
    """Short git revision of the checkout (None outside a repo / without
    git) — pins the code axis of a report."""
    if repo_dir is None:
        repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=repo_dir,
            capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def environment_fingerprint() -> Dict[str, object]:
    """The backend-drift axis: everything about WHERE a run executed
    that can move its numbers without a code change. jax is imported
    lazily and every field degrades to None rather than failing — a
    report must be writable even when the backend is broken (that run
    is the one most worth explaining)."""
    import platform

    env: Dict[str, object] = {
        "python": platform.python_version(),
        "platform": platform.platform(),
        "git_rev": git_rev(),
    }
    try:
        import jax

        env["jax_version"] = jax.__version__
        try:
            import jaxlib

            env["jaxlib_version"] = jaxlib.__version__
        except (ImportError, AttributeError):
            env["jaxlib_version"] = None
        try:
            env["backend"] = jax.default_backend()
            devs = jax.devices()
            env["device_count"] = len(devs)
            env["device_kind"] = devs[0].device_kind if devs else None
        except Exception as e:  # backend init failure: record, don't die
            env["backend"] = None
            env["device_count"] = None
            env["device_kind"] = None
            env["backend_error"] = repr(e)
        try:
            # Also touches the backend — same degrade-to-None contract
            # as above (a broken backend is the run MOST worth a report).
            env["process_count"] = jax.process_count()
        except Exception:
            env["process_count"] = None
        env["x64"] = bool(jax.config.jax_enable_x64)
    except ImportError:
        env["jax_version"] = None
    return env


def build_run_report(
    config=None,
    tracer=None,
    registry=None,
    history: Optional[List[dict]] = None,
    summary: Optional[dict] = None,
    robustness: Optional[dict] = None,
    costs: Optional[dict] = None,
    devices: Optional[dict] = None,
    lowering: Optional[dict] = None,
    job: Optional[dict] = None,
    serving: Optional[dict] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Assemble the report dict. Every section is optional — a bench
    run has no per-iteration history, a CPU run has no profile — but
    every REPORT_KEYS key is always present (null/empty when unused)
    so consumers never key-error across producers. ``costs`` defaults
    to the cost-accounting ledger (obs/costs.py): the per-compiled-form
    FLOPs/HBM-bytes/peak-allocation model — ISSUE 5's "did the model
    change or just the wall time" axis. ``devices`` defaults to the
    device plane's watermark section (obs/devices.report_section):
    the HBM high-water mark + last per-device sample — the evidence
    an OOM post-mortem reads, embedded in failure-marked reports
    too."""
    if costs is None:
        from pagerank_tpu.obs import costs as costs_mod

        costs = costs_mod.ledger_snapshot()
    if devices is None:
        from pagerank_tpu.obs import devices as devices_mod

        devices = devices_mod.report_section()
    if lowering is None:
        # Compiler plane (ISSUE 11): whatever the armed inspector
        # harvested this run — empty on a disarmed (default) run, so
        # the section costs nothing unless asked for.
        from pagerank_tpu.obs import hlo as hlo_mod

        lowering = hlo_mod.ledger_snapshot()
    if serving is None:
        # Query plane (ISSUE 19): whatever the armed plane's flight
        # recorder holds — {"enabled": False} on a disarmed (default)
        # run. Lazy import: qtrace is stdlib+obs only, never the
        # daemon or jax.
        from pagerank_tpu.serving import qtrace as qtrace_mod

        serving = qtrace_mod.report_section()
    report = {
        "schema_version": SCHEMA_VERSION,
        "created_unix": time.time(),
        "environment": environment_fingerprint(),
        "config": _json_safe(config) if config is not None else None,
        "spans": _json_safe(tracer.summary()) if tracer is not None else {},
        "metrics": _json_safe(registry.snapshot())
        if registry is not None else {},
        "iterations": _json_safe(history or []),
        "summary": _json_safe(summary or {}),
        "robustness": _json_safe(robustness or {}),
        "costs": _json_safe(costs or {}),
        "devices": _json_safe(devices or {}),
        "lowering": _json_safe(lowering or {}),
        "job": _json_safe(job or {}),
        # Data plane (ISSUE 13): producers that profiled the graph
        # override via ``extra["graph"]`` (the CLI merges n/num_edges
        # with obs/graph_profile.report_section); the key is always
        # present so consumers never key-error.
        "graph": {},
        # SDC plane (ISSUE 15): producers override via
        # ``extra["sdc"]`` (pagerank_tpu/sdc.report_section); always
        # present, empty on a disarmed run.
        "sdc": {},
        "serving": _json_safe(serving or {"enabled": False}),
    }
    if extra:
        report.update(_json_safe(extra))
    return report


def write_run_report(path: str, report: dict) -> None:
    """Strict-JSON dump (``allow_nan=False``: a non-finite float
    reaching here is a bug in _json_safe coverage, surfaced loudly)."""
    with fsio.fopen(path, "w") as f:
        json.dump(report, f, indent=2, allow_nan=False)
        f.write("\n")


def load_report(path: str) -> dict:
    with fsio.fopen(path) as f:
        return json.load(f)


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "-"


def _fmt_qty(v) -> str:
    """Compact magnitude formatting for cost-model quantities (flops,
    bytes); '-' for unreported (None)."""
    if not isinstance(v, (int, float)):
        return "-"
    for div, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suffix}"
    return f"{v:.0f}"


def render_report(report: dict) -> str:
    """Human view of one report: environment, headline rates, phase
    table, robustness + notable metrics."""
    lines = []
    env = report.get("environment", {})
    lines.append(
        f"run report (schema v{report.get('schema_version')}): "
        f"jax {env.get('jax_version')} / jaxlib {env.get('jaxlib_version')}"
        f", backend {env.get('backend')} ({env.get('device_kind')}, "
        f"{env.get('device_count')} device(s)), x64={env.get('x64')}, "
        f"git {env.get('git_rev')}"
    )
    summ = report.get("summary") or {}
    if summ:
        its = summ.get("iters")
        ms = summ.get("mean_iter_seconds")
        eps = summ.get("edges_per_sec_per_chip")
        lines.append(
            f"solve: {its} iters, "
            + (f"{ms * 1e3:.2f} ms/iter, " if ms is not None else "")
            + (f"{eps:.4g} edges/s/chip" if eps is not None else "")
        )
    spans = report.get("spans") or {}
    if spans:
        lines.append("phases (total wall, count, mean):")
        w = max(len(n) for n in spans)
        for name, a in spans.items():
            lines.append(
                f"  {name:<{w}}  {a['total_s']:9.3f}s  x{a['count']:<5d}"
                f"  mean {a['mean_s'] * 1e3:9.2f} ms"
            )
    costs = report.get("costs") or {}
    if costs:
        lines.append("cost model (per iteration; '-' = backend did not "
                     "report):")
        w = max(len(n) for n in costs)
        for form in sorted(costs):
            c = costs[form]
            lines.append(
                f"  {form:<{w}}  flops {_fmt_qty(c.get('flops_per_iter'))}"
                f"  hbm {_fmt_qty(c.get('bytes_per_iter'))}B"
                f"  peak {_fmt_qty(c.get('peak_bytes'))}B"
                + (f"  {c['bytes_per_edge']:.1f} B/edge"
                   if c.get("bytes_per_edge") is not None else "")
                + (f"  roofline {c['roofline_fraction']:.1%}"
                   if c.get("roofline_fraction") is not None else "")
            )
    low = report.get("lowering") or {}
    if low:
        lines.append("lowering (optimized HLO per compiled form):")
        w = max(len(n) for n in low)
        for form in sorted(low):
            r = low[form]
            g = r.get("gather") or {}
            hg = g.get("hot_gather") or {}
            lines.append(
                f"  {form:<{w}}  gather "
                f"{str(g.get('strategy', '?')).upper():<8}"
                f"  fusions {r.get('fusion_count', 0):<3}"
                + (f"  stream {hg['stream_dtype']}"
                   if hg.get("stream_dtype") else "")
                + (f"  {r['hlo_bytes_per_edge']:.1f} hloB/edge"
                   if r.get("hlo_bytes_per_edge") is not None else "")
                + f"  fp {r.get('fingerprint')}"
            )
    rb = report.get("robustness") or {}
    if any(rb.values()):
        lines.append(
            "robustness: "
            + ", ".join(f"{k}={v}" for k, v in rb.items() if v)
        )
    sdc = report.get("sdc") or {}
    if sdc:
        lines.append(
            f"sdc: {sdc.get('checks', 0)} checked step(s), "
            f"{sdc.get('flips_detected', 0)} breach(es) "
            f"({sdc.get('transient', 0)} transient, "
            f"{sdc.get('sticky', 0)} sticky), quarantined "
            f"{sdc.get('quarantined_devices') or []}"
        )
        lb = sdc.get("last_breach") or {}
        if lb:
            kinds = ", ".join(
                r.get("kind", "?") for r in (lb.get("reasons") or []))
            lines.append(
                f"  last breach @ iter {lb.get('iteration')}: {kinds}"
                + (f" -> {lb.get('classified')}"
                   if lb.get("classified") else "")
                + (f" (device {lb.get('device')})"
                   if lb.get("device") is not None else "")
            )
    sv = report.get("serving") or {}
    if sv.get("enabled"):
        p99 = sv.get("phase_p99_ms") or {}
        dumps = sv.get("flight_dumps") or []
        lines.append(
            f"serving (query plane): {sv.get('settled', 0)} settled, "
            f"{sv.get('slow_queries', 0)} slow; p99 ms "
            + ", ".join(f"{k}={v:g}" for k, v in p99.items())
        )
        if dumps:
            lines.append(
                "  flight dumps: "
                + ", ".join(
                    f"{d.get('reason')}({len(d.get('traces') or [])})"
                    for d in dumps)
            )
    jb = report.get("job") or {}
    if jb.get("stages"):
        mark = ("INTERRUPTED" if report.get("interrupted")
                else jb.get("status"))
        lines.append(
            f"job: {mark}, resume #{jb.get('resumes', 0)} "
            f"({jb.get('dir')})"
        )
        for s, r in jb["stages"].items():
            w = r.get("wall_s")
            lines.append(
                f"  {s:<8} {r.get('status')}"
                + ("  [skipped: durable artifact]" if r.get("skipped")
                   else (f"  {w:.3f}s" if isinstance(w, (int, float))
                         else ""))
            )
    gr = report.get("graph") or {}
    prof = gr.get("profile") or {}
    if prof:
        lines.append(
            f"graph profile: {prof.get('num_edges'):,} unique edges"
            + (f" ({prof.get('duplicate_edges'):,} dups collapsed)"
               if prof.get("duplicate_edges") is not None else "")
            + f", dangling {prof.get('dangling_fraction', 0):.3%}"
            + (f", partition skew {prof['partition_skew']:.2f}"
               if prof.get("partition_skew") is not None else "")
            + (f", alpha {prof['powerlaw_alpha']:.2f}"
               if prof.get("powerlaw_alpha") is not None else "")
        )
        pred = gr.get("prediction") or {}
        if pred:
            lines.append(
                f"  predicted (ndev {pred.get('ndev')}): straggler "
                f"skew {pred.get('predicted_straggler_skew')}, halo "
                f"head-K {pred.get('predicted_halo_head_k')}"
            )
    dv = report.get("devices") or {}
    if dv.get("hbm_high_water_bytes") is not None:
        per_dev = dv.get("per_device_peak_bytes") or {}
        lines.append(
            f"devices: HBM high water "
            f"{dv['hbm_high_water_bytes'] / 1e9:.2f}GB over "
            f"{dv.get('samples', 0)} sample(s)"
            + (f", per device " + ", ".join(
                f"{k}={v / 1e9:.2f}GB" for k, v in per_dev.items())
               if per_dev else "")
        )
    mets = report.get("metrics") or {}
    counters = mets.get("counters") or {}
    if counters:
        lines.append("counters:")
        for k, v in counters.items():
            lines.append(f"  {k} = {v}")
    n_iter = len(report.get("iterations") or [])
    if n_iter:
        lines.append(f"iterations recorded: {n_iter}")
    return "\n".join(lines)


def _rel(a, b) -> Optional[float]:
    if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
        return None
    if a == 0:
        return None
    return (b - a) / a


#: Profile scalars the data-drift diff compares (a subset of
#: obs/graph_profile.GraphProfile.summary() chosen to move whenever
#: the DATA moved: size, dedup shape, mass structure, skew, tail).
GRAPH_DRIFT_KEYS = (
    "n", "num_edges", "raw_edges", "duplicate_edges", "self_loops",
    "dangling_count", "dangling_fraction", "zero_in_count",
    "partition_skew", "powerlaw_alpha", "fingerprint",
)


def _diff_graph_block(ga: dict, gb: dict) -> List[str]:
    """The ``graph`` section's data-drift lines (empty when nothing
    moved / neither run profiled)."""
    lines: List[str] = []
    diffs = []
    for k in ("n", "num_edges"):
        va, vb = ga.get(k), gb.get(k)
        if va != vb and (va is not None or vb is not None):
            diffs.append(f"  {k}: {va!r} -> {vb!r}")
    pa = ga.get("profile") or {}
    pb = gb.get("profile") or {}
    for k in GRAPH_DRIFT_KEYS:
        va, vb = pa.get(k), pb.get(k)
        if va is None and vb is None:
            continue
        if isinstance(va, float) and isinstance(vb, float):
            if va == vb or (va and abs(vb - va) / abs(va) < 1e-9):
                continue
        elif va == vb:
            continue
        diffs.append(f"  profile.{k}: {va!r} -> {vb!r}")
    qa = ga.get("prediction") or {}
    qb = gb.get("prediction") or {}
    for k in ("predicted_straggler_skew", "predicted_halo_head_k"):
        va, vb = qa.get(k), qb.get(k)
        if va != vb and (va is not None or vb is not None):
            diffs.append(f"  prediction.{k}: {va!r} -> {vb!r}")
    if diffs:
        lines.append("data DIFFERS (the GRAPH changed — deltas below "
                     "may be data-shaped, not code or backend):")
        lines.extend(diffs)
    elif pa or pb:
        lines.append("data: graph profile identical (deltas below are "
                     "not data drift)")
    return lines


def diff_reports(a: dict, b: dict) -> str:
    """Phase-by-phase diff of two reports: environment differences
    first (the backend-drift axis — if these differ, wall deltas below
    may be drift, not code), then per-phase wall deltas, rate deltas,
    and counter deltas. The r5 'environment variance' problem as a
    mechanical comparison."""
    lines = []
    ea, eb = a.get("environment", {}), b.get("environment", {})
    keys = sorted(set(ea) | set(eb))
    env_diffs = [
        f"  {k}: {ea.get(k)!r} -> {eb.get(k)!r}"
        for k in keys if ea.get(k) != eb.get(k) and k != "git_rev"
    ]
    if ea.get("git_rev") != eb.get("git_rev"):
        lines.append(
            f"code: git {ea.get('git_rev')} -> {eb.get('git_rev')}"
        )
    if env_diffs:
        lines.append("environment DIFFERS (wall deltas below may be "
                     "backend drift, not code):")
        lines.extend(env_diffs)
    else:
        lines.append("environment: identical (deltas below are code or "
                     "load, not backend drift)")

    # Data-plane drift (ISSUE 13; obs/graph_profile.py) — called out
    # BEFORE any perf delta, like env drift: if the GRAPH changed,
    # wall/rate/skew deltas below may be data-shaped, not code.
    lines.extend(_diff_graph_block(a.get("graph") or {},
                                   b.get("graph") or {}))

    sa, sb = a.get("spans") or {}, b.get("spans") or {}
    names = sorted(set(sa) | set(sb),
                   key=lambda n: -(sa.get(n, sb.get(n))["total_s"]))
    if names:
        lines.append("phase wall deltas (A -> B):")
        w = max(len(n) for n in names)
        for name in names:
            ta = sa.get(name, {}).get("total_s")
            tb = sb.get(name, {}).get("total_s")
            rel = _rel(ta, tb)
            tag = (f"{rel:+.1%}" if rel is not None
                   else "only in B" if ta is None else "only in A")
            lines.append(
                f"  {name:<{w}}  {_fmt_s(ta):>10} -> {_fmt_s(tb):>10}"
                f"  {tag}"
            )

    ra, rb = a.get("summary") or {}, b.get("summary") or {}
    rate_keys = ("mean_iter_seconds", "iters_per_sec",
                 "edges_per_sec_per_chip")
    rate_lines = []
    for k in rate_keys:
        va, vb = ra.get(k), rb.get(k)
        if va is None and vb is None:
            continue
        rel = _rel(va, vb)
        rate_lines.append(
            f"  {k}: {va if va is not None else '-'} -> "
            f"{vb if vb is not None else '-'}"
            + (f"  ({rel:+.1%})" if rel is not None else "")
        )
    if rate_lines:
        lines.append("rate deltas:")
        lines.extend(rate_lines)

    # Cost-model deltas (ISSUE 5): a changed model means the CODE
    # changed what a step should cost; identical models with moved wall
    # times point at the backend — the regression-vs-drift separation,
    # now on the analytic axis too.
    qa, qb = a.get("costs") or {}, b.get("costs") or {}
    cost_lines = []
    for form in sorted(set(qa) | set(qb)):
        fa, fb = qa.get(form, {}), qb.get(form, {})
        deltas = []
        for key, tag in (("flops_per_iter", "flops"),
                         ("bytes_per_iter", "hbm"),
                         ("peak_bytes", "peak"),
                         # The size-normalized axis the perf-history
                         # ledger baselines on (obs/history.py) — a
                         # pseudo-baseline report may carry ONLY this.
                         ("bytes_per_edge", "B/edge")):
            va, vb = fa.get(key), fb.get(key)
            if va == vb:
                continue
            rel = _rel(va, vb)
            deltas.append(
                f"{tag} {_fmt_qty(va)} -> {_fmt_qty(vb)}"
                + (f" ({rel:+.1%})" if rel is not None else "")
            )
        if not fa:
            deltas = ["only in B"]
        elif not fb:
            deltas = ["only in A"]
        if deltas:
            cost_lines.append(f"  {form}: " + ", ".join(deltas))
    if cost_lines:
        lines.append("cost-model deltas (the program changed, not just "
                     "the wall):")
        lines.extend(cost_lines)
    elif qa or qb:
        lines.append("cost model: identical (wall deltas above are "
                     "execution, not program, changes)")

    # Compiler-plane deltas (ISSUE 11): per-form lowering changes —
    # gather strategy, fusion count, the structural fingerprint. A
    # moved fingerprint with identical code/env means the COMPILER
    # changed the program (a jax/libtpu upgrade), which is exactly the
    # attribution the r5-class incidents needed.
    la, lb = a.get("lowering") or {}, b.get("lowering") or {}
    low_lines = []
    for form in sorted(set(la) | set(lb)):
        fa, fb = la.get(form) or {}, lb.get(form) or {}
        if not fa:
            low_lines.append(f"  {form}: only in B")
            continue
        if not fb:
            low_lines.append(f"  {form}: only in A")
            continue
        deltas = []
        ga = (fa.get("gather") or {}).get("strategy")
        gb_ = (fb.get("gather") or {}).get("strategy")
        if ga != gb_:
            deltas.append(f"gather {ga} -> {gb_}")
        if fa.get("fusion_count") != fb.get("fusion_count"):
            deltas.append(f"fusions {fa.get('fusion_count')} -> "
                          f"{fb.get('fusion_count')}")
        ha = ((fa.get("gather") or {}).get("hot_gather") or {})
        hb = ((fb.get("gather") or {}).get("hot_gather") or {})
        if ha.get("stream_dtype") != hb.get("stream_dtype"):
            deltas.append(f"stream {ha.get('stream_dtype')} -> "
                          f"{hb.get('stream_dtype')}")
        if not deltas and fa.get("fingerprint") != fb.get("fingerprint"):
            deltas.append(f"fingerprint {fa.get('fingerprint')} -> "
                          f"{fb.get('fingerprint')}")
        if deltas:
            low_lines.append(f"  {form}: " + ", ".join(deltas))
    if low_lines:
        lines.append("lowering deltas (the COMPILER changed the "
                     "program shape):")
        lines.extend(low_lines)
    elif la or lb:
        lines.append("lowering: identical (the compiler emitted the "
                     "same program shape)")

    # Device-plane deltas (ISSUE 10): the comms attribution gauges
    # (exchange fraction, achieved wire bytes/s) and the per-run HBM
    # high-water mark — "did the exchange get slower or did we start
    # running closer to the memory ceiling" as a mechanical diff.
    ga = (a.get("metrics") or {}).get("gauges") or {}
    gb = (b.get("metrics") or {}).get("gauges") or {}
    comms_keys = sorted(
        k for k in set(ga) | set(gb)
        if k.startswith("comms.") and ga.get(k) != gb.get(k)
    )
    comms_lines = []
    for k in comms_keys:
        va, vb = ga.get(k), gb.get(k)
        rel = _rel(va, vb)
        comms_lines.append(
            f"  {k}: {_fmt_qty(va)} -> {_fmt_qty(vb)}"
            + (f"  ({rel:+.1%})" if rel is not None else "")
        )
    da = (a.get("devices") or {}).get("hbm_high_water_bytes")
    db = (b.get("devices") or {}).get("hbm_high_water_bytes")
    if da != db and (da is not None or db is not None):
        rel = _rel(da, db)
        comms_lines.append(
            f"  hbm_high_water_bytes: {_fmt_qty(da)} -> {_fmt_qty(db)}"
            + (f"  ({rel:+.1%})" if rel is not None else "")
        )
    if comms_lines:
        lines.append("device-plane deltas (comms attribution + HBM "
                     "watermark):")
        lines.extend(comms_lines)

    # Resumable-job deltas (ISSUE 12): which stages a resumed run
    # skipped via durable artifacts vs executed — "did the restart
    # actually avoid the 75 s build" as a mechanical diff.
    ja, jb = a.get("job") or {}, b.get("job") or {}
    if ja.get("stages") or jb.get("stages"):
        job_lines = []
        if ja.get("resumes") != jb.get("resumes"):
            job_lines.append(
                f"  resumes: {ja.get('resumes', 0)} -> "
                f"{jb.get('resumes', 0)}"
            )
        names = sorted(set(ja.get("stages") or {})
                       | set(jb.get("stages") or {}))
        for s in names:
            ra = (ja.get("stages") or {}).get(s) or {}
            rb_ = (jb.get("stages") or {}).get(s) or {}
            da = ("skipped" if ra.get("skipped") else ra.get("status"))
            db_ = ("skipped" if rb_.get("skipped") else rb_.get("status"))
            if da != db_:
                job_lines.append(f"  {s}: {da} -> {db_}")
        if job_lines:
            lines.append("job-stage deltas (resume skips vs executed "
                         "work):")
            lines.extend(job_lines)

    # SDC-plane deltas (ISSUE 15): detection/classification/quarantine
    # movement between two runs — "did the integrity plane fire" as a
    # mechanical diff, next to the robustness counters it extends.
    xa, xb = a.get("sdc") or {}, b.get("sdc") or {}
    if xa or xb:
        sdc_lines = []
        for k in ("checks", "flips_detected", "transient", "sticky"):
            va, vb = xa.get(k, 0), xb.get(k, 0)
            if va != vb:
                sdc_lines.append(f"  {k}: {va} -> {vb}")
        qa_, qb_ = (xa.get("quarantined_devices") or [],
                    xb.get("quarantined_devices") or [])
        if qa_ != qb_:
            sdc_lines.append(
                f"  quarantined_devices: {qa_!r} -> {qb_!r}")
        if sdc_lines:
            lines.append("sdc deltas (silent-data-corruption plane):")
            lines.extend(sdc_lines)

    ca = (a.get("metrics") or {}).get("counters") or {}
    cb = (b.get("metrics") or {}).get("counters") or {}
    counter_lines = [
        f"  {k}: {ca.get(k, 0)} -> {cb.get(k, 0)}"
        for k in sorted(set(ca) | set(cb)) if ca.get(k, 0) != cb.get(k, 0)
    ]
    if counter_lines:
        lines.append("counter deltas:")
        lines.extend(counter_lines)
    return "\n".join(lines)
