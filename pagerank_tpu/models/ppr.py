"""Personalized PageRank (BASELINE.md config 5).

The reference computes only global PageRank; PPR is the natural model
extension the north star asks for: a *batch* of source-personalized rank
vectors, so the per-iteration SpMV becomes an SpMM (rank matrix [n, k])
— exactly the arithmetic-intensity upgrade TPUs want (more FLOPs per
byte of edge data).

Update (textbook formulation, batch columns independent):

    R' = (1-d) P + d (Aᵀ_norm R + dangling_redistribution)

where P[:, j] is the personalization distribution of source j (one-hot
e_{s_j} here) and dangling mass is redistributed either to the
personalization vector (standard PPR; keeps each column a probability
distribution) or uniformly.
"""

from __future__ import annotations


DANGLING_TO_SOURCE = "source"
DANGLING_TO_UNIFORM = "uniform"


def apply_ppr_update(contrib, p_onehot, dangling_mass, n, damping, dangling_to, xp):
    """One batched PPR update.

    Args:
      contrib: [n, k] — Aᵀ_norm R.
      p_onehot: [n, k] personalization distributions (columns sum to 1).
      dangling_mass: [k] — per-column Σ_dangling R.
      dangling_to: "source" (mass re-enters via P) or "uniform" (/n).
    """
    if dangling_to == DANGLING_TO_SOURCE:
        redistributed = contrib + p_onehot * dangling_mass[None, :]
    elif dangling_to == DANGLING_TO_UNIFORM:
        redistributed = contrib + dangling_mass[None, :] / n
    else:
        raise ValueError(f"unknown dangling_to: {dangling_to!r}")
    return (1.0 - damping) * p_onehot + damping * redistributed
