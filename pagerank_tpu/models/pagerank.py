"""The PageRank update rules — pure math, backend-agnostic.

Two semantics modes (SURVEY.md §2a):

**reference** — exactly what `Sparky.java`'s local-mode run computes:
    r0 = 1                                          (Sparky.java:168)
    r' = 0.15 + d * (Aᵀ_norm r  +  z ⊙ r  +  (mᵀ r)/N · 1)   (:229-235)
  where
    Aᵀ_norm[t, s] = 1/out_degree[s] per unique edge s→t (:124,:192-216),
    z = (in_degree == 0)  — vertices that receive no contributions keep
        their *old rank* as their contribution sum, via
        ``ranks.subtractByKey(contribs)`` + union (:224-225),
    m = (out_degree == 0) — dangling mass spread uniformly,
        ``danglingContrib / totalUrlCount`` (:219-222, :233).
  Ranks sum ≈ N ("N-scaled" formulation — 0.15, not (1-d)/N).

**textbook** — standard normalized PageRank:
    r0 = 1/N
    r' = (1-d)/N + d * (Aᵀ_norm r + (mᵀ r)/N · 1)

Both are expressed over a *contribution sum* computed by the backend
(segment-sum over edges on device, scipy SpMV on host), so the same
update applies to every engine.
"""

from __future__ import annotations


def apply_update(contrib_sum, r_old, zero_in_mask, dangling_mass, n, damping, semantics, xp):
    """Combine the per-vertex contribution sum into the next rank vector.

    Args:
      contrib_sum: [n] (or [n, k] for personalized batches) — Aᵀ_norm r.
      r_old: previous rank vector, same shape.
      zero_in_mask: [n] float mask, 1.0 where in_degree == 0.
      dangling_mass: scalar (or [k]) — Σ_dangling r_old.
      n: vertex count.
      damping: d in (0,1).
      semantics: "reference" | "textbook".
      xp: array namespace (numpy or jax.numpy).
    """
    if semantics == "reference":
        s = contrib_sum + _bcast(zero_in_mask, r_old) * r_old
        return (1.0 - damping) + damping * (s + dangling_mass / n)
    elif semantics == "textbook":
        return (1.0 - damping) / n + damping * (contrib_sum + dangling_mass / n)
    raise ValueError(f"unknown semantics: {semantics!r}")


def initial_rank(n, semantics, dtype, xp, batch: int | None = None):
    """r0 = 1.0 per vertex in reference mode (Sparky.java:165-170);
    1/N in textbook mode. ``batch`` adds a trailing axis for PPR."""
    shape = (n,) if batch is None else (n, batch)
    v = 1.0 if semantics == "reference" else 1.0 / n
    return xp.full(shape, v, dtype=dtype)


def _bcast(mask, like):
    # Broadcast a [n] mask against [n] or [n, k] rank arrays.
    return mask if like.ndim == 1 else mask[:, None]
