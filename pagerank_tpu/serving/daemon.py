"""The resident PPR query daemon (ISSUE 18 tentpole).

:class:`PprServer` owns a sharded resident graph and ONE AOT-warmed
compiled PPR batch program: every dispatched batch is padded to
exactly ``max_batch`` sources with static ``num_iters``/``topk``, so
after the ``start()`` warm-up no query ever waits on a compile
(``utils/compile_cache`` persists the executable across restarts on
real backends). Top-k runs on device — only ``[batch, k]`` leaves the
chip.

Failure modes map to typed, bounded, observable outcomes:

- **overload**: decided at admission by :class:`~pagerank_tpu.serving.
  admission.AdmissionQueue` (typed ``Overloaded`` with retry-after);
- **chip loss / sticky-SDC quarantine** mid-serve: the PR 7/15 elastic
  rescue — probe liveness, re-shard onto the survivors
  (``mesh.surviving_devices`` + a rebuilt engine), RE-RUN the
  in-flight batch. Counted (``serve.rescues``, ``serve.batch_reruns``)
  and never silently dropped; subsequent answers are marked
  ``degraded``;
- **SIGTERM**: the PR 12 drain — :meth:`drain` closes admission
  (typed ``Draining`` rejections), in-flight batches finish inside the
  drain deadline, the rest are typed-rejected, exit 75 at the CLI;
- **stuck dispatch**: bounded by ``mesh.run_with_deadline`` — the
  batch fails typed (``QueryDeadlineExceeded``) instead of hanging the
  queue.

Concurrency (PTR rules): the admission queue's Condition is the
cross-thread meeting point; server-side mutable state (engine,
devices, degraded flag) lives behind ``_state_lock`` and is only
written by the dispatch context. Blocking work (device dispatch,
``run_with_deadline``) happens outside every lock (PTR004). The
dispatcher thread is named and joined (PTR005); clocks are injected
(PTR006).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

from pagerank_tpu.engines.ppr import PprJaxEngine
from pagerank_tpu.graph import Graph
from pagerank_tpu.models import ppr as ppr_model
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.parallel import mesh as mesh_lib
from pagerank_tpu.parallel.elastic import (DeviceLostError,
                                           ElasticExhaustedError,
                                           looks_like_device_loss)
from pagerank_tpu.serving import qtrace
from pagerank_tpu.serving.admission import AdmissionQueue, BatchWallModel
from pagerank_tpu.serving.cache import ResultCache
from pagerank_tpu.serving.query import (Draining, PendingQuery,
                                        QueryDeadlineExceeded,
                                        ServeRejected)
from pagerank_tpu.utils.config import PageRankConfig


@dataclass
class ServeConfig:
    """Knobs of the serving layer (engine numerics stay in
    :class:`PageRankConfig`)."""

    max_batch: int = 8           # compiled batch width (pad-to-full)
    queue_depth: int = 64        # bounded admission
    deadline_ms: float = 500.0   # default per-query deadline
    topk: int = 100              # static on-device top-k width
    num_iters: Optional[int] = None   # None -> engine config's
    batch_margin_s: float = 0.02      # close-early margin before oldest deadline
    dispatch_timeout_s: float = 30.0  # run_with_deadline bound per batch
    drain_deadline_s: float = 5.0     # SIGTERM drain budget
    cache_capacity: int = 1024        # 0 disables the LRU
    wall_initial_s: float = 0.05      # batch wall model prior
    wall_alpha: float = 0.3           # EWMA weight; 0 freezes (determinism)
    max_rescues: int = 2              # elastic rescue budget while serving
    probe_timeout_s: float = 2.0      # liveness probe bound during rescue

    def validate(self) -> "ServeConfig":
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0, got {self.deadline_ms}"
            )
        if self.topk < 1:
            raise ValueError(f"topk must be >= 1, got {self.topk}")
        return self


class PprServer:
    """Deadline-honest PPR query daemon over a resident sharded graph.

    Two drive modes share every code path except the thread:

    - ``start()`` (daemon): a named dispatcher thread blocks in
      ``AdmissionQueue.next_batch`` and serves batches as they close;
    - ``start(dispatcher=False)`` + :meth:`pump` (synchronous): the
      caller advances batches explicitly — the deterministic chaos
      harness's mode (``testing/load.py``).

    ``engine_factory(devices)`` must return a built engine over
    exactly ``devices``; the default rebuilds :class:`PprJaxEngine`
    with ``num_devices=len(devices)`` — the rescue path calls it again
    with the survivor list. ``liveness_probe(devices, timeout_s)``
    defaults to ``mesh.probe_liveness``; the fault harness injects
    ``DeviceFaultSchedule.liveness_probe`` so CPU chaos sees the same
    dead set a real backend would report.
    """

    def __init__(
        self,
        graph: Graph,
        config: Optional[PageRankConfig] = None,
        serve_config: Optional[ServeConfig] = None,
        dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
        devices: Optional[Sequence] = None,
        engine_factory: Optional[Callable[[Sequence], PprJaxEngine]] = None,
        liveness_probe: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.graph = graph
        self.config = (config or PageRankConfig()).validate()
        self.serve_config = (serve_config or ServeConfig()).validate()
        self.dangling_to = dangling_to
        self._clock = clock
        self._engine_factory = engine_factory or self._default_factory
        self._liveness_probe = liveness_probe or mesh_lib.probe_liveness

        sc = self.serve_config
        self.wall_model = BatchWallModel(
            initial_s=sc.wall_initial_s, alpha=sc.wall_alpha
        )
        self.queue = AdmissionQueue(
            max_batch=sc.max_batch,
            queue_depth=sc.queue_depth,
            batch_margin_s=sc.batch_margin_s,
            wall_model=self.wall_model,
            clock=clock,
        )
        self.cache = ResultCache(capacity=sc.cache_capacity)

        # Engine / mesh state crosses the submit and dispatch contexts:
        # every non-construction access goes through _state_lock.
        self._state_lock = threading.Lock()
        self._engine: Optional[PprJaxEngine] = None
        self._devices: List = list(devices) if devices is not None else []
        self._degraded = False
        self._rescues_done = 0  # per-instance budget (counters are global)
        self._fatal: Optional[BaseException] = None
        self._started = False
        self._dispatcher: Optional[threading.Thread] = None
        self._graph_fp = graph.fingerprint()
        self._params_key = (
            self._iters(), self.config.damping,
            str(self.config.dtype), str(self.config.accum_dtype),
            dangling_to,
        )

        self._qid_lock = threading.Lock()
        self._next_qid = 0

        c = obs_metrics.counter
        self._c_accepted = c("serve.accepted", "queries admitted to the queue")
        self._c_answered = c("serve.answered", "queries resolved with a result")
        self._c_answered_cache = c(
            "serve.answered_cache", "queries resolved from the LRU at admission"
        )
        self._c_answered_degraded = c(
            "serve.answered_degraded", "queries answered on a degraded mesh"
        )
        self._c_shed = c(
            "serve.shed_overload", "typed Overloaded rejections at admission"
        )
        self._c_rej_draining = c(
            "serve.rejected_draining", "typed Draining rejections"
        )
        self._c_rej_deadline = c(
            "serve.rejected_deadline", "typed deadline rejections"
        )
        self._c_batches = c("serve.batches", "batches dispatched to the mesh")
        self._c_reruns = c(
            "serve.batch_reruns", "in-flight batches re-run after a rescue"
        )
        self._c_rescues = c("serve.rescues", "elastic rescues while serving")
        self._c_devices_lost = c(
            "serve.devices_lost", "devices lost or quarantined while serving"
        )
        self._c_dispatch_timeouts = c(
            "serve.dispatch_timeouts", "batches killed by run_with_deadline"
        )
        self._g_occupancy = obs_metrics.gauge(
            "serve.occupancy", "fill fraction of the last dispatched batch"
        )
        self._g_devices = obs_metrics.gauge(
            "serve.devices", "current mesh width"
        )
        self._h_latency = obs_metrics.histogram(
            "serve.latency_ms", "submit-to-resolve latency per answered query"
        )

    # -- lifecycle ----------------------------------------------------------

    def _default_factory(self, devices: Sequence) -> PprJaxEngine:
        cfg = self.config.replace(num_devices=len(devices))
        eng = PprJaxEngine(
            cfg, dangling_to=self.dangling_to, devices=list(devices)
        )
        eng.build(self.graph)
        return eng

    def start(self, dispatcher: bool = True) -> "PprServer":
        """Build + AOT-warm the one compiled batch program, then
        (daemon mode) start the named dispatcher thread."""
        from pagerank_tpu.utils.compile_cache import enable_compile_cache

        import jax

        with self._state_lock:
            if self._started:
                raise RuntimeError("PprServer.start() called twice")
            if not self._devices:
                self._devices = list(jax.devices())
            devices = list(self._devices)
        enable_compile_cache()
        engine = self._engine_factory(devices)
        with self._state_lock:
            self._engine = engine
            self._started = True
        self._g_devices.set(len(devices))
        # Warm the exact serving shapes (full-width batch, static
        # iters/topk) so no query ever pays the compile.
        self._execute(np.zeros(self.serve_config.max_batch, np.int64))
        if dispatcher:
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop,
                name="pagerank-serve-dispatch",
            )
            self._dispatcher.start()
        return self

    def _iters(self) -> int:
        sc = self.serve_config
        return (self.config.num_iters if sc.num_iters is None
                else sc.num_iters)

    @property
    def degraded(self) -> bool:
        with self._state_lock:
            return self._degraded

    @property
    def fatal(self) -> Optional[BaseException]:
        with self._state_lock:
            return self._fatal

    @property
    def device_count(self) -> int:
        with self._state_lock:
            return len(self._devices)

    @property
    def rescues_done(self) -> int:
        with self._state_lock:
            return self._rescues_done

    def device_ids(self) -> List[int]:
        with self._state_lock:
            return [int(d.id) for d in self._devices]

    # -- submit side --------------------------------------------------------

    def submit(self, source: int, k: Optional[int] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> PendingQuery:
        """Admit one query. ALWAYS returns a :class:`PendingQuery` —
        rejections settle the handle with the typed error instead of
        raising here, so every submission has exactly one terminal
        outcome to account for (the zero-silent-drops ledger).
        ``trace_id`` adopts an upstream W3C trace id (the HTTP
        ``traceparent``); every outcome carries ``q.trace_id`` either
        way, armed or not."""
        with self._state_lock:
            started = self._started
        if not started:
            raise RuntimeError("call start() before submit()")
        sc = self.serve_config
        k = sc.topk if k is None else min(int(k), sc.topk)
        k = max(1, min(k, self.graph.n))
        if deadline_s is None:
            deadline_s = sc.deadline_ms / 1000.0
        now = self._clock()
        with self._qid_lock:
            qid = self._next_qid
            self._next_qid += 1
        q = PendingQuery(qid=qid, source=int(source), k=k,
                         deadline=now + deadline_s, t_submit=now)
        if trace_id is not None:
            q.set_trace_id(trace_id)
        # Query plane (ISSUE 19): tr stays None while disarmed, and
        # every tracing branch below gates on it — the disarmed hot
        # path is byte-identical to the untraced one (booby-trap test).
        plane = qtrace.get_query_plane()
        tr = None
        if plane is not None:
            tr = q.trace = plane.new_trace(
                q.qid, q.source, q.trace_id, start_s=now
            )

        # Publish-last ordering (also below, in _serve_batch): settle
        # the trace BEFORE resolve()/reject() set the done event, so
        # the awakened caller thread can never observe — or touch — a
        # trace mid-settle (qtrace's happens-before contract).
        key = ResultCache.key(self._graph_fp, q.source, self._params_key, k)
        if tr is not None:
            t_c0 = self._clock()
        hit = self.cache.get(key)
        if hit is not None:
            self._c_accepted.inc()
            self._c_answered_cache.inc()
            now2 = self._clock()
            lat_ms = 1000.0 * max(0.0, now2 - q.t_submit)
            if tr is not None:
                tr.phase("query/cache", t_c0, now2 - t_c0, hit=True)
                self._h_latency.record(lat_ms, trace_id=q.trace_id)
                plane.settle(tr, "answered_cache", now2, lat_ms)
            else:
                self._h_latency.record(lat_ms)
            q.resolve(hit[0], hit[1], "cache", now2)
            return q
        if tr is not None:
            tr.phase("query/cache", t_c0, self._clock() - t_c0, hit=False)
            t_a0 = self._clock()
        try:
            self.queue.offer(q)
        except Draining as e:
            self._c_rej_draining.inc()
            now2 = self._clock()
            if tr is not None:
                tr.phase("query/admission", t_a0, now2 - t_a0,
                         decision="rejected_draining")
                plane.settle(tr, "rejected_draining", now2,
                             1000.0 * max(0.0, now2 - q.t_submit))
            q.reject(e, now2)
            return q
        except ServeRejected as e:  # Overloaded
            self._c_shed.inc()
            now2 = self._clock()
            if tr is not None:
                tr.phase("query/admission", t_a0, now2 - t_a0,
                         decision="shed_overload")
                plane.settle(tr, "shed_overload", now2,
                             1000.0 * max(0.0, now2 - q.t_submit))
            q.reject(e, now2)
            return q
        self._c_accepted.inc()
        if tr is not None:
            now2 = self._clock()
            tr.phase("query/admission", t_a0, now2 - t_a0,
                     decision="admitted")
            tr.t_admitted = now2
        return q

    # -- dispatch side ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        if qtrace.get_query_plane() is not None:
            from pagerank_tpu.obs import trace as obs_trace
            obs_trace.get_tracer().set_thread_label(
                threading.get_ident(), "serve-dispatch"
            )
        while True:
            batch = self.queue.next_batch()
            if batch is None:
                return
            try:
                self._serve_batch(batch)
            finally:
                self.queue.batch_done()

    def pump(self, max_batches: Optional[int] = None) -> int:
        """Synchronously serve every closeable batch (harness mode);
        returns the number of batches dispatched."""
        served = 0
        while max_batches is None or served < max_batches:
            batch = self.queue.try_close_batch()
            if batch is None:
                return served
            try:
                self._serve_batch(batch)
            finally:
                self.queue.batch_done()
            served += 1
        return served

    def _execute(self, sources: np.ndarray):
        """One compiled-batch dispatch: ``[max_batch] -> ([max_batch,
        topk] ids, scores)``. The fault harness wraps THIS seam — it
        survives an engine rebuild because the rescue path swaps
        ``_engine`` underneath it."""
        with self._state_lock:
            engine = self._engine
        res = engine.run(
            sources, num_iters=self._iters(),
            topk=self.serve_config.topk, chunk=self.serve_config.max_batch,
        )
        return res.topk_ids, res.topk_scores

    def _rescue(self, exc: BaseException) -> None:
        """Chip loss / quarantine mid-serve: classify the casualty,
        re-shard onto the survivors, swap the engine. Raises
        ``ElasticExhaustedError`` when the budget is spent."""
        with self._state_lock:
            devices = list(self._devices)
            rescues = self._rescues_done
        if rescues >= self.serve_config.max_rescues:
            raise ElasticExhaustedError(
                f"serving rescue budget spent ({rescues} rescues): {exc}",
                tuple(getattr(exc, "device_ids", ())), rescues,
            )
        dead = set(getattr(exc, "device_ids", ()) or ())
        alive = self._liveness_probe(
            devices, timeout_s=self.serve_config.probe_timeout_s
        )
        dead |= {i for i, ok in alive.items() if not ok}
        if not dead:
            raise exc  # loss-shaped but every device answers: surface it
        survivors = mesh_lib.surviving_devices(dead, devices=devices)
        engine = self._engine_factory(survivors)
        with self._state_lock:
            self._engine = engine
            self._devices = survivors
            self._degraded = True
            self._rescues_done += 1
        self._c_rescues.inc()
        self._c_devices_lost.inc(len(dead))
        self._g_devices.set(len(survivors))

    def _serve_batch(self, batch: List[PendingQuery]) -> None:
        sc = self.serve_config
        plane = qtrace.get_query_plane()
        close_reason = getattr(batch, "close_reason", None)
        now = self._clock()
        live = []
        for q in batch:
            if q.deadline <= now:
                self._c_rej_deadline.inc()
                tr = q.trace
                if tr is not None:
                    if tr.t_admitted is not None:
                        tr.phase("query/batch_wait", tr.t_admitted,
                                 now - tr.t_admitted,
                                 close_reason=close_reason, expired=True)
                    if plane is not None:
                        plane.settle(tr, "rejected_deadline", now,
                                     1000.0 * max(0.0, now - q.t_submit))
                q.reject(QueryDeadlineExceeded(
                    f"deadline passed in-queue "
                    f"({now - q.deadline:.3f}s late)"), now)
            else:
                live.append(q)
        if not live:
            return
        self._g_occupancy.set(len(live) / sc.max_batch)

        traced = [q for q in live if q.trace is not None]
        if traced:
            # Batch membership: every member's trace links to its
            # batch-mates' trace ids (the span-link half of the plane).
            members = [q.trace_id for q in live]
            for q in traced:
                tr = q.trace
                if tr.t_admitted is not None:
                    tr.phase("query/batch_wait", tr.t_admitted,
                             now - tr.t_admitted,
                             close_reason=close_reason,
                             batch_size=len(live))
                for m in members:
                    if m != q.trace_id:
                        tr.link(m)

        sources = np.full(sc.max_batch, live[0].source, np.int64)
        sources[: len(live)] = [q.source for q in live]

        rerun = False
        attempts = 0
        while True:
            t0 = self._clock()
            attempts += 1
            try:
                ids, scores = mesh_lib.run_with_deadline(
                    lambda: self._execute(sources), sc.dispatch_timeout_s
                )
                break
            except mesh_lib.DeadlineExpired as e:
                self._c_dispatch_timeouts.inc()
                now = self._clock()
                for q in live:
                    self._c_rej_deadline.inc()
                    tr = q.trace
                    if tr is not None:
                        tr.phase("query/dispatch", t0, now - t0,
                                 error="DeadlineExpired",
                                 attempts=attempts)
                        if plane is not None:
                            plane.settle(tr, "rejected_deadline", now,
                                         1000.0 * max(0.0,
                                                      now - q.t_submit))
                    q.reject(QueryDeadlineExceeded(
                        f"device dispatch exceeded its "
                        f"{sc.dispatch_timeout_s}s bound: {e}"), now)
                return
            except Exception as e:  # noqa: BLE001 - classified below
                if not (isinstance(e, DeviceLostError)
                        or looks_like_device_loss(e)):
                    raise
                try:
                    self._rescue(e)
                except ElasticExhaustedError as term:
                    with self._state_lock:
                        self._fatal = term
                    now = self._clock()
                    for q in live:
                        tr = q.trace
                        if tr is not None:
                            tr.phase("query/dispatch", t0, now - t0,
                                     error="ElasticExhausted",
                                     attempts=attempts)
                            if plane is not None:
                                plane.settle(
                                    tr, "rejected", now,
                                    1000.0 * max(0.0, now - q.t_submit))
                        q.reject(ServeRejected(
                            f"serving terminal: {term}"), now)
                    self.queue.stop()
                    if plane is not None:
                        plane.flight_dump("fatal")
                    return
                rerun = True  # RE-RUN the same in-flight batch
                if plane is not None:
                    plane.flight_dump("rescue")
        wall = self._clock() - t0
        self.wall_model.observe(wall)
        self._c_batches.inc()
        if rerun:
            self._c_reruns.inc()
        for q in traced:
            q.trace.phase("query/dispatch", t0, wall, rerun=rerun,
                          attempts=attempts)

        degraded = self.degraded
        served_from = "degraded" if degraded else "compute"
        outcome = "answered_degraded" if degraded else "answered"
        now = self._clock()
        for i, q in enumerate(live):
            tr = q.trace
            if tr is not None:
                t_f0 = self._clock()
            q_ids = np.array(ids[i, : q.k])
            q_scores = np.array(scores[i, : q.k])
            key = ResultCache.key(
                self._graph_fp, q.source, self._params_key, q.k
            )
            self.cache.put(key, q_ids, q_scores)
            self._c_answered.inc()
            if degraded:
                self._c_answered_degraded.inc()
            lat_ms = 1000.0 * max(0.0, now - q.t_submit)
            if tr is not None:
                tr.phase("query/fetch", t_f0, self._clock() - t_f0)
                self._h_latency.record(lat_ms, trace_id=q.trace_id)
                if plane is not None:
                    plane.settle(tr, outcome, now, lat_ms)
            else:
                self._h_latency.record(lat_ms)
            # resolve LAST: the done event publishes the query to the
            # blocked ingress thread, so the settled record is complete
            # before any other thread can see this query again.
            q.resolve(q_ids, q_scores, served_from, now)

    # -- drain side ---------------------------------------------------------

    def drain(self, deadline_s: Optional[float] = None) -> int:
        """The SIGTERM path: close admission (new offers raise typed
        ``Draining``), let queued batches finish inside the drain
        deadline, typed-reject whatever remains. Returns the number of
        flushed (rejected) queries. Idempotent."""
        if deadline_s is None:
            deadline_s = self.serve_config.drain_deadline_s
        t_end = self._clock() + deadline_s
        self.queue.stop()
        if self._dispatcher is not None:
            self._dispatcher.join(
                timeout=max(0.1, t_end - self._clock())
            )
        else:
            while self._clock() < t_end and len(self.queue) > 0:
                if self.pump() == 0:
                    break
        plane = qtrace.get_query_plane()

        def _drain_reject(q: PendingQuery) -> Draining:
            if plane is not None and q.trace is not None:
                plane.settle(q.trace, "rejected_draining",
                             self._clock(), None)
            return Draining(
                "drain deadline reached before this query's batch "
                "dispatched; retry against another replica"
            )

        flushed = self.queue.flush_rejected(_drain_reject)
        self._c_rej_draining.inc(flushed)
        if self._dispatcher is not None:
            # Queue is now empty + stopped: the thread exits its wait
            # promptly; join for real (PTR005).
            self._dispatcher.join()
            self._dispatcher = None
        if plane is not None:
            plane.flight_dump("drain")
        return flushed

    def stop(self) -> None:
        """drain() with the configured deadline — the normal shutdown."""
        self.drain()
