"""LRU result cache for served PPR queries (ISSUE 18).

Keyed ``(graph fingerprint, source, params key, k)`` — the graph
fingerprint (``Graph.fingerprint()``, a structural sha256) makes a
cached entry self-invalidating when the resident graph changes, and
the params key folds in everything that changes the answer
(iterations, damping, dtype, dangling policy, mesh width after a
degraded re-shard is NOT included: a degraded mesh computes the same
numbers, only slower, so hits stay valid across a rescue).

Thread discipline (PTR001): a single lock guards the OrderedDict; the
stored arrays are immutable by convention (the daemon stores the
device-fetched numpy copies and hands the same objects back).

Query plane (ISSUE 19): the daemon wraps every admission-time lookup
in a ``query/cache`` phase (attr ``hit``) on the query's trace — a
cache-hit settle is ``answered_cache`` with a one-phase timeline, so
even never-queued queries carry a complete causal record. The cache
itself stays observability-free beyond its aggregate hit/miss
counters: it cannot see the querying context, only keys.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Hashable, Optional, Tuple

from pagerank_tpu.obs import metrics as obs_metrics


class ResultCache:
    """Bounded LRU of ``key -> (topk_ids, topk_scores)``.

    ``capacity=0`` disables caching (every ``get`` misses, ``put`` is
    a no-op) — the chaos harness uses that to keep every query on the
    compute path."""

    def __init__(self, capacity: int = 1024):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, Tuple]" = OrderedDict()
        self._hits = obs_metrics.counter(
            "serve.cache_hits", "queries answered from the LRU cache"
        )
        self._misses = obs_metrics.counter(
            "serve.cache_misses", "queries that went to the mesh"
        )

    @staticmethod
    def key(graph_fingerprint: str, source: int, params_key: Hashable,
            k: int) -> Tuple:
        return (graph_fingerprint, int(source), params_key, int(k))

    def get(self, key: Hashable) -> Optional[Tuple]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._misses.inc()
                return None
            self._entries.move_to_end(key)
            self._hits.inc()
            return entry

    def put(self, key: Hashable, ids, scores) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            self._entries[key] = (ids, scores)
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def hits(self) -> int:
        return self._hits.value

    @property
    def misses(self) -> int:
        return self._misses.value
