"""Bounded admission + dynamic micro-batching + predictive shedding.

The queue is the deadline-honesty mechanism (ISSUE 18): admission is
where "cannot finish" becomes a typed :class:`~pagerank_tpu.serving.
query.Overloaded` rejection with a retry-after hint, instead of a
query that times out deep in the pipeline. Two rules, both decided on
the injectable clock so the chaos harness replays them bit-for-bit:

- **shed NOW, not later**: a query is admitted only when the modeled
  wait (batches ahead of it x the modeled batch wall) plus one batch
  wall fits inside its remaining deadline;
- **batch close**: a batch closes at ``max_batch`` OR when the OLDEST
  queued query's remaining deadline margin is down to one modeled
  batch wall + ``batch_margin_s`` — whichever comes first.

Concurrency (PTR rules): one ``threading.Condition`` guards every
mutable field; the dispatcher blocks in :meth:`next_batch` (the wait
releases the lock), submitters never block. No raw clock calls — the
clock is injected (PTR006).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.serving.query import (Draining, Overloaded,
                                        PendingQuery)


class BatchWallModel:
    """EWMA model of one compiled batch's wall seconds — the quantity
    the shedding rule multiplies queue depth by. ``alpha=0`` freezes
    the model at ``initial_s`` (the chaos harness's determinism knob:
    admission decisions become a pure function of the seed)."""

    def __init__(self, initial_s: float = 0.2, alpha: float = 0.3,
                 floor_s: float = 1e-4):
        self.alpha = float(alpha)
        self.floor_s = float(floor_s)
        self._estimate = max(float(initial_s), self.floor_s)
        self._lock = threading.Lock()

    def observe(self, wall_s: float) -> None:
        if self.alpha <= 0.0:
            return
        wall_s = max(float(wall_s), self.floor_s)
        with self._lock:
            self._estimate = (
                (1.0 - self.alpha) * self._estimate + self.alpha * wall_s
            )

    def estimate(self) -> float:
        with self._lock:
            return self._estimate


class ClosedBatch(list):
    """One closed batch of :class:`PendingQuery`. A plain list (every
    existing consumer indexes/iterates it unchanged) that additionally
    carries WHY it closed — 'full' / 'deadline' / 'drain' — so the
    query plane can attribute batch-wait tails to the close policy
    instead of discarding the reason at the pop."""

    __slots__ = ("close_reason",)

    def __init__(self, queries, close_reason: str):
        super().__init__(queries)
        self.close_reason = close_reason


class AdmissionQueue:
    """Bounded FIFO of :class:`PendingQuery` with micro-batch close.

    ``submit``-side API: :meth:`offer` (typed rejections, never
    blocks). Dispatcher-side: :meth:`next_batch` (blocking, daemon
    mode) / :meth:`try_close_batch` (non-blocking, harness pump).
    Drain-side: :meth:`close` then :meth:`flush_rejected`."""

    def __init__(
        self,
        max_batch: int = 8,
        queue_depth: int = 64,
        batch_margin_s: float = 0.05,
        wall_model: Optional[BatchWallModel] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.max_batch = int(max_batch)
        self.queue_depth = int(queue_depth)
        self.batch_margin_s = float(batch_margin_s)
        self.wall_model = wall_model or BatchWallModel()
        self._clock = clock
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._closed = False
        self._stopped = False
        self._in_flight = 0  # batches currently executing
        self._depth_gauge = obs_metrics.gauge(
            "serve.queue_depth", "admitted queries waiting in a batch"
        )

    # -- submit side --------------------------------------------------------

    def offer(self, q: PendingQuery) -> None:
        """Admit ``q`` or raise a typed rejection (never blocks, never
        silently drops). The predictive shed is the ISSUE-18 rule:
        queue depth x modeled batch wall vs remaining deadline."""
        wall = self.wall_model.estimate()
        with self._cond:
            if self._closed:
                raise Draining(
                    "admission closed: the daemon is draining "
                    "(SIGTERM); retry against another replica"
                )
            now = self._clock()
            remaining = q.deadline - now
            if len(self._queue) >= self.queue_depth:
                raise Overloaded(
                    f"queue full ({self.queue_depth} queued)",
                    retry_after_s=wall,
                )
            # Batches that must complete before q's own: everything
            # queued ahead of it (including itself) plus any batch
            # already executing on the mesh.
            batches_ahead = (
                -(-(len(self._queue) + 1) // self.max_batch)
                + self._in_flight
            )
            predicted = batches_ahead * wall
            if predicted > remaining:
                raise Overloaded(
                    f"predicted wait {predicted:.3f}s exceeds remaining "
                    f"deadline {remaining:.3f}s "
                    f"({batches_ahead} batch(es) x {wall:.3f}s modeled "
                    "wall)",
                    retry_after_s=max(wall, predicted - remaining),
                )
            self._queue.append(q)
            self._depth_gauge.set(len(self._queue))
            self._cond.notify_all()

    # -- dispatcher side ----------------------------------------------------

    def _close_reason(self, now: float) -> Optional[str]:
        """Why a batch should close NOW ('full' / 'deadline' /
        'drain'), or None to keep accumulating. Callers already hold
        the condition; its RLock makes the re-entry free — and keeps
        every state access lexically guarded (PTR001)."""
        with self._cond:
            if not self._queue:
                return None
            if len(self._queue) >= self.max_batch:
                return "full"
            oldest = self._queue[0]
            margin = self.wall_model.estimate() + self.batch_margin_s
            if oldest.deadline - now <= margin:
                return "deadline"
            if self._closed:
                # Draining: no more arrivals will ever top this batch up.
                return "drain"
            return None

    def _pop_batch(self, reason: str) -> ClosedBatch:
        with self._cond:
            batch = []
            while self._queue and len(batch) < self.max_batch:
                batch.append(self._queue.popleft())
            self._depth_gauge.set(len(self._queue))
            self._in_flight += 1
            return ClosedBatch(batch, reason)

    def try_close_batch(self) -> Optional[ClosedBatch]:
        """Non-blocking close check (the harness pump / drain loop)."""
        with self._cond:
            reason = self._close_reason(self._clock())
            if reason is None:
                return None
            return self._pop_batch(reason)

    def next_batch(self, poll_s: float = 0.05
                   ) -> Optional[ClosedBatch]:
        """Block until a batch closes (daemon dispatcher loop); None
        once :meth:`stop` was called and the queue is empty. The wait
        is bounded by the time to the oldest query's close point, so
        a deadline-driven close fires without a new arrival."""
        with self._cond:
            while True:
                now = self._clock()
                reason = self._close_reason(now)
                if reason is not None:
                    return self._pop_batch(reason)
                if self._stopped and not self._queue:
                    return None
                timeout = poll_s
                if self._queue:
                    oldest = self._queue[0]
                    margin = (self.wall_model.estimate()
                              + self.batch_margin_s)
                    timeout = min(
                        poll_s, max(0.0, (oldest.deadline - margin) - now)
                    )
                self._cond.wait(timeout if timeout > 0 else poll_s)

    def batch_done(self) -> None:
        with self._cond:
            self._in_flight = max(0, self._in_flight - 1)
            self._cond.notify_all()

    # -- drain side ---------------------------------------------------------

    def close(self) -> None:
        """Stop admitting (subsequent offers raise Draining); queued
        work remains servable."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def stop(self) -> None:
        """close() + let next_batch return None once empty — the
        dispatcher thread's shutdown signal."""
        with self._cond:
            self._closed = True
            self._stopped = True
            self._cond.notify_all()

    def flush_rejected(self, error_factory) -> int:
        """Typed-reject everything still queued (the drain deadline
        ran out); returns the count. ``error_factory(q)`` builds the
        typed error per query."""
        with self._cond:
            flushed = list(self._queue)
            self._queue.clear()
            self._depth_gauge.set(0)
        now = self._clock()
        for q in flushed:
            q.reject(error_factory(q), now)
        return len(flushed)

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
