"""Production PPR serving (ISSUE 18): a deadline-honest query daemon.

The serving layer turns :class:`~pagerank_tpu.engines.ppr.PprJaxEngine`
into a resident query path: one AOT-warmed compiled batch program over a
sharded graph, a bounded admission queue with dynamic micro-batching,
per-query deadlines with predictive load shedding, an LRU result cache,
and on-device top-k so only ``[batch, k]`` ever leaves the chip.

The robustness spine maps every failure mode the repo defends against
offline to a *typed, bounded, observable* outcome for an in-flight
query (docs/ROBUSTNESS.md "Serving"):

- overload        -> typed :class:`Overloaded` rejection with a
                     retry-after hint, decided AT ADMISSION (never
                     accept work that cannot finish);
- chip loss / SDC quarantine -> the PR 7/15 elastic rescue: re-shard
                     onto the survivors and RE-RUN the in-flight batch
                     (counted, never silently dropped);
- SIGTERM         -> the PR 12 drain: admission closes with typed
                     :class:`Draining` rejections, in-flight batches
                     finish inside the drain deadline, exit 75;
- stuck dispatch  -> bounded by ``mesh.run_with_deadline``; the batch
                     fails typed (:class:`QueryDeadlineExceeded`)
                     instead of hanging the queue.

Telemetry rides the existing planes: ``serve.*`` counters/gauges and
the ``serve.latency_ms`` histogram through the PR 5 exporter, and a
``ppr_serve`` leg in the perf ledger (``bench.py --ppr-serve``).

The **query plane** (ISSUE 19, :mod:`pagerank_tpu.serving.qtrace`) is
the serving-side observability sibling: one cross-thread trace per
query (W3C ``traceparent`` in/out over HTTP), exemplar trace ids on the
latency histogram's tail buckets, a slow-query JSONL log, and a
flight-recorder ring dumped into the run report on drain/rescue/crash.
It is DISARMED by default — the hot admission/dispatch path then makes
zero tracer or exemplar calls.
"""

from pagerank_tpu.serving.admission import (
    AdmissionQueue,
    BatchWallModel,
    ClosedBatch,
)
from pagerank_tpu.serving.cache import ResultCache
from pagerank_tpu.serving.daemon import PprServer, ServeConfig
from pagerank_tpu.serving.http import QueryIngress
from pagerank_tpu.serving.qtrace import (
    QueryPlane,
    QueryTrace,
    arm_query_plane,
    disarm_query_plane,
    get_query_plane,
)
from pagerank_tpu.serving.query import (
    Draining,
    Overloaded,
    PendingQuery,
    QueryDeadlineExceeded,
    ServeRejected,
)

__all__ = [
    "AdmissionQueue",
    "BatchWallModel",
    "ClosedBatch",
    "Draining",
    "Overloaded",
    "PendingQuery",
    "PprServer",
    "QueryDeadlineExceeded",
    "QueryIngress",
    "QueryPlane",
    "QueryTrace",
    "ResultCache",
    "ServeConfig",
    "ServeRejected",
    "arm_query_plane",
    "disarm_query_plane",
    "get_query_plane",
]
