"""The query plane (ISSUE 19): per-query causal timelines for serving.

The solver's five obs planes attribute ITERATION time; this plane
attributes QUERY time. One served query hops threads — ingress ->
admission -> dispatcher -> response — which the classic
:class:`~pagerank_tpu.obs.trace.Tracer` span stack cannot follow.
A :class:`QueryTrace` is the cross-thread handle: call sites record
pre-measured phases (named ``query/<phase>`` on the server's injected
clock), the trace links to its batch-mates, and every settled outcome
carries a W3C-shaped ``trace_id``.

Three consumers hang off :class:`QueryPlane`:

- **tail decomposition** — bounded per-phase samples feed
  :meth:`QueryPlane.phase_p99_ms` (``bench.py --ppr-serve``'s
  admission_wait / batch_wait / dispatch / fetch ledger columns);
- **slow-query log** — settles with latency >= ``slow_query_ms`` write
  one strict-JSON line with the full phase breakdown;
- **flight recorder** — a ring of the last N settled timelines,
  snapshotted on drain / rescue / fatal into the run report's
  ``serving`` section (:func:`report_section`).

Memory discipline: the plane retains NOTHING per settled query beyond
the fixed-size structures above — bounded per-leg sample deques, the
flight ring, counters, and a rolling order-independent structure
digest (each settle folds ``sha256(trace.structure())`` into one
accumulator). A daemon armed for its whole process lifetime
(``python -m pagerank_tpu.serve --slow-query-ms``) stays O(1) in
query count; degrading instead of dying includes not OOMing on
observability state.

Zero-cost discipline (the booby-trap contract): the plane is DISARMED
by default (:func:`get_query_plane` returns None) and every serving
call site gates on ``q.trace is not None`` — a disarmed admitted query
makes zero tracer, plane, or exemplar calls on the hot path
(tests/test_qtrace.py::test_disarmed_booby_trap).

Import discipline: stdlib + ``obs.trace`` only — ``obs/report.py``
imports this module lazily for the report's serving section, so it
must never pull in the daemon or jax.

Phase glossary (docs/OBSERVABILITY.md "Query plane"):

==================  =====================================================
phase               measures
==================  =====================================================
query/cache         LRU lookup at admission (attr ``hit``)
query/admission     the typed admission decision (attr ``decision``)
query/batch_wait    admitted -> batch close (attrs ``close_reason``,
                    ``batch_size``; links = batch-mates' trace ids)
query/dispatch      compiled-batch device run (attrs ``rerun``,
                    ``attempts``; covers elastic-rescue re-runs)
query/fetch         on-device top-k -> host copy + cache put
query/serialize     HTTP response body build (ingress only; recorded
                    AFTER the query settles, so it appears in the live
                    Chrome trace but never in the settled record —
                    slow-query log, flight dumps, structure digest)
==================  =====================================================
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from pagerank_tpu.obs import trace as obs_trace

#: phase name -> bench/history decomposition leg column.
PHASE_TO_LEG = {
    "query/admission": "admission_wait",
    "query/batch_wait": "batch_wait",
    "query/dispatch": "dispatch",
    "query/fetch": "fetch",
}

#: the decomposition columns, in ledger order.
DECOMPOSITION_LEGS = ("admission_wait", "batch_wait", "dispatch", "fetch")

#: keys of one slow-query JSONL record (schema pinned in tests).
SLOW_QUERY_KEYS = ("type", "trace_id", "qid", "source", "outcome",
                   "latency_ms", "phases")


def default_trace_id(qid: int) -> str:
    """Deterministic W3C trace id for query ``qid``: 32 lowercase hex
    digits, never all-zero (the spec's invalid value) — same seed =>
    same qids => same trace ids, the chaos harness's determinism
    contract."""
    return format(int(qid) + 1, "032x")


class QueryTrace:
    """One query's causal timeline — the handle that crosses threads.

    Phases are PRE-MEASURED on the server's injected clock and appended
    in lifecycle order (submit thread, then dispatcher), so no lock is
    needed: every hand-off happens-before via the admission queue's
    condition, and the daemon publishes the query (``resolve``/
    ``reject``, which set the done event) only AFTER :meth:`finish`
    sealed the trace. A phase recorded after the seal — the ingress
    thread's ``query/serialize`` — mirrors into the tracer (its own
    lock) but does NOT touch ``phases``, so the settled record is
    immutable and flight-dump readers never race an append. When the
    process tracer is armed, each phase mirrors immediately into a
    handle-parented span (:meth:`Tracer.start_span`) so the Chrome
    export shows the query as one tree spanning thread lanes.
    """

    __slots__ = ("trace_id", "qid", "source", "phases", "links",
                 "outcome", "attrs", "t_start", "t_admitted",
                 "_tracer", "_root", "_sealed")

    def __init__(self, qid: int, source: int, trace_id: str,
                 start_s: float, tracer=None):
        self.trace_id = trace_id
        self.qid = int(qid)
        self.source = int(source)
        self.phases: List[dict] = []
        self.links: List[str] = []
        self.outcome = ""
        self.attrs: Dict = {}
        self.t_start = float(start_s)
        self.t_admitted: Optional[float] = None
        self._sealed = False
        self._tracer = tracer if tracer is not None else obs_trace.NULL_TRACER
        self._root = self._tracer.start_span(
            "query", trace_id=trace_id, start_s=start_s,
            qid=self.qid, source=self.source,
        )

    def phase(self, name: str, start_s: float, duration_s: float,
              **attrs) -> None:
        """Record one pre-measured phase (server-clock seconds). After
        :meth:`finish` sealed the trace, the phase still lands in the
        live tracer (Chrome lanes) but NOT in ``phases`` — the settled
        record is immutable, so post-settle ingress work
        (``query/serialize``) can never race a flight-dump reader or
        perturb the structure digest."""
        rec = {
            "name": name,
            "start_s": float(start_s),
            "duration_s": max(0.0, float(duration_s)),
            "tid": threading.get_ident(),
        }
        if attrs:
            rec["attrs"] = attrs
        if not self._sealed:
            self.phases.append(rec)
        sp = self._tracer.start_span(
            name, parent=self._root, trace_id=self.trace_id,
            start_s=rec["start_s"], **attrs
        )
        if sp is not None:
            self._tracer.finish_span(
                sp, end_s=rec["start_s"] + rec["duration_s"]
            )

    def link(self, other_trace_id: str) -> None:
        """Link to another trace (batch membership)."""
        self.links.append(other_trace_id)

    def finish(self, outcome: str, end_s: float) -> None:
        """Seal the trace (called once, by :meth:`QueryPlane.settle`):
        ``phases`` is immutable from here on."""
        self.outcome = outcome
        self._sealed = True
        if self._root is not None:
            self._root.attrs["outcome"] = outcome
            if self.links:
                self._root.links = list(self.links)
            self._tracer.finish_span(self._root, end_s=float(end_s))

    def to_json(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "qid": self.qid,
            "source": self.source,
            "outcome": self.outcome,
            "phases": list(self.phases),
        }
        if self.links:
            out["links"] = list(self.links)
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out

    def structure(self) -> dict:
        """The timestamp-free shape used by the determinism digest:
        identity, outcome, phase names + attrs (close reasons,
        decisions), and links — no clocks, no tids, no span ids."""
        return {
            "trace_id": self.trace_id,
            "qid": self.qid,
            "source": self.source,
            "outcome": self.outcome,
            "phases": [
                {"name": p["name"], "attrs": p.get("attrs", {})}
                for p in self.phases
            ],
            "links": sorted(self.links),
        }


class QueryPlane:
    """The armed query plane: trace factory, settle ledger, tail
    samplers, slow-query log, and the flight-recorder ring.

    Every retained structure is bounded (deques with maxlen, counters,
    one digest accumulator) — an armed plane's memory is O(1) in the
    number of settled queries, so arming it for a daemon's whole
    process lifetime is safe."""

    def __init__(self, ring_size: int = 64,
                 slow_query_ms: Optional[float] = None,
                 slow_query_path: Optional[str] = None,
                 max_samples: int = 8192,
                 max_dumps: int = 8):
        self.slow_query_ms = slow_query_ms
        self.slow_query_path = slow_query_path
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(ring_size)))
        # Rolling structure digest: per-trace sha256 values summed mod
        # 2**256 — order-independent (settle order may differ across
        # threads) and O(1) memory, unlike retaining every trace.
        self._digest_sum = 0
        self._samples: Dict[str, deque] = {
            leg: deque(maxlen=max_samples) for leg in DECOMPOSITION_LEGS
        }
        self._dumps: deque = deque(maxlen=max(1, int(max_dumps)))
        self._settled_count = 0
        self._slow_count = 0
        # O_APPEND fd opened at arm time (still single-threaded): each
        # outlier is then ONE os.write of one full line outside the
        # plane lock, so settles on different threads never tear lines
        # and never serialize on filesystem waits.
        self._slow_fd: Optional[int] = None
        if slow_query_path is not None:
            self._slow_fd = os.open(
                slow_query_path,
                os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644,
            )

    # -- trace lifecycle ----------------------------------------------------

    def new_trace(self, qid: int, source: int, trace_id: str,
                  start_s: float) -> QueryTrace:
        return QueryTrace(qid, source, trace_id, start_s,
                          tracer=obs_trace.get_tracer())

    def settle(self, trace: QueryTrace, outcome: str, end_s: float,
               latency_ms: Optional[float]) -> None:
        """One query reached its typed terminal state: seal the trace,
        feed the tail samplers, ring-buffer the timeline, and (when it
        qualifies) write the slow-query JSONL line."""
        trace.finish(outcome, end_s)
        slow = (self.slow_query_ms is not None
                and latency_ms is not None
                and latency_ms >= self.slow_query_ms)
        shape = hashlib.sha256(
            json.dumps(trace.structure(), sort_keys=True).encode("utf-8")
        ).digest()
        with self._lock:
            self._settled_count += 1
            self._ring.append(trace)
            self._digest_sum = (
                self._digest_sum + int.from_bytes(shape, "big")
            ) % (1 << 256)
            for p in trace.phases:
                leg = PHASE_TO_LEG.get(p["name"])
                if leg is not None:
                    self._samples[leg].append(1000.0 * p["duration_s"])
            if slow:
                self._slow_count += 1
        if slow:
            # Outside the lock: the trace is sealed, and the O_APPEND
            # write is a single syscall — no torn lines, and a slow
            # filesystem never stalls other settling threads.
            self._write_slow(trace, latency_ms)

    def _write_slow(self, trace: QueryTrace, latency_ms: float) -> None:
        """One strict-JSON line per outlier."""
        if self._slow_fd is None:
            return
        rec = {
            "type": "slow_query",
            "trace_id": trace.trace_id,
            "qid": trace.qid,
            "source": trace.source,
            "outcome": trace.outcome,
            "latency_ms": round(float(latency_ms), 3),
            "phases": list(trace.phases),
        }
        line = json.dumps(rec, allow_nan=False, sort_keys=True) + "\n"
        os.write(self._slow_fd, line.encode("utf-8"))

    # -- flight recorder ----------------------------------------------------

    def flight_dump(self, reason: str) -> dict:
        """Snapshot the ring (last-N settled timelines) — the black box
        pulled on drain / rescue / fatal."""
        with self._lock:
            dump = {
                "reason": reason,
                "settled": self._settled_count,
                "traces": [t.to_json() for t in self._ring],
            }
            self._dumps.append(dump)
        return dump

    # -- views --------------------------------------------------------------

    def phase_p99_ms(self) -> Dict[str, float]:
        """p99 milliseconds per decomposition leg (0.0 when a leg has
        no samples — e.g. every query shed at admission)."""
        out = {}
        with self._lock:
            for leg in DECOMPOSITION_LEGS:
                xs = sorted(self._samples[leg])
                out[leg] = (
                    round(xs[int(0.99 * (len(xs) - 1))], 6) if xs else 0.0
                )
        return out

    def structure_digest(self) -> str:
        """Rolling digest over every settled trace's timestamp-free
        structure: the sum (mod 2**256) of per-trace sha256 values,
        folded in at settle time — order-independent, so it is equal
        across same-seed chaos runs regardless of settle interleaving,
        and O(1) memory regardless of query count."""
        with self._lock:
            return format(self._digest_sum, "064x")

    @property
    def settled_count(self) -> int:
        with self._lock:
            return self._settled_count

    @property
    def slow_count(self) -> int:
        with self._lock:
            return self._slow_count

    def report_section(self) -> dict:
        """The run report's ``serving`` section."""
        with self._lock:
            dumps = list(self._dumps)
            settled = self._settled_count
            slow = self._slow_count
        return {
            "enabled": True,
            "settled": settled,
            "slow_queries": slow,
            "slow_query_ms": self.slow_query_ms,
            "phase_p99_ms": self.phase_p99_ms(),
            "flight_dumps": dumps,
        }

    def close(self) -> None:
        with self._lock:
            fd, self._slow_fd = self._slow_fd, None
        if fd is not None:
            os.close(fd)


# -- process-global plane (disarmed by default) -----------------------------

_PLANE: Optional[QueryPlane] = None


def get_query_plane() -> Optional[QueryPlane]:
    """The armed plane, or None (the zero-cost default — call sites
    gate on this / on ``q.trace is not None``)."""
    return _PLANE


def arm_query_plane(ring_size: int = 64,
                    slow_query_ms: Optional[float] = None,
                    slow_query_path: Optional[str] = None,
                    plane: Optional[QueryPlane] = None) -> QueryPlane:
    """Install (and return) a recording query plane."""
    global _PLANE
    _PLANE = plane if plane is not None else QueryPlane(
        ring_size=ring_size, slow_query_ms=slow_query_ms,
        slow_query_path=slow_query_path,
    )
    return _PLANE


def disarm_query_plane() -> Optional[QueryPlane]:
    """Restore the disarmed default; returns the plane that was active
    (so a caller can still read what it recorded)."""
    global _PLANE
    prev = _PLANE
    _PLANE = None
    if prev is not None:
        prev.close()
    return prev


def report_section() -> dict:
    """The run report's ``serving`` section for the CURRENT plane —
    ``{"enabled": False}`` when disarmed (the report stays
    schema-complete either way)."""
    plane = get_query_plane()
    if plane is None:
        return {"enabled": False}
    return plane.report_section()
