"""HTTP query ingress for the PPR daemon (ISSUE 18 satellite: the
``python -m pagerank_tpu.serve`` entry point's front door).

Mirrors the ``obs/live.py`` ``MetricsExporter`` shape: zero
dependencies (``http.server``), loopback bind, port 0 supported (the
resolved port is published on ``.port``). The typed query outcomes map
onto HTTP statuses so a load balancer can act on them without parsing
bodies:

===========================  ======  ================================
outcome                      status  notes
===========================  ======  ================================
answered / answered_cache /  200     JSON body with ids + scores
answered_degraded
``Overloaded`` (shed)        429     ``Retry-After`` header carries
                                     the hint from admission
``Draining`` (SIGTERM)       503     retry against another replica
``QueryDeadlineExceeded``    504     deadline passed / dispatch bound
===========================  ======  ================================
"""

from __future__ import annotations

import json
import re
import threading
from typing import Optional
from urllib.parse import parse_qs, urlparse

from pagerank_tpu.serving.daemon import PprServer
from pagerank_tpu.serving.query import (Draining, Overloaded,
                                        QueryDeadlineExceeded,
                                        ServeRejected)

_STATUS = {
    "shed_overload": 429,
    "rejected_draining": 503,
    "rejected_deadline": 504,
    "rejected": 500,
}

# W3C trace-context level-1: version-traceid-parentid-flags, lowercase
# hex, all-zero trace/parent ids invalid (the spec's "not a trace").
_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def parse_traceparent(value: Optional[str]) -> Optional[str]:
    """The trace id of a valid ``traceparent`` header, else None
    (malformed headers degrade to a server-assigned id, never a 4xx —
    trace context is best-effort metadata)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id, parent_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return trace_id


def format_traceparent(trace_id: str, qid: int) -> str:
    """The response ``traceparent``: the query's trace id with the
    server's span id (a deterministic function of the qid, matching
    the trace-id fallback) and the sampled flag. The ONE encoder for
    both helper and header paths, so the span-id scheme cannot
    drift."""
    return "00-%s-%016x-01" % (trace_id, (int(qid) + 1) & (2 ** 64 - 1))


def _query_payload(q, ids, scores) -> dict:
    return {
        "qid": q.qid,
        "source": q.source,
        "k": q.k,
        "outcome": q.outcome,
        "served_from": q.served_from,
        "trace_id": q.trace_id,
        "latency_ms": round(1000.0 * (q.latency_s or 0.0), 3),
        "ids": [int(i) for i in ids],
        "scores": [float(s) for s in scores],
    }


class QueryIngress:
    """Loopback HTTP front door over a started :class:`PprServer`.

    ``GET /ppr?source=<id>[&k=<k>][&deadline_ms=<ms>]`` submits one
    query and blocks the handler thread (ThreadingHTTPServer: one
    thread per connection) until its typed terminal state.
    ``GET /healthz`` reports serving/degraded/draining."""

    def __init__(self, server: PprServer, port: int = 0):
        self.server = server
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._start(port)

    def _handle_ppr(self, params: dict, traceparent: Optional[str] = None):
        try:
            source = int(params["source"][0])
        except (KeyError, ValueError, IndexError):
            return 400, {"error": "missing or non-integer 'source'"}
        k = None
        if "k" in params:
            try:
                k = int(params["k"][0])
            except ValueError:
                return 400, {"error": "non-integer 'k'"}
        deadline_s = None
        if "deadline_ms" in params:
            try:
                deadline_s = float(params["deadline_ms"][0]) / 1000.0
            except ValueError:
                return 400, {"error": "non-numeric 'deadline_ms'"}

        srv = self.server
        q = srv.submit(source, k=k, deadline_s=deadline_s,
                       trace_id=parse_traceparent(traceparent))
        if q.trace is not None:
            from pagerank_tpu.obs import trace as obs_trace
            obs_trace.get_tracer().set_thread_label(
                threading.get_ident(), "serve-http"
            )
        # Settlement is guaranteed typed; the bound below only trips if
        # that contract is broken (surfaced as a 500, not a hang).
        settle_bound = (
            (deadline_s or srv.serve_config.deadline_ms / 1000.0)
            + srv.serve_config.dispatch_timeout_s + 1.0
        )
        try:
            ids, scores = q.result(timeout=settle_bound)
        except Overloaded as e:
            return 429, {"error": str(e), "outcome": e.outcome,
                         "qid": q.qid, "trace_id": q.trace_id,
                         "retry_after_s": e.retry_after_s}
        except ServeRejected as e:
            return (_STATUS.get(e.outcome, 500),
                    {"error": str(e), "outcome": e.outcome,
                     "qid": q.qid, "trace_id": q.trace_id})
        except TimeoutError as e:
            return 500, {"error": str(e), "outcome": "unsettled",
                         "qid": q.qid, "trace_id": q.trace_id}
        tr = q.trace
        if tr is not None:
            t0 = srv._clock()
        payload = _query_payload(q, ids, scores)
        if tr is not None:
            # The query settled before resolve() woke this thread, so
            # the trace is sealed: this phase mirrors into the live
            # tracer (the serve-http Chrome lane) but stays out of the
            # settled record — slow-log, flight dumps, digest.
            tr.phase("query/serialize", t0, srv._clock() - t0)
        return 200, payload

    def _handle_healthz(self):
        srv = self.server
        if srv.queue.closed:
            state = "draining"
        elif srv.degraded:
            state = "degraded"
        else:
            state = "serving"
        return (200 if state != "draining" else 503), {
            "status": state,
            "devices": srv.device_count,
            "queue_depth": len(srv.queue),
        }

    def _start(self, port: int) -> None:
        import http.server

        ingress = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                parsed = urlparse(self.path)
                if parsed.path == "/ppr":
                    status, payload = ingress._handle_ppr(
                        parse_qs(parsed.query),
                        traceparent=self.headers.get("traceparent"),
                    )
                elif parsed.path == "/healthz":
                    status, payload = ingress._handle_healthz()
                else:
                    self.send_error(404)
                    return
                body = json.dumps(payload).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if status == 429 and "retry_after_s" in payload:
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(round(payload["retry_after_s"]))))
                    )
                if "trace_id" in payload:
                    # Every payload that carries trace_id carries qid;
                    # a missing qid is a bug and should fail loudly,
                    # never encode span id 0x1 for the wrong query.
                    self.send_header(
                        "traceparent",
                        format_traceparent(payload["trace_id"],
                                           payload["qid"]),
                    )
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self.port = self._httpd.server_address[1]  # resolved (port 0 ok)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pagerank-serve-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    def __enter__(self) -> "QueryIngress":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
