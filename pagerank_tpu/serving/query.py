"""Typed queries and outcomes for the PPR serving layer (ISSUE 18).

Every query submitted to the daemon ends in exactly ONE of the typed
terminal states below — the query-outcome state machine
(docs/ROBUSTNESS.md "Serving"). There is no silent drop: an accepted
query either resolves with a result or is rejected with a typed error
that names the policy that rejected it.

    submit ──► REJECTED_OVERLOAD   (Overloaded: predictive shed or
      │                             queue full; carries retry-after)
      │    ──► REJECTED_DRAINING   (Draining: admission closed by the
      │                             SIGTERM drain)
      ▼
    ANSWERED_CACHE                 (LRU hit at admission; never queued)
      │
    queued ──► ANSWERED            (batch computed on the mesh;
      │                             possibly after an elastic rescue —
      │                             ``degraded`` marks those)
      └────► REJECTED_DEADLINE     (QueryDeadlineExceeded: the deadline
                                    passed in-queue, or the bounded
                                    dispatch timed out)
"""

from __future__ import annotations

import threading
from typing import Optional


class ServeRejected(RuntimeError):
    """Base of every typed serving rejection. ``outcome`` is the
    stable machine-readable label the harness / HTTP layer report."""

    outcome = "rejected"


class Overloaded(ServeRejected):
    """Admission refused NOW because the query provably cannot finish:
    queue full, or queue depth x modeled batch wall exceeds the
    query's remaining deadline (predictive shed — never accept work
    that cannot finish). ``retry_after_s`` is the earliest point a
    retry with the same deadline could plausibly be admitted."""

    outcome = "shed_overload"

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class Draining(ServeRejected):
    """Admission is closed: the daemon received SIGTERM and is draining
    (docs/ROBUSTNESS.md "Graceful drain"). In-flight batches still
    finish; new work must go to another replica."""

    outcome = "rejected_draining"


class QueryDeadlineExceeded(ServeRejected):
    """The query's deadline passed before a result existed — either
    in-queue (a drain or rescue consumed its margin) or because the
    deadline-bounded device dispatch (``mesh.run_with_deadline``)
    timed out. The queue keeps moving; the query fails typed."""

    outcome = "rejected_deadline"


class PendingQuery:
    """One admitted query: the handle ``submit`` returns.

    Cross-thread discipline (PTR001): the dispatcher thread resolves,
    the submitting thread reads — every mutable field access happens
    under ``_lock``, and :meth:`result` blocks on the ``_done`` event
    (a sync primitive) outside any lock."""

    __slots__ = ("qid", "source", "k", "deadline", "t_submit",
                 "_lock", "_done", "_ids", "_scores", "_error",
                 "_served_from", "_latency_s", "trace", "_trace_id")

    def __init__(self, qid: int, source: int, k: int, deadline: float,
                 t_submit: float):
        self.qid = int(qid)
        self.source = int(source)
        self.k = int(k)
        self.deadline = float(deadline)  # absolute, on the server clock
        self.t_submit = float(t_submit)
        # Query plane (ISSUE 19): None while disarmed — every tracing
        # call site gates on it, so the hot path pays one attr read.
        self.trace = None
        self._trace_id: Optional[str] = None
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._ids = None
        self._scores = None
        self._error: Optional[ServeRejected] = None
        self._served_from = ""
        self._latency_s: Optional[float] = None

    # -- dispatcher side ----------------------------------------------------

    def resolve(self, ids, scores, served_from: str, now: float) -> None:
        with self._lock:
            self._ids = ids
            self._scores = scores
            self._served_from = served_from
            self._latency_s = max(0.0, now - self.t_submit)
        self._done.set()

    def reject(self, error: ServeRejected, now: float) -> None:
        with self._lock:
            self._error = error
            self._latency_s = max(0.0, now - self.t_submit)
        self._done.set()

    # -- caller side --------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        """``(ids, scores)`` once resolved; raises the typed rejection
        otherwise. ``TimeoutError`` only if the daemon never settled
        the query within ``timeout`` — which the zero-silent-drops
        contract makes a bug, not an outcome."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.qid} unsettled after {timeout}s — the "
                "serving layer guarantees a typed terminal state"
            )
        with self._lock:
            if self._error is not None:
                raise self._error
            return self._ids, self._scores

    @property
    def trace_id(self) -> str:
        """Stable W3C-shaped trace id: the caller's ``traceparent``
        override when one arrived, else a deterministic function of
        the qid (``qid+1`` as 32 hex digits — never the spec's
        all-zero invalid value). A plain property read: no tracer or
        plane call, so every typed outcome carries an id even with
        the query plane disarmed."""
        if self._trace_id is not None:
            return self._trace_id
        return format(self.qid + 1, "032x")

    def set_trace_id(self, trace_id: str) -> None:
        """Adopt an upstream trace id (the HTTP ``traceparent``)."""
        self._trace_id = trace_id

    @property
    def outcome(self) -> str:
        """Terminal state label ('' while pending)."""
        if not self._done.is_set():
            return ""
        with self._lock:
            if self._error is not None:
                return self._error.outcome
            return ("answered_cache" if self._served_from == "cache"
                    else "answered_degraded"
                    if self._served_from == "degraded" else "answered")

    @property
    def served_from(self) -> str:
        with self._lock:
            return self._served_from

    @property
    def latency_s(self) -> Optional[float]:
        with self._lock:
            return self._latency_s

    def error(self) -> Optional[ServeRejected]:
        with self._lock:
            return self._error
