"""`PageRankEngine` — the thin API layer the reference lacks (SURVEY.md §1:
"no API layer, no CLI"; the BASELINE.json north star asks for a
`PageRankEngine` interface with a CPU-oracle impl and a JAX/TPU impl).

An engine owns the L3 iterative-solver state. The driver loop here plays
the role of the reference's `for (iter = 0; iter < 10; iter++)` block
(Sparky.java:187-238): step, log (`:188`), snapshot (`:237`), repeat.
"""

from __future__ import annotations

import abc
import math
from typing import Callable, Dict, Optional

import numpy as np

from pagerank_tpu.graph import Graph
from pagerank_tpu.obs import devices as obs_devices
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.utils.config import PageRankConfig


class SolverHealthError(RuntimeError):
    """The solver state went bad (NaN/Inf step info, rank-mass drift)
    and could not be healed by snapshot rollback. Carries the FIRST
    iteration that produced a bad step and the number of rollbacks
    attempted — the diagnostic a 3am page needs (docs/ROBUSTNESS.md)."""

    def __init__(self, message: str, first_bad_iteration: int,
                 rollbacks: int):
        super().__init__(message)
        self.first_bad_iteration = first_bad_iteration
        self.rollbacks = rollbacks


def _health_reason(info: Dict[str, float]) -> Optional[str]:
    """Non-finite scalar in the step info, or None when healthy. A NaN
    rank vector always surfaces here: l1_delta is a sum over every
    component, so one NaN poisons it."""
    for k, v in info.items():
        if isinstance(v, (int, float, np.floating, np.integer)):
            if not math.isfinite(float(v)):
                return f"non-finite step info {k}={float(v)!r}"
    return None


class PageRankEngine(abc.ABC):
    """Base class for PageRank execution engines."""

    name: str = "abstract"

    def __init__(self, config: Optional[PageRankConfig] = None):
        self.config = (config or PageRankConfig()).validate()
        self.graph: Optional[Graph] = None
        self.iteration = 0
        # Self-healing counters (populated by run(); docs/ROBUSTNESS.md)
        self.health: Dict[str, Optional[int]] = {
            "rollbacks": 0, "first_bad_iteration": None,
        }

    @abc.abstractmethod
    def build(self, graph: Graph) -> "PageRankEngine":
        """Prepare solver state (device placement, sharding, r0)."""

    @abc.abstractmethod
    def step(self) -> Dict[str, float]:
        """Run one power iteration; returns per-iteration info
        (at least ``dangling_mass``; ``l1_delta`` when cheap)."""

    @abc.abstractmethod
    def ranks(self) -> np.ndarray:
        """Current rank vector as a host numpy array."""

    def set_ranks(self, r: np.ndarray, iteration: int = 0) -> None:
        """Overwrite solver state — used by checkpoint resume."""
        raise NotImplementedError

    def rank_mass(self) -> float:
        """sum(ranks) as a host scalar — the mass-drift health probe.
        Engines override with a cheaper device-side reduction."""
        return float(np.asarray(self.ranks(), dtype=np.float64).sum())

    def snapshot_meta(self) -> Dict[str, object]:
        """Mesh topology + partition geometry provenance recorded in
        snapshot metadata (utils/snapshot.Snapshotter.mesh_meta;
        ISSUE 7). Diagnostic only — resume is mesh-shape-agnostic.
        The jax engine overrides with the real mesh/layout view."""
        return {"num_devices": 1, "engine": self.name}

    def sdc_supported(self) -> bool:
        """Whether this engine can run the SDC-checked step (ISSUE 15;
        pagerank_tpu/sdc.py). The base engine cannot — the invariants
        need per-device check partials only a device mesh has."""
        return False

    def retain_state(self, iteration=None):
        """Opaque rewind token (iteration, rank copy) — the SDC redo's
        double buffer. Base impl holds a host copy; the jax engine
        keeps it on device."""
        it = self.iteration if iteration is None else int(iteration)
        return (it, np.array(self.ranks(), copy=True))

    def restore_state(self, token) -> None:
        it, ranks = token
        self.set_ranks(np.array(ranks, copy=True), iteration=int(it))

    # -- convergence probes (obs/probes.py; ISSUE 5) -----------------------

    def probe_values(self, k: int, prev_ids):
        """(rank_mass, entered_count, topk_ids_engine_space,
        topk_ids_original_space, topk_mass) of the CURRENT state — the
        standalone probe used at fused-chunk boundaries. ``prev_ids``
        is the previous probe's engine-space top-k (None on the first
        probe); ``entered_count`` is how many current top-k ids are
        NOT in it; ``topk_mass`` is the rank mass the top-k hold (the
        concentration signal, ISSUE 13). Base impl: host numpy over
        ranks() (the CPU oracle's own probe — what the device path is
        parity-tested against). Ties break by lowest id, matching
        ``lax.top_k``."""
        r = np.asarray(self.ranks(), dtype=np.float64)
        k = min(int(k), r.shape[0])
        ids = np.argsort(-r, kind="stable")[:k].astype(np.int64)
        entered = (
            k if prev_ids is None
            else int(k - np.isin(ids, np.asarray(prev_ids)).sum())
        )
        return float(r.sum()), entered, ids, ids, float(r[ids].sum())

    def ledger_values(self):
        """Raw rank-mass-ledger sums of the step just taken —
        ``(mass_prev, contrib_total, retained_total)`` measured INSIDE
        the step, or None when this engine cannot measure them (the
        ledger fields then stay absent; obs/graph_profile.py
        ``mass_ledger_entry`` documents the decomposition). The CPU
        oracle and the JAX engine both override."""
        return None

    def _ledger_eps(self) -> float:
        """Machine epsilon of the accumulation dtype the ledger sums
        were computed in (the dtype-tolerance axis of the ledger)."""
        return float(np.finfo(np.float64).eps)

    def _stale_slack(self) -> float:
        """Staleness bound (mass units) on the conservation identities
        of the step just taken — 0.0 for every synchronous engine.
        The asynchronous stale-boundary form (config.halo_async,
        ISSUE 17) overrides with the PREVIOUS step's L1 delta: its
        contribution total mixes fresh own-block mass with lag-1
        boundary mass, so link/flow conservation hold only up to how
        much the rank vector moved last iteration."""
        return 0.0

    def _ledger_entry(self, info: Dict[str, float]):
        """Assemble one mass-ledger entry from a probed step's info
        (requires the ``ledger_*`` sums; obs/graph_profile.py owns the
        decomposition + leak naming)."""
        from pagerank_tpu.obs import graph_profile

        return graph_profile.mass_ledger_entry(
            damping=self.config.damping,
            semantics=self.config.semantics,
            n=int(self.graph.n),
            eps=self._ledger_eps(),
            mass_prev=info["ledger_mass_prev"],
            mass=info["rank_mass"],
            dangling_mass=info["dangling_mass"],
            contrib_total=info["ledger_contrib_total"],
            retained_total=info["ledger_retained_total"],
            flow_slack=self._stale_slack(),
        )

    def step_probed(self, probes):
        """One iteration WITH the convergence probe: returns
        ``(info, (ids_engine, ids_original))`` where ``info`` carries
        ``rank_mass``, ``topk_churn``, ``topk_mass``, and — when the
        engine measures the ledger sums — the ``mass_ledger``
        decomposition (ISSUE 13) next to the step scalars. Base impl:
        plain step() + the host probe; JaxTpuEngine overrides with one
        fused device dispatch (zero extra host syncs — contract
        PTC007). Never called when probing is off (the zero-probe-call
        contract, tests/test_telemetry.py)."""
        info = self.step()
        prev = probes.prev_ids
        mass, entered, ids_engine, ids_original, topk_mass = \
            self.probe_values(probes.topk, prev)
        info["rank_mass"] = mass
        info["topk_churn"] = 0 if prev is None else entered
        info["topk_mass"] = topk_mass
        led = self.ledger_values()
        if led is not None:
            (info["ledger_mass_prev"], info["ledger_contrib_total"],
             info["ledger_retained_total"]) = led
            info["mass_ledger"] = self._ledger_entry(info)
        return info, (ids_engine, ids_original)

    def run(
        self,
        num_iters: Optional[int] = None,
        on_iteration: Optional[Callable[[int, Dict[str, float]], None]] = None,
        snapshotter=None,
        probes=None,
    ) -> np.ndarray:
        """Drive ``num_iters`` iterations (default: config.num_iters).

        ``on_iteration(i, info)`` fires after each step — the hook point
        for metrics logging and per-iteration snapshots (the reference's
        println + saveAsTextFile, Sparky.java:188,237).

        Self-healing (config.robustness; docs/ROBUSTNESS.md): each
        step's info is health-checked (NaN/Inf always; rank-mass drift
        when ``mass_tol`` is set — sound because the asynchronous-
        PageRank literature shows the iteration tolerates rolled-back /
        stale state, PAPERS.md). On a bad step, when a ``snapshotter``
        is attached, the engine rolls back to the newest VALID snapshot
        at or below the bad iteration (corrupt files are skipped) and
        recomputes, up to ``max_rollbacks`` times; the bad step's
        ``on_iteration`` never fires, so a poisoned iterate is never
        snapshotted or logged as good. Exhausting the budget — or
        having nothing to roll back to — raises
        :class:`SolverHealthError` naming the first bad iteration.
        Recomputed steps re-fire ``on_iteration`` (snapshot re-saves
        are idempotent; metrics may show repeated iterations).
        Rollback/retry counts land in ``self.health``.

        ``probes`` (obs/probes.ConvergenceProbes; ISSUE 5): at its
        cadence the step runs as :meth:`step_probed` — residual, rank
        mass, and top-k churn in the step's own dispatch — and the
        record is committed AFTER the health check accepts the step
        (a rolled-back iterate is never probed into history). Its
        ``stop_tol`` early-exits at probe points; None/off takes the
        exact pre-probe code path — zero probe calls per iteration
        (the booby-trap contract, tests/test_telemetry.py). An armed
        stall watchdog (obs/live.py) is heartbeat on every completed
        step; disarmed costs one ``is None`` check per iteration.
        """
        if self.graph is None:
            raise RuntimeError("call build(graph) before run()")
        total = self.config.num_iters if num_iters is None else num_iters
        tol = self.config.tol
        rb = self.config.robustness
        self.health = {"rollbacks": 0, "first_bad_iteration": None}
        last_mass: Optional[float] = None
        # Tracer read ONCE per run: with observability disabled the
        # loop body touches the tracer zero times per iteration (the
        # no-op contract tests/test_obs.py::test_noop_tracer_hot_path
        # pins); enabled, each step is a solve/step span.
        tracer = obs_trace.get_tracer()
        trace_steps = tracer.enabled
        # Watchdog, device sampler, and probes read ONCE per run, same
        # discipline as the tracer: disarmed/off, the loop body adds
        # one `is None` check and one `False and` short-circuit per
        # iteration (the sampler's booby-trap contract,
        # tests/test_devices.py).
        watchdog = obs_live.get_watchdog()
        sampler = obs_devices.get_sampler()
        probing = probes is not None and probes.enabled
        probe_ids = None
        # SDC guard (ISSUE 15; pagerank_tpu/sdc.py): built ONCE per
        # run, None when --sdc-check-every is 0 — the loop body then
        # adds one `is not None` check per iteration and the solve is
        # bit-identical to the unchecked path (zero check
        # computations; tests/test_sdc.py booby-traps it).
        sdc_guard = None
        if getattr(self.config, "sdc_check_every", 0):
            from pagerank_tpu import sdc as sdc_mod

            sdc_guard = sdc_mod.attach_guard(self)
        while self.iteration < total:
            probe_now = probing and probes.wants(self.iteration)
            sdc_now = (sdc_guard is not None
                       and sdc_guard.wants(self.iteration))
            if trace_steps:
                with tracer.span("solve/step", iteration=self.iteration):
                    if sdc_now:
                        # Checked step: detect -> bounded redo ->
                        # transient/sticky; a sticky conviction raises
                        # DeviceQuarantinedError for the rescue path.
                        info = sdc_guard.checked_step()
                    elif probe_now:
                        info, probe_ids = self.step_probed(probes)
                    else:
                        info = self.step()
            elif sdc_now:
                info = sdc_guard.checked_step()
            elif probe_now:
                info, probe_ids = self.step_probed(probes)
            else:
                info = self.step()
            if watchdog is not None:
                watchdog.heartbeat(self.iteration)
            if sampler is not None:
                # Per-device HBM samples at the armed cadence
                # (obs/devices.DeviceSampler; ISSUE 10).
                sampler.on_step(self.iteration)
            i = self.iteration
            reason = None
            if rb.health_checks:
                reason = _health_reason(info)
                if reason is None and rb.mass_tol is not None:
                    mass = info.get("rank_mass")
                    mass = self.rank_mass() if mass is None else float(mass)
                    if not math.isfinite(mass):
                        reason = f"non-finite rank mass {mass!r}"
                    elif (last_mass is not None
                          and abs(mass - last_mass)
                          > rb.mass_tol * max(abs(last_mass), 1e-30)):
                        reason = (
                            f"rank mass drifted {last_mass!r} -> {mass!r} "
                            f"(> mass_tol={rb.mass_tol:g} per step)"
                        )
                        # Rank-mass ledger (ISSUE 13): on probed steps
                        # the drift scalar upgrades to a named leak —
                        # WHICH term of the mass decomposition broke
                        # (link / teleport / dangling), the diagnostic
                        # the CLI robustness summary surfaces.
                        led = info.get("mass_ledger")
                        if led and led.get("leak"):
                            self.health["mass_leak"] = led["leak"]
                            reason += (
                                f"; mass ledger names the "
                                f"{led['leak']} term (residual "
                                f"{led['residual']:.3e}, unaccounted "
                                f"{led['unaccounted']!r})"
                            )
                    else:
                        last_mass = mass
            if reason is not None:
                obs_metrics.counter(
                    "engine.health_check_failures",
                    "solver steps declared unhealthy (NaN/Inf, mass "
                    "drift)",
                ).inc()
                if trace_steps:
                    tracer.add_event("solve/unhealthy_step",
                                     iteration=i, reason=reason)
                if self.health["first_bad_iteration"] is None:
                    self.health["first_bad_iteration"] = i
                first_bad = self.health["first_bad_iteration"]
                rolled = None
                if (snapshotter is not None
                        and self.health["rollbacks"] < rb.max_rollbacks):
                    # match=True: never restore a snapshot from another
                    # graph/semantics (a reused snapshot dir) — skip it
                    # like corruption rather than solving from it
                    rolled = snapshotter.load_latest_valid(
                        max_iteration=i, match=True
                    )
                if rolled is None:
                    if snapshotter is None:
                        why = "no snapshotter attached"
                    elif self.health["rollbacks"] >= rb.max_rollbacks:
                        why = f"rollback budget ({rb.max_rollbacks}) exhausted"
                    else:
                        why = "no valid snapshot to roll back to"
                    raise SolverHealthError(
                        f"engine {self.name}: unhealthy step at iteration "
                        f"{i} ({reason}); first bad iteration {first_bad}, "
                        f"{self.health['rollbacks']} rollback(s) attempted, "
                        f"{why}",
                        first_bad_iteration=first_bad,
                        rollbacks=self.health["rollbacks"],
                    )
                it0, ranks, _meta = rolled
                self.set_ranks(ranks, iteration=it0)
                if sdc_guard is not None:
                    # The SDC double buffer must follow the rollback:
                    # a retained token AHEAD of the restored iteration
                    # would let a later redo jump the solve forward
                    # onto the rejected state.
                    sdc_guard.note_rollback()
                self.health["rollbacks"] += 1
                obs_metrics.counter(
                    "engine.rollbacks",
                    "snapshot rollbacks performed by the self-healing "
                    "solve loop",
                ).inc()
                last_mass = None  # re-baseline the drift check
                continue
            self.iteration = i + 1
            if on_iteration is not None:
                on_iteration(i, info)
            if probe_now:
                # Committed only AFTER the health check accepted the
                # step (rolled-back iterates `continue` above) and
                # after on_iteration saw the probe-augmented info.
                if sdc_now:
                    # The SDC-checked step took this iteration, so the
                    # fused probe tail never ran: probe the boundary
                    # standalone (the fused-chunk idiom) — same
                    # record shape, one extra small dispatch at
                    # overlapping cadences only.
                    rec = probes.probe_boundary(
                        self, i, l1_delta=info.get("l1_delta"))
                else:
                    rec = probes.commit(i, info, *probe_ids)
                if probes.should_stop(rec):
                    break
            if tol is not None:
                delta = info.get("l1_delta")
                if delta is None:
                    raise RuntimeError(
                        f"engine {self.name} does not report l1_delta; cannot use tol"
                    )
                if float(delta) <= tol:
                    break
        return self.ranks()


_REGISTRY: Dict[str, type] = {}


def register_engine(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        cls.name = name
        return cls

    return deco


def make_engine(name: str, config: Optional[PageRankConfig] = None) -> PageRankEngine:
    """Engine factory: "cpu" (reference-semantics numpy/scipy oracle) or
    "jax" (TPU-native; aliases "tpu", "jax_tpu")."""
    # Import for registration side effects.
    import pagerank_tpu.engines.cpu  # noqa: F401
    import pagerank_tpu.engines.jax_engine  # noqa: F401

    alias = {"tpu": "jax", "jax_tpu": "jax", "reference": "cpu", "oracle": "cpu"}
    key = alias.get(name, name)
    if key not in _REGISTRY:
        raise ValueError(f"unknown engine {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](config)
