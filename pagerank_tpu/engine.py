"""`PageRankEngine` — the thin API layer the reference lacks (SURVEY.md §1:
"no API layer, no CLI"; the BASELINE.json north star asks for a
`PageRankEngine` interface with a CPU-oracle impl and a JAX/TPU impl).

An engine owns the L3 iterative-solver state. The driver loop here plays
the role of the reference's `for (iter = 0; iter < 10; iter++)` block
(Sparky.java:187-238): step, log (`:188`), snapshot (`:237`), repeat.
"""

from __future__ import annotations

import abc
from typing import Callable, Dict, Optional

import numpy as np

from pagerank_tpu.graph import Graph
from pagerank_tpu.utils.config import PageRankConfig


class PageRankEngine(abc.ABC):
    """Base class for PageRank execution engines."""

    name: str = "abstract"

    def __init__(self, config: Optional[PageRankConfig] = None):
        self.config = (config or PageRankConfig()).validate()
        self.graph: Optional[Graph] = None
        self.iteration = 0

    @abc.abstractmethod
    def build(self, graph: Graph) -> "PageRankEngine":
        """Prepare solver state (device placement, sharding, r0)."""

    @abc.abstractmethod
    def step(self) -> Dict[str, float]:
        """Run one power iteration; returns per-iteration info
        (at least ``dangling_mass``; ``l1_delta`` when cheap)."""

    @abc.abstractmethod
    def ranks(self) -> np.ndarray:
        """Current rank vector as a host numpy array."""

    def set_ranks(self, r: np.ndarray, iteration: int = 0) -> None:
        """Overwrite solver state — used by checkpoint resume."""
        raise NotImplementedError

    def run(
        self,
        num_iters: Optional[int] = None,
        on_iteration: Optional[Callable[[int, Dict[str, float]], None]] = None,
    ) -> np.ndarray:
        """Drive ``num_iters`` iterations (default: config.num_iters).

        ``on_iteration(i, info)`` fires after each step — the hook point
        for metrics logging and per-iteration snapshots (the reference's
        println + saveAsTextFile, Sparky.java:188,237).
        """
        if self.graph is None:
            raise RuntimeError("call build(graph) before run()")
        total = self.config.num_iters if num_iters is None else num_iters
        tol = self.config.tol
        while self.iteration < total:
            info = self.step()
            i = self.iteration
            self.iteration += 1
            if on_iteration is not None:
                on_iteration(i, info)
            if tol is not None:
                delta = info.get("l1_delta")
                if delta is None:
                    raise RuntimeError(
                        f"engine {self.name} does not report l1_delta; cannot use tol"
                    )
                if float(delta) <= tol:
                    break
        return self.ranks()


_REGISTRY: Dict[str, type] = {}


def register_engine(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        cls.name = name
        return cls

    return deco


def make_engine(name: str, config: Optional[PageRankConfig] = None) -> PageRankEngine:
    """Engine factory: "cpu" (reference-semantics numpy/scipy oracle) or
    "jax" (TPU-native; aliases "tpu", "jax_tpu")."""
    # Import for registration side effects.
    import pagerank_tpu.engines.cpu  # noqa: F401
    import pagerank_tpu.engines.jax_engine  # noqa: F401

    alias = {"tpu": "jax", "jax_tpu": "jax", "reference": "cpu", "oracle": "cpu"}
    key = alias.get(name, name)
    if key not in _REGISTRY:
        raise ValueError(f"unknown engine {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](config)
