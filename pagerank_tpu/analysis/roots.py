"""Shared source of truth for process-global handler ownership
(ISSUE 14 satellite).

Two rules look at signal handlers from different angles and MUST agree
on where handlers live, or moving :class:`pagerank_tpu.jobs.
GracefulDrain` would silently split their views:

- lint **PTL008** (``analysis/lint.py``) bans ``signal.signal`` /
  ``atexit.register`` OUTSIDE the supervisor modules — its
  ``handler_free`` scope reads :data:`HANDLER_OWNER_MODULES`;
- concurrency **PTR003** (``analysis/concurrency.py``) analyzes the
  PURITY of whatever handlers those modules install — its
  signal-context root discovery uses :func:`iter_handler_installs`,
  which recognizes both installation idioms this repo sanctions: the
  direct ``signal.signal(sig, handler)`` call and the injectable-
  install attribute (``self._install(sig, self._handler)`` where the
  class's ``__init__`` defaults ``install=signal.signal`` — the
  GracefulDrain idiom PTL008's scope note documents).

Keep this module dependency-free (pure ``ast``): the lint pass and the
acceptance pre-gate import it without jax.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

#: Package-relative modules allowed to install process-global
#: signal/exit handlers: the job supervisor (GracefulDrain) and the CLI
#: entry point that installs it around ``main`` (docs/ROBUSTNESS.md
#: "Preemption & resumable jobs"). PTL008's scope and PTR003's
#: in-package root discovery both read THIS tuple.
HANDLER_OWNER_MODULES = ("jobs.py", "cli.py")

#: The canonical installer spelling both discovery idioms anchor on.
INSTALLER = "signal.signal"


def dotted_name(node: ast.expr) -> str:
    """'a.b.c' for a plain dotted expression, '' otherwise — THE one
    dotted-name resolver the analysis package shares (roots discovery
    and the concurrency call graph must spell names identically)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


_dotted = dotted_name


def install_param_attrs(cls: ast.ClassDef) -> Tuple[str, ...]:
    """The ``self.<attr>`` names an injectable installer is stored
    under: ``__init__`` parameters whose DEFAULT is ``signal.signal``,
    followed to their ``self.X = param`` assignment (the GracefulDrain
    ``install=signal.signal`` idiom). Empty when the class doesn't use
    the idiom."""
    for item in cls.body:
        if not (isinstance(item, ast.FunctionDef)
                and item.name == "__init__"):
            continue
        args = item.args
        params = args.posonlyargs + args.args
        defaults = args.defaults
        injectable = set()
        # Positional defaults align to the TAIL of the parameter list.
        for param, default in zip(params[len(params) - len(defaults):],
                                  defaults):
            if _dotted(default) == INSTALLER:
                injectable.add(param.arg)
        for param, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and _dotted(default) == INSTALLER:
                injectable.add(param.arg)
        if not injectable:
            return ()
        attrs = []
        for node in ast.walk(item):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in injectable):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        attrs.append(t.attr)
        return tuple(attrs)
    return ()


def iter_handler_installs(
    tree: ast.AST,
) -> Iterator[Tuple[ast.Call, ast.expr, Optional[str]]]:
    """Yield ``(call, handler_expr, owning_class)`` for every
    signal-handler installation a module performs:

    - direct ``signal.signal(sig, handler)`` calls anywhere
      (``owning_class`` is None outside a class);
    - injectable-install calls ``self.<attr>(sig, handler)`` inside a
      class whose ``__init__`` takes ``install=signal.signal``.

    The handler expression is the SECOND argument — resolve it to a
    function/method in the caller's context to get the signal-context
    root (PTR003)."""
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    install_attrs = {id(c): install_param_attrs(c) for c in classes}
    # Nearest enclosing class per node: ast.walk is breadth-first, so
    # an inner class's own sweep overwrites the outer's entries.
    owner = {}
    for cls in classes:
        for sub in ast.walk(cls):
            owner[id(sub)] = cls
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and len(node.args) >= 2):
            continue
        name = _dotted(node.func)
        cls = owner.get(id(node))
        if name == INSTALLER or (
            cls is not None
            and name.startswith("self.")
            and name[len("self."):] in install_attrs[id(cls)]
        ):
            yield (node, node.args[1], cls.name if cls is not None else None)
