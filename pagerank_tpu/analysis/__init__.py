"""Static analysis for the TPU hot path — AST lint, concurrency rules,
jaxpr contracts.

Three passes, one CLI (``python -m pagerank_tpu.analysis``):

- :mod:`pagerank_tpu.analysis.lint` — repo-specific AST rules over the
  package source (magic lane geometry, implicit dtypes, host syncs
  inside jit, mutable defaults, stray float64).
- :mod:`pagerank_tpu.analysis.concurrency` — the whole-program
  thread/signal-context race detector (PTR rules): execution-context
  inference over every ``threading.Thread``/signal-handler root,
  per-context shared-state and lock-scope tracking, lock-order cycles,
  signal-handler purity, blocking-under-lock, thread lifecycle, and
  the injectable-clock idiom.
- :mod:`pagerank_tpu.analysis.contracts` — abstract-evals every engine
  dispatch form and the registered kernels, then asserts the
  performance invariants nothing else checks mechanically: the
  per-iteration collective budget, no f64 promotion under f32 configs,
  donation actually consumed, stable step compilation keys, and no
  host callbacks inside the step.

Findings carry a stable rule id (``PTLnnn`` lint / ``PTRnnn``
concurrency / ``PTCnnn``+``PTHnnn`` contracts); deliberate exceptions
are waived in ``allowlist.txt`` with a reason. Rule catalogue and
workflow: ``docs/ANALYSIS.md``.
"""

from pagerank_tpu.analysis.findings import (  # noqa: F401
    Finding,
    load_allowlist,
    split_allowlisted,
)
