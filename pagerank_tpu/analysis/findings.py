"""Finding/allowlist plumbing shared by the lint and contract passes.

Kept jax-free on purpose: the lint pass (and the CLI's argument
handling) must work in environments where importing jax is expensive or
unavailable — only :mod:`pagerank_tpu.analysis.contracts` pays that
import.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    """One analysis finding with a stable, documented rule id."""

    rule: str  # PTLnnn (lint) / PTCnnn (contracts)
    path: str  # repo-relative posix path ("" for whole-run findings)
    line: int  # 1-based; 0 when the finding has no source anchor
    message: str
    snippet: str = ""  # stripped source line / contract case label
    col: int = 0  # 0-based column offset

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.path else "<run>"
        tail = f"  [{self.snippet}]" if self.snippet else ""
        return f"{self.rule} {loc}: {self.message}{tail}"


@dataclass(frozen=True)
class Waiver:
    """One allowlist entry: ``rule | path-glob | anchor | reason``.

    ``anchor`` is a substring of the finding's snippet (the source line
    for lint findings, the case label for contract findings) — matching
    on content, not line numbers, so waivers survive unrelated edits.
    ``*`` matches any snippet.
    """

    rule: str
    path_glob: str
    anchor: str
    reason: str

    def matches(self, f: Finding) -> bool:
        if self.rule != f.rule:
            return False
        if not fnmatch.fnmatch(f.path, self.path_glob):
            return False
        return self.anchor == "*" or self.anchor in f.snippet


def load_allowlist(path: str) -> List[Waiver]:
    """Parse an allowlist file. Lines are ``rule | path-glob | anchor |
    reason``; ``#`` comments and blank lines are skipped. A malformed
    line raises — a silently dropped waiver would flip the exit code of
    every clean run."""
    waivers: List[Waiver] = []
    with open(path, encoding="utf-8") as f:
        for ln, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split("|")]
            if len(parts) < 4 or not all(parts[:3]) or not parts[3]:
                raise ValueError(
                    f"{path}:{ln}: allowlist lines are "
                    f"'rule | path-glob | anchor | reason' — got {raw!r}"
                )
            waivers.append(Waiver(parts[0], parts[1], parts[2],
                                  "|".join(parts[3:])))
    return waivers


def split_allowlisted(
    findings: List[Finding], waivers: List[Waiver]
) -> Tuple[List[Finding], List[Tuple[Finding, Waiver]]]:
    """(active, waived) — each finding is waived by the FIRST matching
    allowlist entry."""
    active: List[Finding] = []
    waived: List[Tuple[Finding, Waiver]] = []
    for f in findings:
        for w in waivers:
            if w.matches(f):
                waived.append((f, w))
                break
        else:
            active.append(f)
    return active, waived
