"""Concurrency-plane static analysis — thread/signal-context race rules
(PTR; ISSUE 14, docs/ANALYSIS.md "PTR rules").

The repo runs six host-side thread roots around the solve (rank-writer,
stall watchdog, metrics HTTP server, deadline dispatch, liveness
probes) plus a SIGTERM drain handler, and the staged async-iteration
work (arXiv:cs/0606047) deliberately adds relaxed-consistency
concurrency on top. Every cross-thread invariant was defended only by
hand-written tests; this pass makes concurrency discipline a GATED
artifact like lane geometry (PTL) and collective budgets (PTC/PTH).

The pass is whole-program and jax-free (pure ``ast``):

1. parse every package module and build an approximate CALL GRAPH
   (name/import/annotation-based resolution — ``self`` methods, typed
   attributes, package imports, constructor return types; unresolvable
   calls stay unresolved, so the graph UNDER-approximates reach);
2. infer EXECUTION CONTEXTS: the main thread (implicit), one context
   per ``threading.Thread(target=...)`` root (labelled by the
   ``name=`` literal), one per signal-handler installation
   (:mod:`pagerank_tpu.analysis.roots` — the SAME source of truth
   PTL008 scopes by), and the ``BaseHTTPRequestHandler`` heuristic for
   server threads whose target is an external ``serve_forever``;
3. track per-context state accesses — ``self._x`` attributes keyed
   ``(Class, attr)`` and module-global rebindings — together with
   lexical LOCK SCOPES (``with self._lock:`` over
   ``threading.Lock/RLock/Condition``, instance or module-global);
4. enforce the six PTR rules (docs/ANALYSIS.md has the catalogue with
   provenance).

Precision notes (documented, deliberate): a function reachable from no
thread/signal root is attributed to ``main``; construction-phase
accesses (``__init__``) are exempt from PTR001 — writes that complete
before ``Thread.start()`` are published by the start's happens-before;
attributes bound to threading primitives (locks, events, queues,
``threading.local``) are exempt as state — they ARE the
synchronization. Findings flow through the same
``findings.py``/``allowlist.txt`` machinery as PTL/PTC: benign races
get waivers WITH REASONS, never rule carve-outs.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from pagerank_tpu.analysis import roots as roots_mod
from pagerank_tpu.analysis.findings import Finding
from pagerank_tpu.analysis.lint import iter_python_files, package_root

MAIN = "main"

# attr kinds recognized from construction-time assignments. "lock"
# participates in guard analysis; every non-"plain" kind is exempt
# from PTR001 (the binding IS the synchronization primitive).
_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition")
_SYNC_CTORS = ("threading.Event", "threading.Semaphore",
               "threading.BoundedSemaphore", "threading.Barrier",
               "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
               "queue.PriorityQueue")
_LOCAL_CTORS = ("threading.local",)

# Dotted spellings (import-canonicalized) that BLOCK the calling
# thread: the PTR004 set, shared with PTR003's handler scan.
_BLOCKING_EXACT = {
    "time.sleep", "jax.device_get", "jax.block_until_ready",
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
    "os.waitpid", "select.select",
}
_BLOCKING_SUFFIX = (".deadline_device_get", ".run_with_deadline")

# Filesystem / network I/O (blocking under a lock; forbidden outright
# in a signal-handler closure).
_IO_EXACT = {"open", "print", "os.write", "json.dump", "warnings.warn"}
_IO_SUFFIX = (".fopen", ".atomic_write", ".makedirs", ".listdir",
              ".savez", ".savez_compressed", ".urlopen")
_IO_SYS_WRITE = ("sys.stdout.write", "sys.stderr.write")

# Raw-clock spellings PTR006 bans in context-reachable code (the
# injectable clock/sleep idiom — utils/retry.py — is the fix; a
# DEFAULT-argument reference is not a call and never flags).
_RAW_CLOCK = {"time.time", "time.monotonic", "time.sleep",
              "time.perf_counter", "time.process_time"}

StateKey = Tuple[str, str, str]  # ("attr", Class, name) | ("global", mod, name)
LockKey = Tuple[str, str, str]

# Container methods that mutate their receiver — a call through one is
# a WRITE of the container binding (PTR001).
_MUTATORS = frozenset((
    "append", "extend", "insert", "clear", "update", "setdefault",
    "pop", "popitem", "add", "discard", "remove",
))


# The shared dotted-name resolver (analysis/roots.py): root discovery
# and this call graph must spell names identically.
_dotted = roots_mod.dotted_name


def _snippet(lines: List[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def _const_str(node: Optional[ast.expr]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class Access:
    key: StateKey
    write: bool
    line: int
    col: int
    locks: FrozenSet[LockKey]
    func: "FuncInfo"
    in_init: bool


@dataclass
class CallSite:
    name: str                    # import-canonicalized dotted spelling
    raw: str                     # as written
    node: ast.Call
    line: int
    col: int
    locks: FrozenSet[LockKey]
    func: "FuncInfo"


@dataclass
class Acquire:
    lock: LockKey
    line: int
    col: int
    held: FrozenSet[LockKey]     # locks already held at this acquire
    func: "FuncInfo"
    is_with: bool                # with-statement scope vs bare .acquire()


@dataclass
class FuncInfo:
    qual: str
    rel: str
    cls: Optional[str]
    name: str
    node: ast.AST
    lineno: int
    calls: List[CallSite] = field(default_factory=list)
    accesses: List[Access] = field(default_factory=list)
    acquires: List[Acquire] = field(default_factory=list)
    nested: List[str] = field(default_factory=list)  # nested def quals


@dataclass
class ThreadSite:
    label: str
    roots: List[str]             # root function quals (may be empty)
    daemon: Optional[bool]       # literal daemon kwarg; None = absent
    func: "FuncInfo"             # creating function
    line: int
    col: int
    target_spelling: str
    stored_attr: Optional[str]   # self.X the Thread is stored under
    stored_local: Optional[str]  # local var it is stored under


@dataclass
class ClassInfo:
    name: str
    rel: str
    node: ast.ClassDef
    bases: List[str]
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    attr_kinds: Dict[str, str] = field(default_factory=dict)  # lock/sync/local/thread
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> ClassName


@dataclass
class ModuleInfo:
    rel: str
    report_as: str
    tree: ast.Module
    lines: List[str]
    imports: Dict[str, str] = field(default_factory=dict)   # alias -> dotted/rel
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    global_names: Set[str] = field(default_factory=set)
    global_kinds: Dict[str, str] = field(default_factory=dict)
    global_types: Dict[str, str] = field(default_factory=dict)  # name -> Class


class Program:
    """The parsed whole-program view the PTR rules run over."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}  # name -> defs
        self.thread_sites: List[ThreadSite] = []
        self.signal_roots: List[Tuple[str, str]] = []  # (label, root qual)
        self.contexts: Dict[str, Set[str]] = {}        # qual -> root labels
        self._resolve_memo: Dict[Tuple[str, str], Tuple[str, ...]] = {}


# -- module scanning --------------------------------------------------------


_PKG_PREFIX = "pagerank_tpu."


def _module_rel_of(dotted: str) -> Optional[str]:
    """'pagerank_tpu.obs.metrics' -> 'obs/metrics.py' (None for
    external modules)."""
    if dotted == "pagerank_tpu":
        return "__init__.py"
    if not dotted.startswith(_PKG_PREFIX):
        return None
    return dotted[len(_PKG_PREFIX):].replace(".", "/") + ".py"


def _scan_imports(tree: ast.AST, imports: Dict[str, str]) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                imports[alias] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                alias = a.asname or a.name
                imports[alias] = node.module + "." + a.name


def _ctor_kind(value: ast.expr, imports: Dict[str, str]) -> Optional[str]:
    """lock/sync/local/thread when ``value`` constructs a threading
    primitive (import-alias aware), else None."""
    if not isinstance(value, ast.Call):
        # `a if cond else b` — either branch constructing a primitive
        # makes the attribute that primitive's binding.
        if isinstance(value, ast.IfExp):
            return (_ctor_kind(value.body, imports)
                    or _ctor_kind(value.orelse, imports))
        return None
    name = _canonical_name(_dotted(value.func), imports)
    if name in _LOCK_CTORS:
        return "lock"
    if name in _SYNC_CTORS:
        return "sync"
    if name in _LOCAL_CTORS:
        return "local"
    if name == "threading.Thread":
        return "thread"
    return None


def _canonical_name(dotted: str, imports: Dict[str, str]) -> str:
    """Rewrite the leading alias through the import map:
    ``_time.monotonic`` -> ``time.monotonic``, ``obs_metrics.counter``
    -> ``pagerank_tpu.obs.metrics.counter``."""
    if not dotted:
        return dotted
    head, _, rest = dotted.partition(".")
    target = imports.get(head)
    if target is None:
        return dotted
    return target + ("." + rest if rest else "")


class _FuncVisitor(ast.NodeVisitor):
    """One function body -> calls, state accesses, lock scopes. Nested
    defs are recorded (and scanned as their own FuncInfo by the module
    scan), not walked here."""

    def __init__(self, prog: Program, mod: ModuleInfo, fi: FuncInfo,
                 cls: Optional[ClassInfo], local_names: Set[str],
                 imports: Dict[str, str]):
        self.prog = prog
        self.mod = mod
        self.fi = fi
        self.cls = cls
        self.local_names = local_names
        self.imports = imports
        self.held: Tuple[LockKey, ...] = ()
        # Construction-phase exemption (PTR001): __init__ runs before
        # Thread.start() publishes, and module BODIES run at import
        # time before any thread exists.
        self.in_init = fi.name in ("__init__", "<module>")

    # -- helpers ----------------------------------------------------------

    def _lock_key(self, expr: ast.expr) -> Optional[LockKey]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.cls is not None):
            if self.cls.attr_kinds.get(expr.attr) == "lock":
                return ("attr", self.cls.name, expr.attr)
        elif isinstance(expr, ast.Name):
            if self.mod.global_kinds.get(expr.id) == "lock":
                return ("global", self.mod.rel, expr.id)
        return None

    def _record_access(self, key: StateKey, write: bool,
                       node: ast.AST) -> None:
        self.fi.accesses.append(Access(
            key=key, write=write, line=node.lineno, col=node.col_offset,
            locks=frozenset(self.held), func=self.fi,
            in_init=self.in_init,
        ))

    # -- structure --------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested defs are scanned separately (encloser edge added)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass  # nested classes are scanned by the module pass

    def visit_With(self, node: ast.With) -> None:
        keys = []
        for item in node.items:
            k = self._lock_key(item.context_expr)
            if k is not None:
                keys.append(k)
                self.fi.acquires.append(Acquire(
                    lock=k, line=node.lineno, col=node.col_offset,
                    held=frozenset(self.held), func=self.fi, is_with=True,
                ))
            # The context expression itself (e.g. a call) still scans.
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        prev = self.held
        self.held = prev + tuple(keys)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    def visit_Call(self, node: ast.Call) -> None:
        raw = _dotted(node.func)
        if not raw and isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Call):
            # Chained call — `counter(...).inc()`: record it so the
            # resolver can chase the inner call's return annotation.
            inner = _dotted(node.func.value.func)
            if inner:
                raw = f"{inner}().{node.func.attr}"
        name = _canonical_name(raw, self.imports)
        if raw:
            self.fi.calls.append(CallSite(
                name=name, raw=raw, node=node, line=node.lineno,
                col=node.col_offset, locks=frozenset(self.held),
                func=self.fi,
            ))
            if raw.endswith(".acquire") and isinstance(node.func,
                                                       ast.Attribute):
                k = self._lock_key(node.func.value)
                if k is not None:
                    self.fi.acquires.append(Acquire(
                        lock=k, line=node.lineno, col=node.col_offset,
                        held=frozenset(self.held), func=self.fi,
                        is_with=False,
                    ))
            # Container mutation through a method — `self.dropped
            # .append(...)`, `self._metrics.clear()` — is a WRITE of
            # the container binding for PTR001 purposes.
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                base = node.func.value
                if (isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                        and self.cls is not None):
                    self._record_access(
                        ("attr", self.cls.name, base.attr), True, node)
                elif isinstance(base, ast.Name):
                    self._name_access_mutation(base)
        self.generic_visit(node)

    def _name_access_mutation(self, node: ast.Name) -> None:
        if node.id in self.mod.global_names and (
                node.id not in self.local_names
                or node.id in self._declared_global()):
            self._record_access(("global", self.mod.rel, node.id),
                                True, node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # `self.buckets[key] = n` / `GLOBAL[k] = v`: a subscript store
        # mutates the CONTAINER — record a write of its binding.
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            base = node.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self" and self.cls is not None):
                self._record_access(("attr", self.cls.name, base.attr),
                                    True, node)
            elif isinstance(base, ast.Name):
                self._name_access_mutation(base)
        self.generic_visit(node)

    # -- state accesses ---------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and self.cls is not None):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._record_access(("attr", self.cls.name, node.attr),
                                write, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # `self.x += 1` parses the target as Store; it is BOTH a read
        # and a write — record the read too.
        t = node.target
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self" and self.cls is not None):
            self._record_access(("attr", self.cls.name, t.attr), False, t)
        elif isinstance(t, ast.Name):
            self._name_access(t, write=False)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        self._name_access(node, write=isinstance(node.ctx,
                                                 (ast.Store, ast.Del)))
        self.generic_visit(node)

    def _name_access(self, node: ast.Name, write: bool) -> None:
        name = node.id
        if name not in self.mod.global_names:
            return
        if name in self.local_names and name not in self._declared_global():
            return
        if write and name not in self._declared_global():
            return  # a local shadowing assignment, not a global write
        self._record_access(("global", self.mod.rel, name), write, node)

    def _declared_global(self) -> Set[str]:
        decl = getattr(self.fi, "_globals_decl", None)
        if decl is None:
            decl = set()
            for n in ast.walk(self.fi.node):
                if isinstance(n, ast.Global):
                    decl.update(n.names)
            self.fi._globals_decl = decl  # type: ignore[attr-defined]
        return decl


def _fn_prelude(fn: ast.AST) -> Tuple[Set[str], Dict[str, str]]:
    """ONE walk over ``fn``: (locally bound names, function-level
    import overlay). Local names (params, assignments, for targets,
    with-as, imports, comprehension targets, nested defs) are never
    module-global accesses; function-level imports overlay the module
    map for canonicalization."""
    out: Set[str] = set()
    overlay: Dict[str, str] = {}
    args = fn.args  # type: ignore[attr-defined]
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            if node is not fn:
                out.add(node.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                out.add(alias)
                overlay[alias] = (a.name if a.asname
                                  else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                alias = a.asname or a.name
                out.add(alias.split(".")[0])
                if node.module and not node.level:
                    overlay[alias] = node.module + "." + a.name
    return out, overlay


def _ann_class(ann: Optional[ast.expr]) -> Optional[str]:
    """'Snapshotter' from ``x: Snapshotter`` / ``x:
    Optional[Snapshotter]`` — the parameter-annotation typing the attr
    tracker uses."""
    if ann is None:
        return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Subscript):  # Optional[X] / "Optional[X]"
        inner = ann.slice
        if isinstance(inner, ast.Name):
            return inner.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.strip()
        if name.startswith("Optional[") and name.endswith("]"):
            name = name[len("Optional["):-1]
        return name if name.isidentifier() else None
    return None


def _scan_class(prog: Program, mod: ModuleInfo, cls: ast.ClassDef,
                qual_prefix: str) -> ClassInfo:
    ci = ClassInfo(name=cls.name, rel=mod.rel, node=cls,
                   bases=[_canonical_name(_dotted(b), mod.imports)
                          for b in cls.bases])
    # attr kinds/types from class-body and every method's
    # `self.X = ...` assignments (Tracer builds its lock in __init__;
    # dataclass fields ride the class body).
    for item in cls.body:
        if isinstance(item, ast.Assign) and len(item.targets) == 1 and \
                isinstance(item.targets[0], ast.Name):
            kind = _ctor_kind(item.value, mod.imports)
            if kind:
                ci.attr_kinds[item.targets[0].id] = kind
        elif isinstance(item, ast.AnnAssign) and isinstance(
                item.target, ast.Name) and item.value is not None:
            kind = _ctor_kind(item.value, mod.imports)
            if kind:
                ci.attr_kinds[item.target.id] = kind
    init_ann: Dict[str, str] = {}
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if item.name == "__init__":
                a = item.args
                for p in (a.posonlyargs + a.args + a.kwonlyargs):
                    t = _ann_class(p.annotation)
                    if t:
                        init_ann[p.arg] = t
            for node in ast.walk(item):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    kind = _ctor_kind(node.value, mod.imports)
                    if kind:
                        ci.attr_kinds.setdefault(t.attr, kind)
                        continue
                    typ = _value_class(node.value, mod, init_ann)
                    if typ:
                        ci.attr_types.setdefault(t.attr, typ)
    return ci


def _value_class(value: ast.expr, mod: ModuleInfo,
                 param_ann: Dict[str, str]) -> Optional[str]:
    """The package class an assigned value constructs or carries:
    ``self._g = SinkGuard()`` / ``self._g = g if g else SinkGuard()``
    / ``self._p = policy`` (annotated param)."""
    if isinstance(value, ast.IfExp):
        return (_value_class(value.body, mod, param_ann)
                or _value_class(value.orelse, mod, param_ann))
    if isinstance(value, ast.Call):
        name = _dotted(value.func)
        tail = name.rsplit(".", 1)[-1]
        if tail and tail[:1].isupper():
            return tail
        return None
    if isinstance(value, ast.Name):
        return param_ann.get(value.id)
    return None


def _scan_module(prog: Program, path: str, rel: str,
                 report_as: str) -> Optional[ModuleInfo]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None  # PTL000 already reports this; PTR skips the file
    mod = ModuleInfo(rel=rel, report_as=report_as, tree=tree,
                     lines=source.splitlines())
    _scan_imports(tree, mod.imports)
    # Module globals: top-level assigned names (+ their primitive kind).
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                mod.global_names.add(t.id)
                if value is not None:
                    kind = _ctor_kind(value, mod.imports)
                    if kind:
                        mod.global_kinds[t.id] = kind
                    elif isinstance(value, ast.Call):
                        tail = _dotted(value.func).rsplit(".", 1)[-1]
                        if tail[:1].isupper():
                            mod.global_types[t.id] = tail
    # Classes (anywhere, including nested) and functions.
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            ci = _scan_class(prog, mod, node, rel)
            mod.classes[node.name] = ci
            prog.classes.setdefault(node.name, []).append(ci)

    def scan_fn(fn: ast.AST, cls: Optional[ClassInfo],
                prefix: str) -> FuncInfo:
        qual = f"{rel}::{prefix}{fn.name}"  # type: ignore[attr-defined]
        fi = FuncInfo(qual=qual, rel=rel, cls=cls.name if cls else None,
                      name=fn.name,  # type: ignore[attr-defined]
                      node=fn, lineno=fn.lineno)
        prog.functions[qual] = fi
        if cls is not None:
            cls.methods[fn.name] = fi  # type: ignore[attr-defined]
        elif prefix == "":
            mod.functions[fn.name] = fi  # type: ignore[attr-defined]
        # Function-level imports overlay the module map.
        local_names, overlay = _fn_prelude(fn)
        imports = {**mod.imports, **overlay} if overlay else mod.imports
        fi._imports = imports  # type: ignore[attr-defined]
        visitor = _FuncVisitor(prog, mod, fi, cls, local_names, imports)
        for stmt in fn.body:  # type: ignore[attr-defined]
            visitor.visit(stmt)
        # Nested defs: scanned as their own FuncInfo, linked by an
        # encloser edge (a closure runs in whatever context its
        # encloser runs in — SinkGuard.__call__'s on_retry, _run's
        # work()).
        for child in _direct_nested_defs(fn):
            sub = scan_fn(
                child, cls,
                f"{prefix}{fn.name}.<locals>.",  # type: ignore[attr-defined]
            )
            fi.nested.append(sub.qual)
        return fi

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_fn(node, None, "")
        elif isinstance(node, ast.ClassDef):
            _scan_class_methods(node, mod, scan_fn)
    # The module BODY as a synthetic function: top-level
    # ``threading.Thread(...)`` / ``signal.signal(...)`` sites (the
    # natural shape of a standalone fixture — and of a script-style
    # module) must be visible to thread/signal discovery. Accesses it
    # records are import-time initialization (in_init above), so
    # module constants never read as cross-context writes.
    mod_fi = FuncInfo(qual=f"{rel}::<module>", rel=rel, cls=None,
                      name="<module>", node=tree, lineno=0)
    prog.functions[mod_fi.qual] = mod_fi
    mod_fi._imports = mod.imports  # type: ignore[attr-defined]
    visitor = _FuncVisitor(prog, mod, mod_fi, None, set(), mod.imports)
    for stmt in tree.body:
        visitor.visit(stmt)
    prog.modules[rel] = mod
    return mod


def _direct_nested_defs(fn: ast.AST) -> List[ast.AST]:
    """Function defs DIRECTLY nested in ``fn`` (not inside a deeper
    def/class) — one linear scan, no per-child re-walk."""
    out: List[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            elif not isinstance(child, ast.ClassDef):
                walk(child)

    for stmt in fn.body:  # type: ignore[attr-defined]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(stmt)
        elif not isinstance(stmt, ast.ClassDef):
            walk(stmt)
    return out


def _scan_class_methods(cls: ast.ClassDef, mod: ModuleInfo,
                        scan_fn) -> None:
    ci = mod.classes[cls.name]
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_fn(item, ci, f"{cls.name}.")
        elif isinstance(item, ast.ClassDef):
            _scan_class_methods(item, mod, scan_fn)


# Nested classes defined inside functions (live.py's HTTP Handler) are
# not in tree.body; scan them off the walk.
def _scan_function_nested_classes(prog: Program, mod: ModuleInfo) -> None:
    if not any(not ci.methods for ci in mod.classes.values()):
        return  # no function-nested classes here — skip the re-walk
    seen = {id(fi.node) for fi in prog.functions.values()}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = mod.classes.get(node.name)
        if ci is None or ci.methods:
            continue
        # The class is defined inside a function: its methods close
        # over that function's locals (`exporter = self`), so they
        # inherit its alias map for resolution.
        encl = _func_containing(prog, mod, node)
        closure_aliases = _local_alias_type(encl) if encl else {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(item) not in seen:
                qual = f"{mod.rel}::{node.name}.{item.name}"
                fi = FuncInfo(qual=qual, rel=mod.rel, cls=node.name,
                              name=item.name, node=item, lineno=item.lineno)
                prog.functions[qual] = fi
                ci.methods[item.name] = fi
                local_names, overlay = _fn_prelude(item)
                imports = ({**mod.imports, **overlay} if overlay
                           else mod.imports)
                fi._imports = imports  # type: ignore[attr-defined]
                fi._closure_aliases = closure_aliases  # type: ignore[attr-defined]
                visitor = _FuncVisitor(prog, mod, fi, ci, local_names,
                                       imports)
                for stmt in item.body:
                    visitor.visit(stmt)


# -- call resolution --------------------------------------------------------


def _class_infos(prog: Program, mod: ModuleInfo,
                 name: str) -> List[ClassInfo]:
    if name in mod.classes:
        return [mod.classes[name]]
    # imported package class?
    target = mod.imports.get(name)
    if target:
        dotted_mod, _, cls_name = target.rpartition(".")
        rel = _module_rel_of(dotted_mod)
        if rel and rel in prog.modules and \
                cls_name in prog.modules[rel].classes:
            return [prog.modules[rel].classes[cls_name]]
    return prog.classes.get(name, [])


def _method_lookup(prog: Program, mod: ModuleInfo, cls_name: str,
                   method: str) -> List[str]:
    out = []
    for ci in _class_infos(prog, mod, cls_name):
        fi = ci.methods.get(method)
        if fi is not None:
            out.append(fi.qual)
            continue
        for base in ci.bases:
            base_name = base.rsplit(".", 1)[-1]
            if base_name != cls_name:
                out.extend(_method_lookup(prog, mod, base_name, method))
    return out


def _return_class(fi: FuncInfo) -> Optional[str]:
    ret = getattr(fi.node, "returns", None)
    return _ann_class(ret)


def _resolve_call(prog: Program, site: CallSite) -> List[str]:
    """Callee quals for one call site (possibly empty — unresolved).
    Handles: self methods (incl. base classes), typed self-attributes
    (instance ``__call__`` and ``self._policy.call``), local
    ``v = self`` / ``v = Class()`` aliases, plain/module-level names,
    nested defs, package imports (module functions + constructors),
    and one level of return-annotation chaining
    (``obs_metrics.counter(...).inc``)."""
    memo_key = (site.func.qual, f"{site.line}:{site.col}:{site.raw}")
    hit = prog._resolve_memo.get(memo_key)
    if hit is not None:
        return list(hit)
    out = _resolve_uncached(prog, site)
    prog._resolve_memo[memo_key] = tuple(out)
    return out


def _resolve_uncached(prog: Program, site: CallSite) -> List[str]:
    fi = site.func
    mod = prog.modules[fi.rel]
    imports = getattr(fi, "_imports", mod.imports)
    raw = site.raw

    # method on a call result: obs_metrics.counter(...).inc(...)
    fnode = site.node.func
    if isinstance(fnode, ast.Attribute) and isinstance(fnode.value,
                                                       ast.Call):
        inner_name = _dotted(fnode.value.func)
        if inner_name:
            inner = CallSite(name=_canonical_name(inner_name, imports),
                             raw=inner_name, node=fnode.value,
                             line=site.line, col=site.col,
                             locks=site.locks, func=fi)
            for q in _resolve_uncached(prog, inner):
                ret = _return_class(prog.functions[q])
                if ret:
                    m = _method_lookup(prog, mod, ret, fnode.attr)
                    if m:
                        return m
        return []

    if raw.startswith("self.") and fi.cls is not None:
        rest = raw[len("self."):]
        ci = mod.classes.get(fi.cls)
        if "." not in rest:
            m = _method_lookup(prog, mod, fi.cls, rest)
            if m:
                return m
            # calling a typed attribute -> its __call__
            if ci is not None and rest in ci.attr_types:
                return _method_lookup(prog, mod, ci.attr_types[rest],
                                      "__call__")
            return []
        attr, _, meth = rest.partition(".")
        if "." in meth or ci is None:
            return []
        typ = ci.attr_types.get(attr)
        if typ:
            return _method_lookup(prog, mod, typ, meth)
        return []

    if "." not in raw:
        # nested def in this function?
        for q in fi.nested:
            if prog.functions[q].name == raw:
                return [q]
        # enclosing function's nested sibling (closure call)
        if ".<locals>." in fi.qual:
            parent_qual = fi.qual.rsplit(".<locals>.", 1)[0]
            parent = prog.functions.get(parent_qual)
            if parent is not None:
                for q in parent.nested:
                    f2 = prog.functions[q]
                    if f2.name == raw and q != fi.qual:
                        return [q]
        if raw in mod.functions:
            return [mod.functions[raw].qual]
        if raw in mod.classes or raw in imports:
            ctor = _method_lookup(prog, mod, raw, "__init__")
            if ctor:
                return ctor
            target = imports.get(raw)
            if target:
                dotted_mod, _, name = target.rpartition(".")
                rel = _module_rel_of(dotted_mod)
                if rel and rel in prog.modules:
                    m2 = prog.modules[rel]
                    if name in m2.functions:
                        return [m2.functions[name].qual]
        return []

    head, _, rest = raw.partition(".")
    target = imports.get(head)
    if target is not None:
        rel = _module_rel_of(target)
        if rel and rel in prog.modules:
            m2 = prog.modules[rel]
            if "." not in rest:
                if rest in m2.functions:
                    return [m2.functions[rest].qual]
                if rest in m2.classes:
                    return [q for ci in [m2.classes[rest]]
                            for q in ([ci.methods["__init__"].qual]
                                      if "__init__" in ci.methods else [])]
            else:
                cls_name, _, meth = rest.partition(".")
                if "." not in meth and cls_name in m2.classes:
                    fi2 = m2.classes[cls_name].methods.get(meth)
                    return [fi2.qual] if fi2 else []
        return []
    # ClassName.method in this module / module-global instance
    # (`_REGISTRY.counter`) / local alias `v = self` / closure alias
    # from the enclosing function (live.py's HTTP Handler sees
    # `exporter = self` from _start_http).
    if head in mod.classes and "." not in rest:
        fi2 = mod.classes[head].methods.get(rest)
        return [fi2.qual] if fi2 else []
    if head in mod.global_types and "." not in rest:
        return _method_lookup(prog, mod, mod.global_types[head], rest)
    alias_t = dict(getattr(fi, "_closure_aliases", {}))
    alias_t.update(_local_alias_type(fi))
    typ = alias_t.get(head)
    if typ and "." not in rest:
        return _method_lookup(prog, mod, typ, rest)
    return []


def _local_alias_type(fi: FuncInfo) -> Dict[str, str]:
    """Minimal local type inference: ``v = self`` (enclosing class) and
    ``v = ClassName(...)`` — enough to see through live.py's
    ``exporter = self`` HTTP-handler closure."""
    memo = getattr(fi, "_alias_types", None)
    if memo is not None:
        return memo
    out: Dict[str, str] = {}
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, ast.Name) and v.id == "self" and fi.cls:
                out[node.targets[0].id] = fi.cls
            elif isinstance(v, ast.Call):
                name = _dotted(v.func)
                tail = name.rsplit(".", 1)[-1]
                if tail[:1].isupper():
                    out[node.targets[0].id] = tail
    fi._alias_types = out  # type: ignore[attr-defined]
    return out


# -- context inference ------------------------------------------------------


def _resolve_callable_expr(prog: Program, fi: FuncInfo,
                           expr: ast.expr) -> List[str]:
    """A callable EXPRESSION (a Thread target / signal handler) ->
    function quals."""
    mod = prog.modules[fi.rel]
    name = _dotted(expr)
    if not name:
        return []
    if name.startswith("self.") and fi.cls is not None and \
            "." not in name[len("self."):]:
        return _method_lookup(prog, mod, fi.cls, name[len("self."):])
    if "." not in name:
        for q in fi.nested:
            if prog.functions[q].name == name:
                return [q]
        if name in mod.functions:
            return [mod.functions[name].qual]
    return []


def _discover_threads(prog: Program) -> None:
    for fi in list(prog.functions.values()):
        for site in fi.calls:
            if site.name != "threading.Thread":
                continue
            target_expr = None
            label = None
            daemon: Optional[bool] = None
            for kw in site.node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
                elif kw.arg == "name":
                    label = _const_str(kw.value)
                elif kw.arg == "daemon" and isinstance(kw.value,
                                                       ast.Constant):
                    daemon = bool(kw.value.value)
            spelling = _dotted(target_expr) if target_expr is not None \
                else ""
            roots = (_resolve_callable_expr(prog, fi, target_expr)
                     if target_expr is not None else [])
            stored_attr = stored_local = None
            # `self.X = threading.Thread(...)` / `t = threading.Thread(...)`
            assign = _enclosing_assign(fi.node, site.node)
            if assign is not None:
                t = assign.targets[0] if isinstance(assign, ast.Assign) \
                    else None
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    stored_attr = t.attr
                elif isinstance(t, ast.Name):
                    stored_local = t.id
            prog.thread_sites.append(ThreadSite(
                label=label or f"thread:{spelling or '?'}",
                roots=roots, daemon=daemon, func=fi, line=site.line,
                col=site.col, target_spelling=spelling,
                stored_attr=stored_attr, stored_local=stored_local,
            ))
        # HTTP server threads: the target is an external
        # serve_forever; the code that RUNS on that thread is the
        # module's BaseHTTPRequestHandler subclass.
    for rel, mod in prog.modules.items():
        handler_classes = [
            ci for ci in mod.classes.values()
            if any(b.rsplit(".", 1)[-1] == "BaseHTTPRequestHandler"
                   for b in ci.bases)
        ]
        if not handler_classes:
            continue
        server_sites = [
            ts for ts in prog.thread_sites
            if ts.func.rel == rel and "serve_forever" in ts.target_spelling
        ]
        label = (server_sites[0].label if server_sites
                 else f"http:{rel}")
        for ci in handler_classes:
            for m in ci.methods.values():
                site = server_sites[0] if server_sites else None
                prog.thread_sites.append(ThreadSite(
                    label=label, roots=[m.qual],
                    daemon=site.daemon if site else True,
                    func=site.func if site else m, line=m.lineno, col=0,
                    target_spelling=f"{ci.name}.{m.name}",
                    stored_attr=site.stored_attr if site else None,
                    stored_local=None,
                ))


def _enclosing_assign(root: ast.AST,
                      call: ast.Call) -> Optional[ast.Assign]:
    for node in ast.walk(root):
        if isinstance(node, ast.Assign) and node.value is call:
            return node
    return None


def _discover_signal_roots(prog: Program) -> None:
    for rel, mod in prog.modules.items():
        # Both installation idioms require the signal module — skip
        # the per-module re-walk everywhere it isn't even imported.
        if not any(t == "signal" or t.startswith("signal.")
                   for t in mod.imports.values()):
            continue
        for call, handler_expr, cls_name in roots_mod.iter_handler_installs(
                mod.tree):
            # find the enclosing FuncInfo for resolution context
            fi = _func_containing(prog, mod, call)
            if fi is None:
                continue
            quals = _resolve_callable_expr(prog, fi, handler_expr)
            for q in quals:
                prog.signal_roots.append((f"signal:{q.split('::')[-1]}", q))


def _func_containing(prog: Program, mod: ModuleInfo,
                     node: ast.AST) -> Optional[FuncInfo]:
    best = None
    for fi in prog.functions.values():
        if fi.rel != mod.rel:
            continue
        for sub in ast.walk(fi.node):
            if sub is node:
                if best is None or fi.lineno >= best.lineno:
                    best = fi
    return best


def _compute_contexts(prog: Program) -> None:
    """BFS every root through the call graph (plus encloser->nested
    edges). ``prog.contexts[qual]`` = the set of non-main context
    labels reaching it; a function reached by none runs in MAIN."""
    edges: Dict[str, Set[str]] = {q: set() for q in prog.functions}
    for fi in prog.functions.values():
        for site in fi.calls:
            for q in _resolve_call(prog, site):
                edges[fi.qual].add(q)
        for q in fi.nested:
            edges[fi.qual].add(q)
    roots: List[Tuple[str, str]] = []
    for ts in prog.thread_sites:
        for q in ts.roots:
            roots.append((ts.label, q))
    roots.extend(prog.signal_roots)
    prog.contexts = {q: set() for q in prog.functions}
    for label, root in roots:
        seen = set()
        frontier = [root]
        while frontier:
            q = frontier.pop()
            if q in seen or q not in prog.contexts:
                continue
            seen.add(q)
            prog.contexts[q].add(label)
            frontier.extend(edges.get(q, ()))


def _ctxs_of(prog: Program, fi: FuncInfo) -> FrozenSet[str]:
    labels = prog.contexts.get(fi.qual, set())
    return frozenset(labels) if labels else frozenset((MAIN,))


# -- the rules --------------------------------------------------------------


def _state_label(key: StateKey) -> str:
    kind, owner, name = key
    if kind == "attr":
        return f"{owner}.{name}"
    return f"{owner}:{name}"


def _lock_label(key: LockKey) -> str:
    return _state_label(key)


def rule_ptr001(prog: Program) -> Iterable[Finding]:
    """PTR001: mutable state (``self._x`` / module global) written in
    one context and touched in another without a common guarding lock.
    Construction-phase (``__init__``) accesses and threading-primitive
    bindings are exempt; one finding per state key."""
    by_key: Dict[StateKey, List[Access]] = {}
    for fi in prog.functions.values():
        for acc in fi.accesses:
            by_key.setdefault(acc.key, []).append(acc)
    for key in sorted(by_key):
        kind, owner, name = key
        if kind == "attr":
            owner_infos = prog.classes.get(owner, [])
            if any(ci.attr_kinds.get(name) in ("lock", "sync", "local",
                                               "thread")
                   for ci in owner_infos):
                continue
        else:
            mod = prog.modules.get(owner)
            if mod is not None and mod.global_kinds.get(name) in (
                    "lock", "sync", "local", "thread"):
                continue
        accs = [a for a in by_key[key] if not a.in_init]
        writes = [a for a in accs if a.write]
        if not writes:
            continue
        ctxs = set()
        for a in accs:
            ctxs |= _ctxs_of(prog, a.func)
        if len(ctxs) < 2 or ctxs == {MAIN}:
            continue
        common = frozenset.intersection(*(a.locks for a in accs)) \
            if accs else frozenset()
        if common:
            continue  # every access shares a guarding lock
        rep = next((w for w in writes
                    if _ctxs_of(prog, w.func) != frozenset((MAIN,))),
                   writes[0])
        mod = prog.modules[rep.func.rel]
        yield Finding(
            "PTR001", mod.report_as, rep.line,
            f"shared state {_state_label(key)} is written in context "
            f"{'/'.join(sorted(_ctxs_of(prog, rep.func)))} and accessed "
            f"from {'/'.join(sorted(ctxs))} with no common guarding "
            f"lock: guard every access with one lock, make it a "
            f"documented GIL-atomic handoff (allowlist with the "
            f"reason), or confine it to one context",
            _state_label(key), rep.col,
        )


def rule_ptr002(prog: Program) -> Iterable[Finding]:
    """PTR002: lock-order inversion — a cycle in the lock-acquisition
    graph (lock A held while acquiring B, elsewhere B held while
    acquiring A) deadlocks the first unlucky interleaving."""
    # transitive lock set a function may acquire
    acq_memo: Dict[str, FrozenSet[LockKey]] = {}

    def acq_trans(qual: str, stack: FrozenSet[str]) -> FrozenSet[LockKey]:
        hit = acq_memo.get(qual)
        if hit is not None:
            return hit
        if qual in stack:
            return frozenset()
        fi = prog.functions[qual]
        out = {a.lock for a in fi.acquires}
        for site in fi.calls:
            for q in _resolve_call(prog, site):
                out |= acq_trans(q, stack | {qual})
        memo = frozenset(out)
        acq_memo[qual] = memo
        return memo

    edges: Dict[LockKey, Dict[LockKey, Tuple[str, int, str]]] = {}
    for fi in prog.functions.values():
        for a in fi.acquires:
            for held in a.held:
                if held != a.lock:
                    edges.setdefault(held, {}).setdefault(
                        a.lock, (fi.rel, a.line, fi.qual))
        for site in fi.calls:
            if not site.locks:
                continue
            for q in _resolve_call(prog, site):
                for inner in acq_trans(q, frozenset()):
                    for held in site.locks:
                        if held != inner:
                            edges.setdefault(held, {}).setdefault(
                                inner, (fi.rel, site.line, fi.qual))
    # cycle detection (DFS)
    seen_cycles: Set[Tuple[LockKey, ...]] = set()

    def dfs(start: LockKey, node: LockKey, path: List[LockKey]):
        for nxt in sorted(edges.get(node, {})):
            if nxt == start:
                cyc = tuple(sorted(path))
                if cyc not in seen_cycles:
                    seen_cycles.add(cyc)
                    yield path + [start]
            elif nxt not in path:
                yield from dfs(start, nxt, path + [nxt])

    for start in sorted(edges):
        for cycle in dfs(start, start, [start]):
            rel, line, qual = edges[cycle[0]][cycle[1]]
            order = " -> ".join(_lock_label(k) for k in cycle)
            mod = prog.modules[rel]
            yield Finding(
                "PTR002", mod.report_as, line,
                f"lock-order inversion: {order} — two contexts taking "
                f"these locks in opposite orders deadlock; impose one "
                f"global acquisition order",
                "lockcycle:" + "<>".join(sorted(
                    _lock_label(k) for k in set(cycle))),
            )


# forbidden-operation classification for the PTR003 handler scan
def _handler_violation(prog: Program, fi: FuncInfo,
                       site: CallSite) -> Optional[str]:
    name = site.name
    if name in _IO_EXACT or name in _IO_SYS_WRITE or \
            name.endswith(_IO_SUFFIX):
        return f"performs I/O ({site.raw})"
    if _is_blocking(prog, site):
        return f"blocks ({site.raw})"
    if name.startswith(("jax.", "jnp.", "numpy.", "np.")) or \
            name.startswith("pagerank_tpu.") and ".ops." in name:
        return f"calls into jax/numpy ({site.raw})"
    if name in ("list", "dict", "set", "bytearray"):
        return f"allocates a container ({site.raw})"
    for q in _resolve_call(prog, site):
        tgt = prog.functions[q]
        if tgt.rel == "obs/metrics.py" and tgt.name in (
                "counter", "gauge", "histogram", "_get"):
            return (f"get-or-creates a registry metric ({site.raw}) — "
                    f"allocation plus the registry lock; pre-allocate "
                    f"the instrument and set/inc it instead")
        if tgt.name == "__init__" and tgt.cls is not None:
            return f"allocates ({site.raw}(...) constructs {tgt.cls})"
    return None


def rule_ptr003(prog: Program) -> Iterable[Finding]:
    """PTR003: signal-handler purity. The closure reachable from an
    installed handler may only set pre-allocated flags/simple scalars:
    no lock acquisition (a handler interrupting the lock's holder ON
    THE SAME THREAD self-deadlocks — CPython runs handlers between
    bytecodes of whatever the main thread is doing), no I/O, no
    allocation, no blocking calls, no jax."""
    emitted = set()
    for label, root in sorted(set(prog.signal_roots)):
        closure = _closure(prog, root)
        for qual in sorted(closure):
            fi = prog.functions[qual]
            mod = prog.modules[fi.rel]
            for a in fi.acquires:
                key = (qual, a.line, "lock")
                if key in emitted:
                    continue
                emitted.add(key)
                yield Finding(
                    "PTR003", mod.report_as, a.line,
                    f"signal-handler closure (root {root.split('::')[-1]}"
                    f") acquires lock {_lock_label(a.lock)} in "
                    f"{fi.name}: a signal delivered while the main "
                    f"thread holds it self-deadlocks — handlers may "
                    f"only set pre-allocated flags",
                    _snippet(mod.lines, a.line), a.col,
                )
            for site in fi.calls:
                why = _handler_violation(prog, fi, site)
                if why is None:
                    continue
                key = (qual, site.line, site.raw)
                if key in emitted:
                    continue
                emitted.add(key)
                yield Finding(
                    "PTR003", mod.report_as, site.line,
                    f"signal-handler closure (root "
                    f"{root.split('::')[-1]}) {why} in {fi.name}: "
                    f"handlers may only set pre-allocated flags/simple "
                    f"scalars — defer the work to the next safe point",
                    _snippet(mod.lines, site.line), site.col,
                )


def _closure(prog: Program, root: str) -> Set[str]:
    seen: Set[str] = set()
    frontier = [root]
    while frontier:
        q = frontier.pop()
        if q in seen or q not in prog.functions:
            continue
        seen.add(q)
        fi = prog.functions[q]
        for site in fi.calls:
            frontier.extend(_resolve_call(prog, site))
        frontier.extend(fi.nested)
    return seen


def _is_blocking(prog: Program, site: CallSite) -> bool:
    name = site.name
    if name in _BLOCKING_EXACT or name.endswith(_BLOCKING_SUFFIX):
        return True
    # .get/.put/.join/.wait on a sync-primitive or thread attribute
    if name.startswith("self.") and site.func.cls is not None:
        rest = name[len("self."):]
        if "." in rest:
            attr, _, meth = rest.partition(".")
            mod = prog.modules[site.func.rel]
            ci = mod.classes.get(site.func.cls)
            kind = ci.attr_kinds.get(attr) if ci is not None else None
            if kind in ("sync", "thread") and meth in (
                    "get", "put", "join", "wait", "acquire"):
                return True
    return False


_IO_DURABLE = ("fopen", "atomic_write", "savez", "savez_compressed")


def rule_ptr004(prog: Program) -> Iterable[Finding]:
    """PTR004: blocking call while holding a lock — queue get/join,
    thread join, sleep, device_get, filesystem/network I/O inside a
    lock scope serializes every other context on an unbounded wait."""
    block_memo: Dict[str, Tuple[Tuple[str, str], ...]] = {}

    def blocks_in(qual: str, stack: FrozenSet[str]):
        hit = block_memo.get(qual)
        if hit is not None:
            return hit
        if qual in stack:
            return ()
        fi = prog.functions[qual]
        out = []
        for site in fi.calls:
            if site.locks:
                continue  # reported at ITS lock scope, not ours
            if _is_blocking(prog, site) or site.name in _IO_EXACT or \
                    site.name.endswith(_IO_SUFFIX):
                out.append((site.raw, fi.qual))
            else:
                for q in _resolve_call(prog, site):
                    out.extend(blocks_in(q, stack | {qual}))
        memo = tuple(out[:4])
        block_memo[qual] = memo
        return memo

    for fi in prog.functions.values():
        mod = prog.modules[fi.rel]
        for site in fi.calls:
            if not site.locks:
                continue
            label = None
            if _is_blocking(prog, site):
                label = site.raw
            elif site.name in _IO_EXACT or site.name.endswith(_IO_SUFFIX):
                label = site.raw
            else:
                for q in _resolve_call(prog, site):
                    inner = blocks_in(q, frozenset())
                    if inner:
                        label = (f"{site.raw} -> {inner[0][0]} "
                                 f"(via {inner[0][1].split('::')[-1]})")
                        break
            if label is None:
                continue
            locks = "/".join(sorted(_lock_label(k) for k in site.locks))
            yield Finding(
                "PTR004", mod.report_as, site.line,
                f"blocking call {label} while holding lock {locks}: "
                f"move the wait outside the lock scope (snapshot state "
                f"under the lock, block after releasing)",
                _snippet(mod.lines, site.line), site.col,
            )


def rule_ptr005(prog: Program) -> Iterable[Finding]:
    """PTR005: thread-lifecycle hygiene — a non-daemon thread nobody
    joins outlives every exit path (the interpreter waits on it
    forever); a daemon thread that performs DURABLE writes with no
    join anywhere can be torn mid-write by process exit."""
    for ts in prog.thread_sites:
        fi = ts.func
        mod = prog.modules[fi.rel]
        joined = _has_join(prog, ts)
        if ts.daemon is not True:
            if not joined:
                yield Finding(
                    "PTR005", mod.report_as, ts.line,
                    f"non-daemon thread '{ts.label}' "
                    f"(target {ts.target_spelling}) is never joined: "
                    f"the process cannot exit while it runs — join it "
                    f"on every exit path or make it a daemon with a "
                    f"bounded join",
                    _snippet(mod.lines, ts.line), ts.col,
                )
            continue
        if joined:
            continue
        durable = _durable_write_in_closure(prog, ts)
        if durable:
            yield Finding(
                "PTR005", mod.report_as, ts.line,
                f"daemon thread '{ts.label}' performs durable writes "
                f"({durable}) and is never joined: a process exit can "
                f"tear the write mid-file — join it (bounded) on the "
                f"shutdown path",
                _snippet(mod.lines, ts.line), ts.col,
            )


def _has_join(prog: Program, ts: ThreadSite) -> bool:
    if ts.stored_attr is not None and ts.func.cls is not None:
        needle = f"self.{ts.stored_attr}.join"
        for fi in prog.functions.values():
            if fi.cls != ts.func.cls or fi.rel != ts.func.rel:
                continue
            if any(s.raw == needle for s in fi.calls):
                return True
        return False
    if ts.stored_local is not None:
        needle = f"{ts.stored_local}.join"
        scope = [ts.func] + [prog.functions[q] for q in ts.func.nested]
        return any(s.raw == needle for fi in scope for s in fi.calls)
    return False


def _durable_write_in_closure(prog: Program, ts: ThreadSite
                              ) -> Optional[str]:
    for root in ts.roots:
        for qual in _closure(prog, root):
            fi = prog.functions[qual]
            for site in fi.calls:
                tail = site.name.rsplit(".", 1)[-1]
                if tail in _IO_DURABLE or site.name == "json.dump":
                    return f"{site.raw} in {fi.name}"
    return None


def rule_ptr006(prog: Program) -> Iterable[Finding]:
    """PTR006: raw ``time.time/monotonic/sleep/perf_counter`` CALLS in
    context-reachable code (reachable from a thread/signal root).
    Virtual-time tests cannot drive them, and the repo's injectable
    clock idiom (``clock=time.monotonic`` DEFAULT arguments —
    utils/retry.py) exists precisely so they can; the default-argument
    REFERENCE never flags, only direct calls do."""
    for fi in prog.functions.values():
        ctxs = _ctxs_of(prog, fi)
        if ctxs == frozenset((MAIN,)):
            continue
        mod = prog.modules[fi.rel]
        for site in fi.calls:
            if site.name in _RAW_CLOCK:
                yield Finding(
                    "PTR006", mod.report_as, site.line,
                    f"raw {site.name}() in code reachable from context "
                    f"{'/'.join(sorted(ctxs))}: take an injectable "
                    f"clock/sleep (the utils/retry.py idiom) so "
                    f"virtual-time tests can drive this path",
                    _snippet(mod.lines, site.line), site.col,
                )


RULES: Dict[str, Tuple] = {
    "PTR001": (rule_ptr001,
               "cross-context state without a common guarding lock"),
    "PTR002": (rule_ptr002, "lock-order inversion cycles"),
    "PTR003": (rule_ptr003,
               "signal-handler purity (pre-allocated flags only)"),
    "PTR004": (rule_ptr004, "blocking call while holding a lock"),
    "PTR005": (rule_ptr005, "thread-lifecycle hygiene (join discipline)"),
    "PTR006": (rule_ptr006,
               "raw time.* in context-reachable code (injectable clock)"),
}


# -- drivers ----------------------------------------------------------------


def _build_program(files: List[Tuple[str, str, str]]) -> Program:
    """files: (abs path, rel module path, report-as path)."""
    prog = Program()
    for path, rel, report_as in files:
        _scan_module(prog, path, rel, report_as)
    for mod in prog.modules.values():
        _scan_function_nested_classes(prog, mod)
    _discover_threads(prog)
    _discover_signal_roots(prog)
    _compute_contexts(prog)
    return prog


def _run_rules(prog: Program) -> List[Finding]:
    findings: List[Finding] = []
    for rule_id in sorted(RULES):
        findings.extend(RULES[rule_id][0](prog))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def build_package_program(root: Optional[str] = None) -> Program:
    """The parsed whole-program view for the package tree (or an
    explicit directory treated as its own program). Tests and the
    acceptance smoke introspect discovered thread/signal roots and
    per-function contexts through this."""
    root = os.path.abspath(root or package_root())
    pkg = package_root()
    inside = root == pkg or root.startswith(pkg + os.sep)
    base = pkg if inside else root
    files = []
    for path in iter_python_files(root):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        files.append((path, rel, rel if inside else path))
    return _build_program(files)


def analyze_program(prog: Program) -> List[Finding]:
    """Run the PTR rules over an already-built Program (the acceptance
    smoke builds once and both introspects roots and gates findings)."""
    return _run_rules(prog)


def analyze_package(root: Optional[str] = None) -> List[Finding]:
    """The PTR pass over the installed package (or an explicit
    directory treated as its own whole program — fixture space)."""
    return _run_rules(build_package_program(root))


def analyze_file(path: str) -> List[Finding]:
    """One file as a standalone program (seeded-defect fixtures).
    Thread/signal roots and state are discovered within the file; the
    report path is the path as given."""
    ap = os.path.abspath(path)
    rel = os.path.basename(ap)
    return _run_rules(_build_program([(ap, rel, path)]))
