"""``python -m pagerank_tpu.analysis`` — run the AST lint and the jaxpr
contract suite over the repo; nonzero exit on any non-waived finding.

Exit codes: 0 clean, 1 findings, 2 usage/configuration error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _prepare_jax_env() -> None:
    """The contract pass abstract-evals sharded programs: force the CPU
    backend (analysis must never squat on — or hang trying to
    initialize — a TPU) with a small fake mesh, BEFORE any backend
    initializes. jax is usually ALREADY IMPORTED here (the package
    import pulls it in), so the platform pin must go through
    jax.config, which beats the env var (the conftest does the same);
    the device-count XLA flag is still read at first backend use, so
    the env write works. An explicit user JAX_PLATFORMS is respected."""
    user_choice = os.environ.get("JAX_PLATFORMS")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    if user_choice is None:
        import jax

        jax.config.update("jax_platforms", "cpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pagerank_tpu.analysis",
        description="AST lint + jaxpr contract checker for the TPU hot "
        "path (rule catalogue: docs/ANALYSIS.md).",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the installed "
        "pagerank_tpu package). Paths outside the package are treated "
        "as fixture space: every rule applies regardless of scope",
    )
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (schema version 1)")
    p.add_argument(
        "--allowlist", default=None,
        help="waiver file (default: the checked-in "
        "pagerank_tpu/analysis/allowlist.txt; 'none' disables)",
    )
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--lint-only", action="store_true",
                      help="skip the jaxpr contract suite (no jax import)")
    mode.add_argument("--contracts-only", action="store_true",
                      help="skip the AST lint")
    p.add_argument(
        "--forms", default=None,
        help="comma-separated engine dispatch forms for the contract "
        "suite (default: all; see docs/ANALYSIS.md)",
    )
    p.add_argument(
        "--select", default=None,
        help="comma-separated rule or family prefixes to run (e.g. "
        "'PTK', 'PTL005,PTR'); passes whose families are not selected "
        "are skipped entirely (so '--select PTK' is the fast "
        "kernel-plane gate)",
    )
    p.add_argument(
        "--kernel-fixture", nargs="?", const="all", default=None,
        metavar="NAME",
        help="run the kernel-plane pass over the seeded-defect "
        "fixtures instead of the shipped registry ('all' or one of "
        "vmem_overflow/misaligned_tile/index_gap/index_overlap/"
        "f64_scratch/cost_mismatch) — each must exit nonzero; the "
        "acceptance harness pins this",
    )
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def _selected(select, *families: str) -> bool:
    """Whether any of a pass's rule families ('PTL', 'PTK', ...) is
    covered by the --select prefixes (None selects everything). A
    selector may be a family ('PTK') or a full rule id ('PTL005')."""
    if select is None:
        return True
    sels = [s.strip().upper() for s in select.split(",") if s.strip()]
    return any(
        s.startswith(fam) or fam.startswith(s)
        for s in sels for fam in families
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from pagerank_tpu.analysis import load_allowlist, split_allowlisted
    from pagerank_tpu.analysis import lint as lint_mod

    if args.list_rules:
        from pagerank_tpu.analysis import concurrency as conc_mod

        for rid, (_fn, scope, desc) in sorted(lint_mod.RULES.items()):
            print(f"{rid}  [{scope:6}] {desc}")
        for rid, (_fn, desc) in sorted(conc_mod.RULES.items()):
            print(f"{rid}  [thread] {desc}")
        for rid, desc in (
            ("PTC001", "per-iteration collective budget / kernel shapes"),
            ("PTC002", "no f64 promotion under f32 configs"),
            ("PTC003", "donation actually consumed"),
            ("PTC004", "step compilation key independent of num_iters/tol"),
            ("PTC005", "no host callbacks inside iteration programs"),
            ("PTC006", "device build chain 32-bit under x64 (no i64/f64 op)"),
            ("PTC007", "probe-enabled step: same collectives, no "
                       "callbacks, no f64, donation intact"),
        ):
            print(f"{rid}  [jaxpr ] {desc}")
        for rid, desc in (
            ("PTH001", "optimized-HLO gather strategy: native gather, "
                       "never the while/scalar expansion"),
            ("PTH002", "optimized-HLO fusion count within budget"),
            ("PTH003", "no while-loop carrying gather-class traffic "
                       "as scalar dynamic-slices"),
            ("PTH004", "pallas engine optimized HLO: the Mosaic custom "
                       "call present AND the gathers gone"),
        ):
            print(f"{rid}  [hlo   ] {desc}")
        from pagerank_tpu.analysis import kernels as kernels_mod

        for rid, desc in sorted(kernels_mod.RULES.items()):
            print(f"{rid}  [kernel] {desc}")
        return 0

    allowlist_path = args.allowlist
    if allowlist_path is None:
        allowlist_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "allowlist.txt"
        )
    waivers = []
    if allowlist_path and allowlist_path != "none":
        try:
            waivers = load_allowlist(allowlist_path)
        except (OSError, ValueError) as e:
            print(f"analysis: bad allowlist: {e}", file=sys.stderr)
            return 2

    findings = []
    if not args.contracts_only and _selected(args.select, "PTL", "PTR"):
        from pagerank_tpu.analysis import concurrency as conc_mod

        if args.paths:
            pkg = lint_mod.package_root()
            in_pkg_rels = []
            in_pkg_prefixes = []
            for path in args.paths:
                ap = os.path.abspath(path)
                inside = ap == pkg or ap.startswith(pkg + os.sep)
                if os.path.isdir(path):
                    findings.extend(lint_mod.lint_tree(path))
                    if inside:
                        # PTR is whole-program: an in-package subtree's
                        # threads/callers live elsewhere in the
                        # package, so analyze the FULL package and
                        # filter (the file form's rationale).
                        rel = os.path.relpath(ap, pkg).replace(os.sep, "/")
                        in_pkg_prefixes.append(
                            "" if rel == "." else rel + "/")
                    else:
                        # An OUTSIDE directory is its own whole
                        # program (fixture space).
                        findings.extend(conc_mod.analyze_package(path))
                    continue
                # An explicit IN-PACKAGE file keeps package-relative
                # scoping and reporting (so allowlist globs match and
                # only in-scope rules run); outside files are fixture
                # space.
                rel = None
                if inside:
                    rel = os.path.relpath(ap, pkg).replace(os.sep, "/")
                    in_pkg_rels.append(rel)
                else:
                    # Standalone fixture file: the file IS the program
                    # (thread/signal roots discovered within it).
                    findings.extend(conc_mod.analyze_file(path))
                findings.extend(lint_mod.lint_file(path, rel))
            if in_pkg_rels or in_pkg_prefixes:
                wanted = set(in_pkg_rels)
                findings.extend(
                    f for f in conc_mod.analyze_package()
                    if f.path in wanted
                    or any(f.path.startswith(p) for p in in_pkg_prefixes)
                )
        else:
            findings.extend(lint_mod.lint_tree())
            findings.extend(conc_mod.analyze_package())

    if not args.lint_only and _selected(args.select, "PTK"):
        # Kernel plane BEFORE the contract pass: PTK traces the Pallas
        # kernels at their shipped dtypes and must not run under the
        # x64 flip the contract suite needs for PTC002.
        _prepare_jax_env()
        from pagerank_tpu.analysis import kernels as kernels_mod

        cases = None
        if args.kernel_fixture is not None:
            cases = kernels_mod.defect_cases()
            if args.kernel_fixture != "all":
                cases = [
                    c for c in cases
                    if c.label == f"fixture:{args.kernel_fixture}"
                ]
                if not cases:
                    print(
                        f"analysis: unknown kernel fixture "
                        f"'{args.kernel_fixture}'",
                        file=sys.stderr,
                    )
                    return 2
        findings.extend(kernels_mod.check_kernel_plane(cases))

    if not args.lint_only and _selected(args.select, "PTC", "PTH"):
        _prepare_jax_env()
        import jax

        jax.config.update("jax_enable_x64", True)  # makes PTC002 real
        from pagerank_tpu.analysis.contracts import run_contracts

        forms = args.forms.split(",") if args.forms else None
        findings.extend(run_contracts(forms=forms))

    active, waived = split_allowlisted(findings, waivers)

    if args.json:
        print(json.dumps({
            "version": 1,
            "ok": not active,
            "counts": {"active": len(active), "waived": len(waived)},
            "findings": [f.to_json() for f in active],
            "waived": [
                {"finding": f.to_json(), "reason": w.reason}
                for f, w in waived
            ],
        }, indent=2))
    else:
        for f in active:
            print(f.render())
        print(
            f"analysis: {len(active)} finding(s), {len(waived)} waived",
            file=sys.stderr,
        )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
