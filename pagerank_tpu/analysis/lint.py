"""Repo-specific AST lint for the TPU hot path.

Every rule here exists because a review round caught (or nearly missed)
the defect class by hand — see docs/ANALYSIS.md for the catalogue with
``file:line`` provenance. Rules are scoped: lane geometry and dtype
hygiene police the kernel modules (``ops/``, the jax engines), the
host-sync and mutable-default rules police the whole package. Files
OUTSIDE the package tree (test fixtures) get every rule, so seeded
violations exercise each id.

Rule ids are stable (``PTL001``..); deliberate exceptions live in
``analysis/allowlist.txt`` with a reason, never as rule carve-outs.
"""

from __future__ import annotations

import ast
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from pagerank_tpu.analysis import roots as roots_mod
from pagerank_tpu.analysis.findings import Finding

# The lane-geometry constants whose literal spelling is banned in ops/:
# 128 (the lane count), 127 (its mask), and shifts by 7 (its log2). The
# one allowed spelling is the `LANES = 128` assignment in ops/__init__.
_LANE_LITERALS = (127, 128)
_LANE_SHIFT = 7

# jnp constructors whose result dtype silently follows the x64 flag (or
# a weak-typed fill) unless pinned. Maps name -> index of the positional
# dtype argument.
_DTYPE_CTORS = {
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "array": 1,
    "arange": None,  # dtype is keyword-position-dependent; require kwarg
}

# Calls that force a device->host sync (or silently materialize on
# host) when they execute inside a traced/jitted function.
_HOST_SYNC_NAMES = {"print", "float", "int"}
_HOST_SYNC_ATTRS = {"item"}  # x.item()


def _is_jit_decorator(dec: ast.expr) -> bool:
    """@jax.jit / @jit / @functools.partial(jax.jit, ...) /
    @partial(jit, ...) — including jax.jit called as a factory."""

    def jit_ish(node: ast.expr) -> bool:
        return (isinstance(node, ast.Name) and node.id == "jit") or (
            isinstance(node, ast.Attribute) and node.attr == "jit"
        )

    if jit_ish(dec):
        return True
    if isinstance(dec, ast.Call):
        if jit_ish(dec.func):  # @jax.jit(static_argnums=...)
            return True
        f = dec.func
        partial_ish = (isinstance(f, ast.Name) and f.id == "partial") or (
            isinstance(f, ast.Attribute) and f.attr == "partial"
        )
        if partial_ish and dec.args and jit_ish(dec.args[0]):
            return True
    return False


def _snippet(lines: List[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def _int_const(node: ast.expr) -> Optional[int]:
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    return None


def _dotted(node: ast.expr) -> str:
    """'jnp.zeros' for Attribute(Name(jnp), zeros); '' when not a plain
    dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# -- rules -----------------------------------------------------------------


def rule_ptl001(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL001: magic lane-geometry constants in kernel modules. Bans
    literal 128/127 and ``>> 7``/``<< 7`` outside the canonical
    ``LANES = 128`` assignment — hardcoded geometry diverges silently
    when the layout changes (the ell.py deal composition did exactly
    that; ADVICE r5)."""
    allowed_lines = set()
    for node in ast.walk(tree):
        # The one allowed spelling: `LANES = <int>` at module level.
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "LANES":
                for sub in ast.walk(node):
                    allowed_lines.add(getattr(sub, "lineno", node.lineno))
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and node.lineno not in allowed_lines:
            if type(node.value) is int and node.value in _LANE_LITERALS:
                yield Finding(
                    "PTL001", path, node.lineno,
                    f"magic lane constant {node.value}: derive from LANES "
                    f"(pagerank_tpu.ops.LANES) instead",
                    _snippet(lines, node.lineno), node.col_offset,
                )
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.LShift, ast.RShift)
        ):
            if _int_const(node.right) == _LANE_SHIFT:
                yield Finding(
                    "PTL001", path, node.lineno,
                    "magic lane shift by 7: use LANES-derived arithmetic "
                    "(// LANES, % LANES, or LANES.bit_length() - 1)",
                    _snippet(lines, node.lineno), node.col_offset,
                )


def rule_ptl002(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL002: jnp array constructors without an explicit dtype in
    kernel modules. The result dtype then follows the process-global
    x64 flag (which this package flips at runtime for f64 configs) or
    a weak-typed fill — an accidental widening doubles HBM traffic on
    the hot path."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if not name.startswith("jnp."):
            continue
        ctor = name[len("jnp."):]
        if ctor not in _DTYPE_CTORS:
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        pos = _DTYPE_CTORS[ctor]
        if pos is not None and len(node.args) > pos:
            continue  # positional dtype argument
        if ctor == "full" and len(node.args) > 1 and isinstance(
            node.args[1], ast.Call
        ):
            continue  # fill like jnp.int32(x) pins the dtype itself
        yield Finding(
            "PTL002", path, node.lineno,
            f"jnp.{ctor} without an explicit dtype: the result follows "
            f"the global x64 flag — pin it",
            _snippet(lines, node.lineno), node.col_offset,
        )


def rule_ptl003(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL003: host-sync calls inside jit-decorated functions. A
    ``print``/``float()``/``.item()``/``np.asarray``/``jax.device_get``
    reached under trace either fails or forces a device->host round
    trip per call — the exact overhead the one-dispatch-per-iteration
    design exists to remove."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(_is_jit_decorator(d) for d in fn.decorator_list):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            bad = None
            if name in _HOST_SYNC_NAMES:
                bad = f"{name}()"
            elif name.startswith("np.") or name.startswith("numpy."):
                bad = name
            elif name == "jax.device_get":
                bad = name
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_ATTRS
            ):
                bad = f".{node.func.attr}()"
            if bad:
                yield Finding(
                    "PTL003", path, node.lineno,
                    f"host-sync call {bad} inside jit-decorated "
                    f"'{fn.name}': hoist it out of the traced region",
                    _snippet(lines, node.lineno), node.col_offset,
                )


def rule_ptl004(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL004: mutable default arguments — shared across calls, a
    classic aliasing bug; engine builders cache per-instance state and
    a shared default list/dict corrupts it silently."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and _dotted(d.func) in ("list", "dict", "set")
            )
            if mutable:
                yield Finding(
                    "PTL004", path, d.lineno,
                    f"mutable default argument in '{fn.name}': use None "
                    f"and construct inside",
                    _snippet(lines, d.lineno), d.col_offset,
                )


def rule_ptl005(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL005: float64 literals in kernel modules outside the
    config-gated pair-f64 paths. TPUs have no native f64 — a stray
    float64 constant/dtype string drags a kernel onto the ~3.4x-slower
    emulated path (or trips the process-global x64 flip); wide
    accumulation must come from config.accum_dtype, never a literal."""
    for node in ast.walk(tree):
        name = _dotted(node) if isinstance(node, ast.Attribute) else ""
        if name in ("np.float64", "jnp.float64", "numpy.float64"):
            yield Finding(
                "PTL005", path, node.lineno,
                f"{name} literal: route wide precision through "
                f"config.accum_dtype (pair-f64 path) instead",
                _snippet(lines, node.lineno), node.col_offset,
            )
        elif isinstance(node, ast.Constant) and node.value == "float64":
            yield Finding(
                "PTL005", path, node.lineno,
                "'float64' dtype string: route wide precision through "
                "config.accum_dtype (pair-f64 path) instead",
                _snippet(lines, node.lineno), node.col_offset,
            )


def rule_ptl006(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL006: exception swallows — the failure mode the fault-
    tolerance layer exists to prevent (docs/ROBUSTNESS.md): a bare
    ``except:`` that never re-raises, or a broad ``except Exception``/
    ``except BaseException`` whose body is only ``pass``/constants,
    silently discards an error that retry/rollback/dead-letter
    machinery should have seen. Deliberate best-effort sites carry an
    allowlist entry with the reason, never a rule carve-out."""

    def broad(t: Optional[ast.expr]) -> bool:
        if t is None:
            return True
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for el in elts:
            name = (
                el.id if isinstance(el, ast.Name)
                else el.attr if isinstance(el, ast.Attribute) else ""
            )
            if name in ("Exception", "BaseException"):
                return True
        return False

    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        reraises = any(
            isinstance(sub, ast.Raise)
            for stmt in node.body for sub in ast.walk(stmt)
        )
        swallow = all(
            isinstance(s, ast.Pass)
            or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
            for s in node.body
        )
        if node.type is None and not reraises:
            yield Finding(
                "PTL006", path, node.lineno,
                "bare 'except:' without re-raise swallows every error "
                "(including KeyboardInterrupt/SystemExit): name the "
                "exceptions or re-raise",
                _snippet(lines, node.lineno), node.col_offset,
            )
        elif node.type is not None and broad(node.type) and swallow:
            yield Finding(
                "PTL006", path, node.lineno,
                "broad exception swallow ('except Exception: pass'): "
                "handle, log, or narrow it — silent drops hide the "
                "faults the robustness layer must surface",
                _snippet(lines, node.lineno), node.col_offset,
            )


def rule_ptl007(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL007: bare ``print(...)`` / direct ``sys.stderr.write`` /
    ``sys.stdout.write`` in LIBRARY modules (scope excludes CLI entry
    points: ``cli.py`` and ``*/__main__.py``). Ad-hoc prints bypass the
    observability layer — they never land in traces or run reports and
    cannot be silenced as a unit; telemetry flows through
    ``pagerank_tpu.obs`` (spans, metrics, ``obs.log``) instead. The
    deliberate exceptions (MetricsLogger's per-iteration stream,
    obs/log.py's own stderr write) carry allowlist entries."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name == "print":
            yield Finding(
                "PTL007", path, node.lineno,
                "bare print() in a library module: route diagnostics "
                "through pagerank_tpu.obs (obs.log / spans / metrics)",
                _snippet(lines, node.lineno), node.col_offset,
            )
        elif name in ("sys.stderr.write", "sys.stdout.write"):
            yield Finding(
                "PTL007", path, node.lineno,
                f"direct {name} in a library module: route diagnostics "
                "through pagerank_tpu.obs (obs.log / spans / metrics)",
                _snippet(lines, node.lineno), node.col_offset,
            )


def rule_ptl008(tree: ast.AST, path: str, lines: List[str]) -> Iterable[Finding]:
    """PTL008: process-global handler installation (``signal.signal``,
    ``atexit.register``) outside the supervisor modules (scope excludes
    ``jobs.py`` and ``cli.py``). Signal handlers and exit hooks are
    PROCESS-wide state: a library module that installs one hijacks the
    embedding application's preemption story (and the GracefulDrain
    contract — jobs.py owns SIGTERM/SIGINT, docs/ROBUSTNESS.md
    "Preemption & resumable jobs"). Library code takes an injectable
    callback instead; only the entry-point supervisor wires it to real
    signals."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name in ("signal.signal", "atexit.register"):
            yield Finding(
                "PTL008", path, node.lineno,
                f"{name}() in a library module installs process-global "
                "handler state: only the job supervisor (jobs.py) and "
                "the CLI entry point own signal/exit hooks — accept an "
                "injectable callback instead",
                _snippet(lines, node.lineno), node.col_offset,
            )


RuleFn = Callable[[ast.AST, str, List[str]], Iterable[Finding]]

# rule id -> (fn, scope, one-line description). Scopes:
#   ops     — files under ops/
#   kernel  — ops/ plus the jax engines (the modules that trace device code)
#   all     — every package file
#   library — every package file EXCEPT CLI entry points (cli.py,
#             */__main__.py), which legitimately print to the terminal
#   handler_free — every package file EXCEPT jobs.py and cli.py, the
#             two modules allowed to install process-global
#             signal/exit handlers (ISSUE 12)
RULES: Dict[str, Tuple[RuleFn, str, str]] = {
    "PTL001": (rule_ptl001, "ops",
               "magic lane-geometry constants outside LANES"),
    "PTL002": (rule_ptl002, "kernel",
               "jnp constructors without an explicit dtype"),
    "PTL003": (rule_ptl003, "all",
               "host-sync calls inside jit-decorated functions"),
    "PTL004": (rule_ptl004, "all", "mutable default arguments"),
    "PTL005": (rule_ptl005, "kernel",
               "float64 literals outside config-gated paths"),
    "PTL006": (rule_ptl006, "all",
               "bare/broad exception swallows"),
    "PTL007": (rule_ptl007, "library",
               "bare print()/sys.std*.write outside CLI entry points"),
    "PTL008": (rule_ptl008, "handler_free",
               "signal.signal/atexit.register outside jobs.py/cli.py"),
}

_KERNEL_FILES = ("engines/jax_engine.py", "engines/ppr.py")


def _scope_match(scope: str, rel: str) -> bool:
    if scope == "all":
        return True
    if scope == "ops":
        return rel.startswith("ops/")
    if scope == "kernel":
        return rel.startswith("ops/") or rel in _KERNEL_FILES
    if scope == "library":
        return rel != "cli.py" and not rel.endswith("__main__.py")
    if scope == "handler_free":
        # Everything but the modules that OWN process-global handlers
        # (the job supervisor and the CLI entry point that installs
        # its GracefulDrain, ISSUE 12) — read from the SHARED source
        # of truth PTR003's signal-root discovery also uses
        # (analysis/roots.py, ISSUE 14), so moving GracefulDrain can
        # never silently split the two rules' views.
        return rel not in roots_mod.HANDLER_OWNER_MODULES
    raise ValueError(f"unknown rule scope {scope!r}")


def package_root() -> str:
    """The installed pagerank_tpu package directory — the default lint
    target."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_python_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if not d.startswith((".", "__pycache__"))
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    """Run every in-scope rule over one file. ``rel`` is the
    package-relative posix path used for scoping and reporting; files
    outside the package pass every scope (fixture mode)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    report_as = rel if rel is not None else path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("PTL000", report_as, e.lineno or 0,
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = []
    for rule_id, (fn, scope, _desc) in RULES.items():
        if rel is not None and not _scope_match(scope, rel):
            continue
        findings.extend(fn(tree, report_as, lines))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def lint_tree(root: Optional[str] = None) -> List[Finding]:
    """Lint the package tree (default) or an explicit directory. Inside
    the package, rules apply by scope; an external directory is treated
    as fixture space (every rule, paths reported relative to it)."""
    root = os.path.abspath(root or package_root())
    pkg = package_root()
    inside = root == pkg or root.startswith(pkg + os.sep)
    findings: List[Finding] = []
    for path in iter_python_files(root):
        rel = os.path.relpath(path, pkg if inside else root).replace(
            os.sep, "/"
        )
        findings.extend(lint_file(path, rel if inside else None))
    return findings
