"""Kernel-plane static analysis: PTK rules that prove a Pallas kernel
safe BEFORE TPU time (ISSUE 16 tentpole).

Hand-written kernels reintroduce the failure classes XLA used to
absorb — VMEM overflow, tile/lane misalignment, index maps that skip or
double-cover output rows — and the repo's standing rule (PR 11 for
gather lowering, PR 14 for races) is that every TPU risk becomes a
typed pre-mesh verdict first. This module walks every
``pl.pallas_call`` site of a registered kernel *abstractly*: the kernel
is traced with ``jax.make_jaxpr`` at the instantiated geometry (shapes
only — nothing executes, no TPU, no Mosaic), the grid spec /
BlockSpecs / index maps / scratch shapes are read off the jaxpr, and
each index map is evaluated symbolically over the FULL grid (the
state-discharged map jaxpr, vmapped over grid coordinates against the
case's concrete scalar-prefetch arrays). Rules:

  PTK001  VMEM budget: every VMEM-resident block (x2 when its index
          map varies across the grid — the pipeline double-buffers it)
          plus VMEM scratch, tile-padded, must fit the per-device-kind
          VMEM capacity table with headroom
          (obs/costs.VMEM_CAPACITY_BYTES / pallas_vmem_budget — the
          HBM_CAPACITY_BYTES idiom). The legacy ell_contrib_pallas
          whole-z_ext design FAILS this at the bench scales and
          carries a geometry-bounded allowlist entry; the runtime
          guard (engine pallas probe) enforces the same shared bound.
  PTK002  Tile/lane geometry: a >=2-D VMEM block's trailing dims must
          be divisible by the dtype's sublane x lane tile — 8x128 f32,
          16x128 bf16, 32x128 int8 (the words24 planar-int8 slot
          stream makes the int8 row a live hazard). A trailing dim of
          exactly 1 is allowed (Mosaic pads; PTK001 charges the full
          128 lanes).
  PTK003  Index-map coverage: every blocked input read in bounds over
          the full grid; every output element written exactly once —
          blocked VMEM outputs must cover every block with no
          non-consecutive revisit (gap AND overlapping-write races),
          ANY-space RMW outputs must declare a write model (window
          starts x width) whose union covers the full logical length
          in bounds (a chunk whose rank span outgrew the static width
          would silently drop rows — this is the rule that catches
          it).
  PTK004  Memory-space discipline: float VMEM scratch accumulators
          must be f32, no f64 value anywhere in a kernel body, and
          ANY-space (HBM-resident) refs may be touched ONLY by
          explicit DMA (make_async_copy's dma_start/dma_wait) — never
          direct get/swap.
  PTK005  Grid/cost sanity: static per-sweep FLOPs (dot_generals over
          the grid) and HBM bytes (streamed blocks x distinct-index
          runs + RMW traffic) reconciled against the case's analytic
          model within 25% (the PR 11 obs/costs reconciliation idiom).

Verdicts are deterministic and CPU-only; the CLI front-end is
``python -m pagerank_tpu.analysis --select PTK`` and the shipped-kernel
registry pins the TPU campaign's scale 22-25 geometries so the next
mesh session starts from a green exit code. Seeded-defect fixtures
(``defect_cases``) each trip exactly their rule and are wired into
scripts/acceptance.py.
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pagerank_tpu.analysis.findings import Finding

LANES = 128

#: rule id -> one-line description (the CLI --list-rules catalogue).
RULES: Dict[str, str] = {
    "PTK001": "VMEM budget: resident blocks x buffering + scratch vs "
              "per-device-kind capacity with headroom",
    "PTK002": "tile/lane geometry: 8x128 f32 / 16x128 bf16 / 32x128 int8 "
              "block divisibility",
    "PTK003": "index-map coverage: reads in bounds; outputs written "
              "exactly once (gaps AND overlaps)",
    "PTK004": "memory-space discipline: f32 VMEM scratch, no f64 in "
              "kernels, ANY refs only via explicit DMA",
    "PTK005": "grid/cost sanity: static FLOPs+bytes vs the obs/costs "
              "analytic model",
}

#: dtype itemsize -> required sublane multiple (lane is always 128).
_SUBLANES = {8: 4, 4: 8, 2: 16, 1: 32}


# ---------------------------------------------------------------------------
# Case registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KernelCase:
    """One kernel at one instantiated geometry.

    ``fn(*args)`` must trace (jax.make_jaxpr) to a jaxpr containing
    exactly one ``pallas_call``; ``scalar_args`` are the CONCRETE
    scalar-prefetch operands (index maps and the write model evaluate
    against them). ``write_model`` describes an ANY-space RMW output:
    ``(starts, width, length)`` — per-grid-step window starts, static
    window width, logical output length that must be covered.
    ``cost_model`` is the analytic {"flops", "bytes"} expectation per
    sweep (PTK005); None skips the reconciliation."""

    label: str
    fn: Callable
    args: tuple
    scalar_args: tuple = ()
    write_model: Optional[Callable[[], Tuple[np.ndarray, int, int]]] = None
    cost_model: Optional[Dict[str, float]] = None
    rmw: bool = True
    path: str = ""
    line: int = 0


def _package_root() -> str:
    import pagerank_tpu

    return os.path.dirname(os.path.abspath(pagerank_tpu.__file__))


def _loc(obj) -> Tuple[str, int]:
    """(package-relative path, 1-based line) of a kernel's def — the
    finding anchor. Unwraps jit/partial wrappers; falls back to an
    empty anchor rather than failing the analysis."""
    try:
        fn = obj
        while isinstance(fn, functools.partial):
            fn = fn.func
        fn = inspect.unwrap(fn)
        src = inspect.getsourcefile(fn)
        line = inspect.getsourcelines(fn)[1]
        rel = os.path.relpath(src, _package_root()).replace(os.sep, "/")
        if rel.startswith(".."):
            rel = os.path.basename(src)
        return rel, line
    except Exception:
        return "", 0


def _synth_ranks(rows: int, pairs: int) -> np.ndarray:
    """Dense non-decreasing global pair ranks spread evenly over the
    rows — the engine's dense_block_ranks invariant (increment <= 1
    per row) at synthetic-geometry fidelity."""
    return ((np.arange(rows, dtype=np.int64) * pairs) // rows).astype(
        np.int32
    )


def _legacy_case(*, label: str, n_pad: int, rows: int, chunk: int = 256,
                 gather: str = "take") -> KernelCase:
    """ops/pallas_spmv.ell_contrib_pallas at a synthetic geometry:
    whole z_ext resident, global block ids, per-chunk rb0 RMW."""
    import jax
    import jax.numpy as jnp

    from pagerank_tpu.ops import pallas_spmv

    nb = n_pad // LANES
    nc = rows // chunk
    rb = _synth_ranks(rows, nb)
    rb0 = rb[::chunk].copy()
    fn = functools.partial(
        pallas_spmv.ell_contrib_pallas, num_blocks=nb, chunk=chunk,
        gather=gather, interpret=False,
    )
    args = (
        jax.ShapeDtypeStruct((n_pad + 8,), jnp.float32),
        jax.ShapeDtypeStruct((rows, LANES), jnp.int32),
        jax.ShapeDtypeStruct((rows,), jnp.int32),
        jnp.asarray(rb0),
    )
    z_bytes = (n_pad + 8) * 4
    cost = {
        # one (chunk, chunk) x (chunk, 128) one-hot segment matmul per
        # grid step
        "flops": nc * 2.0 * chunk * chunk * LANES,
        # z resident once + streamed src/rb blocks + RMW window traffic
        "bytes": (
            z_bytes
            + nc * (chunk * LANES * 4 + chunk * 4)
            + 2.0 * nc * chunk * LANES * 4
        ),
    }
    path, line = _loc(pallas_spmv.ell_contrib_pallas)
    return KernelCase(
        label=label, fn=fn, args=args, scalar_args=(rb0,),
        write_model=lambda: (rb0, chunk, nb),
        cost_model=cost, rmw=True, path=path, line=line,
    )


def _pallas_span(n_pad: int, edges: int, z_item: int) -> int:
    """The partition span a pallas campaign pins at a given scale: the
    engine's auto rule (JaxTpuEngine.partition_span) when its pick
    also fits the kernel's DOUBLE-buffered z window in the VMEM budget
    with ~2MB of stream/scratch headroom, else the largest
    power-of-two span that does. The auto rule caps the window for
    single-copy cache residency on the XLA ell path; the Pallas
    pipeline keeps two copies in flight, so the big f32 scales pin one
    notch finer (the same bound
    jax_engine._setup_ell_partitioned_pallas enforces at runtime)."""
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine
    from pagerank_tpu.obs import costs

    budget = costs.pallas_vmem_budget(None) - (2 << 20)

    def fits(span: int) -> bool:
        pspan = -(-(span + 8) // 2048) * 2048
        return 2 * pspan * z_item <= budget

    auto = JaxTpuEngine.partition_span(n_pad, edges, z_item)
    if auto and fits(auto):
        return auto
    best, span = 0, 1 << 15
    while span * 2 <= n_pad:
        if fits(span):
            best = span
        span *= 2
    return best


def _partitioned_case(*, label: str, scale: int, stream: str = "float32",
                      chunk: int = 1024, width: int = 128) -> KernelCase:
    """ops/pallas_spmv.ell_contrib_pallas_partitioned at the geometry
    the engine would instantiate for an R-MAT graph of ``scale`` with
    the campaign's edge factor 16: partition span from ``_pallas_span``,
    rows padded per partition, words24 slot words when the span fits
    24 bits."""
    import jax
    import jax.numpy as jnp

    from pagerank_tpu.engines.jax_engine import JaxTpuEngine
    from pagerank_tpu.ops import pallas_spmv

    n_pad = 1 << scale
    edges = 16 * n_pad
    z_dt = jnp.bfloat16 if stream == "bfloat16" else jnp.float32
    z_item = jnp.dtype(z_dt).itemsize
    psz = _pallas_span(n_pad, edges, z_item)
    assert psz, (scale, stream)  # every campaign scale has a fitting span
    K = -(-n_pad // psz)
    pspan = -(-(psz + 8) // 2048) * 2048
    w_rows = pspan // LANES
    rows_per_part = max(chunk, -(-(edges // LANES) // K // 2048) * 2048)
    rows = K * rows_per_part
    nc = rows // chunk
    pairs = nc * (width // 2)  # per-chunk span ~width/2: engine headroom
    rk = _synth_ranks(rows, pairs)
    rb0 = rk[::chunk].copy()
    part_ids = np.repeat(
        np.arange(K, dtype=np.int32), rows_per_part // chunk
    )
    bases = np.stack([part_ids, rb0], axis=1).astype(np.int32)
    words24 = JaxTpuEngine.partition_words24(psz, 1)
    src_lanes, src_dt, src_item = (
        (3 * LANES, jnp.int8, 1) if words24 else (LANES, jnp.int32, 4)
    )
    fn = functools.partial(
        pallas_spmv.ell_contrib_pallas_partitioned, num_pairs=pairs,
        chunk=chunk, width=width, gather="take", interpret=False,
    )
    args = (
        jax.ShapeDtypeStruct((K, w_rows, LANES), z_dt),
        jax.ShapeDtypeStruct((rows, src_lanes), src_dt),
        jax.ShapeDtypeStruct((rows // LANES, LANES), jnp.int32),
        jnp.asarray(bases),
    )
    cost = {
        # one (chunk, width) x (chunk, 128) segment matmul per step
        "flops": nc * 2.0 * chunk * width * LANES,
        # each partition window streams through VMEM exactly once +
        # slot words + rank rows + RMW window traffic
        "bytes": (
            K * pspan * z_item
            + nc * (chunk * src_lanes * src_item + chunk * 4)
            + 2.0 * nc * width * LANES * 4
        ),
    }
    path, line = _loc(pallas_spmv.ell_contrib_pallas_partitioned)
    return KernelCase(
        label=label, fn=fn, args=args, scalar_args=(bases,),
        write_model=lambda: (rb0, width, pairs),
        cost_model=cost, rmw=True, path=path, line=line,
    )


#: The TPU campaign's bench scales (perf_budgets.json env scopes).
BENCH_SCALES = (22, 23, 24, 25)


def shipped_cases() -> List[KernelCase]:
    """Both shipped kernels: a sound toy geometry each, plus the bench
    scales. The legacy kernel's scale cases FAIL PTK001 by design
    (whole z_ext resident) and are waived in allowlist.txt with the
    geometry bound; the partitioned kernel must be clean everywhere."""
    cases = [
        _legacy_case(label="ell_contrib_pallas@toy", n_pad=1 << 20,
                     rows=1 << 16),
    ]
    for s in BENCH_SCALES:
        cases.append(_legacy_case(
            label=f"ell_contrib_pallas@scale{s}", n_pad=1 << s,
            rows=max(256, (1 << s) // 8 // 256 * 256),
        ))
    cases.append(_partitioned_case(
        label="ell_contrib_pallas_partitioned@toy-span", scale=18,
    ))
    for s in BENCH_SCALES:
        cases.append(_partitioned_case(
            label=f"ell_contrib_pallas_partitioned@scale{s}", scale=s,
        ))
    cases.append(_partitioned_case(
        label="ell_contrib_pallas_partitioned@scale24-bf16", scale=24,
        stream="bfloat16",
    ))
    return cases


# ---------------------------------------------------------------------------
# Seeded-defect fixtures: one per rule; each must trip exactly its rule
# (scripts/acceptance.py + tests/test_kernel_analysis.py pin this).
# ---------------------------------------------------------------------------


def _fx_copy(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _fx_scratch(x_ref, o_ref, acc):
    acc[...] = -acc[...]
    o_ref[...] = x_ref[...]


def _fx_matmul(x_ref, y_ref, o_ref):
    import jax.numpy as jnp

    o_ref[...] = jnp.dot(
        x_ref[...], y_ref[...], preferred_element_type=jnp.float32
    )


def defect_cases() -> List[KernelCase]:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    here, _ = _loc(defect_cases)
    cases = []

    # PTK001: 32MB f32 whole-resident input (over every budget tier).
    n = 8 << 20
    fn = pl.pallas_call(
        _fx_copy, grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
    )
    cases.append(KernelCase(
        label="fixture:vmem_overflow", fn=fn,
        args=(jax.ShapeDtypeStruct((n,), jnp.float32),),
        path=here, line=_loc(_fx_copy)[1],
    ))

    # PTK002: (100, 64) f32 blocks — sublane 100 % 8 != 0, lane 64.
    fn = pl.pallas_call(
        _fx_copy, grid=(2, 2),
        in_specs=[pl.BlockSpec((100, 64), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((100, 64), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((200, 128), jnp.float32),
    )
    cases.append(KernelCase(
        label="fixture:misaligned_tile", fn=fn,
        args=(jax.ShapeDtypeStruct((200, 128), jnp.float32),),
        path=here, line=_loc(_fx_copy)[1],
    ))

    # PTK003 (gap): output map i -> 2i skips every odd block.
    fn = pl.pallas_call(
        _fx_copy, grid=(2,),
        in_specs=[pl.BlockSpec((8, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, LANES), lambda i: (2 * i, 0)),
        out_shape=jax.ShapeDtypeStruct((32, LANES), jnp.float32),
    )
    cases.append(KernelCase(
        label="fixture:index_gap", fn=fn,
        args=(jax.ShapeDtypeStruct((16, LANES), jnp.float32),),
        path=here, line=_loc(_fx_copy)[1],
    ))

    # PTK003 (overlap): output map i -> i % 2 revisits blocks 0/1
    # non-consecutively (steps 0,1,2,3 -> blocks 0,1,0,1).
    fn = pl.pallas_call(
        _fx_copy, grid=(4,),
        in_specs=[pl.BlockSpec((8, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, LANES), lambda i: (i % 2, 0)),
        out_shape=jax.ShapeDtypeStruct((16, LANES), jnp.float32),
    )
    cases.append(KernelCase(
        label="fixture:index_overlap", fn=fn,
        args=(jax.ShapeDtypeStruct((32, LANES), jnp.float32),),
        path=here, line=_loc(_fx_copy)[1],
    ))

    # PTK004: float64 VMEM scratch accumulator.
    fn = pl.pallas_call(
        _fx_scratch, grid=(2,),
        in_specs=[pl.BlockSpec((8, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((16, LANES), jnp.float32),
        scratch_shapes=[pltpu.VMEM((8, LANES), jnp.float64)],
    )
    cases.append(KernelCase(
        label="fixture:f64_scratch", fn=fn,
        args=(jax.ShapeDtypeStruct((16, LANES), jnp.float32),),
        path=here, line=_loc(_fx_scratch)[1],
    ))

    # PTK005: a correct kernel with a deliberately wrong analytic model.
    fn = pl.pallas_call(
        _fx_matmul, grid=(2,),
        in_specs=[
            pl.BlockSpec((LANES, LANES), lambda i: (i, 0)),
            pl.BlockSpec((LANES, LANES), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((LANES, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((2 * LANES, LANES), jnp.float32),
    )
    cases.append(KernelCase(
        label="fixture:cost_mismatch", fn=fn,
        args=(
            jax.ShapeDtypeStruct((2 * LANES, LANES), jnp.float32),
            jax.ShapeDtypeStruct((LANES, LANES), jnp.float32),
        ),
        cost_model={"flops": 1.0, "bytes": 1.0},
        path=here, line=_loc(_fx_matmul)[1],
    ))
    return cases


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------


def _find_pallas_eqns(jaxpr, out):
    for eq in jaxpr.eqns:
        if eq.primitive.name == "pallas_call":
            out.append(eq)
        for v in eq.params.values():
            if hasattr(v, "jaxpr"):
                _find_pallas_eqns(v.jaxpr, out)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if hasattr(x, "jaxpr"):
                        _find_pallas_eqns(x.jaxpr, out)
    return out


def _space(aval_or_bm) -> str:
    return str(getattr(aval_or_bm, "memory_space", "")).lower()


def _bm_space(bm) -> str:
    """'vmem' or 'any' for a BlockMapping. An unspecified memory space
    (``MemRef<None>``) is Pallas's default for blocked operands —
    VMEM."""
    ms = getattr(bm.transformed_block_aval, "memory_space", None)
    if ms is None:
        return "vmem"
    s = str(ms).lower()
    return "any" if "any" in s else ("vmem" if "vmem" in s else s)


class _NpUnsupported(Exception):
    """A map primitive outside the numpy fast path's vocabulary."""


def _nonneg(*arrays) -> bool:
    return all(np.all(np.asarray(a) >= 0) for a in arrays)


#: Elementwise primitives the numpy index-map interpreter understands.
#: div/rem guard to non-negative operands (numpy floors, lax
#: truncates; index arithmetic is non-negative in practice — anything
#: else falls back to the vmap path).
def _np_div(a, b):
    if not _nonneg(a, b):
        raise _NpUnsupported("div on negative operands")
    return np.floor_divide(a, b)


def _np_rem(a, b):
    if not _nonneg(a, b):
        raise _NpUnsupported("rem on negative operands")
    return np.remainder(a, b)


_NP_ELEMENTWISE = {
    "add": np.add, "sub": np.subtract, "mul": np.multiply,
    "max": np.maximum, "min": np.minimum, "neg": np.negative,
    "lt": np.less, "le": np.less_equal, "gt": np.greater,
    "ge": np.greater_equal, "eq": np.equal, "ne": np.not_equal,
    "and": np.bitwise_and, "or": np.bitwise_or, "xor": np.bitwise_xor,
    "not": np.bitwise_not, "div": _np_div, "rem": _np_rem,
    "shift_left": np.left_shift,
    "shift_right_logical": np.right_shift,
    "shift_right_arithmetic": np.right_shift,
    "stop_gradient": lambda a: a,
}


def _np_eval_index_map(dj, dconsts, coords: np.ndarray, scalars,
                       nd: int) -> np.ndarray:
    """Numpy fast path for the (overwhelmingly common) scalar index
    map: every value is a scalar — possibly batched over the grid
    steps as a leading axis — except the scalar-prefetch arrays, which
    appear only as all-1 dynamic_slice operands (batched fancy
    indexing). Raises _NpUnsupported on anything richer; the caller
    falls back to the jax vmap evaluator. This exists because eager
    vmap re-compiles the batched scalar gather once per distinct grid
    shape (~0.3s per kernel case — the difference between a <2s and a
    ~3s acceptance smoke)."""
    import jax

    steps = len(coords)
    env = {}

    def read(v):
        if isinstance(v, jax.core.Literal):
            return np.asarray(v.val), False
        return env[v]

    ngrid = coords.shape[1]
    for k in range(ngrid):
        env[dj.invars[k]] = (coords[:, k].astype(np.int64), True)
    for var, s in zip(dj.invars[ngrid:], scalars):
        env[var] = (np.asarray(s), False)
    for var, c in zip(dj.constvars, dconsts):
        env[var] = (np.asarray(c), False)

    for eqn in dj.eqns:
        name = eqn.primitive.name
        ins = [read(x) for x in eqn.invars]
        batched = any(b for _, b in ins)
        scalarish = all(
            v.ndim == 0 or (b and v.ndim == 1) for v, b in ins
        )
        if name in _NP_ELEMENTWISE and scalarish:
            out = _NP_ELEMENTWISE[name](*(v for v, _ in ins))
        elif name == "select_n" and scalarish:
            which, *cases = (v for v, _ in ins)
            out = np.choose(which.astype(np.int64), cases)
        elif name == "dynamic_slice" and all(
            s == 1 for s in eqn.params["slice_sizes"]
        ):
            (op, opb), *starts = ins
            if opb or not all(
                v.ndim == 0 or (b and v.ndim == 1) for v, b in starts
            ):
                raise _NpUnsupported("batched dynamic_slice operand")
            # lax clamps starts into [0, dim - 1] for size-1 slices.
            sidx = tuple(
                np.clip(v, 0, dim - 1)
                for (v, _), dim in zip(starts, op.shape)
            )
            out = op[sidx]
        elif name in ("squeeze", "reshape", "broadcast_in_dim") and (
            int(np.prod(eqn.outvars[0].aval.shape)) == 1
            or (batched and ins[0][0].ndim == 1)
        ):
            out = ins[0][0]
        elif name == "convert_element_type" and scalarish:
            out = ins[0][0].astype(
                np.dtype(eqn.params["new_dtype"])
                if np.dtype(eqn.params["new_dtype"]).kind in "iub"
                else np.int64
            )
        else:
            raise _NpUnsupported(name)
        env[eqn.outvars[0]] = (np.asarray(out), batched)

    cols = []
    for v in dj.outvars[:nd]:
        val, b = read(v)
        col = val.astype(np.int64).reshape(-1)
        cols.append(col if b else np.full(steps, int(col[0]) if col.size
                                          else 0, np.int64))
    return np.stack(cols, axis=1)


def _eval_index_map(bm, grid: Tuple[int, ...], scalars) -> np.ndarray:
    """Evaluate one BlockSpec index map over the full grid: the map
    jaxpr reads scalar-prefetch REFS, so it is state-discharged to a
    pure jaxpr first, then evaluated over all grid coordinates — by
    the numpy interpreter when the map stays in its scalar vocabulary,
    else vmapped through jax. Returns int64 [steps, ndim] block
    indices (row-major grid order — the TPU's sequential execution
    order)."""
    import jax
    import jax.numpy as jnp
    from jax._src.state.discharge import discharge_state

    cj = bm.index_map_jaxpr
    dj, dconsts = discharge_state(cj.jaxpr, cj.consts)
    nd = len(bm.block_shape)
    steps = int(np.prod(grid)) if grid else 1
    coords = np.indices(grid).reshape(len(grid), steps).T.astype(np.int32)
    try:
        return _np_eval_index_map(dj, dconsts, coords, scalars, nd)
    except _NpUnsupported:
        pass
    scal = tuple(jnp.asarray(s) for s in scalars)

    def one(c):
        out = jax.core.eval_jaxpr(
            dj, dconsts, *(c[k] for k in range(len(grid))), *scal
        )
        return tuple(jnp.asarray(o, jnp.int32) for o in out[:nd])

    outs = jax.vmap(one)(jnp.asarray(coords))
    return np.stack(
        [np.asarray(o, np.int64) for o in outs], axis=1
    )  # (steps, nd)


@dataclasses.dataclass
class _Site:
    """One extracted pallas_call: the grid mapping, per-operand block
    info, scratch avals, and the kernel jaxpr."""

    grid: Tuple[int, ...]
    in_blocks: list  # (bm, index array) for inputs
    out_blocks: list  # (bm, index array) for outputs
    scratch_avals: list
    kernel_jaxpr: object


def extract_site(case: KernelCase) -> _Site:
    """Trace ``case.fn(*case.args)`` and read the single pallas_call's
    grid/Block/scratch structure off the jaxpr — no execution."""
    import jax

    jx = jax.make_jaxpr(case.fn)(*case.args)
    eqns = _find_pallas_eqns(jx.jaxpr, [])
    if len(eqns) != 1:
        raise ValueError(
            f"{case.label}: expected exactly one pallas_call in the "
            f"traced jaxpr, found {len(eqns)}"
        )
    eq = eqns[0]
    gm = eq.params["grid_mapping"]
    grid = tuple(int(g) for g in gm.grid)
    bms = list(gm.block_mappings)
    n_in = gm.num_inputs
    in_blocks = [
        (bm, _eval_index_map(bm, grid, case.scalar_args))
        for bm in bms[:n_in]
    ]
    out_blocks = [
        (bm, _eval_index_map(bm, grid, case.scalar_args))
        for bm in bms[n_in:]
    ]
    kj = eq.params["jaxpr"]
    n_lead = gm.num_index_operands + gm.num_inputs + gm.num_outputs
    scratch_avals = [v.aval for v in kj.invars[n_lead:]]
    return _Site(grid, in_blocks, out_blocks, scratch_avals, kj)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


def _tile_padded_bytes(shape, itemsize: int) -> int:
    """VMEM footprint of one block: trailing dims padded to the
    dtype's sublane x 128 tile (Mosaic's physical layout)."""
    sub = _SUBLANES.get(itemsize, 8)
    dims = list(shape)
    if not dims:
        return sub * LANES * itemsize
    if len(dims) == 1:
        return -(-dims[0] // (sub * LANES)) * sub * LANES * itemsize
    dims[-1] = -(-dims[-1] // LANES) * LANES
    dims[-2] = -(-dims[-2] // sub) * sub
    return int(np.prod(dims)) * itemsize


def _buffer_count(idx: np.ndarray) -> int:
    """1 when the block index never changes over the grid (one
    resident copy), else 2 (the Pallas pipeline double-buffers)."""
    return 2 if len(idx) > 1 and np.any(np.diff(idx, axis=0) != 0) else 1


def _block_runs(idx: np.ndarray) -> int:
    """Number of DISTINCT-consecutive index runs over the grid — how
    many times the pipeline actually fetches the block."""
    if len(idx) == 0:
        return 0
    return 1 + int(np.count_nonzero(np.any(np.diff(idx, axis=0) != 0,
                                           axis=1)))


def _f(case: KernelCase, rule: str, msg: str) -> Finding:
    return Finding(rule=rule, path=case.path, line=case.line,
                   message=msg, snippet=f"kernel={case.label}")


def check_vmem_budget(case: KernelCase, site: _Site,
                      device_kind: Optional[str]) -> List[Finding]:
    """PTK001."""
    from pagerank_tpu.obs import costs

    total = 0
    parts = []
    for bm, idx in site.in_blocks + site.out_blocks:
        if _bm_space(bm) != "vmem":
            continue
        item = np.dtype(bm.array_shape_dtype.dtype).itemsize
        b = _tile_padded_bytes(bm.block_shape, item)
        bufs = _buffer_count(idx)
        total += b * bufs
        parts.append(f"{tuple(bm.block_shape)}x{bufs}={b * bufs}")
    for av in site.scratch_avals:
        if "vmem" not in _space(av) or not hasattr(av, "shape"):
            continue
        b = _tile_padded_bytes(av.shape, np.dtype(av.dtype).itemsize)
        total += b
        parts.append(f"scratch{tuple(av.shape)}={b}")
    budget = costs.pallas_vmem_budget(device_kind)
    if total > budget:
        kind = device_kind or costs.DEFAULT_VMEM_TARGET_KIND
        return [_f(
            case, "PTK001",
            f"VMEM residency {total / 1e6:.1f}MB exceeds the "
            f"{budget / 1e6:.0f}MB budget for '{kind}' "
            f"({costs.PALLAS_VMEM_HEADROOM:.0%} of capacity): "
            + ", ".join(parts),
        )]
    return []


def check_tile_geometry(case: KernelCase, site: _Site) -> List[Finding]:
    """PTK002 (>=2-D VMEM blocks only: 1-D whole-array operands lay
    out as (1, n) with Mosaic's own lane padding, charged by
    PTK001)."""
    out = []
    for bm, _idx in site.in_blocks + site.out_blocks:
        if _bm_space(bm) != "vmem":
            continue
        bs = tuple(bm.block_shape)
        if len(bs) < 2:
            continue
        item = np.dtype(bm.array_shape_dtype.dtype).itemsize
        sub = _SUBLANES.get(item, 8)
        lane, subl = bs[-1], bs[-2]
        if lane != 1 and lane % LANES:
            out.append(_f(
                case, "PTK002",
                f"block {bs} ({bm.array_shape_dtype.dtype}) lane dim "
                f"{lane} not a multiple of {LANES}",
            ))
        if subl != 1 and subl % sub:
            out.append(_f(
                case, "PTK002",
                f"block {bs} ({bm.array_shape_dtype.dtype}) sublane "
                f"dim {subl} not a multiple of {sub} "
                f"({sub}x{LANES} tile for itemsize {item})",
            ))
    return out


def check_index_coverage(case: KernelCase, site: _Site) -> List[Finding]:
    """PTK003."""
    out: List[Finding] = []
    for bm, idx in site.in_blocks:
        dims = bm.array_shape_dtype.shape
        bs = bm.block_shape
        for d in range(len(bs)):
            lo = int(idx[:, d].min())
            hi = int(idx[:, d].max())
            if lo < 0 or hi * bs[d] >= max(1, dims[d]) + (bs[d] - 1):
                # A block STARTING at or past the dim end reads fully
                # out of bounds (partial trailing blocks are legal —
                # Pallas masks them).
                pass
            if lo < 0 or hi * bs[d] >= dims[d]:
                out.append(_f(
                    case, "PTK003",
                    f"input block map for {tuple(bs)} reaches index "
                    f"{lo if lo < 0 else hi} on dim {d} "
                    f"(array dim {dims[d]}, block {bs[d]}): read out "
                    f"of bounds",
                ))
                break
    for bm, idx in site.out_blocks:
        if _bm_space(bm) == "vmem":
            dims = bm.array_shape_dtype.shape
            bs = bm.block_shape
            nblocks = [
                -(-dims[d] // bs[d]) for d in range(len(bs))
            ]
            # Collapse consecutive repeats (a block legally stays
            # resident across adjacent steps — the accumulate
            # pattern); any remaining duplicate is a non-consecutive
            # revisit, i.e. an overwrite race with the earlier write.
            keep = np.ones(len(idx), bool)
            keep[1:] = np.any(np.diff(idx, axis=0) != 0, axis=1)
            dedup = idx[keep]
            seen = set()
            for row in dedup:
                t = tuple(int(x) for x in row)
                if t in seen:
                    out.append(_f(
                        case, "PTK003",
                        f"output block {t} written on non-consecutive "
                        f"grid steps (overlapping writes: the later "
                        f"visit overwrites the earlier result)",
                    ))
                seen.add(t)
            expect = int(np.prod(nblocks))
            if len(seen) < expect:
                missing = expect - len(seen)
                out.append(_f(
                    case, "PTK003",
                    f"output coverage gap: {missing} of {expect} "
                    f"blocks never written (first missing: "
                    f"{_first_missing(seen, nblocks)})",
                ))
        else:
            # ANY-space output: writes happen via explicit DMA at
            # data-dependent offsets — verify the registered write
            # model instead.
            if case.write_model is None:
                out.append(_f(
                    case, "PTK003",
                    "ANY-space output has no registered write model: "
                    "coverage of the DMA RMW windows cannot be proven",
                ))
                continue
            starts, width, length = case.write_model()
            starts = np.asarray(starts, np.int64)
            dim0 = int(bm.array_shape_dtype.shape[0])
            if starts.min(initial=0) < 0 or (
                len(starts) and int(starts.max()) + width > dim0
            ):
                out.append(_f(
                    case, "PTK003",
                    f"RMW window out of bounds: starts in "
                    f"[{int(starts.min())}, {int(starts.max())}] with "
                    f"width {width} against output dim {dim0}",
                ))
            ss = np.sort(starts)
            ends = np.maximum.accumulate(ss + width)
            gaps = ss[1:] > ends[:-1]
            covered_to = int(ends[-1]) if len(ends) else 0
            if len(ss) and int(ss[0]) > 0:
                out.append(_f(
                    case, "PTK003",
                    f"RMW coverage gap: first window starts at "
                    f"{int(ss[0])}, elements [0, {int(ss[0])}) never "
                    f"written",
                ))
            elif np.any(gaps & (ss[1:] < length)):
                at = int(ss[1:][gaps & (ss[1:] < length)][0])
                out.append(_f(
                    case, "PTK003",
                    f"RMW coverage gap before element {at}: a chunk's "
                    f"rank span exceeds the static window width "
                    f"{width} — rows silently dropped",
                ))
            elif covered_to < length:
                out.append(_f(
                    case, "PTK003",
                    f"RMW coverage gap: windows end at {covered_to} "
                    f"of {length} logical elements",
                ))
    return out


def _first_missing(seen, nblocks):
    it = np.ndindex(*nblocks)
    for t in it:
        if t not in seen:
            return t
    return None


def _walk_eqns(jaxpr):
    for eq in jaxpr.eqns:
        yield eq
        for v in eq.params.values():
            if hasattr(v, "jaxpr"):
                yield from _walk_eqns(v.jaxpr)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if hasattr(x, "jaxpr"):
                        yield from _walk_eqns(x.jaxpr)


def check_memory_discipline(case: KernelCase, site: _Site) -> List[Finding]:
    """PTK004."""
    import jax

    out = []
    kj = site.kernel_jaxpr
    for av in site.scratch_avals:
        dt = getattr(av, "dtype", None)
        if dt is None or "vmem" not in _space(av):
            continue
        if np.issubdtype(dt, np.floating) and dt != np.float32:
            out.append(_f(
                case, "PTK004",
                f"float VMEM scratch accumulator is {dt}, not "
                f"float32 (the accumulation contract; f64 has no "
                f"Mosaic tile, bf16 loses the accumulated bits)",
            ))
    f64_seen = False
    for eq in _walk_eqns(kj):
        for v in list(eq.invars) + list(eq.outvars):
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and dt == np.float64 and not f64_seen:
                f64_seen = True
                out.append(_f(
                    case, "PTK004",
                    f"float64 value inside the kernel body "
                    f"(primitive '{eq.primitive.name}'): f64 is not a "
                    f"TPU vector dtype",
                ))
    any_vars = {
        v for v in kj.invars
        if "any" in _space(getattr(v, "aval", None))
    }
    for eq in kj.eqns:
        if not any(
            isinstance(v, jax.core.Var) and v in any_vars
            for v in eq.invars
        ):
            continue
        if eq.primitive.name not in ("dma_start", "dma_wait"):
            out.append(_f(
                case, "PTK004",
                f"ANY-space (HBM) ref touched by primitive "
                f"'{eq.primitive.name}' — HBM operands may be "
                f"accessed only via explicit DMA "
                f"(make_async_copy)",
            ))
    return out


def check_cost_sanity(case: KernelCase, site: _Site) -> List[Finding]:
    """PTK005."""
    if case.cost_model is None:
        return []
    steps = int(np.prod(site.grid)) if site.grid else 1
    flops_step = 0.0
    for eq in _walk_eqns(site.kernel_jaxpr):
        if eq.primitive.name != "dot_general":
            continue
        (lc, _rc), _batch = eq.params["dimension_numbers"]
        lhs = eq.invars[0].aval
        contract = int(np.prod([lhs.shape[d] for d in lc])) or 1
        out_elems = int(np.prod(eq.outvars[0].aval.shape)) or 1
        flops_step += 2.0 * out_elems * contract
    flops = flops_step * steps

    bytes_total = 0.0
    for bm, idx in site.in_blocks:
        if _bm_space(bm) != "vmem":
            continue
        item = np.dtype(bm.array_shape_dtype.dtype).itemsize
        bytes_total += (
            _block_runs(idx) * int(np.prod(bm.block_shape)) * item
        )
    for bm, idx in site.out_blocks:
        item = np.dtype(bm.array_shape_dtype.dtype).itemsize
        if _bm_space(bm) == "vmem":
            bytes_total += (
                _block_runs(idx) * int(np.prod(bm.block_shape)) * item
            )
        elif case.write_model is not None:
            _starts, width, _length = case.write_model()
            row = int(np.prod(bm.array_shape_dtype.shape[1:])) or 1
            bytes_total += 2.0 * steps * width * row * item  # RMW r+w

    out = []
    for name, got, want in (
        ("flops", flops, float(case.cost_model.get("flops", flops))),
        ("bytes", bytes_total,
         float(case.cost_model.get("bytes", bytes_total))),
    ):
        ref = max(abs(want), 1.0)
        if abs(got - want) / ref > 0.25:
            out.append(_f(
                case, "PTK005",
                f"static {name} {got:.3g} vs analytic model "
                f"{want:.3g} (>{25}% apart): the kernel's geometry "
                f"and the obs/costs-style model have drifted",
            ))
    return out


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def check_kernel_case(case: KernelCase,
                      device_kind: Optional[str] = None) -> List[Finding]:
    try:
        site = extract_site(case)
    except Exception as e:  # a kernel that cannot even trace
        msg = str(e).splitlines()[0][:200] if str(e) else type(e).__name__
        return [_f(case, "PTK003",
                   f"kernel failed to trace abstractly: {msg}")]
    out: List[Finding] = []
    out += check_vmem_budget(case, site, device_kind)
    out += check_tile_geometry(case, site)
    out += check_index_coverage(case, site)
    out += check_memory_discipline(case, site)
    out += check_cost_sanity(case, site)
    return out


def check_kernel_plane(cases: Optional[Sequence[KernelCase]] = None,
                       device_kind: Optional[str] = None) -> List[Finding]:
    """Run PTK001-005 over the registered kernel cases (default: the
    shipped registry at toy + bench geometries). Deterministic,
    CPU-only, no execution."""
    if cases is None:
        cases = shipped_cases()
    findings: List[Finding] = []
    for case in cases:
        findings.extend(check_kernel_case(case, device_kind))
    return findings
