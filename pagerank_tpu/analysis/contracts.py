"""jaxpr contract checker for the engine dispatch forms and kernels.

The solver's performance story rests on invariants the type system
cannot see: ONE bulk collective per iteration (the psum that merges
per-device partials — SURVEY.md §3's "3 shuffles -> 1 psum"), no
accidental f64 promotion under f32 configs (TPUs emulate f64 ~3.4x
slower), rank-buffer donation actually consumed (O(1) device memory in
iterations), a step executable whose compilation key ignores the
iteration budget, and zero host callbacks inside the hot loop. Each is
checked here MECHANICALLY by abstract-evaluating every dispatch form on
a tiny graph (CPU-fake mesh, the tests' own substrate) and walking the
resulting jaxprs.

Dispatch forms covered (engines/jax_engine.py plus the device-build
paths):

  ell / pair / striped    — replicated, one fused shard_map program
  elastic_resume          — the post-rescue re-sharded step (ISSUE 7):
                            N-device snapshot resumed on 1 device
  partitioned (+bf16,     — partition-centric windowed layout
    +device_build)          (ISSUE 6): one program at any size
  multi_dispatch          — per-stripe executables + finalize
  coo                     — segment-sum baseline
  device_build            — build_device (presentinel) + ell step
  vertex_sharded (+ms)    — sharded state, all_gather/reduce_scatter
  vs_halo                 — sparse boundary exchange (ISSUE 8): head
                            psum + static ppermute halo/band rounds;
                            the budget reflects the SMALLER collectives
                            (one round per active offset, no dense
                            all_gather/reduce_scatter)
  vs_halo_async           — asynchronous stale-boundary exchange
                            (ISSUE 17): the same plan double-buffered
                            through the step carry; budget pinned
                            IDENTICAL to vs_halo (overlap reorders
                            collectives, never adds one)
  vs_bounded (+ms)        — owner-computes, per-stripe z psums
  ppr_batch               — the serving hot path (ISSUE 18): the
                            batched-PPR chunk program
                            (engines/ppr.py:PprJaxEngine._run_chunk;
                            one psum per iteration, k-fold intensity)
                            plus its on-device top-k, which must be
                            collective- and callback-free so only
                            [batch, k] leaves the chip

Rule ids: PTC001 collective budget, PTC002 f64 promotion, PTC003
donation consumed (warning capture per form + the structural
build-chain check ``check_build_donations`` — every donating build
stage's donated avals must match distinct output avals), PTC004
step-key stability, PTC005 host callbacks,
PTC006 32-bit build chain (the device graph-build stages must emit no
64-bit op under x64 — the pair-f64 config flips ``jax_enable_x64``
process-wide, and a weak-typed promotion in the per-edge path silently
doubles sort/scatter bytes; it is also what licenses
utils/compile_cache.stage_call to key executables WITHOUT the x64
flag), PTC007 probe transparency (the probe-enabled step —
``JaxTpuEngine.step_probed``, ISSUE 5 — must keep the EXACT collective
multiset of the plain step, add no host callback, no f64 under f32
configs, and keep the rank donation consumable; on multi-dispatch
layouts the standalone probe program must be collective- and
callback-free), and PTC008 SDC-check transparency (the same
discipline for the ABFT-checked step and the standalone
boundary-state program — ISSUE 15, pagerank_tpu/sdc.py).

The PTH family (ISSUE 11; obs/hlo.py) checks the OPTIMIZED HLO the
backend actually compiled, not the jaxpr: PTH001 gather strategy —
every dispatch form's hot traffic must lower to a NATIVE gather op
(never the while-loop/scalar dynamic-slice expansion, the documented
"fast gather defeated" signature; PERF_NOTES "Scan bodies defeat the
fast gather"); PTH002 fusion-count budget — a fusion blow-up marks a
lowering class change; PTH003 no while-loop around the hot gather —
no iteration program may carry gather-class traffic as scalar
dynamic-slices inside a while body, even partially. Backends whose
``Compiled`` exposes no HLO text degrade to a surfaced-but-non-
blocking "unknown" verdict (obs_log), mirroring the device plane's
memory_analysis handling. Waivers (with the root cause) live in
analysis/allowlist.txt.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from pagerank_tpu.analysis.findings import Finding

_ENGINE_PATH = "engines/jax_engine.py"

# Cross-device collective primitives by jaxpr name, normalized across
# jax versions (psum is rewritten to psum2 under shard_map's
# replication checker; psum_scatter traces as reduce_scatter).
_COLLECTIVE_NORM = {
    "psum": "psum",
    "psum2": "psum",
    "all_reduce": "psum",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
}

# Host-callback primitives — any of these inside an iteration program
# breaks the zero-host-round-trips contract.
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback", "host_callback_call"}

_DONATION_MSG = "Some donated buffers were not usable"


# -- jaxpr walking ---------------------------------------------------------


def _sub_jaxprs(params: dict):
    for v in params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for j in vs:
            if hasattr(j, "eqns"):  # Jaxpr
                yield j
            elif hasattr(j, "jaxpr") and hasattr(j.jaxpr, "eqns"):
                yield j.jaxpr  # ClosedJaxpr


def walk_eqns(jaxpr):
    """Every equation in ``jaxpr`` and its nested sub-jaxprs (pjit,
    scan, while, shard_map, custom_* ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from walk_eqns(sub)


def collectives(closed_jaxpr) -> List[Tuple[str, int]]:
    """[(normalized primitive, max operand element count)] for every
    cross-device collective in the program."""
    out = []
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        norm = _COLLECTIVE_NORM.get(eqn.primitive.name)
        if norm is None:
            continue
        sizes = [
            int(np.prod(v.aval.shape))
            for v in eqn.invars
            if hasattr(v, "aval") and hasattr(v.aval, "shape")
        ]
        out.append((norm, max(sizes) if sizes else 0))
    return out


def callback_prims(closed_jaxpr) -> List[str]:
    return [
        eqn.primitive.name
        for eqn in walk_eqns(closed_jaxpr.jaxpr)
        if eqn.primitive.name in _CALLBACK_PRIMS
    ]


def f64_avals(closed_jaxpr) -> List[str]:
    """Descriptions of every float64 value in the program (conversion
    targets and intermediate avals)."""
    import jax.numpy as jnp

    hits = []
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "convert_element_type":
            if jnp.dtype(eqn.params.get("new_dtype")) == jnp.float64:
                hits.append("convert_element_type -> float64")
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "dtype", None) is not None:
                if jnp.dtype(aval.dtype) == jnp.float64:
                    hits.append(
                        f"{eqn.primitive.name} produces "
                        f"f64[{','.join(map(str, aval.shape))}]"
                    )
    return hits


_WIDE64 = ("int64", "uint64", "float64")


def wide64_avals(closed_jaxpr) -> List[str]:
    """Descriptions of every 64-bit value (int64/uint64/float64) in the
    program — PTC006's detector (f64_avals stays PTC002's float-only
    one). Compares dtype NAMES so extended dtypes (PRNG keys) pass
    through untouched."""
    hits = []
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "convert_element_type":
            nd = eqn.params.get("new_dtype")
            if getattr(nd, "name", str(nd)) in _WIDE64:
                hits.append(f"convert_element_type -> {nd}")
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and getattr(dt, "name", str(dt)) in _WIDE64:
                hits.append(
                    f"{eqn.primitive.name} produces "
                    f"{getattr(dt, 'name', dt)}"
                    f"[{','.join(map(str, aval.shape))}]"
                )
    return hits


# -- engine form construction ----------------------------------------------


def _tiny_graph(n=512, e=4096, seed=0):
    from pagerank_tpu import build_graph

    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def _classes():
    """Engine classes that force the striped / multi-dispatch layouts
    at toy scale (the tests' own pattern)."""
    from pagerank_tpu import JaxTpuEngine

    class TinyStripes(JaxTpuEngine):
        def _stripe_max(self):
            return 256

        def _stripe_target(self):
            return 256

    class TinyScan(TinyStripes):
        SCAN_STRIPE_UNITS = 0

    return JaxTpuEngine, TinyStripes, TinyScan


@dataclass
class Form:
    """One dispatch form: how to build it and what it promises."""

    name: str
    build: Callable[[], object]  # () -> built engine
    f32: bool  # config stores AND accumulates in f32 (PTC002 applies)


def engine_forms(ndev: int) -> List[Form]:
    from pagerank_tpu import PageRankConfig

    Eng, Tiny, Scan = _classes()
    g = _tiny_graph()

    def cfg(**kw):
        return PageRankConfig(num_iters=2, num_devices=ndev, **kw)

    def dev_build():
        import jax.numpy as jnp

        from pagerank_tpu.ops import device_build as db

        rng = np.random.default_rng(1)
        src = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
        dst = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
        dg = db.build_ell_device(src, dst, n=512, with_weights=False)
        return Eng(cfg()).build_device(dg)

    def dev_build_striped():
        # The multichip dryrun's grouped+striped presentinel shape
        # (__graft_entry__.dryrun_multichip step 5: group=4,
        # stripe_size=128, with_weights=False, 4096 raw edges) — the
        # dispatch whose build once left a residual "Some donated
        # buffers were not usable: int32[4096], int32[4096],
        # int8[4096]" warning in the MULTICHIP_r05 tail. Covering it
        # here puts the PTC003 warning capture on that exact shape so
        # an unconsumable donation in the grouped/striped stage chain
        # cannot regress silently again.
        import jax.numpy as jnp

        from pagerank_tpu.ops import device_build as db

        rng = np.random.default_rng(2)
        src = jnp.asarray(rng.integers(0, 256, 4096), jnp.int32)
        dst = jnp.asarray(rng.integers(0, 256, 4096), jnp.int32)
        dg = db.build_ell_device(
            src, dst, n=256, group=4, stripe_size=128, with_weights=False
        )
        return Eng(cfg()).build_device(dg)

    def dev_build_partitioned():
        # The partition-centric device path (ISSUE 6): a device graph
        # whose stripes ARE the partitions, consumed with
        # cfg.partition_span set — windowed gather, 3-byte planar slot
        # words, chunk-local int16 pair ranks, per-partition expand
        # scatters; PTC007 then proves its probed step is
        # communication-transparent like every other form.
        import jax.numpy as jnp

        from pagerank_tpu.ops import device_build as db

        rng = np.random.default_rng(3)
        src = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
        dst = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
        dg = db.build_ell_device(
            src, dst, n=512, group=4, stripe_size=256, with_weights=False
        )
        return Eng(cfg(partition_span=256)).build_device(dg)

    def elastic_resume():
        # ISSUE 7: the re-sharded engine AFTER an elastic rescue. Build
        # at ndev, snapshot (canonical host-order payload + mesh-meta
        # provenance), rebuild at ONE device, resume through the
        # mesh-shape-agnostic path — then every contract below runs
        # against the resumed engine: the post-rescue step must keep
        # the original sharded form's collective multiset (PTC001),
        # dtype discipline (PTC002), and consumable rank donation
        # (PTC003/007), so a rescue can never silently compile a
        # slower or f64-widened program.
        import shutil
        import tempfile

        from pagerank_tpu.utils.snapshot import Snapshotter, resume_engine

        e0 = Eng(cfg()).build(g)
        e0._device_step()
        e0.fence()
        work = tempfile.mkdtemp(prefix="pagerank_ctc_elastic_")
        try:
            snap = Snapshotter(work, g.fingerprint(), "reference",
                               mesh_meta=e0.snapshot_meta())
            snap.save(1, e0.ranks())
            e1 = Eng(PageRankConfig(num_iters=2, num_devices=1)).build(g)
            resumed = resume_engine(e1, snap)
            assert resumed == 1, resumed
        finally:
            shutil.rmtree(work, ignore_errors=True)
        return e1

    return [
        Form("ell", lambda: Eng(cfg()).build(g), True),
        Form("elastic_resume", elastic_resume, True),
        Form("pair", lambda: Eng(cfg(
            dtype="float32", accum_dtype="float64", wide_accum="pair",
        )).build(g), False),
        Form("partitioned", lambda: Eng(cfg(
            partition_span=256,
        )).build(g), True),
        Form("partitioned_bf16", lambda: Eng(cfg(
            partition_span=256, stream_dtype="bfloat16",
        )).build(g), True),
        Form("device_build_partitioned", dev_build_partitioned, True),
        Form("striped", lambda: Tiny(cfg()).build(g), True),
        Form("multi_dispatch", lambda: Scan(cfg()).build(g), True),
        Form("coo", lambda: Eng(cfg(kernel="coo")).build(g), True),
        Form("device_build", dev_build, True),
        Form("device_build_striped", dev_build_striped, True),
        Form("vertex_sharded", lambda: Eng(cfg(
            vertex_sharded=True,
        )).build(g), True),
        Form("vs_multi_dispatch", lambda: Scan(cfg(
            vertex_sharded=True,
        )).build(g), True),
        # Sparse boundary exchange (ISSUE 8): halo_head pinned explicit
        # so the head psum is always in the traced budget (the auto
        # rule legitimately resolves K=0 on this tiny graph at 2 fake
        # devices, where no vertex has enough remote readers).
        Form("vs_halo", lambda: Eng(cfg(
            vertex_sharded=True, halo_exchange=True, halo_head=128,
        )).build(g), True),
        # Asynchronous stale-boundary exchange (ISSUE 17): the same
        # plan double-buffered through the step carry. halo_head
        # pinned (as above) so the head psum is in the budget;
        # halo_async_min_gain=0 so the tiny graph's honest low
        # predicted gain cannot downgrade the form out from under the
        # sweep (the GATE has its own tests — here we must trace the
        # async program itself).
        Form("vs_halo_async", lambda: Eng(cfg(
            vertex_sharded=True, halo_exchange=True, halo_head=128,
            halo_async=True, halo_async_min_gain=0.0,
        )).build(g), True),
        Form("vs_bounded", lambda: Eng(cfg(
            vertex_sharded=True, vs_bounded=True,
        )).build(g), True),
        Form("vsb_multi_dispatch", lambda: Scan(cfg(
            vertex_sharded=True, vs_bounded=True,
        )).build(g), True),
    ]


def iteration_programs(engine) -> List[Tuple[str, object]]:
    """(label, ClosedJaxpr) for every program one iteration dispatches —
    the fused step, or the prescale/per-stripe/finalize sequence on
    multi-dispatch layouts. Abstract evaluation only; nothing runs."""
    import jax

    if engine._ms_stripe is None:
        jx = jax.make_jaxpr(engine._step_core)(*engine._device_args())
        return [("step", jx)]
    progs = [(
        "prescale",
        jax.make_jaxpr(engine._ms_prescale)(engine._r, engine._inv_out),
    )]
    zs = engine._ms_prescale(engine._r, engine._inv_out)
    parts = []
    for s in range(engine._ms_n_stripes):
        fn = engine._ms_stripe_fns[s]
        progs.append((
            f"stripe{s}",
            jax.make_jaxpr(fn)(*zs, engine._src[s], engine._row_block[s]),
        ))
        parts.append(fn(*zs, engine._src[s], engine._row_block[s]))
    final_args = (engine._r, *parts, *engine._ms_ids,
                  engine._dangling, engine._zero_in, engine._valid)
    final = getattr(engine._ms_final, "__wrapped__", engine._ms_final)
    progs.append(("final", jax.make_jaxpr(final)(*final_args)))
    return progs


def expected_collectives(engine, form: str) -> Dict[str, int]:
    """The per-iteration BULK-collective budget a form promises (bulk =
    operand larger than one element; the vertex-sharded tails also psum
    two scalars, which are excluded here and checked separately)."""
    import jax
    import jax.numpy as jnp

    n_stripes = len(engine._src) if getattr(engine, "_src", None) is not None \
        and isinstance(engine._src, list) else 1
    if form in ("ell", "pair", "striped", "coo", "device_build",
                "device_build_striped", "partitioned", "partitioned_bf16",
                "device_build_partitioned", "elastic_resume"):
        return {"psum": 1}
    if form == "multi_dispatch":
        # The cross-device merge is the finalize's sharded .sum(0)
        # (GSPMD inserts the all-reduce below jaxpr level): zero
        # EXPLICIT collectives is the contract.
        return {}
    use_rs = (
        jnp.dtype(engine._accum_dtype).itemsize < 8
        or jax.default_backend() != "tpu"
    )
    merge = {"reduce_scatter": 1} if use_rs else {"psum": 1}
    if form in ("vertex_sharded", "vs_multi_dispatch"):
        return {"all_gather": 1, **merge}
    if form in ("vs_halo", "vs_halo_async"):
        # The sparse boundary exchange (ISSUE 8): NO dense
        # all_gather/reduce_scatter — one ppermute per active
        # read/write round (static at build, from the halo plan this
        # exact engine carries) plus the head-replication psum. The
        # budget is read off the plan so a layout change that silently
        # reintroduces a dense collective (or doubles the rounds)
        # fails here. The ASYNC form's budget is PINNED IDENTICAL
        # (ISSUE 17): the stale-boundary overlap may only REORDER the
        # collectives (ship-side vs read-side of the double buffer) —
        # an extra or missing collective means the overlap changed the
        # exchange itself, not just its schedule.
        plan = engine._halo_plan
        rounds = len(plan.read_rounds) + len(plan.write_rounds)
        out: Dict[str, int] = {}
        if rounds:
            out["ppermute"] = rounds
        if plan.head_k:
            out["psum"] = 1
        return out
    if form == "vs_bounded":
        return {"psum": n_stripes}
    if form == "vsb_multi_dispatch":
        return {"psum": n_stripes}
    raise ValueError(f"unknown form {form!r}")


# -- checks ----------------------------------------------------------------


def _finding(rule, msg, form, path=_ENGINE_PATH):
    return Finding(rule, path, 0, msg, snippet=f"form={form}")


def check_engine_form(form: Form) -> List[Finding]:
    """Build one dispatch form and run every contract against it."""
    import jax

    findings: List[Finding] = []
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        engine = form.build()
        engine._device_step()  # one real step: donation warnings fire
        engine.fence()
    for w in wlog:
        if _DONATION_MSG in str(w.message):
            findings.append(_finding(
                "PTC003",
                f"donation not consumed during build/step: "
                f"{str(w.message).splitlines()[0][:160]}",
                form.name,
            ))

    progs = iteration_programs(engine)

    # PTC001 — bulk collective budget.
    got: Dict[str, int] = {}
    scalars = 0
    for _label, jx in progs:
        for prim, size in collectives(jx):
            if size > 1:
                got[prim] = got.get(prim, 0) + 1
            else:
                scalars += 1
    want = expected_collectives(engine, form.name)
    if got != want:
        findings.append(_finding(
            "PTC001",
            f"bulk collective budget violated: expected {want or 'none'}, "
            f"traced {got or 'none'}",
            form.name,
        ))
    # The sharded tails psum exactly two scalars (dangling mass, L1
    # delta); every other form psums none.
    want_scalars = 2 if engine.config.vertex_sharded else 0
    if scalars != want_scalars:
        findings.append(_finding(
            "PTC001",
            f"scalar collective count {scalars} != {want_scalars} "
            f"(dangling-mass/L1 psums)",
            form.name,
        ))

    # PTC002 — no f64 anywhere under an all-f32 config.
    if form.f32:
        for label, jx in progs:
            hits = f64_avals(jx)
            if hits:
                findings.append(_finding(
                    "PTC002",
                    f"f64 promotion in f32 config ({label}): "
                    + "; ".join(sorted(set(hits))[:4]),
                    form.name,
                ))

    # PTC003 (structural) — the step's donated rank buffer must match
    # an output aval exactly, or the donation silently no-ops. On
    # multi-dispatch layouts (the vertex-sharded forms included,
    # ISSUE 8 satellite) the donated buffer lives in the FINALIZE
    # dispatch — the same structural matching runs against it, so an
    # unconsumable rank donation in any dispatch form fails analysis
    # instead of warning at scale (the MULTICHIP_r05 tail class).
    if engine._ms_stripe is None:
        args = engine._device_args()
        out_avals = jax.tree_util.tree_leaves(
            jax.eval_shape(engine._step_core, *args)
        )
        r_aval = (tuple(args[0].shape), np.dtype(args[0].dtype))
        if not any(
            (tuple(o.shape), np.dtype(o.dtype)) == r_aval
            for o in out_avals
        ):
            findings.append(_finding(
                "PTC003",
                "donated rank buffer has no matching output aval: "
                "donation can never be consumed",
                form.name,
            ))
    else:
        zs = engine._ms_prescale(engine._r, engine._inv_out)
        parts = [
            engine._ms_stripe_fns[s](
                *zs, engine._src[s], engine._row_block[s]
            )
            for s in range(engine._ms_n_stripes)
        ]
        final_args = (engine._r, *parts, *engine._ms_ids,
                      engine._dangling, engine._zero_in, engine._valid)
        out_avals = jax.tree_util.tree_leaves(
            jax.eval_shape(engine._ms_final, *final_args)
        )
        r_aval = (tuple(engine._r.shape), np.dtype(engine._r.dtype))
        if not any(
            (tuple(o.shape), np.dtype(o.dtype)) == r_aval
            for o in out_avals
        ):
            findings.append(_finding(
                "PTC003",
                "finalize's donated rank buffer has no matching output "
                "aval: donation can never be consumed",
                form.name,
            ))

    # PTC005 — no host callbacks inside any iteration program.
    for label, jx in progs:
        cbs = callback_prims(jx)
        if cbs:
            findings.append(_finding(
                "PTC005",
                f"host callback(s) {sorted(set(cbs))} inside {label}",
                form.name,
            ))

    # PTC007 — probe transparency (ISSUE 5).
    findings.extend(check_probe_form(engine, form))

    # PTC008 — SDC-check transparency (ISSUE 15).
    findings.extend(check_sdc_form(engine, form))

    # PTH001-003 — optimized-HLO lowering contracts (ISSUE 11).
    findings.extend(check_hlo_form(engine, form))
    return findings


#: PTH002's per-program fusion ceiling at the contract geometry: every
#: current form lands under ~20 fusions on the CPU backend (ell 8,
#: partitioned 19, coo 5); 64 gives ~3x headroom while still catching
#: a lowering class change (an unrolled/scalarized expansion multiplies
#: fusions by the chunk or index count).
PTH_FUSION_BUDGET = 64


def _hlo_programs(engine):
    """(label, Compiled) for every program one iteration dispatches —
    the engine's own enumeration (`iteration_programs`, the one place
    that knows the dispatch set and its argument threading — shared
    with cost_reports so the contract can never inspect a program the
    run doesn't dispatch). ``wrap_unjitted``: stage fns the engine
    doesn't keep jitted (the vs-bounded multi-dispatch stripes) still
    hold the hot gather, so the contract inspects those too. AOT
    lowering only; nothing executes."""
    return [(label, compiled) for label, compiled, _ne
            in engine.iteration_programs(wrap_unjitted=True)]


def check_hlo_form(engine, form: Form) -> List[Finding]:
    """PTH001-003: the backend's OPTIMIZED HLO for every iteration
    program of one built dispatch form, through the obs/hlo classifier.

      - **PTH001** (gather strategy): no program may classify
        ``expanded`` (the while-loop/scalar dynamic-slice emulation of
        a gather — the exact lowering that measured 0.91e8 vs 3.33e8
        edges/s/chip, PERF_NOTES "Scan bodies defeat the fast
        gather"), and at least one program must carry a NATIVE hot
        gather (every form's hot traffic is a slot-table gather —
        including coo's rank gather).
      - **PTH002** (fusion budget): per-program fusion count within
        :data:`PTH_FUSION_BUDGET` — a blow-up marks a lowering class
        change even when the gather survives.
      - **PTH003** (no while-loop around the hot gather): NO program
        may carry gather-class traffic as scalar float dynamic-slices
        inside a while body, even alongside a surviving native gather
        (a partial defeat — e.g. one stripe scalarized).

    Degradation (the ISSUE-11 satellite, mirroring PR 10's
    memory_analysis handling): a backend/jax whose ``Compiled``
    raises from / returns empty ``as_text()`` yields an "unknown"
    verdict — surfaced via obs_log, never a finding, and the
    no-native-gather check is skipped (absence cannot be proven on a
    backend that hides its HLO)."""
    from pagerank_tpu.obs import hlo as obs_hlo
    from pagerank_tpu.obs import log as obs_log
    from pagerank_tpu.utils import jax_compat

    findings: List[Finding] = []
    any_native = False
    any_unknown = False
    for label, compiled in _hlo_programs(engine):
        text = jax_compat.compiled_hlo_text(compiled)
        if not text:
            any_unknown = True
            obs_log.info(
                f"PTH: backend reports no optimized HLO for "
                f"{form.name}/{label}; gather-strategy verdict "
                f"unknown (non-blocking)"
            )
            continue
        try:
            rep = obs_hlo.inspect_text(f"{form.name}/{label}", text)
        except Exception as e:  # a parser gap is an unknown, not a fail
            any_unknown = True
            obs_log.info(
                f"PTH: lowering inspection failed for "
                f"{form.name}/{label} ({type(e).__name__}); verdict "
                f"unknown (non-blocking)"
            )
            continue
        g = rep.gather
        if g["strategy"] == "native":
            any_native = True
        if g["strategy"] == "expanded":
            findings.append(_finding(
                "PTH001",
                f"hot gather lowered to a while-loop/scalar "
                f"dynamic-slice expansion in '{label}' (sites: "
                + ", ".join(g["expansion_sites"][:3])
                + ") — the fast-gather-defeated signature",
                form.name,
            ))
        if rep.fusion_count > PTH_FUSION_BUDGET:
            findings.append(_finding(
                "PTH002",
                f"fusion count {rep.fusion_count} in '{label}' exceeds "
                f"the budget {PTH_FUSION_BUDGET} — the lowering "
                f"changed class",
                form.name,
            ))
        if g["strategy"] != "expanded" and g["expansion_sites"]:
            findings.append(_finding(
                "PTH003",
                f"while-loop carries gather-class traffic as scalar "
                f"dynamic-slices in '{label}' "
                f"({', '.join(g['expansion_sites'][:3])}) despite a "
                f"surviving native gather — a partial defeat",
                form.name,
            ))
    if not any_native and not any_unknown:
        findings.append(_finding(
            "PTH001",
            "no iteration program carries a native hot gather (every "
            "dispatch form's hot traffic is a slot-table gather)",
            form.name,
        ))
    return findings


def check_pallas_hlo(ndev: int) -> List[Finding]:
    """PTH004 (ISSUE 16): the PALLAS engine's optimized step HLO must
    show the Mosaic custom call AND the slot-table gathers GONE — the
    fused kernel subsumed gather+contrib+segment-sum, so a surviving
    native hot gather alongside the custom call means the engine is
    paying both costs (the XLA gather AND the kernel). Off-TPU the
    engine probes the kernel in interpret mode (pure-jax emulation —
    there is no Mosaic custom call to inspect), so the verdict
    degrades to a non-blocking "unknown" via obs_log, exactly like
    PTH001-003's missing-HLO path. A probe DOWNGRADE on an actual TPU
    backend is a finding: the static gate exists so the campaign
    learns before mesh time, not from a silently slower leg."""
    import jax

    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.obs import hlo as obs_hlo
    from pagerank_tpu.obs import log as obs_log
    from pagerank_tpu.utils import jax_compat

    if jax.default_backend() != "tpu":
        obs_log.info(
            "PTH004: no TPU backend — the pallas engine probes in "
            "interpret mode (no Mosaic custom call exists); verdict "
            "unknown (non-blocking)"
        )
        return []
    Eng, _Tiny, _Scan = _classes()
    cfg = PageRankConfig(num_iters=2, num_devices=ndev,
                         kernel="pallas", partition_span=256)
    engine = Eng(cfg).build(_tiny_graph())
    if not str(engine._kernel).startswith("pallas"):
        return [_finding(
            "PTH004",
            f"kernel='pallas' downgraded to '{engine._kernel}' at the "
            f"contract geometry — the Mosaic kernel failed to lower on "
            f"this backend",
            "pallas_partitioned",
        )]
    findings: List[Finding] = []
    saw_custom = False
    for label, compiled in _hlo_programs(engine):
        text = jax_compat.compiled_hlo_text(compiled)
        if not text:
            obs_log.info(
                f"PTH004: backend reports no optimized HLO for "
                f"pallas_partitioned/{label}; verdict unknown "
                f"(non-blocking)"
            )
            return findings
        if "custom-call" in text:
            saw_custom = True
        try:
            rep = obs_hlo.inspect_text(f"pallas_partitioned/{label}",
                                       text)
        except Exception as e:
            obs_log.info(
                f"PTH004: lowering inspection failed for "
                f"pallas_partitioned/{label} ({type(e).__name__}); "
                f"verdict unknown (non-blocking)"
            )
            return findings
        if label == "step" and rep.gather["strategy"] != "none":
            findings.append(_finding(
                "PTH004",
                f"hot gather survives in the pallas step program "
                f"(strategy '{rep.gather['strategy']}') — the fused "
                f"kernel should have subsumed it",
                "pallas_partitioned",
            ))
    if not saw_custom:
        findings.append(_finding(
            "PTH004",
            "no custom call in any pallas iteration program — the "
            "Mosaic kernel is not in the compiled step",
            "pallas_partitioned",
        ))
    return findings


def _collective_tally(jx) -> Tuple[Dict[str, int], int]:
    """(bulk-collective multiset, scalar-collective count) of one
    program — the communication structure PTC007 compares across the
    plain and probe-enabled steps."""
    bulk: Dict[str, int] = {}
    scalars = 0
    for prim, size in collectives(jx):
        if size > 1:
            bulk[prim] = bulk.get(prim, 0) + 1
        else:
            scalars += 1
    return bulk, scalars


def check_probe_form(form_engine, form: Form) -> List[Finding]:
    """PTC007: enabling convergence probes (obs/probes.py) must be
    COMMUNICATION-TRANSPARENT. On single-program forms the probed step
    (``_get_probed_step``: step body + on-device mass/top-k/churn tail
    in ONE program) must trace to the exact collective multiset of the
    plain step, add no host callback, introduce no f64 under an
    all-f32 config, and keep the donated rank buffer consumable. On
    multi-dispatch layouts the standalone probe program
    (``_get_probe_fn``) must be collective- and callback-free (the
    probe reductions are local; GSPMD owns any sharded gather below
    jaxpr level). Abstract evaluation only; nothing runs."""
    import jax
    import jax.numpy as jnp

    findings: List[Finding] = []
    k = 8
    prev = jnp.zeros(k, jnp.int32)
    if form_engine._ms_stripe is None:
        args = form_engine._device_args()
        plain = jax.make_jaxpr(form_engine._step_core)(*args)
        # Both probed programs are checked: the plain probed step AND
        # the LEDGER-enabled one a probed run actually dispatches
        # (ISSUE 13; step_probed prefers the ledger core when the
        # build stashed one — its three extra reductions must be as
        # communication-transparent as the probe tail).
        variants = [("probed", form_engine._get_probed_step(k))]
        if form_engine._step_core_ledger is not None:
            variants.append(
                ("probed+ledger",
                 form_engine._get_probed_step(k, ledger=True)))
        for tag, probed_fn in variants:
            probed = jax.make_jaxpr(probed_fn)(*args, prev)
            if _collective_tally(probed) != _collective_tally(plain):
                findings.append(_finding(
                    "PTC007",
                    f"{tag} step changed the collective structure: "
                    f"plain {_collective_tally(plain)} vs {tag} "
                    f"{_collective_tally(probed)}",
                    form.name,
                ))
            cbs = callback_prims(probed)
            if cbs:
                findings.append(_finding(
                    "PTC007",
                    f"{tag} step emits host callback(s) "
                    f"{sorted(set(cbs))}",
                    form.name,
                ))
            if form.f32:
                hits = f64_avals(probed)
                if hits:
                    findings.append(_finding(
                        "PTC007",
                        f"{tag} tail promotes to f64 in f32 config: "
                        + "; ".join(sorted(set(hits))[:4]),
                        form.name,
                    ))
            # The probed step donates the rank buffer exactly like the
            # plain step — its output set must still carry a matching
            # aval.
            out_avals = jax.tree_util.tree_leaves(
                jax.eval_shape(probed_fn, *args, prev)
            )
            r_aval = (tuple(args[0].shape), np.dtype(args[0].dtype))
            if not any(
                (tuple(o.shape), np.dtype(o.dtype)) == r_aval
                for o in out_avals
            ):
                findings.append(_finding(
                    "PTC007",
                    f"{tag} step has no output aval matching the "
                    "donated rank buffer: donation can never be "
                    "consumed",
                    form.name,
                ))
    else:
        probe_jx = jax.make_jaxpr(form_engine._get_probe_fn(k))(
            form_engine._r, form_engine._valid, prev
        )
        colls = [p for p, _s in collectives(probe_jx)]
        if colls:
            findings.append(_finding(
                "PTC007",
                f"standalone probe program emits collective(s) "
                f"{sorted(set(colls))} (probes must add none beyond "
                f"the form's budget)",
                form.name,
            ))
        cbs = callback_prims(probe_jx)
        if cbs:
            findings.append(_finding(
                "PTC007",
                f"standalone probe program emits host callback(s) "
                f"{sorted(set(cbs))}",
                form.name,
            ))
        if form.f32:
            hits = f64_avals(probe_jx)
            if hits:
                findings.append(_finding(
                    "PTC007",
                    "probe program promotes to f64 in f32 config: "
                    + "; ".join(sorted(set(hits))[:4]),
                    form.name,
                ))
    return findings


def check_sdc_form(form_engine, form: Form) -> List[Finding]:
    """PTC008: the SDC-checked step (ISSUE 15; pagerank_tpu/sdc.py)
    must be COMMUNICATION-TRANSPARENT exactly like the probe. On
    single-program forms the checked step (``_get_sdc_step``: the
    ledger core + the per-device ABFT check tail in ONE program) must
    trace to the exact collective multiset of the plain step, add no
    host callback, introduce no f64 under an all-f32 config, and keep
    the donated rank buffer consumable. On every form the standalone
    boundary-state program (``_get_sdc_state_fn`` — the
    dual-fingerprint dispatch, and the multi-dispatch layouts' whole
    check) must be collective- and callback-free: its per-device
    values are local reductions concatenated by out-spec, never
    merged. Abstract evaluation only; nothing runs."""
    import jax
    import numpy as np

    findings: List[Finding] = []
    if not form_engine.sdc_supported():
        return findings
    w = form_engine._sdc_w()
    inv = ((form_engine._inv_out,)
           if form_engine._sdc_has_inv() else ())
    state_jx = jax.make_jaxpr(form_engine._get_sdc_state_fn())(
        w, form_engine._r, *inv
    )
    colls = [p for p, _s in collectives(state_jx)]
    if colls:
        findings.append(_finding(
            "PTC008",
            f"standalone SDC state program emits collective(s) "
            f"{sorted(set(colls))} (check partials are local "
            f"reductions by contract)",
            form.name,
        ))
    cbs = callback_prims(state_jx)
    if cbs:
        findings.append(_finding(
            "PTC008",
            f"standalone SDC state program emits host callback(s) "
            f"{sorted(set(cbs))}",
            form.name,
        ))
    if form.f32:
        hits = f64_avals(state_jx)
        if hits:
            findings.append(_finding(
                "PTC008",
                "SDC state program promotes to f64 in f32 config: "
                + "; ".join(sorted(set(hits))[:4]),
                form.name,
            ))
    if form_engine._ms_stripe is not None:
        # Multi-dispatch layouts run the ledger sequence bracketed by
        # the (already checked) standalone state program — nothing
        # else to prove here.
        return findings
    args = form_engine._device_args()
    plain = jax.make_jaxpr(form_engine._step_core)(*args)
    sdc_fn = form_engine._get_sdc_step()
    sdc_jx = jax.make_jaxpr(sdc_fn)(w, *args)
    if _collective_tally(sdc_jx) != _collective_tally(plain):
        findings.append(_finding(
            "PTC008",
            f"SDC-checked step changed the collective structure: "
            f"plain {_collective_tally(plain)} vs checked "
            f"{_collective_tally(sdc_jx)}",
            form.name,
        ))
    cbs = callback_prims(sdc_jx)
    if cbs:
        findings.append(_finding(
            "PTC008",
            f"SDC-checked step emits host callback(s) "
            f"{sorted(set(cbs))}",
            form.name,
        ))
    if form.f32:
        hits = f64_avals(sdc_jx)
        if hits:
            findings.append(_finding(
                "PTC008",
                "SDC check tail promotes to f64 in f32 config: "
                + "; ".join(sorted(set(hits))[:4]),
                form.name,
            ))
    out_avals = jax.tree_util.tree_leaves(
        jax.eval_shape(sdc_fn, w, *args)
    )
    r_aval = (tuple(args[0].shape), np.dtype(args[0].dtype))
    if not any(
        (tuple(o.shape), np.dtype(o.dtype)) == r_aval
        for o in out_avals
    ):
        findings.append(_finding(
            "PTC008",
            "SDC-checked step has no output aval matching the donated "
            "rank buffer: donation can never be consumed",
            form.name,
        ))
    return findings


def check_step_key_stability(ndev: int) -> List[Finding]:
    """PTC004: the step executable's compilation key must not depend on
    the iteration budget (or tol) — a config that only changes
    ``num_iters`` must lower to byte-identical step HLO, so long runs
    and resumed runs reuse the cached executable."""
    import jax

    from pagerank_tpu import JaxTpuEngine, PageRankConfig

    findings: List[Finding] = []
    g = _tiny_graph()
    texts = []
    for iters, tol in ((2, None), (9, 1e-9)):
        cfg = PageRankConfig(num_iters=iters, tol=tol, num_devices=ndev)
        eng = JaxTpuEngine(cfg).build(g)
        lowered = jax.jit(eng._step_core, donate_argnums=(0,)).lower(
            *eng._device_args()
        )
        # as_text can raise / return empty on backends that keep their
        # IR to themselves (bare PJRT plugins; the ISSUE-11 satellite)
        # — degrade to a surfaced-but-non-blocking unknown verdict,
        # never a crash of the whole contract sweep.
        try:
            text = lowered.as_text()
        except Exception as e:
            text = ""
            from pagerank_tpu.obs import log as obs_log

            obs_log.info(
                f"PTC004: lowering text unavailable "
                f"({type(e).__name__}); step-key stability verdict "
                f"unknown (non-blocking)"
            )
        texts.append(text)
    if all(texts) and texts[0] != texts[1]:
        findings.append(_finding(
            "PTC004",
            "step lowering differs across num_iters/tol configs: the "
            "iteration budget leaked into the compilation key",
            "step_key",
        ))
    elif not all(texts):
        from pagerank_tpu.obs import log as obs_log

        obs_log.info(
            "PTC004: step-key stability unverifiable on this backend "
            "(empty lowering text) — skipped, not failed"
        )

    # And the jitted step must hit its cache across repeated dispatches.
    eng = JaxTpuEngine(PageRankConfig(num_iters=4, num_devices=ndev)).build(g)
    eng._device_step()
    eng._device_step()
    eng.fence()
    cache_size = getattr(eng._step_fn, "_cache_size", None)
    if callable(cache_size) and cache_size() > 1:
        findings.append(_finding(
            "PTC004",
            f"step executable recompiled across iterations "
            f"(cache size {cache_size()})",
            "step_cache",
        ))
    return findings


def check_kernels() -> List[Finding]:
    """Abstract-eval the registered kernels on symbolic shapes: no
    collectives, no callbacks, no f64 under f32 instantiation, and the
    documented output shapes."""
    import jax
    import jax.numpy as jnp

    from pagerank_tpu.ops import LANES, spmv

    findings: List[Finding] = []
    rows, nb, gw = 8, 4, 8
    n_pad = nb * LANES
    S = jax.ShapeDtypeStruct

    def case(path, label, fn, *avals, out_shape=None, f32=True):
        jx = jax.make_jaxpr(fn)(*avals)
        for prim, _size in collectives(jx):
            findings.append(Finding(
                "PTC001", path, 0,
                f"kernel emits collective {prim} (kernels must be "
                f"collective-free; the engine owns the merge)",
                snippet=f"kernel={label}",
            ))
        for cb in callback_prims(jx):
            findings.append(Finding(
                "PTC005", path, 0, f"kernel emits host callback {cb}",
                snippet=f"kernel={label}",
            ))
        if f32:
            hits = f64_avals(jx)
            if hits:
                findings.append(Finding(
                    "PTC002", path, 0,
                    "f64 promotion in f32 kernel instantiation: "
                    + "; ".join(sorted(set(hits))[:4]),
                    snippet=f"kernel={label}",
                ))
        if out_shape is not None:
            got = jax.eval_shape(fn, *avals)
            if tuple(got.shape) != tuple(out_shape):
                findings.append(Finding(
                    "PTC001", path, 0,
                    f"kernel output shape {tuple(got.shape)} != "
                    f"documented {tuple(out_shape)}",
                    snippet=f"kernel={label}",
                ))

    i32, f4 = jnp.int32, jnp.float32
    case(
        "ops/spmv.py", "ell_contrib",
        lambda z, s, rb: spmv.ell_contrib(z, s, rb, nb, gather_width=gw),
        S((n_pad + gw,), f4), S((rows, LANES), i32), S((rows,), i32),
        out_shape=(nb * LANES,),
    )
    # Partition-centric window mode (ISSUE 6): 2 partitions of 256
    # lanes, 3-byte planar slot words, chunk-local int16 pair ranks,
    # per-chunk (window, rank) bases. Collective-free, callback-free,
    # f64-free like every kernel; compact per-PAIR output shape.
    case(
        "ops/spmv.py", "ell_contrib:partitioned",
        lambda z, s, rb, b: spmv.ell_contrib(
            z, s, rb, nb, gather_width=gw, chunk_rows=4,
            num_present=6, window_rows=(256 + gw) // gw,
            chunk_bases=b,
        ),
        S((2 * (256 + gw),), f4), S((rows, 3 * LANES), jnp.int8),
        S((rows,), jnp.int16), S((2, 2), i32),
        out_shape=(6 * LANES,),
    )
    case(
        "ops/spmv.py", "ell_contrib_pair",
        lambda h, lo, s, rb: spmv.ell_contrib_pair(
            h, lo, s, rb, nb, accum_dtype=jnp.float64, gather_width=gw
        ),
        S((n_pad + gw,), f4), S((n_pad + gw,), f4),
        S((rows, LANES), i32), S((rows,), i32),
        out_shape=(nb * LANES,), f32=False,
    )
    case(
        "ops/spmv.py", "ell_contrib_spmm",
        lambda z2, s, rb: spmv.ell_contrib_spmm(z2, s, rb, nb),
        S((n_pad + 1, 4), f4), S((rows, LANES), i32), S((rows,), i32),
        out_shape=(nb * LANES, 4),
    )
    case(
        "ops/spmv.py", "edge_contrib_segment_sum",
        lambda r, s, d, w: spmv.edge_contrib_segment_sum(r, s, d, w, 64),
        S((64,), f4), S((128,), i32), S((128,), i32), S((128,), f4),
        out_shape=(64,),
    )
    try:
        from pagerank_tpu.ops import pallas_spmv

        case(
            "ops/pallas_spmv.py", "ell_contrib_pallas",
            lambda z, s, rb, rb0: pallas_spmv.ell_contrib_pallas(
                z, s, rb, rb0, nb, chunk=rows, gather="onehot8",
                interpret=True,
            ),
            S((n_pad + 8,), f4), S((rows, LANES), i32), S((rows,), i32),
            S((1,), i32), out_shape=(nb * LANES,),
        )
    except Exception as e:  # pragma: no cover - jax-version dependent
        findings.append(Finding(
            "PTC005", "ops/pallas_spmv.py", 0,
            f"pallas kernel failed to abstract-eval: "
            f"{type(e).__name__}: {str(e)[:120]}",
            snippet="kernel=ell_contrib_pallas",
        ))
    return findings


_BUILD_PATH = "ops/device_build.py"


def check_build_chain() -> List[Finding]:
    """PTC006: the device graph-build chain is pinned to 32-bit
    indices. Abstract-eval every build stage (ops/device_build.py —
    the restaged single-sort pipeline plus the R-MAT generator) with
    x64 ENABLED — exactly the process state the pair-f64 config leaves
    behind — on int32 edge avals, and fail on ANY 64-bit integer or
    float in the jaxpr. A weak-typed promotion here (an argsort's
    default iota, a cumsum's default accumulator, a permutation of a
    default-int arange) silently doubles per-edge sort/scatter bytes;
    this rule is also what makes utils/compile_cache.stage_call's
    x64-agnostic executable keying sound. The per-slot weight plane is
    dtype-contracted (f64 by request is legal), so the checked configs
    are the 32-bit index paths: presentinel (with_weights=False) and
    f32 weights."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    from pagerank_tpu.ops import LANES
    from pagerank_tpu.ops import device_build as db

    findings: List[Finding] = []
    S = jax.ShapeDtypeStruct
    e, n, n_padded = 4096, 500, 512
    nb = n_padded // LANES
    i32, i8, f4 = jnp.int32, jnp.int8, jnp.float32

    def stage_cases():
        yield ("raw_in_degree", functools.partial(db._raw_in_degree, n=n),
               (S((e,), i32),))
        yield ("relabel_perm", db._relabel_perm, (S((n,), i32),))
        yield ("unrelabel_degree", db._unrelabel_degree,
               (S((n,), i32), S((n,), i32)))
        for stripe in (0, 256):  # single-stripe and striped keys
            ns = 1 if not stripe else n_padded // stripe
            tag = f":stripe{stripe}" if stripe else ""
            yield (f"relabel_sort{tag}",
                   functools.partial(db._relabel_sort, n_padded=n_padded,
                                     stripe_size=stripe),
                   (S((e,), i32), S((e,), i32), S((n,), i32)))
            for group, ww in ((1, True), (8, False)):
                yield (f"slot_coords:g{group}:w{int(ww)}{tag}",
                       functools.partial(
                           db._slot_coords, n=n, n_padded=n_padded,
                           weight_dtype=jnp.dtype(f4), group=group,
                           stripe_size=stripe, with_weights=ww),
                       (S((e,), i32), S((e,), i32)))
            yield (f"scatter_slots{tag}",
                   functools.partial(db._scatter_slots, rows_total=64,
                                     num_blocks=nb, n_stripes=ns, fill=0),
                   (S((e,), i32), S((e,), i32), S((e,), i8),
                    S((ns * nb,), i32), S((e,), f4)))
        yield ("rmat_gen",
               functools.partial(db._rmat_gen, scale=8, n_edges=1024),
               (jax.random.key(0, impl="rbg"), jnp.float32(0.76),
                jnp.float32(0.75), jnp.float32(0.79)))

    with enable_x64():
        for label, fn, avals in stage_cases():
            try:
                jx = jax.make_jaxpr(fn)(*avals)
            except Exception as ex:
                findings.append(Finding(
                    "PTC006", _BUILD_PATH, 0,
                    f"build stage failed to abstract-eval: "
                    f"{type(ex).__name__}: {str(ex)[:140]}",
                    snippet=f"stage={label}",
                ))
                continue
            hits = wide64_avals(jx)
            if hits:
                findings.append(Finding(
                    "PTC006", _BUILD_PATH, 0,
                    "64-bit op in the 32-bit-pinned build chain under "
                    "x64: " + "; ".join(sorted(set(hits))[:4]),
                    snippet=f"stage={label}",
                ))
    return findings


def check_build_donations() -> List[Finding]:
    """PTC003 (build chain, ISSUE 6 satellite): every donation the
    device graph-build stages declare must be CONSUMABLE — each donated
    input aval must have a distinct matching output aval, the same
    structural matching jax's lowering performs. An unconsumable
    donation never aliases; it only produces the "Some donated buffers
    were not usable" warning that sat in the r1-r5 bench/multichip
    tails (int32[e] x2 + int8[e] — per-edge planes whose shapes can
    never match the slot-plane outputs). ``stage_call`` additionally
    pre-filters donations and re-lowers clean if a version-specific
    matcher still rejects one (utils/compile_cache.usable_donations) —
    this check pins the STRUCTURAL half so a new unconsumable donation
    in the chain fails analysis instead of warning at scale.

    Checks every donating stage dispatch of ops/device_build.py at
    single-stripe, striped, and partition-spanned keys, presentinel
    and weighted."""
    import functools

    import jax
    import jax.numpy as jnp

    from pagerank_tpu.ops import device_build as db
    from pagerank_tpu.utils.compile_cache import usable_donations

    findings: List[Finding] = []
    S = jax.ShapeDtypeStruct
    e, n, n_padded = 4096, 500, 512
    i32, f4 = jnp.int32, jnp.float32

    def donating_stages():
        for stripe in (0, 256, 128):  # 128 = partition-sized key
            tag = f":stripe{stripe}" if stripe else ""
            yield (f"relabel_sort{tag}",
                   functools.partial(db._relabel_sort, n_padded=n_padded,
                                     stripe_size=stripe),
                   (S((e,), i32), S((e,), i32), S((n,), i32)), (0, 1))
            for group, ww in ((1, True), (8, False)):
                yield (f"slot_coords:g{group}:w{int(ww)}{tag}",
                       functools.partial(
                           db._slot_coords, n=n, n_padded=n_padded,
                           weight_dtype=jnp.dtype(f4), group=group,
                           stripe_size=stripe, with_weights=ww),
                       (S((e,), i32), S((e,), i32)), (0, 1))

    for label, fn, avals, donate in donating_stages():
        kept = usable_donations(fn, avals, donate)
        if kept != tuple(donate):
            dropped = sorted(set(donate) - set(kept))
            findings.append(Finding(
                "PTC003", _BUILD_PATH, 0,
                f"unconsumable donation(s) at arg(s) {dropped}: no "
                "matching output aval — the donation can never alias "
                "and only emits the 'donated buffers were not usable' "
                "warning",
                snippet=f"stage={label}",
            ))
    return findings


_PPR_PATH = "engines/ppr.py"


def check_ppr_batch_form(ndev: int) -> List[Finding]:
    """Contract coverage for the PPR serving hot path (ISSUE 18): the
    batched dispatch program ``PprJaxEngine._run_chunk`` and its
    on-device top-k, statically gated like every solver form.

    - PTC001: exactly ONE bulk psum per iteration of the chunk body
      (the [n, k] partial merge — SURVEY.md §3's shuffle collapse holds
      at k-fold arithmetic intensity), zero scalar collectives;
    - PTC002: no f64 under the all-f32 default config (the serving
      path must not pay the TPU f64 emulation tax per query);
    - PTC005: no host callbacks in either program;
    - PTC007-adapted: the top-k program is collective- AND
      callback-free — it runs replicated post-psum, so a collective
      here means the layout regressed and more than ``[batch, k]``
      would leave the chip.
    """
    import jax
    import jax.numpy as jnp

    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.engines.ppr import PprJaxEngine
    from pagerank_tpu.parallel.mesh import replicated

    findings: List[Finding] = []
    try:
        g = _tiny_graph()
        eng = PprJaxEngine(
            PageRankConfig(num_iters=2, num_devices=ndev)
        ).build(g)
        batch = np.zeros(4, np.int64)
        p = np.zeros((eng._n_state, len(batch)), eng._dtype)
        p[eng._inv_perm[batch], np.arange(len(batch))] = 1.0
        p_dev = jax.device_put(jnp.asarray(p), replicated(eng._mesh))
        progs = [
            ("chunk", jax.make_jaxpr(eng._run_chunk, static_argnums=(2,))(
                p_dev.copy(), p_dev, 2, eng._inv_out, eng._dangling,
                eng._valid, *eng._slot_args,
            )),
            ("topk", jax.make_jaxpr(eng._topk, static_argnums=(1,))(
                p_dev, 4
            )),
        ]
    except Exception as e:
        return [_finding(
            "PTC001",
            f"ppr_batch form failed to build/trace: "
            f"{type(e).__name__}: {str(e)[:160]}",
            "ppr_batch", path=_PPR_PATH,
        )]

    got: Dict[str, int] = {}
    scalars = 0
    for _label, jx in progs[:1]:  # chunk program owns the budget
        for prim, size in collectives(jx):
            if size > 1:
                got[prim] = got.get(prim, 0) + 1
            else:
                scalars += 1
    if got != {"psum": 1} or scalars:
        findings.append(_finding(
            "PTC001",
            f"ppr chunk bulk collective budget violated: expected "
            f"{{'psum': 1}} and 0 scalar collectives, traced "
            f"{got or 'none'} + {scalars} scalar(s)",
            "ppr_batch", path=_PPR_PATH,
        ))
    for label, jx in progs:
        hits = f64_avals(jx)
        if hits:
            findings.append(_finding(
                "PTC002",
                f"f64 under the f32 serving config in {label}: "
                f"{hits[0]} (+{len(hits) - 1} more)",
                "ppr_batch", path=_PPR_PATH,
            ))
        cbs = callback_prims(jx)
        if cbs:
            findings.append(_finding(
                "PTC005",
                f"host callback(s) {sorted(set(cbs))} in {label}",
                "ppr_batch", path=_PPR_PATH,
            ))
    for prim, _size in collectives(progs[1][1]):
        findings.append(_finding(
            "PTC007",
            f"top-k program contains collective {prim}: top-k must run "
            f"replicated post-psum so only [batch, k] leaves the chip",
            "ppr_batch", path=_PPR_PATH,
        ))
    return findings


def run_contracts(forms: Optional[List[str]] = None) -> List[Finding]:
    """Run the full contract suite; returns findings (empty = clean).
    ``forms`` filters the engine dispatch forms by name."""
    import jax

    ndev = min(2, len(jax.devices()))
    findings: List[Finding] = []
    for form in engine_forms(ndev):
        if forms is not None and form.name not in forms:
            continue
        try:
            findings.extend(check_engine_form(form))
        except Exception as e:
            findings.append(_finding(
                "PTC001",
                f"dispatch form failed to build/trace: "
                f"{type(e).__name__}: {str(e)[:160]}",
                form.name,
            ))
    if forms is None or "pallas_partitioned" in forms:
        findings.extend(check_pallas_hlo(ndev))
    if forms is None or "ppr_batch" in forms:
        findings.extend(check_ppr_batch_form(ndev))
    if forms is None:
        findings.extend(check_step_key_stability(ndev))
        findings.extend(check_kernels())
        findings.extend(check_build_chain())
        findings.extend(check_build_donations())
    return findings
