"""CLI for the deadline-honest PPR query daemon (ISSUE 18).

    python -m pagerank_tpu.serve --scale 14 --max-batch 8 \
        --deadline-ms 500 --port 8080 --metrics-port 9100

Builds a synthetic R-MAT graph (the repo's zero-egress workload
stand-in), AOT-warms the one compiled batch program, and serves
``GET /ppr?source=<id>`` over loopback HTTP until SIGTERM enters the
PR 12 drain (admission closes with typed rejections, in-flight batches
finish, exit 75). ``--serve-smoke N`` instead runs N seeded queries
in-process against the started daemon and exits — the self-test mode
the acceptance harness and a fresh checkout both use.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from pagerank_tpu import PageRankConfig, build_graph, jobs
from pagerank_tpu.exitcodes import ExitCode
from pagerank_tpu.utils import synth

#: span-retention bound for --query-trace: the daemon may trace for its
#: whole lifetime, so the Tracer keeps a ring of the most recent spans
#: (~6 spans per query -> tens of thousands of queries of tail) instead
#: of growing without bound the way a finite solver capture may.
QUERY_TRACE_MAX_SPANS = 200_000


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m pagerank_tpu.serve",
        description="Deadline-honest PPR query daemon over a resident "
        "sharded graph (typed overload/drain/degraded outcomes).",
    )
    g = p.add_argument_group("graph / solver")
    g.add_argument("--scale", type=int, default=12,
                   help="R-MAT scale: 2**scale vertices (default 12)")
    g.add_argument("--edge-factor", type=int, default=16,
                   help="edges per vertex (default 16)")
    g.add_argument("--seed", type=int, default=0,
                   help="graph + smoke load seed (default 0)")
    g.add_argument("--iters", type=int, default=10,
                   help="PPR power iterations per query (default 10)")
    g.add_argument("--damping", type=float, default=0.85)
    g.add_argument("--num-devices", type=int, default=None,
                   help="mesh width (default: all visible devices)")
    s = p.add_argument_group("serving")
    s.add_argument("--topk", type=int, default=100,
                   help="on-device top-k width (default 100)")
    s.add_argument("--max-batch", type=int, default=8,
                   help="compiled batch width (default 8)")
    s.add_argument("--deadline-ms", type=float, default=500.0,
                   help="default per-query deadline (default 500)")
    s.add_argument("--queue-depth", type=int, default=64,
                   help="bounded admission depth (default 64)")
    s.add_argument("--cache-capacity", type=int, default=1024,
                   help="LRU result-cache entries; 0 disables")
    s.add_argument("--port", type=int, default=8080,
                   help="query ingress HTTP port (0 = ephemeral)")
    s.add_argument("--metrics-port", type=int, default=None,
                   help="serve /metrics on this port too")
    s.add_argument("--drain-deadline", type=float, default=5.0,
                   help="SIGTERM drain budget, seconds (default 5)")
    s.add_argument("--serve-smoke", type=int, default=None, metavar="N",
                   help="self-test: run N in-process queries, print a "
                   "JSON summary, exit (no HTTP)")
    o = p.add_argument_group("query plane (observability)")
    o.add_argument("--metrics-format", choices=("prometheus", "openmetrics"),
                   default="prometheus",
                   help="/metrics text format; openmetrics carries "
                   "trace-id exemplars on latency buckets")
    o.add_argument("--slow-query-ms", type=float, default=None,
                   help="log queries slower than this as strict JSONL "
                   "phase breakdowns (arms the query plane; requires "
                   "--slow-query-log)")
    o.add_argument("--slow-query-log", default=None, metavar="PATH",
                   help="slow-query JSONL destination (required with, "
                   "and only meaningful with, --slow-query-ms)")
    o.add_argument("--query-trace", default=None, metavar="PATH",
                   help="debug/short-capture: export a Chrome trace of "
                   "per-query spans (one lane per thread) at shutdown; "
                   f"retains only the most recent {QUERY_TRACE_MAX_SPANS} "
                   "spans (a bounded ring), so long-lived daemons stay "
                   "bounded but export only the tail of the run")
    o.add_argument("--run-report", default=None, metavar="PATH",
                   help="write the run report (with the serving flight "
                   "recorder section) here on SIGTERM drain")
    return p


def _build_server(args):
    from pagerank_tpu.serving import PprServer, ServeConfig

    src, dst = synth.rmat_edges(
        args.scale, edge_factor=args.edge_factor, seed=args.seed
    )
    graph = build_graph(src, dst, n=1 << args.scale)
    config = PageRankConfig(
        num_iters=args.iters, damping=args.damping,
        num_devices=args.num_devices,
    )
    serve_config = ServeConfig(
        max_batch=args.max_batch,
        queue_depth=args.queue_depth,
        deadline_ms=args.deadline_ms,
        topk=args.topk,
        cache_capacity=args.cache_capacity,
        drain_deadline_s=args.drain_deadline,
    )
    return PprServer(graph, config=config, serve_config=serve_config)


def _run_smoke(server, args) -> int:
    """N seeded in-process queries against the started daemon; prints
    one JSON summary line. Exit 0 iff every query reached a typed
    terminal state (answered or typed-rejected, zero unsettled)."""
    import random

    rng = random.Random(args.seed)
    n = server.graph.n
    handles = [
        server.submit(rng.randrange(n), k=min(args.topk, 8))
        for _ in range(args.serve_smoke)
    ]
    settle = args.deadline_ms / 1000.0 + 5.0
    outcomes = {}
    unsettled = 0
    for q in handles:
        q.wait(settle)
        out = q.outcome or "<unsettled>"
        unsettled += out == "<unsettled>"
        outcomes[out] = outcomes.get(out, 0) + 1
    server.stop()
    print(json.dumps({
        "smoke": "ppr_serve",
        "queries": len(handles),
        "outcomes": outcomes,
        "unsettled": unsettled,
        "devices": server.device_count,
        "degraded": server.degraded,
    }, sort_keys=True))
    return int(ExitCode.OK) if unsettled == 0 else int(ExitCode.FAILURE)


def _write_run_report(path: str) -> None:
    """Dump the run report (serving flight-recorder section included —
    ``build_run_report`` picks up the armed query plane by default)."""
    from pagerank_tpu.obs.report import build_run_report

    with open(path, "w") as f:
        json.dump(build_run_report(), f, sort_keys=True)
        f.write("\n")


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if (args.slow_query_ms is None) != (args.slow_query_log is None):
        # Half a pair is a silent no-op (counting without writing, or a
        # path that never arms the plane) — refuse it at parse time.
        parser.error(
            "--slow-query-ms and --slow-query-log must be given together"
        )
    try:
        server = _build_server(args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return int(ExitCode.USAGE)

    # Query plane (ISSUE 19): armed only on request — the disarmed
    # daemon keeps its zero-tracer-call hot path (the booby-trap pin).
    plane_armed = (args.slow_query_ms is not None or args.query_trace
                   or args.run_report)
    tracer = None
    if plane_armed:
        from pagerank_tpu.serving import qtrace

        if args.query_trace:
            import threading

            from pagerank_tpu.obs import trace as obs_trace

            tracer = obs_trace.enable_tracing(
                obs_trace.Tracer(max_spans=QUERY_TRACE_MAX_SPANS)
            )
            tracer.set_thread_label(threading.get_ident(), "serve-main")
        qtrace.arm_query_plane(slow_query_ms=args.slow_query_ms,
                               slow_query_path=args.slow_query_log)

    # SIGTERM/SIGINT handlers live ONLY around entry points (PTL008);
    # a drain request surfaces as DrainInterrupt at the poll below and
    # the daemon exits ExitCode.INTERRUPTED after the bounded drain.
    drain = jobs.GracefulDrain(deadline_s=args.drain_deadline)
    with drain:
        server.start()
        try:
            if args.serve_smoke is not None:
                return _run_smoke(server, args)
            from pagerank_tpu.serving.http import QueryIngress

            exporter = None
            if args.metrics_port is not None:
                from pagerank_tpu.obs.live import MetricsExporter

                exporter = MetricsExporter(port=args.metrics_port,
                                           format=args.metrics_format)
            with QueryIngress(server, port=args.port) as ingress:
                print(
                    f"serving PPR on http://127.0.0.1:{ingress.port}/ppr "
                    f"(graph 2**{args.scale} vertices, "
                    f"{server.device_count} device(s), "
                    f"batch {args.max_batch}, "
                    f"deadline {args.deadline_ms:g}ms"
                    + (f", metrics :{exporter.port}" if exporter else "")
                    + ") — SIGTERM drains"
                )
                try:
                    while True:
                        drain.check("serve-loop")
                        time.sleep(0.5)
                finally:
                    if exporter is not None:
                        exporter.close()
        except jobs.DrainInterrupt:
            flushed = server.drain(deadline_s=drain.remaining())
            spent = drain.finish()
            if args.run_report:
                # Black-box dump: the drain just pushed a flight-recorder
                # snapshot; persist it before the process exits.
                _write_run_report(args.run_report)
            print(
                f"drained: admission closed, {flushed} queued "
                f"query(ies) typed-rejected, {spent:.2f}s spent "
                f"(exit {int(ExitCode.INTERRUPTED)})"
            )
            return int(ExitCode.INTERRUPTED)
        finally:
            if tracer is not None:
                from pagerank_tpu.obs import trace as obs_trace

                obs_trace.disable_tracing()
                tracer.export_chrome(args.query_trace)
            if plane_armed:
                from pagerank_tpu.serving import qtrace

                qtrace.disarm_query_plane()


if __name__ == "__main__":
    sys.exit(main())
