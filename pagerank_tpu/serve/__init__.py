"""``python -m pagerank_tpu.serve`` — the PPR query daemon entry point
(ISSUE 18 satellite). The implementation lives in ``__main__.py`` (the
lint PTL007 print-exempt surface); these lazy wrappers exist for
in-process tests and avoid importing ``__main__`` at package-import
time (runpy warns when ``-m`` finds it pre-imported)."""


def build_parser():
    from pagerank_tpu.serve.__main__ import build_parser as bp

    return bp()


def main(argv=None) -> int:
    from pagerank_tpu.serve.__main__ import main as m

    return m(argv)


__all__ = ["build_parser", "main"]
