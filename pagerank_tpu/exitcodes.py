"""Exit-code taxonomy — ONE spelling of every process exit code the
CLI, bench, and tooling entry points return (ISSUE 12 satellite;
docs/ROBUSTNESS.md "Exit codes").

The reference job communicates failure only through Spark's own driver
exit; this build's entry points had grown codes organically (preflight
2 in bench vs 3 in the CLI, gate 1, missing-ledger 2). This module is
the audited collection point: entry points return :class:`ExitCode`
members (plain ints to the shell), the docs table renders from the
same enum, and tests/test_jobs.py regression-tests that cli/bench/obs
return codes match it.

Supervisor convention (jobs.py): :data:`ExitCode.INTERRUPTED` (75,
``EX_TEMPFAIL`` — "temporary failure, retry the job") marks a run that
received SIGTERM/SIGINT and DRAINED gracefully — in-flight step
finished, sinks flushed, final snapshot + interrupted-marked run
report written. A retry of the same command against the same
``--job-dir`` resumes instead of recomputing, which is exactly what
``EX_TEMPFAIL`` tells a scheduler to do. A SECOND signal skips the
drain and hard-exits with the shell convention ``128 + signum``
(:func:`hard_exit_code`; 130 for SIGINT, 143 for SIGTERM) — the codes
a SIGKILL'd process's parent observes anyway, so supervisors see one
vocabulary for "died mid-work" regardless of how hard the kill was.
"""

from __future__ import annotations

import enum


class ExitCode(enum.IntEnum):
    """Process exit codes, one member per distinct meaning.

    =================  ====  ==================================================
    member             code  producers
    =================  ====  ==================================================
    OK                 0     every entry point: the run/gate/check succeeded.
                             A RESUMED job that completes also exits OK — the
                             resume count rides the run report's ``job``
                             section, not the exit code.
    FAILURE            1     a gate judged the work bad: ``obs history gate``
                             budget breach / program-change regression,
                             ``obs hlo`` EXPANDED-gather verdict, ``obs fit``
                             does-not-fit verdict, ``python -m
                             pagerank_tpu.analysis`` findings,
                             ``scripts/acceptance.py`` failed config.
    USAGE              2     bad invocation or missing inputs: argparse
                             errors, incompatible flag combinations
                             (``--fused`` + ``--dump-text-dir``, ...),
                             ``obs history`` on a missing ledger, analysis
                             internal errors.
    PREFLIGHT_UNFIT    3     the OOM-preflight fit check refused the
                             geometry BEFORE any allocation (CLI and bench
                             ``--preflight``; bench exited 2 for this before
                             ISSUE 12 unified it here).
    INTERRUPTED        75    graceful preemption drain (jobs.py): first
                             SIGTERM/SIGINT, in-flight step finished, sinks
                             flushed, snapshot + interrupted-marked report
                             written. EX_TEMPFAIL: retry the command with the
                             same ``--job-dir`` to resume.
    SIGINT_HARD        130   second SIGINT during a drain: immediate
                             ``os._exit(128 + SIGINT)`` — no flush.
    SIGTERM_HARD       143   second SIGTERM during a drain: immediate
                             ``os._exit(128 + SIGTERM)`` — no flush.
    =================  ====  ==================================================
    """

    OK = 0
    FAILURE = 1
    USAGE = 2
    PREFLIGHT_UNFIT = 3
    INTERRUPTED = 75
    SIGINT_HARD = 130
    SIGTERM_HARD = 143


def hard_exit_code(signum: int) -> int:
    """Shell convention for death-by-signal: ``128 + signum`` (the code
    a parent observes for an un-caught signal or SIGKILL). The drain's
    second-signal hard exit uses this so supervisors need no special
    case for "the drain itself was killed"."""
    return 128 + int(signum)
