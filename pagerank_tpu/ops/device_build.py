"""On-device graph construction (L2 on the TPU itself).

The reference builds its graph with three cluster-wide shuffles —
``.distinct().groupByKey()`` for dedup + adjacency (Sparky.java:124) and
another distinct for the vertex-universe completion (Sparky.java:137-159).
The host-side builder (graph.py / ops/ell.py) already replaces that with
one sort; this module moves the *entire* build onto the TPU: edges are
generated or uploaded as raw (src, dst) int32 arrays and every later
stage — dedup, degree counts, in-degree relabeling, blocked-ELL slot
packing — runs as XLA sorts/segment-sums/scatters on device.

Why it exists (beyond symmetry): over a tunneled/remote device the
host->device link is the scarcest resource. A scale-22 R-MAT graph's
packed ELL arrays are ~600 MB, but the raw edge list is 8 bytes/edge and
a *synthetic* benchmark graph needs only a PRNG key uploaded. Building
on device makes ingest O(n) in link bytes for real graphs and O(1) for
synthetic ones, and the sort throughput of one TPU chip replaces the
reference's shuffle fabric.

Semantics match graph.py/ell.py exactly (verified slot-for-slot in
tests/test_device_build.py):
  - duplicate (src, dst) edges collapse; out-degree counts unique
    targets (``.distinct()`` before degree, Sparky.java:124, §2a.5);
  - self-loops kept;
  - dangling = out_degree == 0 (edge-list inputs, SURVEY.md §2a.3);
  - vertices relabeled by descending in-degree (stable) so ELL blocks
    waste little padding on power-law graphs (ops/ell.py).

Pipeline (ONE full-edge sort): raw in-degrees by unsorted segment-sum,
relabel, then a single (stripe, new_dst, new_src) composite-key
``lax.sort``; dedup flags and UNIQUE out-degrees fall out of key
adjacency post-sort. The original pipeline ran a second full multi-key
sort first ((dst, src) for dedup-before-degrees); at bench scale the
two sorts together moved ~25 GB through HBM and were the largest build
line (docs/PERF_NOTES.md "Device-build cost"). The one observable
difference: the relabel now orders by RAW in-degree (pre-dedup).
Duplicate edges cannot create or destroy zero-degree vertices and the
relabel is pure layout (perm is carried and decoded), so semantics are
unchanged; on an already-deduplicated edge list — every host-parity
surface, since graph.py dedups on ingest — raw and unique in-degrees
coincide and the output is bit-identical to the two-sort pipeline
(tested in tests/test_device_build.py).

Every stage is pinned to 32-bit indices regardless of the
process-global ``jax_enable_x64`` flag (the pair-f64 config flips it
mid-process): a weak-typed promotion in the per-edge path silently
doubles sort/scatter bytes. The analysis contract PTC006
(pagerank_tpu/analysis/contracts.py) abstract-evals every stage under
x64 and fails on any 64-bit op, and the stages dispatch through
utils/compile_cache.stage_call, whose executable cache deliberately
ignores the x64 flag (legal precisely because of that pin).

Dynamic shapes note: XLA needs static shapes, but dedup/packing sizes
are data-dependent. Instead of compacting arrays (dynamic) the build
keeps duplicate edges in place with weight 0 (they contribute nothing
and are excluded from degrees); only the per-stripe row bounds and the
unique-edge count — S + 2 scalars, fetched in ONE device_get — cross
back to the host to size the final buffers.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pagerank_tpu import graph as graph_lib
from pagerank_tpu.obs import graph_profile
from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.ops import LANES
from pagerank_tpu.utils import compile_cache


def _stage_fence(timings, key, t0, *arrays):
    """Timing-mode stage fence: block on a scalar derived from each
    output (honest on tunneled backends where block_until_ready can
    lie; the in-order device queue means a one-element sum waits for
    the whole stage) and charge the elapsed wall to ``timings[key]``.
    Stage walls INCLUDE any compile that stage paid — the separate
    ``compile_s`` key (stage_call) says how much. No-op (keeping the
    build fully async) when ``timings`` is None.

    The SAME measurement is recorded as a ``build/{stage}`` span on the
    active tracer (obs/trace), so the --build-only breakdown and a
    Chrome trace of the build can never disagree — the dict is a view
    over the fence, not a second clock."""
    if timings is None:
        return
    for a in arrays:
        if a is not None:
            jax.device_get(jnp.sum(jnp.reshape(a, (-1,))[:1]))
    dur = time.perf_counter() - t0
    timings[key] = timings.get(key, 0.0) + dur
    tracer = obs_trace.get_tracer()
    if tracer.enabled:
        stage = key[:-2] if key.endswith("_s") else key
        tracer.add_span("build/" + stage, t0, dur, fenced=True)


@jax.jit
def _mixsum(a):
    """Position-weighted wrapping-uint32 checksum (fingerprint
    ingredient). ONE jitted fusion: eager ops would materialize
    full-array temporaries for the product — at scale-26 slot arrays
    (~10 GB resident) that transient alone OOM'd the build's
    fingerprint pass; fused, XLA streams the multiply into the
    reduction with no temporaries. dtype pinned so the x64 flip cannot
    change the result (see fingerprint docstring)."""
    a = a.reshape(-1).astype(jnp.uint32)
    ix = jax.lax.iota(jnp.uint32, a.shape[0])
    return jnp.sum(a * (ix * jnp.uint32(2654435761)), dtype=jnp.uint32)


@jax.jit
def _u32sum(a):
    return jnp.sum(a.astype(jnp.uint32), dtype=jnp.uint32)


@dataclass
class DeviceEllGraph:
    """Blocked-ELL graph resident on device (relabeled vertex space).

    Mirrors ops/ell.py:EllPack plus the solver masks, with every array a
    jax array. ``perm`` maps relabeled id -> original id.
    """

    n: int
    n_padded: int
    num_blocks: int
    # Striped form (stripe_size set): src/weight/row_block are LISTS of
    # per-stripe arrays with STRIPE-LOCAL source ids, mirroring
    # ops/ell.py:StripedEllPack. Single-stripe: bare arrays, ids span
    # n_padded.
    src: object  # int32 [rows, 128] (or list) source per slot; packed (src << log2(group)) | lane_sub when group > 1
    weight: object  # f32 [rows, 128] (or list), 0 for padding/duplicate slots
    row_block: object  # int32 [rows] (or list), ascending dst-block id
    perm: jax.Array  # int32 [n] relabeled -> original
    dangling_mask: jax.Array  # bool [n] ORIGINAL id space
    zero_in_mask: jax.Array  # bool [n] ORIGINAL id space
    out_degree: jax.Array  # int32 [n] ORIGINAL id space (unique targets)
    num_edges: int  # unique edge count
    group: int = 1  # lane-group size (ops/ell.py grouped-lane layout)
    stripe_size: int = 0  # 0 = single stripe spanning n_padded
    # True: weight is None and slot words are already sentinel-ized
    # (inert slots hold stripe_span << log2(group)) — built with
    # with_weights=False, saving two per-slot planes of HBM.
    presentinel: bool = False
    # Cached fingerprint (set on first call): the engine's build_device
    # releases the slot arrays after placement, so the hash must be
    # computable before then and remembered after.
    _fp: Optional[str] = None

    @property
    def num_rows(self) -> int:
        if isinstance(self.src, (list, tuple)):
            return int(sum(s.shape[0] for s in self.src))
        return int(self.src.shape[0])

    def fingerprint(self) -> str:
        """Stable structural hash for checkpoint validation
        (utils/snapshot.py), mirroring graph.Graph.fingerprint WITHOUT
        fetching bulk arrays to host (the whole point of a device build
        is that only scalars cross the link): layout statics plus
        device-side checksums — degrees, permutation, AND the packed
        slot/row arrays (the adjacency itself: degree-preserving edge
        rewires change the slot words, so they cannot collide the way a
        degree-only checksum would) — in wrapping uint32 arithmetic,
        deterministic for identical builds. Layout-specific by design
        (group/stripe/presentinel change the hash): a snapshot resumes
        against the same build configuration. Cached on first call —
        the engine's build_device frees the slot arrays afterwards and
        computes this eagerly beforehand."""
        import hashlib

        if self._fp is not None:
            return self._fp

        # The dangling mask joins the hash ONLY when it differs from
        # the edge-derivable default (out_degree == 0) — the crawl
        # override makes it an independent semantic input there, while
        # default-mask builds keep pre-override fingerprints so their
        # snapshots still resume (mirrors graph.Graph.fingerprint).
        parts = [_u32sum(self.out_degree), _mixsum(self.out_degree),
                 _mixsum(self.perm)]
        if bool(jax.device_get(
                jnp.any(self.dangling_mask != (self.out_degree == 0)))):
            parts.append(_mixsum(self.dangling_mask.astype(jnp.int32)))
        srcs = self.src if isinstance(self.src, (list, tuple)) else [self.src]
        rbs = (self.row_block
               if isinstance(self.row_block, (list, tuple))
               else [self.row_block])
        parts += [_mixsum(s) for s in srcs] + [_mixsum(r) for r in rbs]
        sums = [int(jax.device_get(p)) for p in parts]
        h = hashlib.sha256()
        for v in (self.n, self.num_edges, self.group, self.stripe_size,
                  int(self.presentinel), *(int(s) for s in sums)):
            h.update(np.int64(v).tobytes())
        self._fp = "dev-" + h.hexdigest()[:12]
        return self._fp


def checkpoint_arrays(dg: "DeviceEllGraph"
                      ) -> Tuple[dict, dict]:
    """Host-side (arrays, meta) snapshot of a built device graph — the
    BUILD-STAGE durable artifact (ISSUE 12, pagerank_tpu/jobs.py): the
    post-sort products (relabel permutation, packed slot planes, row
    bookkeeping, degrees/masks) fetched to host once, so a preempted
    job's warm restart skips the composite-key sort — the single
    biggest unrecoverable cost before this existed. Striped/partitioned
    layouts store their per-stripe lists as ``src_<i>`` planes; the
    meta records the full layout geometry (group/stripe/presentinel)
    plus the structural fingerprint for resume validation.

    Call BEFORE the engine consumes the graph: ``build_device`` donates
    the slot arrays away (``dg.src = None``)."""
    if dg.src is None:
        raise ValueError(
            "device graph already consumed by an engine build; "
            "checkpoint before engine.build_device"
        )
    srcs = dg.src if isinstance(dg.src, (list, tuple)) else [dg.src]
    rbs = (dg.row_block if isinstance(dg.row_block, (list, tuple))
           else [dg.row_block])
    ws = dg.weight if isinstance(dg.weight, (list, tuple)) else [dg.weight]
    arrays = {
        "perm": dg.perm,
        "dangling_mask": dg.dangling_mask,
        "zero_in_mask": dg.zero_in_mask,
        "out_degree": dg.out_degree,
    }
    for i, s in enumerate(srcs):
        arrays[f"src_{i}"] = s
    for i, r in enumerate(rbs):
        arrays[f"row_block_{i}"] = r
    weighted = any(w is not None for w in ws)
    if weighted:
        for i, w in enumerate(ws):
            arrays[f"weight_{i}"] = w
    # ONE host fetch for every plane (device_get batches the transfers).
    host = jax.device_get(arrays)
    arrays = {k: np.asarray(v) for k, v in host.items()}
    meta = {
        "kind": "device_ell_graph",
        "n": dg.n,
        "n_padded": dg.n_padded,
        "num_blocks": dg.num_blocks,
        "num_edges": dg.num_edges,
        "group": dg.group,
        "stripe_size": dg.stripe_size,
        "presentinel": bool(dg.presentinel),
        "n_stripes": len(srcs),
        "listed": isinstance(dg.src, (list, tuple)),
        "weighted": weighted,
        "fingerprint": dg.fingerprint(),
    }
    return arrays, meta


def restore_device_graph(arrays: dict, meta: dict) -> "DeviceEllGraph":
    """Inverse of :func:`checkpoint_arrays`: device_put the persisted
    planes back into a :class:`DeviceEllGraph`, skipping the entire
    gen/relabel/sort/slots/scatter pipeline. The restored graph's
    structural fingerprint is recomputed ON DEVICE and must equal the
    recorded one — a validated artifact whose planes were damaged in a
    way the sha256 somehow missed still cannot resume a solve against
    the wrong adjacency."""
    n_stripes = int(meta["n_stripes"])
    listed = bool(meta.get("listed", n_stripes > 1))
    srcs = [jnp.asarray(arrays[f"src_{i}"]) for i in range(n_stripes)]
    rbs = [jnp.asarray(arrays[f"row_block_{i}"]) for i in range(n_stripes)]
    if meta.get("weighted"):
        ws = [jnp.asarray(arrays[f"weight_{i}"]) for i in range(n_stripes)]
    else:
        ws = [None] * n_stripes
    dg = DeviceEllGraph(
        n=int(meta["n"]), n_padded=int(meta["n_padded"]),
        num_blocks=int(meta["num_blocks"]),
        src=srcs if listed else srcs[0],
        weight=ws if listed else ws[0],
        row_block=rbs if listed else rbs[0],
        perm=jnp.asarray(arrays["perm"]),
        dangling_mask=jnp.asarray(arrays["dangling_mask"]),
        zero_in_mask=jnp.asarray(arrays["zero_in_mask"]),
        out_degree=jnp.asarray(arrays["out_degree"]),
        num_edges=int(meta["num_edges"]), group=int(meta["group"]),
        stripe_size=int(meta["stripe_size"]),
        presentinel=bool(meta["presentinel"]),
    )
    fp = dg.fingerprint()
    if fp != meta.get("fingerprint"):
        raise ValueError(
            f"restored device graph fingerprint {fp} != recorded "
            f"{meta.get('fingerprint')}"
        )
    return dg


def plan_build(cfg, n: int, stripe_size: int = 0, lane_group: int = 0,
               host: bool = False, num_edges: Optional[int] = None,
               partition_span: Optional[int] = None
               ) -> Tuple[int, int, int]:
    """Resolve the (lane_group, stripe_size, partition_span) a build
    should pack so the layout matches what the engine would choose for
    ``cfg`` — THE shared sizing logic for bench.py and the CLI's
    --device-build (VERDICT r2: the fastest build path must not be
    bench-only).

    Mirrors JaxTpuEngine: stripes engage once the gather table outgrows
    the single-stripe fast bound (engine ``stripe_limits``; pair tables
    carry 2x lanes/row), the lane group resolves per accumulation mode
    and stripedness (config ``effective_lane_group``), and the group is
    clamped so packed slot words (src << log2g | sub) fit int32 at the
    packed span. ``host=True`` plans for the host packer (which stripes
    by the engine's own rule and ignores ``stripe_size``) — only the
    clamped lane group is meaningful there. Explicit ``stripe_size`` /
    ``lane_group`` override the automatics. ``num_edges`` (raw counts
    are fine) enables the occupancy-aware pair-span doubling on sparse
    graphs (JaxTpuEngine.occupancy_span — measured +30% at R-MAT 26
    ef 8).

    ``partition_span`` plans the partition-centric layout (ISSUE 6):
    None reads ``cfg.partition_span`` (0 = off), -1 resolves the
    engine's auto rule (``JaxTpuEngine.partition_span`` — dense
    (partition, block) cells + VMEM-resident window, 0 when the graph
    is too small/sparse to win), a positive value is explicit. When it
    engages, the returned STRIPE span equals the partition span — the
    packer's stripes ARE the partitions (the sub-binning permutation
    rides the one composite-key sort) — and the third tuple element is
    that span; the caller sets ``cfg.partition_span`` to it. Pair/wide
    accumulation and vertex-sharded modes plan 0 (unsupported)."""
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine

    n_padded = -(-n // LANES) * LANES
    pair = JaxTpuEngine.resolve_pair(cfg)
    z_item = JaxTpuEngine.gather_z_item(cfg, pair)
    fast_cap, stripe_target = JaxTpuEngine.stripe_limits(z_item, pair)

    part = cfg.partition_span if partition_span is None else partition_span
    if part and (
        pair
        or np.dtype(cfg.accum_dtype).itemsize > 4
        or cfg.vertex_sharded
        or cfg.kernel not in ("auto", "ell", "pallas")
    ):
        if part > 0:
            obs_log.info(
                "partition_span requires the ell kernel with 32-bit "
                "accumulation, replicated mode; planning the default "
                "layout"
            )
        part = 0
    if part == -1:
        part = JaxTpuEngine.partition_span(n_padded, num_edges, z_item)
    part = min(int(part or 0), n_padded)
    if part:
        rounded = max(LANES, part & ~(LANES - 1))
        if rounded != part:
            obs_log.info(
                f"partition_span rounded {part} -> {rounded} "
                f"(must be a multiple of {LANES})"
            )
            part = rounded
        # The pallas partitioned kernel consumes plain partition-local
        # slot ids (it unpacks/gathers on-core); grouped lanes are an
        # XLA-path packing.
        grp = (
            1 if cfg.kernel == "pallas"
            else JaxTpuEngine.clamp_group_for_span(
                lane_group or cfg.effective_lane_group(False), part
            )
        )
        return grp, part, part

    if host:
        stripe = 0  # the host packer stripes internally
        span = min(
            JaxTpuEngine.occupancy_span(
                stripe_target, n_padded, num_edges, pair, z_item
            ) if n_padded > fast_cap else n_padded,
            n_padded,
        )
        is_striped = n_padded > fast_cap
    else:
        if not stripe_size and n_padded > fast_cap:
            stripe = JaxTpuEngine.occupancy_span(
                stripe_target, n_padded, num_edges, pair, z_item
            )
        else:
            stripe = stripe_size
        span = min(stripe or n_padded, n_padded)
        is_striped = bool(stripe) and stripe < n_padded
    grp_req = lane_group or cfg.effective_lane_group(
        pair, striped=is_striped,
        widened=JaxTpuEngine.is_widened_span(span, stripe_target, is_striped),
    )
    grp = JaxTpuEngine.clamp_group_for_span(grp_req, span)
    if grp != grp_req:
        obs_log.info(f"lane group clamped to {grp} for span {span}")
    return grp, stripe, 0


def _rmat_gen(key, ab, a_frac, c_frac, *, scale, n_edges):
    def bit_level(carry, key_lvl):
        src, dst = carry
        kr, kc = jax.random.split(key_lvl)
        r_bit = jax.random.uniform(kr, (n_edges,), jnp.float32)
        c_bit = jax.random.uniform(kc, (n_edges,), jnp.float32)
        src_bit = (r_bit >= ab).astype(jnp.int32)
        threshold = jnp.where(src_bit == 1, c_frac, a_frac).astype(jnp.float32)
        dst_bit = (c_bit >= threshold).astype(jnp.int32)
        return ((src << 1) | src_bit, (dst << 1) | dst_bit), None

    keys = jax.random.split(key, scale)
    init = (jnp.zeros(n_edges, jnp.int32), jnp.zeros(n_edges, jnp.int32))
    (src, dst), _ = jax.lax.scan(bit_level, init, keys)
    # Scramble vertex labels so hubs aren't clustered at id 0 (mirrors
    # the host generator's random permutation). Shuffling an EXPLICIT
    # int32 iota keeps the label table — and therefore the gathered
    # per-edge arrays — 32-bit under x64 (PTC006; permutation(key, int)
    # would shuffle a default-int arange, int64 once the pair-f64
    # config flips the flag, doubling every downstream sort's bytes).
    # Same shuffle, same stream: permutation(key, n) IS a shuffle of
    # arange(n).
    perm = jax.random.permutation(
        jax.random.fold_in(key, 7), jax.lax.iota(jnp.int32, 1 << scale)
    )
    return perm[src], perm[dst]


def uniform_edges_device(
    n: int, num_edges: int, seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Uniform random edges generated on device — the uniform analogue
    of :func:`rmat_edges_device` (only the seed crosses the link; same
    hardware-friendly ``rbg`` PRNG, so the stream differs from the host
    generator ``utils/synth.uniform_edges`` for the same seed)."""
    key = jax.random.key(seed, impl="rbg")
    k1, k2 = jax.random.split(key)
    src = jax.random.randint(k1, (num_edges,), 0, n, dtype=jnp.int32)
    dst = jax.random.randint(k2, (num_edges,), 0, n, dtype=jnp.int32)
    return src, dst


def rmat_edges_device(
    scale: int, edge_factor: int = 16, a: float = 0.57, b: float = 0.19,
    c: float = 0.19, seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """R-MAT edges generated on device (same recursive-quadrant scheme as
    utils/synth.rmat_edges, different PRNG stream). Only the seed crosses
    the host->device link. Uses the hardware-friendly ``rbg`` PRNG
    (threefry is ~4x slower on TPU for this volume of bits); the body
    dispatches through the build-stage executable cache
    (utils/compile_cache.stage_call), so repeat calls — including ones
    across the pair config's x64 flip — reuse the compiled executable."""
    n_edges = edge_factor << scale
    ab = a + b
    key = jax.random.key(seed, impl="rbg")
    return compile_cache.stage_call(
        "rmat_gen",
        functools.partial(_rmat_gen, scale=scale, n_edges=n_edges),
        (key, jnp.float32(ab), jnp.float32(a / ab),
         jnp.float32(c / (1.0 - ab))),
        static_key=(scale, n_edges),
    )


def _raw_in_degree(dst, *, n):
    """Raw (pre-dedup) in-degree by UNSORTED segment-sum — the stage
    that replaced the pipeline's first full-edge sort. The relabel only
    needs an ordering key, and raw in-degree is that key (module
    docstring); a scatter-add over the raw edges is one HBM pass where
    the (dst, src) sort was several."""
    return jax.ops.segment_sum(jnp.ones_like(dst), dst, num_segments=n)


def _relabel_perm(in_degree):
    """Stable in-degree-descending permutation, 32-bit throughout:
    ``jnp.argsort`` would carry an int64 iota payload under x64
    (PTC006), so this sorts an explicit int32 iota instead. Returns
    (perm, inv_perm); perm maps relabeled -> original."""
    n = in_degree.shape[0]
    iota = jax.lax.iota(jnp.int32, n)
    # in_degree <= num edges < 2^31, so int32 negation cannot overflow
    # (int64 here would be silently truncated anyway when x64 is off,
    # with a noisy warning per build).
    _, perm = jax.lax.sort((-in_degree, iota), num_keys=1, is_stable=True)
    inv_perm = jnp.zeros(n, jnp.int32).at[perm].set(iota)
    return perm, inv_perm


def _relabel_sort(src, dst, inv_perm, *, n_padded, stripe_size):
    """Relabel the raw edges and run THE one full-edge sort, by the
    composite key (stripe, new dst) with new src as the tiebreak key.
    Returns (sb_dst, new_src): ``sb_dst`` is the int32 key
    stripe * n_padded + relabeled_dst (decodable, so the big dst/stripe
    arrays aren't carried twice).

    Donates the raw edge arrays — at 500M+ edges every 4-byte per-edge
    temporary is 2GB+ of HBM, and the build's peak live set is what
    bounds single-chip graph capacity. Dedup flags don't exist yet
    (nothing was sorted before this): duplicates land adjacent under
    this total order — identical (src, dst) means identical (stripe,
    new_dst, new_src) — so _slot_coords derives them from key
    adjacency."""
    new_src = inv_perm[src]
    new_dst = inv_perm[dst]
    sz = stripe_size or n_padded
    n_stripes = -(-n_padded // sz)
    if n_stripes > 1:
        # Composite int32 key; build_ell_device guards the range.
        sb_dst = (new_src // sz) * n_padded + new_dst
    else:
        sb_dst = new_dst
    sb_dst, new_src = jax.lax.sort((sb_dst, new_src), num_keys=2)
    return sb_dst, new_src


def _slot_coords(sb_dst, new_src, *, n, n_padded, weight_dtype,
                 group, stripe_size, with_weights=True):
    """Per-edge ELL slot coordinates from the (stripe, dst, src)-sorted
    composite key, plus the dedup-corrected degrees that used to come
    from the pre-relabel sort: first-occurrence flags fall out of key
    adjacency, and the UNIQUE out-degree (``.distinct()`` before
    degree, Sparky.java:124) is one unsorted segment-sum of those flags
    over the relabeled sources — all in the same program, so the
    correction costs no extra HBM pass. Returns everything needed to
    scatter slots once rows_total is known on host. With striping, the
    row space is keyed by (stripe, block): stripe s owns the contiguous
    row range [row_offset[s*num_blocks], row_offset[(s+1)*num_blocks])
    and slot words hold STRIPE-LOCAL source ids
    (ops/ell.py:StripedEllPack)."""
    sz = stripe_size or n_padded
    n_stripes = -(-n_padded // sz)
    new_dst = sb_dst % n_padded if n_stripes > 1 else sb_dst
    stripe_of = sb_dst // n_padded if n_stripes > 1 else None

    # Duplicate edges are adjacent under the (stripe, dst, src) order;
    # first-occurrence flags from key adjacency (see _relabel_sort).
    unique2 = jnp.concatenate(
        [jnp.ones(1, bool),
         (sb_dst[1:] != sb_dst[:-1]) | (new_src[1:] != new_src[:-1])]
    )
    uniq_i = unique2.astype(jnp.int32)
    out_degree_rel = jax.ops.segment_sum(uniq_i, new_src, num_segments=n)
    num_edges = jnp.sum(uniq_i, dtype=jnp.int32)
    if with_weights:
        # Weight = 1/out_degree[src] on unique slots, 0 on duplicate
        # slots (they occupy a slot that contributes nothing — the
        # static-shape alternative to compacting; see module docstring).
        inv_out = graph_lib.inv_out_degree(
            out_degree_rel, jnp, dtype=weight_dtype
        )
        w = jnp.where(unique2, inv_out[new_src], 0.0).astype(weight_dtype)
    else:
        w = None

    # Slot rank k = position within the slot's (stripe, LANE GROUP) run
    # (group=1: k-th in-edge of its dst within the stripe). Runs are
    # contiguous, so first-index-of-run is the running max of run-start
    # positions — one cummax scan, not a searchsorted (33M binary
    # searches = ~840M random gathers, ~25s on a v5e).
    log2g = group.bit_length() - 1
    e = new_dst.shape[0]
    idx = jnp.arange(e, dtype=jnp.int32)
    grp = sb_dst >> log2g  # composite key keeps (stripe, group) distinct
    is_start = jnp.concatenate([jnp.ones(1, bool), grp[1:] != grp[:-1]])
    first = jax.lax.cummax(jnp.where(is_start, idx, 0))
    k = idx - first
    row = k >> log2g
    # Slot position within the 128-lane row: the lane group's band of
    # ``group`` positions, then k's phase within the group (ops/ell.py
    # grouped-lane layout; group=1 reduces to pos = lane).
    pos = (
        ((new_dst % LANES) >> log2g) * group + (k & (group - 1))
    ).astype(jnp.int8)
    local_src = (
        new_src - stripe_of * sz if n_stripes > 1 else new_src
    )
    word = local_src if group == 1 else (
        (local_src << log2g) | (new_dst & (group - 1))
    )

    # Rows per (stripe, 128-dst block) = max rows any of its lane groups
    # uses (for exact parity with the host packer: segment_max of actual
    # use).
    num_blocks = n_padded // LANES
    sb = (
        stripe_of * num_blocks + new_dst // LANES
        if n_stripes > 1 else new_dst // LANES
    )
    sb_rows = jax.ops.segment_max(
        row + 1, sb, num_segments=n_stripes * num_blocks,
        indices_are_sorted=True,
    )
    sb_rows = jnp.maximum(sb_rows, 0)  # empty blocks: segment_max = -inf
    # dtype pinned: jnp.cumsum follows numpy's int32 -> default-int
    # promotion, which under x64 is a silent int64 widening (PTC006).
    row_offset = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(sb_rows, dtype=jnp.int32)]
    )
    row_idx = row_offset[sb] + row
    if not with_weights:
        # Without a weight plane to mark them inert, duplicate slots are
        # DROPPED at scatter instead: route them out of bounds (the
        # sentinel-initialized buffer keeps their slot inert).
        row_idx = jnp.where(unique2, row_idx, row_offset[-1] + 1)
    return word, w, row_idx, pos, sb_rows, row_offset, out_degree_rel, \
        num_edges


def _unrelabel_degree(out_degree_rel, perm):
    """Unique out-degree back in ORIGINAL id space (one small scatter:
    original_degree[perm[i]] = relabeled_degree[i])."""
    n = perm.shape[0]
    return jnp.zeros(n, jnp.int32).at[perm].set(out_degree_rel)


def _scatter_slots(word, row_idx, pos, sb_rows, w=None, *, rows_total,
                   num_blocks, n_stripes=1, fill=0):
    # NOT donated (stage_call passes no donate_argnums): the per-edge
    # inputs ([e] int32/int8/weight vectors) can never alias the
    # (rows_total, 128) slot-plane outputs — the byte sizes differ by
    # construction, so a donation here is unconsumable and XLA warns
    # "Some donated buffers were not usable" on every build (three/four
    # full per-edge planes at bench scale — the r5 bench log's
    # int32[134217728] x2 + int8[134217728]). Peak HBM is identical
    # either way; the caller's `del` after the call frees the buffers
    # as soon as the scatter consumes them. The analysis contract
    # checker (pagerank_tpu/analysis/contracts.py) enforces that every
    # remaining donation in the build chain IS consumable.
    pos = pos.astype(jnp.int32)  # int8 across the phase boundary saves
    # a per-edge array; JAX indexing needs a type that can hold 128
    src_slots = jnp.full((rows_total, LANES), jnp.int32(fill))
    src_slots = src_slots.at[row_idx, pos].set(word, mode="drop")
    if w is not None:
        w_slots = jnp.zeros((rows_total, LANES), w.dtype)
        w_slots = w_slots.at[row_idx, pos].set(w, mode="drop")
    else:
        w_slots = None
    row_block = jnp.repeat(
        jnp.tile(jnp.arange(num_blocks, dtype=jnp.int32), n_stripes),
        sb_rows,
        total_repeat_length=rows_total,
    )
    return src_slots, w_slots, row_block


def build_ell_device(
    src: jax.Array, dst: jax.Array, n: int, weight_dtype=jnp.float32,
    group: int = 1, stripe_size: int = 0, with_weights: bool = True,
    dangling_mask=None, timings: Optional[dict] = None,
) -> DeviceEllGraph:
    """Full graph build on device from raw (possibly duplicated) edges.

    One small transfer (per-stripe row offsets) crosses device->host to
    size the slot buffers; everything else stays on device. ``group``
    selects the grouped-lane slot layout, ``stripe_size`` (multiple of
    128) the source-striped layout for graphs whose gather table exceeds
    the fast regime (ops/ell.py module docstring); 0 = single stripe.

    ``with_weights=False`` skips the per-slot weight plane entirely:
    inert slots (padding, duplicate edges) are written as the engine's
    sentinel word directly (``presentinel`` graphs), saving two
    per-slot/per-edge f32 planes of HBM — the difference between a
    scale-26 build fitting and OOM. The prescaled solver never needs
    per-slot weights; keep weights only for inspection/parity checks.

    ``src``/``dst`` are CONSUMED (donated into the build's sorts — at
    500M+ edges every per-edge buffer matters); don't reuse them after.
    On backends without donation support this emits a harmless
    "donated buffers were not usable" warning.

    ``dangling_mask`` (bool [n], original id space, host or device)
    overrides the default ``out_degree == 0`` mass mask — crawl inputs
    need the reference's post-repair semantics, where only UNCRAWLED
    targets carry dangling mass and a crawled page with no anchor
    links does not (SURVEY.md §2a.3; graph.py carries the same
    override for host builds).

    ``timings`` (optional dict) turns on per-stage attribution: each
    pipeline stage is fenced and its wall-clock recorded under
    ``relabel_s`` / ``sort_s`` / ``slots_s`` / ``scatter_s`` (plus
    ``compile_s`` for any compiles paid), at the cost of serializing
    the stages — leave it None for production builds, which stay fully
    async between host syncs. bench.py --build-only is the consumer.
    """
    if group < 1 or group > LANES or (group & (group - 1)):
        raise ValueError(f"group must be a power of two in [1, {LANES}]")
    if timings is None and obs_trace.get_tracer().enabled:
        # Tracing is on: engage the per-stage fences so the trace
        # carries honest stage walls rather than async dispatch time.
        # Observer effect — the stages serialize, exactly as in
        # --build-only timing mode (docs/OBSERVABILITY.md).
        timings = {}
    n_padded = -(-n // LANES) * LANES
    if stripe_size and (stripe_size <= 0 or stripe_size % LANES):
        raise ValueError("stripe_size must be a positive multiple of 128")
    sz = min(stripe_size, n_padded) if stripe_size and n_padded else n_padded
    if stripe_size and sz < stripe_size:
        stripe_size = sz  # single short stripe; keep ids consistent
    if group > 1 and (sz + 1) * group > np.iinfo(np.int32).max:
        raise ValueError(
            f"grouped slot words overflow int32: stripe span {sz} * "
            f"group {group} (reduce group; same guard as ell_pack_striped)"
        )
    n_stripes = -(-n_padded // sz) if n_padded else 0
    if n_stripes > 1 and n_stripes * n_padded > np.iinfo(np.int32).max:
        raise ValueError(
            f"striped sort key overflows int32: {n_stripes} stripes * "
            f"n_padded {n_padded} (graphs this large exceed single-chip "
            "HBM anyway; use the host build)"
        )
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    num_blocks = n_padded // LANES
    wdt = jnp.dtype(weight_dtype)
    if src.shape[0] == 0 or n == 0:  # edge-free graph (comment-only input)
        empty = (
            [jnp.zeros((0, LANES), jnp.int32)] * n_stripes
            if stripe_size else jnp.zeros((0, LANES), jnp.int32)
        )
        if with_weights:
            empty_w = (
                [jnp.zeros((0, LANES), wdt)] * n_stripes
                if stripe_size else jnp.zeros((0, LANES), wdt)
            )
        else:
            empty_w = [None] * n_stripes if stripe_size else None
        empty_rb = (
            [jnp.zeros(0, jnp.int32)] * n_stripes
            if stripe_size else jnp.zeros(0, jnp.int32)
        )
        return DeviceEllGraph(
            n=n, n_padded=n_padded, num_blocks=num_blocks,
            src=empty, weight=empty_w, row_block=empty_rb,
            perm=jnp.arange(n, dtype=jnp.int32),
            dangling_mask=(jnp.ones(n, bool) if dangling_mask is None
                           else jnp.asarray(dangling_mask, bool)),
            zero_in_mask=jnp.ones(n, bool),
            out_degree=jnp.zeros(n, jnp.int32),
            num_edges=0, group=group, stripe_size=stripe_size,
            presentinel=not with_weights,
        )

    # Stage 1 (relabel): raw in-degrees by unsorted scatter-add, then
    # the stable in-degree-descending permutation — no edge sort needed
    # (module docstring: the pre-relabel (dst, src) sort is gone).
    t0 = time.perf_counter()
    in_raw = compile_cache.stage_call(
        "raw_in_degree", functools.partial(_raw_in_degree, n=n), (dst,),
        static_key=(n,), timings=timings,
    )
    perm, inv_perm = compile_cache.stage_call(
        "relabel_perm", _relabel_perm, (in_raw,), timings=timings,
    )
    # Raw degree == 0 iff unique degree == 0 (a duplicate needs an
    # edge), so the zero-in mask needs no dedup correction.
    zero_in = in_raw == 0
    del in_raw
    _stage_fence(timings, "relabel_s", t0, perm)

    # Stage 2 (sort): relabel the raw edges and run THE one full-edge
    # composite-key sort, consuming the raw arrays.
    stripe_arg = sz if n_stripes > 1 else 0
    t0 = time.perf_counter()
    sb_dst, new_src = compile_cache.stage_call(
        "relabel_sort",
        functools.partial(_relabel_sort, n_padded=n_padded,
                          stripe_size=stripe_arg),
        (src, dst, inv_perm),
        static_key=(n_padded, stripe_arg), donate_argnums=(0, 1),
        timings=timings,
    )
    del src, dst, inv_perm
    _stage_fence(timings, "sort_s", t0, sb_dst)

    # Data-plane profile (ISSUE 13; obs/graph_profile.py): one fused
    # reduction pass over the sorted composite key, BEFORE the sort
    # products are donated into the slot stage. Read-only and
    # armed-only — a disarmed build makes ZERO profile computations
    # and is bit-identical (the booby-trap contract,
    # tests/test_graph_profile.py).
    prof_stats = None
    if graph_profile.armed():
        prof_stats = graph_profile.device_stats(
            sb_dst, new_src, perm, n=n, n_padded=n_padded,
            stripe_size=stripe_arg, num_blocks=num_blocks,
        )

    # Stage 3 (slots): slot coordinates + dedup flags + dedup-corrected
    # unique out-degrees, all from key adjacency in one program.
    t0 = time.perf_counter()
    (word, w, row_idx, pos, sb_rows, row_offset, out_rel,
     num_edges_dev) = compile_cache.stage_call(
        "slot_coords",
        functools.partial(
            _slot_coords, n=n, n_padded=n_padded, weight_dtype=wdt,
            group=group, stripe_size=stripe_arg, with_weights=with_weights,
        ),
        (sb_dst, new_src),
        static_key=(n, n_padded, wdt.name, group, stripe_arg, with_weights),
        donate_argnums=(0, 1),
        timings=timings,
    )
    del sb_dst, new_src
    out_degree = compile_cache.stage_call(
        "unrelabel_degree", _unrelabel_degree, (out_rel, perm),
        timings=timings,
    )
    del out_rel
    # Per-stripe row bounds + the unique-edge count: S + 2 scalars, ONE
    # device->host transfer (the build's only host sync before the
    # buffers are sized). row_offset has n_stripes*num_blocks + 1
    # entries, so the stride-num_blocks slice lands exactly on stripe
    # starts + the total.
    bounds_np, num_edges_np = jax.device_get(
        (row_offset[::num_blocks], num_edges_dev)
    )
    stripe_bounds = [int(b) for b in bounds_np]
    rows_total = stripe_bounds[-1]
    num_edges = int(num_edges_np)
    _stage_fence(timings, "slots_s", t0)
    # Build-shape gauges: with a live exporter attached (obs/live.py)
    # a long build shows its resolved geometry before the solve
    # starts; they also anchor the cost ledger's bytes-per-edge reads.
    from pagerank_tpu.obs import metrics as obs_metrics

    obs_metrics.gauge(
        "build.num_edges", "unique edges of the latest device build"
    ).set(num_edges)
    obs_metrics.gauge(
        "build.slot_rows", "packed 128-lane slot rows of the latest "
        "device build"
    ).set(rows_total)

    if dangling_mask is None:
        mass_mask = out_degree == 0
    else:
        mass_mask = jnp.asarray(dangling_mask, bool)
        # Same invariant the host build enforces (graph.py): a vertex
        # with out-edges cannot carry dangling mass — silently wrong
        # ranks otherwise. (Checked after the sort now: the unique
        # out-degree is a by-product of the composite-key order.)
        if bool(jax.device_get(jnp.any(mass_mask & (out_degree > 0)))):
            raise ValueError("dangling_mask marks a vertex that has out-edges")

    # Stage 4 (scatter): place the slot planes.
    log2g = group.bit_length() - 1
    fill = 0 if with_weights else (sz << log2g)  # engine sentinel word
    t0 = time.perf_counter()
    scatter_args = (word, row_idx, pos, sb_rows)
    if w is not None:
        scatter_args += (w,)
    src_slots, w_slots, row_block = compile_cache.stage_call(
        "scatter_slots",
        functools.partial(_scatter_slots, rows_total=rows_total,
                          num_blocks=num_blocks, n_stripes=n_stripes,
                          fill=fill),
        scatter_args,
        static_key=(rows_total, num_blocks, n_stripes, fill),
        timings=timings,
    )
    del word, w, row_idx, pos  # freed as soon as the scatter consumes them
    if n_stripes > 1 or stripe_size:
        # Slice the concatenated buffers into per-stripe arrays (device
        # copies; the big buffers are dropped one by one as the slices
        # replace them, so the peak is transient and per-plane).
        srcs, ws, rbs = [], [], []
        for s in range(n_stripes):
            lo, hi = stripe_bounds[s], stripe_bounds[s + 1]
            srcs.append(src_slots[lo:hi])
        del src_slots
        for s in range(n_stripes):
            lo, hi = stripe_bounds[s], stripe_bounds[s + 1]
            ws.append(w_slots[lo:hi] if w_slots is not None else None)
            rbs.append(row_block[lo:hi])
        del w_slots, row_block
        src_out, w_out, rb_out = srcs, ws, rbs
    else:
        src_out, w_out, rb_out = src_slots, w_slots, row_block
    _stage_fence(
        timings, "scatter_s", t0,
        rb_out[-1] if isinstance(rb_out, list) else rb_out,
    )
    dg = DeviceEllGraph(
        n=n, n_padded=n_padded, num_blocks=num_blocks,
        src=src_out, weight=w_out, row_block=rb_out,
        perm=perm, dangling_mask=mass_mask, zero_in_mask=zero_in,
        out_degree=out_degree.astype(jnp.int32), num_edges=num_edges,
        group=group, stripe_size=stripe_size,
        presentinel=not with_weights,
    )
    if prof_stats is not None:
        # Finish + publish the data-plane profile (ONE batched
        # device_get): the build's own exact sb_rows vector is the
        # load-prediction substrate, and an explicit dangling-mask
        # override (crawl semantics) replaces the out_degree==0 count.
        profile = graph_profile.finish_device_profile(
            prof_stats, stripe_size=stripe_size, group=group, n=n,
            n_padded=n_padded, block_rows=sb_rows,
            dangling_count_override=(
                jnp.sum(mass_mask.astype(jnp.int32), dtype=jnp.int32)
                if dangling_mask is not None else None
            ),
            fingerprint=dg.fingerprint(),
        )
        graph_profile.publish(profile)
    return dg
