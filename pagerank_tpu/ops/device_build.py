"""On-device graph construction (L2 on the TPU itself).

The reference builds its graph with three cluster-wide shuffles —
``.distinct().groupByKey()`` for dedup + adjacency (Sparky.java:124) and
another distinct for the vertex-universe completion (Sparky.java:137-159).
The host-side builder (graph.py / ops/ell.py) already replaces that with
one sort; this module moves the *entire* build onto the TPU: edges are
generated or uploaded as raw (src, dst) int32 arrays and every later
stage — dedup, degree counts, in-degree relabeling, blocked-ELL slot
packing — runs as XLA sorts/segment-sums/scatters on device.

Why it exists (beyond symmetry): over a tunneled/remote device the
host->device link is the scarcest resource. A scale-22 R-MAT graph's
packed ELL arrays are ~600 MB, but the raw edge list is 8 bytes/edge and
a *synthetic* benchmark graph needs only a PRNG key uploaded. Building
on device makes ingest O(n) in link bytes for real graphs and O(1) for
synthetic ones, and the sort throughput of one TPU chip replaces the
reference's shuffle fabric.

Semantics match graph.py/ell.py exactly (verified slot-for-slot in
tests/test_device_build.py):
  - duplicate (src, dst) edges collapse; out-degree counts unique
    targets (``.distinct()`` before degree, Sparky.java:124, §2a.5);
  - self-loops kept;
  - dangling = out_degree == 0 (edge-list inputs, SURVEY.md §2a.3);
  - vertices relabeled by descending in-degree (stable) so ELL blocks
    waste little padding on power-law graphs (ops/ell.py).

Dynamic shapes note: XLA needs static shapes, but dedup/packing sizes
are data-dependent. Instead of compacting arrays (dynamic) the build
keeps duplicate edges in place with weight 0 (they contribute nothing
and are excluded from degrees); only ``rows_total`` — the ELL row count
— crosses back to the host as one scalar to size the final buffers.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pagerank_tpu import graph as graph_lib

LANES = 128


@dataclass
class DeviceEllGraph:
    """Blocked-ELL graph resident on device (relabeled vertex space).

    Mirrors ops/ell.py:EllPack plus the solver masks, with every array a
    jax array. ``perm`` maps relabeled id -> original id.
    """

    n: int
    n_padded: int
    num_blocks: int
    src: jax.Array  # int32 [rows, 128] relabeled source per slot; packed (src << log2(group)) | lane_sub when group > 1
    weight: jax.Array  # f32 [rows, 128], 0 for padding/duplicate slots
    row_block: jax.Array  # int32 [rows], ascending dst-block id
    perm: jax.Array  # int32 [n] relabeled -> original
    dangling_mask: jax.Array  # bool [n] ORIGINAL id space
    zero_in_mask: jax.Array  # bool [n] ORIGINAL id space
    out_degree: jax.Array  # int32 [n] ORIGINAL id space (unique targets)
    num_edges: int  # unique edge count
    group: int = 1  # lane-group size (ops/ell.py grouped-lane layout)

    @property
    def num_rows(self) -> int:
        return int(self.src.shape[0])


@functools.partial(jax.jit, static_argnums=(1, 2))
def _rmat_gen(key, scale, n_edges, ab, a_frac, c_frac):
    def bit_level(carry, key_lvl):
        src, dst = carry
        kr, kc = jax.random.split(key_lvl)
        r_bit = jax.random.uniform(kr, (n_edges,), jnp.float32)
        c_bit = jax.random.uniform(kc, (n_edges,), jnp.float32)
        src_bit = (r_bit >= ab).astype(jnp.int32)
        threshold = jnp.where(src_bit == 1, c_frac, a_frac).astype(jnp.float32)
        dst_bit = (c_bit >= threshold).astype(jnp.int32)
        return ((src << 1) | src_bit, (dst << 1) | dst_bit), None

    keys = jax.random.split(key, scale)
    init = (jnp.zeros(n_edges, jnp.int32), jnp.zeros(n_edges, jnp.int32))
    (src, dst), _ = jax.lax.scan(bit_level, init, keys)
    # Scramble vertex labels so hubs aren't clustered at id 0
    # (mirrors the host generator's random permutation).
    perm = jax.random.permutation(jax.random.fold_in(key, 7), 1 << scale)
    return perm[src], perm[dst]


def rmat_edges_device(
    scale: int, edge_factor: int = 16, a: float = 0.57, b: float = 0.19,
    c: float = 0.19, seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """R-MAT edges generated on device (same recursive-quadrant scheme as
    utils/synth.rmat_edges, different PRNG stream). Only the seed crosses
    the host->device link. Uses the hardware-friendly ``rbg`` PRNG
    (threefry is ~4x slower on TPU for this volume of bits); the jitted
    body is module-level so repeat calls reuse the compiled executable."""
    n_edges = edge_factor << scale
    ab = a + b
    key = jax.random.key(seed, impl="rbg")
    return _rmat_gen(
        key, scale, n_edges,
        jnp.float32(ab), jnp.float32(a / ab), jnp.float32(c / (1.0 - ab)),
    )


@functools.partial(jax.jit, static_argnums=(2,))
def _sort_dedup_degrees(src, dst, n):
    """Sort edges by (dst, src), mark duplicates, compute unique-edge
    degrees. Returns (src_s, dst_s, unique, out_degree, in_degree)."""
    order = jnp.lexsort((src, dst))
    src_s = src[order]
    dst_s = dst[order]
    same = (src_s[1:] == src_s[:-1]) & (dst_s[1:] == dst_s[:-1])
    unique = jnp.concatenate([jnp.ones(1, bool), ~same])
    uniq_i = unique.astype(jnp.int32)
    out_degree = jax.ops.segment_sum(uniq_i, src_s, num_segments=n)
    in_degree = jax.ops.segment_sum(
        uniq_i, dst_s, num_segments=n, indices_are_sorted=True
    )
    return src_s, dst_s, unique, out_degree, in_degree


@functools.partial(jax.jit, static_argnums=(5, 6, 7))
def _relabel_and_rows(src_s, dst_s, unique, out_degree, in_degree, n_padded,
                      weight_dtype=jnp.float32, group=1):
    """In-degree-descending relabel + per-edge ELL slot coordinates.

    Returns (new_src, new_dst_sorted order arrays...) — everything needed
    to scatter slots once rows_total is known on host."""
    n = out_degree.shape[0]
    order = jnp.argsort(-in_degree.astype(jnp.int64), stable=True)
    perm = order.astype(jnp.int32)  # relabeled -> original
    inv_perm = jnp.zeros(n, jnp.int32).at[perm].set(
        jnp.arange(n, dtype=jnp.int32)
    )

    new_dst = inv_perm[dst_s]
    new_src = inv_perm[src_s]
    # Re-sort by relabeled dst (stable keeps src-ascending order within a
    # dst, matching the host packer's slot order).
    order2 = jnp.argsort(new_dst, stable=True)
    new_dst = new_dst[order2]
    new_src = new_src[order2]
    unique2 = unique[order2]

    # Weight = 1/out_degree[src] on unique slots, 0 on duplicate slots.
    # out_degree is indexed by ORIGINAL id — use the pre-relabel src ids.
    inv_out = graph_lib.inv_out_degree(out_degree, jnp, dtype=weight_dtype)
    w = jnp.where(unique2, inv_out[src_s[order2]], 0.0).astype(weight_dtype)

    # Slot rank k = position within the slot's LANE GROUP run (group=1:
    # k-th in-edge of its dst), counting duplicates too (the host packer
    # indexes depth over the deduped edge list; duplicates here occupy a
    # slot with weight 0 — harmless, slightly deeper blocks). new_dst is
    # sorted, so first-index-of-group is the running max of run-start
    # positions — one cummax scan, not a searchsorted (33M binary
    # searches = ~840M random gathers, ~25s on a v5e).
    log2g = group.bit_length() - 1
    e = new_dst.shape[0]
    idx = jnp.arange(e, dtype=jnp.int32)
    grp = new_dst >> log2g
    is_start = jnp.concatenate([jnp.ones(1, bool), grp[1:] != grp[:-1]])
    first = jax.lax.cummax(jnp.where(is_start, idx, 0))
    k = idx - first
    row = k >> log2g
    # Slot position within the 128-lane row: the lane group's band of
    # ``group`` positions, then k's phase within the group (ops/ell.py
    # grouped-lane layout; group=1 reduces to pos = lane).
    pos = ((new_dst % LANES) >> log2g) * group + (k & (group - 1))
    word = new_src if group == 1 else (
        (new_src << log2g) | (new_dst & (group - 1))
    )

    # Rows per 128-dst block = max rows any of its lane groups uses (for
    # exact parity with the host packer: segment_max of actual use).
    block = new_dst // LANES
    num_blocks = n_padded // LANES
    block_rows = jax.ops.segment_max(
        row + 1, block, num_segments=num_blocks, indices_are_sorted=True
    )
    block_rows = jnp.maximum(block_rows, 0)  # empty blocks: segment_max = -inf
    row_offset = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(block_rows).astype(jnp.int32)]
    )
    row_idx = row_offset[block] + row
    mass_mask = out_degree == 0
    zero_in = in_degree == 0
    return word, w, row_idx, pos, block_rows, row_offset, perm, mass_mask, zero_in


@functools.partial(jax.jit, static_argnums=(5, 6))
def _scatter_slots(new_src, w, row_idx, lane, block_rows, rows_total, num_blocks):
    src_slots = jnp.zeros((rows_total, LANES), jnp.int32)
    w_slots = jnp.zeros((rows_total, LANES), w.dtype)
    src_slots = src_slots.at[row_idx, lane].set(new_src, mode="drop")
    w_slots = w_slots.at[row_idx, lane].set(w, mode="drop")
    row_block = jnp.repeat(
        jnp.arange(num_blocks, dtype=jnp.int32),
        block_rows,
        total_repeat_length=rows_total,
    )
    return src_slots, w_slots, row_block


def build_ell_device(
    src: jax.Array, dst: jax.Array, n: int, weight_dtype=jnp.float32,
    group: int = 1,
) -> DeviceEllGraph:
    """Full graph build on device from raw (possibly duplicated) edges.

    One scalar (rows_total) crosses device->host to size the slot
    buffers; everything else stays on device. ``group`` selects the
    grouped-lane slot layout (ops/ell.py module docstring).
    """
    if group < 1 or group > LANES or (group & (group - 1)):
        raise ValueError(f"group must be a power of two in [1, {LANES}]")
    n_padded = -(-n // LANES) * LANES
    if group > 1 and (n_padded + 1) * group > np.iinfo(np.int32).max:
        raise ValueError(
            f"grouped slot words overflow int32: n_padded {n_padded} * "
            f"group {group} (reduce group; same guard as ell_pack_striped)"
        )
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    if src.shape[0] == 0:  # edge-free graph (e.g. comment-only input)
        num_blocks = n_padded // LANES
        wdt = jnp.dtype(weight_dtype)
        return DeviceEllGraph(
            n=n, n_padded=n_padded, num_blocks=num_blocks,
            src=jnp.zeros((0, LANES), jnp.int32),
            weight=jnp.zeros((0, LANES), wdt),
            row_block=jnp.zeros(0, jnp.int32),
            perm=jnp.arange(n, dtype=jnp.int32),
            dangling_mask=jnp.ones(n, bool),
            zero_in_mask=jnp.ones(n, bool),
            out_degree=jnp.zeros(n, jnp.int32),
            num_edges=0, group=group,
        )

    src_s, dst_s, unique, out_degree, in_degree = _sort_dedup_degrees(src, dst, n)
    (word, w, row_idx, pos, block_rows, row_offset, perm, mass_mask,
     zero_in) = _relabel_and_rows(
        src_s, dst_s, unique, out_degree, in_degree, n_padded,
        jnp.dtype(weight_dtype), group,
    )
    num_blocks = n_padded // LANES
    rows_total = int(jax.device_get(row_offset[-1]))
    num_edges = int(jax.device_get(unique.sum()))
    src_slots, w_slots, row_block = _scatter_slots(
        word, w, row_idx, pos, block_rows, rows_total, num_blocks
    )
    return DeviceEllGraph(
        n=n, n_padded=n_padded, num_blocks=num_blocks,
        src=src_slots, weight=w_slots, row_block=row_block,
        perm=perm, dangling_mask=mass_mask, zero_in_mask=zero_in,
        out_degree=out_degree.astype(jnp.int32), num_edges=num_edges,
        group=group,
    )
