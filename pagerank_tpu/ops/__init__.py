"""TPU-native sparse ops (L3 hot path).

``LANES`` is the ONE spelling of the TPU lane geometry — the 128-lane
vector register width that sizes every dst block, slot row, and padding
round in the blocked-ELL layout. Every module under ``ops/`` imports it
from here; the repo lint (``python -m pagerank_tpu.analysis``, rule
PTL001) rejects magic ``128``/``127``/``>> 7`` lane arithmetic anywhere
else under ``ops/`` so the geometry cannot silently fork.
"""

LANES = 128
