"""Pallas TPU kernel for the blocked-ELL contribution SpMV (the L3 hot
op; SURVEY.md §7 step 4).

Why a hand kernel when ops/spmv.py:ell_contrib already reformulates the
scatter for XLA: the XLA path's per-slot gather re-reads the rank vector
from HBM with random access every chunk — on a power-law graph the
access pattern defeats locality and the op is latency-bound far below
HBM bandwidth. This kernel pins the (pre-scaled) rank vector ``z_ext``
in VMEM for the *entire* sweep, so every gather is served on-chip and
HBM traffic drops to the streaming minimum: 4 bytes per slot (the
source index) plus one read-modify-write of the output block rows.

Structure (grid = row chunks, sequential on the core):

  - ``z_ext`` [n_pad + 8] lives whole in VMEM (BlockSpec with no
    blocking). Budget: ~4 bytes/vertex => graphs to ~2-3M vertices per
    core in f32; the engine falls back to the XLA path above that.
  - Each grid step streams a (CHUNK, 128) block of source indices into
    VMEM, gathers/multiplies against z_ext, and reduces rows to their
    dst blocks with a one-hot matmul on the MXU (block ids within a
    chunk are gap-free because empty blocks are sorted to the tail by
    the in-degree relabel — ops/ell.py).
  - The (CHUNK, 128) segment partial is accumulated into the HBM output
    at a data-dependent row offset (per-chunk first-block id, delivered
    via PrefetchScalarGridSpec) with an explicit DMA read-modify-write.
    The output buffer is donated zeros (input_output_aliased), so
    cross-chunk boundary blocks accumulate correctly; the grid is
    sequential, so the RMW cannot race.

Gather strategies (Mosaic support differs by generation; the engine
probes once at build):
  - "take":    z_ext[src] — direct dynamic gather.
  - "onehot8": width-8 row gather + one-hot dot (the XLA trick, but
               against VMEM-resident data).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pagerank_tpu.ops import LANES
from pagerank_tpu.ops import spmv as spmv_ops
from pagerank_tpu.utils import jax_compat


def _kernel(rb0_ref, z_ref, src_ref, rb_ref, out_in_ref, out_ref, acc, sem,
            *, chunk, gather, accum_dtype):
    del out_in_ref  # aliased with out_ref (donated zeros)
    i = pl.program_id(0)
    rb0 = rb0_ref[i]

    src = src_ref[...]  # (chunk, 128) int32
    z = z_ref[...]
    if gather == "take":
        v = z[src].astype(accum_dtype)
    elif gather == "onehot8":
        zw = z.reshape(-1, 8)
        rows = zw[src >> 3]  # (chunk, 128, 8)
        sel = jax.nn.one_hot(src & 7, 8, dtype=accum_dtype)
        v = (rows.astype(accum_dtype) * sel).sum(-1)
    else:
        raise ValueError(f"unknown gather strategy {gather!r}")

    # Row -> dst-block segment sum on the MXU: one_hot over the chunk's
    # (gap-free, ascending) local block ids, contracted over rows.
    rb_local = rb_ref[...].reshape(chunk) - rb0
    oh = jax.nn.one_hot(rb_local, chunk, dtype=accum_dtype)  # (chunk, chunk)
    seg = jax.lax.dot_general(
        oh, v, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=accum_dtype,
    )  # (chunk, 128)

    # Accumulate into out[rb0 : rb0+chunk] (HBM) via explicit RMW DMA.
    load = pltpu.make_async_copy(
        out_ref.at[pl.ds(rb0, chunk), :], acc, sem
    )
    load.start()
    load.wait()
    acc[...] += seg.astype(out_ref.dtype)
    store = pltpu.make_async_copy(
        acc, out_ref.at[pl.ds(rb0, chunk), :], sem
    )
    store.start()
    store.wait()


@functools.partial(
    jax.jit,
    static_argnames=("num_blocks", "chunk", "gather", "accum_dtype",
                     "interpret"),
)
def ell_contrib_pallas(
    z_ext, src_slots, row_block, rb0_per_chunk, num_blocks, *,
    chunk=256, gather="take", accum_dtype=jnp.float32, interpret=False,
):
    """contrib = Aᵀ_norm r over sentinel-form ELL slots (see
    ops/spmv.py:ell_contrib for the prescaled-z_ext contract).

    Args:
      z_ext: [n_pad + 8] pre-scaled rank vector (trailing 8 lanes zero).
      src_slots: int32 [rows, 128]; rows must be a multiple of ``chunk``.
      row_block: int32 [rows] ascending dst-block id per row.
      rb0_per_chunk: int32 [rows/chunk] first block id of each chunk
        (host-precomputed: ``row_block[::chunk]``).
      num_blocks: static count of 128-lane dst blocks.

    Returns:
      [num_blocks * 128] contribution sums (relabeled, padded).
    """
    n_rows = src_slots.shape[0]
    if n_rows % chunk:
        raise ValueError(f"rows {n_rows} not a multiple of chunk {chunk}")
    nc = n_rows // chunk
    num_blocks_pad = num_blocks + chunk  # slack so the last RMW stays in range
    out_init = jnp.zeros((num_blocks_pad, LANES), z_ext.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # z_ext, whole, resident
            pl.BlockSpec((chunk, LANES), lambda i, rb0: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, 1), lambda i, rb0: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # out buffer stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((chunk, LANES), z_ext.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(
        _kernel, chunk=chunk,
        gather=gather, accum_dtype=jnp.dtype(accum_dtype),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_blocks_pad, LANES), z_ext.dtype),
        input_output_aliases={4: 0},  # donated zeros -> output (RMW target)
        interpret=interpret,
        compiler_params=jax_compat.pallas_tpu_compiler_params(
            has_side_effects=True
        ),
    )(
        rb0_per_chunk, z_ext, src_slots,
        row_block.reshape(-1, 1), out_init,
    )
    return out[:num_blocks].reshape(-1)


# ---------------------------------------------------------------------------
# Partition-centric kernel (ISSUE 16 payload).
#
# The legacy kernel above pins the WHOLE z_ext vector in VMEM — sound
# only while n_pad * itemsize fits the PTK001 budget (~3M f32 vertices),
# which is exactly the geometry band the bench campaign left behind at
# scale 22+. The partitioned kernel keeps the partition-centric layout
# the XLA path already builds (ISSUE 6: rows grouped by source
# partition, slot indices partition-local, pair ranks dense per
# partition) and holds only ONE partition's z-window in VMEM at a time:
#
#   - ``z_windows`` [K, W, 128]: the pre-scaled rank vector split into K
#     partition windows of W*128 = partition_span (+ zero tail) lanes.
#     The BlockSpec picks window ``bases[i, 0]`` per grid step — rows
#     are partition-major, so the index-map output is constant across a
#     partition's chunks and the Pallas pipeline DMAs each window into
#     its double buffer exactly once per sweep.
#   - ``src_slots``: the 3-byte planar slot words (int8 [rows, 384],
#     ops/spmv.py:pack_words24 layout) streamed chunk-at-a-time and
#     unpacked to int32 on-core — 3 bytes of HBM traffic per slot
#     instead of 4 — or plain int32 [rows, 128] when the span exceeds
#     the 24-bit window.
#   - segment sum: pair ranks are dense per partition (increment <= 1
#     per row), so a chunk's CHUNK-LOCAL ranks live in [0, width) for a
#     host-measured ``width`` — one (chunk, width) one-hot matmul on
#     the MXU reduces the whole chunk, f32 whatever the stream dtype.
#   - the (width, 128) f32 partial RMWs into the donated-zeros pair
#     output at the chunk's global first rank (bases[i, 1]), the same
#     sequential-grid DMA accumulate as above.
#
# A chunk whose rank span exceeds ``width`` would silently drop rows
# (its one-hot rows are all-zero); the engine derives width from the
# measured max span, and analysis/kernels.py PTK003 independently
# proves the written windows cover every pair rank — the static gate
# this kernel ships under.
# ---------------------------------------------------------------------------


def _kernel_partitioned(bases_ref, z_ref, src_ref, rk_ref, out_in_ref,
                        out_ref, acc, sem, *, chunk, width, gather):
    del out_in_ref  # aliased with out_ref (donated zeros)
    i = pl.program_id(0)
    rb0 = bases_ref[i, 1]

    if src_ref.dtype == jnp.int8:
        src = spmv_ops.unpack_words24(src_ref[...])  # (chunk, 128) int32
    else:
        src = src_ref[...]
    z = z_ref[...].reshape(-1)  # (1, W, 128) -> flat partition window
    if gather == "take":
        v = z[src]
    elif gather == "onehot8":
        zw = z.reshape(-1, 8)
        rows = zw[src >> 3]  # (chunk, 128, 8)
        sel = jax.nn.one_hot(src & 7, 8, dtype=z.dtype)
        v = (rows * sel).sum(-1)
    else:
        raise ValueError(f"unknown gather strategy {gather!r}")
    v = v.astype(jnp.float32)  # bf16 streams, f32 accumulation

    # Chunk-local pair ranks -> (width, 128) segment partial on the MXU.
    rk = rk_ref[...].reshape(chunk)
    oh = jax.nn.one_hot(rk, width, dtype=jnp.float32)  # (chunk, width)
    seg = jax.lax.dot_general(
        oh, v, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (width, 128)

    load = pltpu.make_async_copy(out_ref.at[pl.ds(rb0, width), :], acc, sem)
    load.start()
    load.wait()
    acc[...] += seg
    store = pltpu.make_async_copy(acc, out_ref.at[pl.ds(rb0, width), :], sem)
    store.start()
    store.wait()


@functools.partial(
    jax.jit,
    static_argnames=("num_pairs", "chunk", "width", "gather", "interpret"),
)
def ell_contrib_pallas_partitioned(
    z_windows, src_slots, rank_rows, chunk_bases, num_pairs, *,
    chunk=1024, width=LANES, gather="take", interpret=False,
):
    """Partition-centric fused gather+contrib+segment-sum (see module
    comment above; the slot/rank layout is the engine's ISSUE-6
    partitioned form).

    Args:
      z_windows: [K, W, 128] pre-scaled rank vector, one row per source
        partition (W*128 >= partition_span + 8, tail zeroed; f32 or
        bf16 stream).
      src_slots: partition-LOCAL slot indices; int8 [rows, 384] planar
        3-byte words (words24) or int32 [rows, 128]. ``rows`` must be a
        multiple of ``chunk``; inert slots point at the zero tail.
      rank_rows: int32 [rows/128, 128] CHUNK-local dense pair rank of
        each slot row (row-major: row r at [r // 128, r % 128]); values
        in [0, width).
      chunk_bases: int32 [rows/chunk, 2]; per chunk ``[partition index,
        global first pair rank]`` (host-precomputed, scalar-prefetched).
      num_pairs: static global count of (dst block, partition) pairs.

    Returns:
      [num_pairs * 128] f32 per-pair contribution sums.
    """
    n_rows = src_slots.shape[0]
    if n_rows % chunk:
        raise ValueError(f"rows {n_rows} not a multiple of chunk {chunk}")
    if chunk % LANES:
        raise ValueError(f"chunk {chunk} not a multiple of {LANES}")
    if width % 8:
        raise ValueError(f"width {width} not a multiple of 8 (f32 sublanes)")
    if z_windows.ndim != 3 or z_windows.shape[2] != LANES:
        raise ValueError(f"z_windows must be [K, W, {LANES}], "
                         f"got {z_windows.shape}")
    src_lanes = 3 * LANES if src_slots.dtype == jnp.int8 else LANES
    if src_slots.shape[1] != src_lanes:
        raise ValueError(f"src_slots {src_slots.shape} / {src_slots.dtype} "
                         f"mismatch (want {src_lanes} lanes)")
    nc = n_rows // chunk
    w_rows = z_windows.shape[1]
    num_pairs_pad = num_pairs + width  # slack so the last RMW stays in range
    out_init = jnp.zeros((num_pairs_pad, LANES), jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, w_rows, LANES), lambda i, b: (b[i, 0], 0, 0),
                         memory_space=pltpu.VMEM),  # one partition window
            pl.BlockSpec((chunk, src_lanes), lambda i, b: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk // LANES, LANES), lambda i, b: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # out buffer stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((width, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(
        _kernel_partitioned, chunk=chunk, width=width, gather=gather,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_pairs_pad, LANES), jnp.float32),
        input_output_aliases={4: 0},  # donated zeros -> output (RMW target)
        interpret=interpret,
        compiler_params=jax_compat.pallas_tpu_compiler_params(
            has_side_effects=True
        ),
    )(
        chunk_bases, z_windows, src_slots, rank_rows, out_init,
    )
    return out[:num_pairs].reshape(-1)
